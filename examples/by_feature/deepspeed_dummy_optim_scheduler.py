"""Feature: ds_config-defined optimizer/scheduler via DummyOptim/DummyScheduler
(reference `utils/deepspeed.py:245-291` + `by_feature/deepspeed_with_config_support.py`
optimizer/scheduler path).

A DeepSpeed script whose optimizer and LR schedule live in `ds_config.json`
keeps its conventional training-loop shape: it constructs `DummyOptim` /
`DummyScheduler` placeholders and `accelerator.prepare(...)` swaps in the real
objects. Here the ds_config sections compile directly to an optax
transformation with the schedule embedded — `scheduler.step()` is a no-op view
(the optimizer update advances the schedule, exactly like DeepSpeed's
engine-internal scheduler) and `get_last_lr()` reads the live update count.
"""

from __future__ import annotations

import json
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))
from _common import apply_fn, base_parser, evaluate, init_params, loss_fn, make_batches

from accelerate_tpu import (
    Accelerator,
    DataLoaderShard,
    DeepSpeedPlugin,
    DummyOptim,
    DummyScheduler,
    set_seed,
)


def main() -> None:
    parser = base_parser()
    parser.add_argument("--ds_config", default=None, help="path to a ds_config.json")
    args = parser.parse_args()
    set_seed(args.seed)

    ds_config = args.ds_config
    if ds_config is None:  # self-contained demo config, the HF-docs shape
        ds_config = str(Path(tempfile.mkdtemp()) / "ds_config.json")
        Path(ds_config).write_text(json.dumps({
            "optimizer": {
                "type": "AdamW",
                "params": {"lr": "auto", "betas": [0.9, 0.999], "eps": 1e-8,
                           "weight_decay": "auto"},
            },
            "scheduler": {
                "type": "WarmupDecayLR",
                "params": {"warmup_min_lr": 0.0, "warmup_max_lr": "auto",
                           "warmup_num_steps": "auto", "total_num_steps": "auto"},
            },
        }))

    accelerator = Accelerator(deepspeed_plugin=DeepSpeedPlugin(hf_ds_config=ds_config))

    n_train = 4 if args.tiny else 16
    total_steps = n_train * args.num_epochs
    # the conventional DeepSpeed loop shape: placeholders, swapped by prepare()
    dummy_optim = DummyOptim(params=None, lr=args.lr, weight_decay=0.01)
    dummy_scheduler = DummyScheduler(
        dummy_optim, total_num_steps=total_steps, warmup_num_steps=max(total_steps // 10, 1)
    )
    model, optimizer, scheduler, train_dl, eval_dl = accelerator.prepare(
        (apply_fn, init_params(args.seed)),
        dummy_optim,
        dummy_scheduler,
        DataLoaderShard(make_batches(n_train, args.batch_size)),
        DataLoaderShard(make_batches(4, args.batch_size, seed=1)),
    )
    accelerator.print(
        f"ds_config compiled: optimizer=AdamW(lr={args.lr}) "
        f"scheduler=WarmupDecayLR(total={total_steps})"
    )

    for epoch in range(args.num_epochs):
        for batch in train_dl:
            with accelerator.accumulate(model):
                loss = accelerator.backward(loss_fn, batch)
                optimizer.step()
                optimizer.zero_grad()
                scheduler.step()  # no-op view; kept for loop-shape parity
        acc = evaluate(accelerator, model, eval_dl)
        accelerator.print(
            f"epoch {epoch}: loss={float(loss):.4f} accuracy={acc:.3f} "
            f"lr={scheduler.get_last_lr()[0]:.2e}"
        )


if __name__ == "__main__":
    main()
