"""Feature: checkpoint/resume (reference `by_feature/checkpointing.py`).

`save_state` captures model/optimizer/scheduler/RNG/step into a rotating
`checkpoints/checkpoint_<i>` directory; `load_state` restores it and
`skip_first_batches` resumes mid-epoch (reference `accelerator.py:2953-3255`,
`data_loader.py:1245`).
"""

from __future__ import annotations

import sys
import tempfile
from pathlib import Path

import numpy as np
import optax

sys.path.insert(0, str(Path(__file__).parent))
from _common import apply_fn, base_parser, evaluate, init_params, loss_fn, make_batches

from accelerate_tpu import Accelerator, DataLoaderShard, set_seed, skip_first_batches
from accelerate_tpu.accelerator import ProjectConfiguration


def main() -> None:
    parser = base_parser()
    parser.add_argument("--resume_from_checkpoint", default=None)
    args = parser.parse_args()
    set_seed(args.seed)
    project_dir = args.project_dir or tempfile.mkdtemp(prefix="ckpt_example_")

    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        project_config=ProjectConfiguration(
            project_dir=project_dir, automatic_checkpoint_naming=True, total_limit=2
        ),
    )
    n_train = 4 if args.tiny else 12
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        (apply_fn, init_params(args.seed)),
        optax.adam(args.lr),
        DataLoaderShard(make_batches(n_train, args.batch_size)),
        DataLoaderShard(make_batches(4, args.batch_size, seed=1)),
    )
    step = accelerator.make_train_step(loss_fn)

    if args.resume_from_checkpoint:
        accelerator.load_state(args.resume_from_checkpoint)

    for epoch in range(args.num_epochs):
        dl = train_dl
        if args.resume_from_checkpoint and epoch == 0:
            dl = skip_first_batches(train_dl, 2)  # demo: resume past 2 batches
        for batch in dl:
            loss = step(batch)
        accelerator.save_state()  # checkpoints/checkpoint_<epoch>, rotated at 2
        acc = evaluate(accelerator, model, eval_dl)
        accelerator.print(f"epoch {epoch}: loss={float(loss):.4f} accuracy={acc:.3f}")

    # round-trip proof: clobber params, restore, same metric
    before = evaluate(accelerator, model, eval_dl)
    model.load_state_dict(
        {k: np.zeros_like(np.asarray(v)) for k, v in model.state_dict().items()}
    )
    accelerator.load_state()  # latest checkpoint
    after = evaluate(accelerator, model, eval_dl)
    accelerator.print(f"restore parity: accuracy {before:.3f} == {after:.3f}")
    assert abs(before - after) < 1e-6


if __name__ == "__main__":
    main()
