"""Feature: coordinated early stopping (reference `by_feature/early_stopping.py`).

Any process may call `set_trigger()`; `check_trigger()` all-reduces the flag so
every process sees it and breaks the loop together — the breakpoint mechanism of
reference `accelerator.py:2233-2290`.
"""

from __future__ import annotations

import sys
from pathlib import Path

import optax

sys.path.insert(0, str(Path(__file__).parent))
from _common import apply_fn, base_parser, evaluate, init_params, loss_fn, make_batches

from accelerate_tpu import Accelerator, DataLoaderShard, set_seed


def main() -> None:
    parser = base_parser(num_epochs=10)
    parser.add_argument("--early_stop_loss", type=float, default=0.2)
    args = parser.parse_args()
    set_seed(args.seed)

    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    n_train = 4 if args.tiny else 12
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        (apply_fn, init_params(args.seed)),
        optax.adam(args.lr),
        DataLoaderShard(make_batches(n_train, args.batch_size)),
        DataLoaderShard(make_batches(4, args.batch_size, seed=1)),
    )
    step = accelerator.make_train_step(loss_fn)

    stopped_at = None
    for epoch in range(args.num_epochs):
        for batch in train_dl:
            loss = step(batch)
            # local decision (e.g. main process watching validation loss) ...
            if float(loss) < args.early_stop_loss:
                accelerator.set_trigger()
            # ... made global: every process agrees to break on the same step
            if accelerator.check_trigger():
                stopped_at = epoch
                break
        if stopped_at is not None:
            break
    acc = evaluate(accelerator, model, eval_dl)
    accelerator.print(
        f"stopped at epoch {stopped_at}: loss={float(loss):.4f} accuracy={acc:.3f}"
    )


if __name__ == "__main__":
    main()
