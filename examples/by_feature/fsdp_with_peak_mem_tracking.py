"""Feature: FSDP with peak-memory tracking (reference
`by_feature/fsdp_with_peak_mem_tracking.py`).

FSDP is a mesh axis, not an engine: `ParallelismConfig(fsdp_size=N)` shards
parameters and optimizer state across the `fsdp` axis (ZeRO-3 placement — each
device holds 1/N of every tensor) and XLA schedules the all-gather/reduce-scatter
pairs. Device memory is read from `Device.memory_stats()` (the reference uses
`torch.cuda.max_memory_allocated` via its TorchTracemalloc helper).
"""

from __future__ import annotations

import sys
from pathlib import Path

import jax
import optax

sys.path.insert(0, str(Path(__file__).parent))
from _common import apply_fn, base_parser, evaluate, init_params, loss_fn, make_batches

from accelerate_tpu import Accelerator, DataLoaderShard, set_seed
from accelerate_tpu.parallel.mesh import ParallelismConfig


def peak_bytes() -> int | None:
    stats = jax.local_devices()[0].memory_stats() or {}
    return stats.get("peak_bytes_in_use")


def main() -> None:
    parser = base_parser()
    parser.add_argument("--fsdp_size", type=int, default=0, help="0 = all devices")
    args = parser.parse_args()
    set_seed(args.seed)

    fsdp = args.fsdp_size or len(jax.devices())
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        parallelism_config=ParallelismConfig(data_parallel_size=1, fsdp_size=fsdp),
    )
    n_train = 4 if args.tiny else 12
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        (apply_fn, init_params(args.seed, hidden=64)),
        optax.adam(args.lr),
        DataLoaderShard(make_batches(n_train, args.batch_size)),
        DataLoaderShard(make_batches(4, args.batch_size, seed=1)),
    )
    shard = jax.tree.leaves(model.params)[0].sharding
    accelerator.print(f"param sharding over mesh axes: {shard.spec}")

    step = accelerator.make_train_step(loss_fn)
    for epoch in range(args.num_epochs):
        for batch in train_dl:
            loss = step(batch)
        acc = evaluate(accelerator, model, eval_dl)
        peak = peak_bytes()
        peak_str = f"{peak / 2**20:.1f} MiB" if peak is not None else "n/a (CPU backend)"
        accelerator.print(
            f"epoch {epoch}: loss={float(loss):.4f} accuracy={acc:.3f} peak_mem={peak_str}"
        )


if __name__ == "__main__":
    main()
