"""Expert-parallel MoE training (beyond the reference, whose only MoE support
is marking DeepSpeed ZeRO-3 leaf modules): Mixtral-style top-2 routing with
static capacity, expert-stacked weights sharded over the tensor axis, the
router's Switch-style aux loss collected from `extra_state` INSIDE the loss.

Run (any box):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/by_feature/moe_expert_parallel.py
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from _common import base_parser

from accelerate_tpu import Accelerator, DataLoaderShard
from accelerate_tpu.models.mixtral import (
    MixtralConfig,
    MixtralForCausalLM,
    mixtral_loss_fn,
    mixtral_sharding_rules,
)
from accelerate_tpu.parallel.mesh import ParallelismConfig


def main():
    args = base_parser(num_epochs=1).parse_args()
    steps = 6 if args.tiny else 10 * args.num_epochs
    cfg = MixtralConfig.tiny(dtype=jnp.float32)
    module = MixtralForCausalLM(cfg)
    params = module.init_params(jax.random.key(args.seed), batch=2, seq=16)

    # dp=2 x ep=4: expert-stacked [E, in, out] weights shard E over 'tensor'
    # (EP rides the TP axis); XLA inserts the token all-to-alls
    acc = Accelerator(
        parallelism_config=ParallelismConfig(data_parallel_size=2, tensor_size=4),
        sharding_rules=mixtral_sharding_rules(),
    )

    rng = np.random.default_rng(args.seed)
    batches = [
        {"input_ids": rng.integers(0, cfg.vocab_size, size=(8, 16)).astype(np.int32)}
        for _ in range(2)
    ] * steps
    # "intermediates": {} asks prepare to thread the mutable collection the
    # router sows its aux loss into; mixtral_loss_fn adds it to the LM loss
    model, opt, dl = acc.prepare(
        (module, {"params": params, "intermediates": {}}), optax.adam(args.lr),
        DataLoaderShard(batches),
    )
    w1 = model.params["layer_0"]["moe"]["w1"]
    acc.print("expert weight sharding:", w1.sharding.spec)

    step = acc.make_train_step(mixtral_loss_fn)
    losses = [float(step(b)) for b in dl]
    acc.print(f"loss {losses[0]:.3f} -> {losses[-1]:.3f} over {len(losses)} steps")
    assert losses[-1] < losses[0], "MoE training did not reduce the loss"


if __name__ == "__main__":
    main()
