"""Feature: gradient accumulation (reference `by_feature/gradient_accumulation.py`).

`Accelerator(gradient_accumulation_steps=N)` makes `make_train_step` fold N
microbatches into one optimizer update (a fused in-jit accumulate; the reference
uses `accumulate()`/no_sync suppression of the DDP all-reduce). The imperative
`accumulate()` context is shown in the commented block — both are supported.
"""

from __future__ import annotations

import sys
from pathlib import Path

import optax

sys.path.insert(0, str(Path(__file__).parent))
from _common import apply_fn, base_parser, evaluate, init_params, loss_fn, make_batches

from accelerate_tpu import Accelerator, DataLoaderShard, set_seed


def main() -> None:
    parser = base_parser()
    parser.add_argument("--gradient_accumulation_steps", type=int, default=4)
    args = parser.parse_args()
    set_seed(args.seed)

    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
    )
    n_train = 4 if args.tiny else 16
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        (apply_fn, init_params(args.seed)),
        optax.adam(args.lr),
        DataLoaderShard(make_batches(n_train, args.batch_size)),
        DataLoaderShard(make_batches(4, args.batch_size, seed=1)),
    )

    step = accelerator.make_train_step(loss_fn)
    for epoch in range(args.num_epochs):
        for batch in train_dl:
            loss = step(batch)  # optimizer advances every N-th call
        # Equivalent imperative form (reference's accumulate() idiom):
        #   with accelerator.accumulate(model):
        #       accelerator.backward(loss_fn, batch)
        #       optimizer.step(); optimizer.zero_grad()
        acc = evaluate(accelerator, model, eval_dl)
        accelerator.print(
            f"epoch {epoch}: loss={float(loss):.4f} accuracy={acc:.3f} "
            f"(updates={optimizer._num_updates})"
        )


if __name__ == "__main__":
    main()
