"""Feature: Local SGD (reference `by_feature/local_sgd.py`).

Each data-parallel replica runs its own optimizer with zero cross-replica
traffic; every `local_sgd_steps` steps the parameter islands are averaged with
one pmean (reference `local_sgd.py` — no_sync + periodic `reduce(mean)`).
"""

from __future__ import annotations

import sys
from pathlib import Path

import optax

sys.path.insert(0, str(Path(__file__).parent))
from _common import apply_fn, base_parser, init_params, loss_fn, make_batches

from accelerate_tpu import Accelerator, set_seed
from accelerate_tpu.local_sgd import LocalSGD, make_local_train_step


def main() -> None:
    parser = base_parser()
    parser.add_argument("--local_sgd_steps", type=int, default=4)
    args = parser.parse_args()
    set_seed(args.seed)

    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    tx = optax.adam(args.lr)
    local_step, sync, replicate, unreplicate = make_local_train_step(
        loss_fn, apply_fn, tx, accelerator.mesh
    )
    island = replicate(init_params(args.seed))

    from accelerate_tpu import DataLoaderShard

    n_train = 4 if args.tiny else 16
    train_dl = accelerator.prepare_data_loader(
        DataLoaderShard(make_batches(n_train, args.batch_size))
    )
    with LocalSGD(sync_fn=sync, local_sgd_steps=args.local_sgd_steps) as lsgd:
        for _ in range(args.num_epochs):
            for batch in train_dl:
                island, loss = local_step(island, batch)
                island = lsgd.step(island)  # pmean every local_sgd_steps
    island = sync(island)  # final average

    params = unreplicate(island)
    import jax.numpy as jnp
    import numpy as np

    correct = total = 0
    for batch in make_batches(4, args.batch_size, seed=1):
        preds = jnp.argmax(apply_fn(params, jnp.asarray(batch["x"])), axis=-1)
        correct += int((np.asarray(preds) == batch["labels"]).sum())
        total += len(batch["labels"])
    accelerator.print(f"loss={float(loss.mean()):.4f} accuracy={correct / total:.3f}")


if __name__ == "__main__":
    main()
