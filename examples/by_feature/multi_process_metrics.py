"""Feature: multi-process metrics (reference `by_feature/multi_process_metrics.py`).

`gather_for_metrics` collects per-shard eval outputs across the mesh and drops
the duplicated tail of the final ragged batch, so metrics match a single-process
run exactly (reference `accelerator.py:2443-2505`).
"""

from __future__ import annotations

import sys
from pathlib import Path

import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, str(Path(__file__).parent))
from _common import apply_fn, base_parser, init_params, loss_fn, make_batches

from accelerate_tpu import Accelerator, DataLoaderShard, set_seed


def main() -> None:
    args = base_parser().parse_args()
    set_seed(args.seed)

    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    n_train = 4 if args.tiny else 12
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        (apply_fn, init_params(args.seed)),
        optax.adam(args.lr),
        DataLoaderShard(make_batches(n_train, args.batch_size)),
        DataLoaderShard(make_batches(5, args.batch_size, seed=1)),
    )
    step = accelerator.make_train_step(loss_fn)
    for _ in range(args.num_epochs):
        for batch in train_dl:
            loss = step(batch)

    # accumulate predictions the metrics-library way: all processes end up with
    # the full, deduplicated eval set
    all_preds, all_labels = [], []
    for batch in eval_dl:
        preds = jnp.argmax(model(batch["x"]), axis=-1)
        g = accelerator.gather_for_metrics({"preds": preds, "labels": batch["labels"]})
        all_preds.append(np.asarray(g["preds"]))
        all_labels.append(np.asarray(g["labels"]))
    preds = np.concatenate(all_preds)
    labels = np.concatenate(all_labels)
    accelerator.print(
        f"eval set {len(labels)} samples, loss={float(loss):.4f} "
        f"accuracy={float((preds == labels).mean()):.3f}"
    )


if __name__ == "__main__":
    main()
