"""Feature: gradient compression hooks (reference `by_feature/ddp_comm_hook.py`).

`make_train_step(comm_hook=...)` compresses the cross-replica gradient
reduction: "bf16"/"fp16" cast the all-reduce payload, "power_sgd" sends a rank-r
factorization with per-replica error feedback (reference DDP comm hooks,
`utils/dataclasses.py:117-213`).
"""

from __future__ import annotations

import sys
from pathlib import Path

import optax

sys.path.insert(0, str(Path(__file__).parent))
from _common import apply_fn, base_parser, evaluate, init_params, loss_fn, make_batches

from accelerate_tpu import Accelerator, DataLoaderShard, DistributedDataParallelKwargs, set_seed


def main() -> None:
    parser = base_parser()
    parser.add_argument(
        "--ddp_comm_hook",
        default="bf16",
        choices=["no", "fp16", "bf16", "power_sgd", "batched_power_sgd"],
    )
    args = parser.parse_args()
    set_seed(args.seed)

    ddp_kwargs = DistributedDataParallelKwargs(
        comm_hook=args.ddp_comm_hook, matrix_approximation_rank=2
    )
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    n_train = 4 if args.tiny else 12
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        (apply_fn, init_params(args.seed)),
        optax.adam(args.lr),
        DataLoaderShard(make_batches(n_train, args.batch_size)),
        DataLoaderShard(make_batches(4, args.batch_size, seed=1)),
    )
    step = accelerator.make_train_step(loss_fn, comm_hook=ddp_kwargs)
    for epoch in range(args.num_epochs):
        for batch in train_dl:
            loss = step(batch)
        acc = evaluate(accelerator, model, eval_dl)
        accelerator.print(
            f"epoch {epoch} [{args.ddp_comm_hook}]: loss={float(loss):.4f} accuracy={acc:.3f}"
        )


if __name__ == "__main__":
    main()
