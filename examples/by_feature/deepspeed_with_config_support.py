"""Feature: DeepSpeed-config-driven training (reference
`by_feature/deepspeed_with_config_support.py`).

A DeepSpeed user's `ds_config.json` drives the run plan unchanged:
`DeepSpeedPlugin(hf_ds_config=...)` resolves `bf16/fp16.enabled` into the
precision policy, `gradient_accumulation_steps` and `gradient_clipping` into
the train step, and `zero_optimization.stage >= 3` onto the `fsdp` mesh axis
(ZeRO-3 = fully sharded parameters; there is no engine — sharding IS the
implementation under SPMD). The same config also activates via env:
`ACCELERATE_TPU_USE_DEEPSPEED=true ACCELERATE_TPU_DEEPSPEED_CONFIG_FILE=...`.
"""

from __future__ import annotations

import json
import sys
import tempfile
from pathlib import Path

import optax

sys.path.insert(0, str(Path(__file__).parent))
from _common import apply_fn, base_parser, evaluate, init_params, loss_fn, make_batches

from accelerate_tpu import Accelerator, DataLoaderShard, DeepSpeedPlugin, set_seed


def main() -> None:
    parser = base_parser()
    parser.add_argument("--ds_config", default=None, help="path to a ds_config.json")
    args = parser.parse_args()
    set_seed(args.seed)

    ds_config = args.ds_config
    if ds_config is None:  # self-contained demo config, the HF-docs shape
        ds_config = str(Path(tempfile.mkdtemp()) / "ds_config.json")
        Path(ds_config).write_text(json.dumps({
            "bf16": {"enabled": True},
            "gradient_accumulation_steps": 2,
            "gradient_clipping": 1.0,
            "zero_optimization": {"stage": 3},
        }))

    accelerator = Accelerator(deepspeed_plugin=DeepSpeedPlugin(hf_ds_config=ds_config))
    # ds_config owns precision (the point of this example): reject a CLI flag
    # that DISAGREES with it rather than silently discarding it, as the
    # reference does for ds_config/Accelerator precision conflicts
    if args.mixed_precision != "no" and args.mixed_precision != accelerator.mixed_precision:
        parser.error(
            f"--mixed_precision={args.mixed_precision} conflicts with the ds_config's "
            f"{accelerator.mixed_precision!r}; set precision in the JSON."
        )
    accelerator.print(
        f"ds_config resolved: precision={accelerator.mixed_precision} "
        f"accum={accelerator.gradient_state.num_steps} "
        f"clip={accelerator.gradient_clipping} "
        f"mesh={dict(accelerator.mesh.shape)}"
    )

    n_train = 4 if args.tiny else 16
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        (apply_fn, init_params(args.seed)),
        optax.adam(args.lr),
        DataLoaderShard(make_batches(n_train, args.batch_size)),
        DataLoaderShard(make_batches(4, args.batch_size, seed=1)),
    )

    # gradient_clipping from the JSON is the step's default max_grad_norm
    step = accelerator.make_train_step(loss_fn)
    for epoch in range(args.num_epochs):
        for batch in train_dl:
            loss = step(batch)
        acc = evaluate(accelerator, model, eval_dl)
        accelerator.print(f"epoch {epoch}: loss={float(loss):.4f} accuracy={acc:.3f}")


if __name__ == "__main__":
    main()
