"""Shared tiny workload for the by_feature examples: a 2-class MLP on separable
synthetic features. Kept deliberately small so every feature script runs in
seconds on CPU; swap in a real model/dataset for production use.

(The reference's by_feature scripts each carry a BERT/MRPC setup inline; here the
setup lives in one module so each script shows only the feature it demonstrates.)
"""

from __future__ import annotations

import argparse

import jax
import numpy as np

FEATURES = 16
CLASSES = 2


def make_batches(n_batches: int, batch_size: int, seed: int = 0):
    """Separable 2-class problem: class 1 has a positive mean shift."""
    rng = np.random.default_rng(seed)
    n = n_batches * batch_size
    labels = rng.integers(0, CLASSES, size=(n,)).astype(np.int32)
    x = rng.normal(size=(n, FEATURES)).astype(np.float32) + labels[:, None] * 1.5
    return [
        {"x": x[i * batch_size : (i + 1) * batch_size],
         "labels": labels[i * batch_size : (i + 1) * batch_size]}
        for i in range(n_batches)
    ]


def init_params(seed: int = 0, hidden: int = 32):
    rng = np.random.default_rng(seed)
    return {
        "w1": (rng.normal(size=(FEATURES, hidden)) * 0.1).astype(np.float32),
        "b1": np.zeros((hidden,), np.float32),
        "w2": (rng.normal(size=(hidden, CLASSES)) * 0.1).astype(np.float32),
        "b2": np.zeros((CLASSES,), np.float32),
    }


def apply_fn(params, x):
    import jax.numpy as jnp

    h = jnp.tanh(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"]


def loss_fn(model, batch):
    import jax.numpy as jnp
    import optax

    logits = model(batch["x"])
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, batch["labels"]
    ).mean()


def evaluate(accelerator, model, eval_batches):
    """Distributed eval with duplicate-tail-safe gathering."""
    import jax.numpy as jnp

    correct = total = 0
    for batch in eval_batches:
        preds = jnp.argmax(model(batch["x"]), axis=-1)
        g = accelerator.gather_for_metrics({"preds": preds, "labels": batch["labels"]})
        correct += int((np.asarray(g["preds"]) == np.asarray(g["labels"])).sum())
        total += len(np.asarray(g["labels"]))
    return correct / max(total, 1)


def base_parser(**extra_defaults) -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", default="no", choices=["no", "bf16", "fp16"])
    parser.add_argument("--lr", type=float, default=extra_defaults.get("lr", 1e-2))
    parser.add_argument("--num_epochs", type=int, default=extra_defaults.get("num_epochs", 2))
    parser.add_argument("--batch_size", type=int, default=extra_defaults.get("batch_size", 32))
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--project_dir", default=None)
    parser.add_argument("--tiny", action="store_true", help="smoke-test sizes")
    return parser
