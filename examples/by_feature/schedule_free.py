"""Feature: schedule-free training (reference `by_feature/schedule_free.py`).

The reference uses `schedulefree.AdamWScheduleFree`; the optax-native equivalent
is `optax.contrib.schedule_free` wrapping any base optimizer — no LR schedule
object, and evaluation should use the schedule-free "eval params".
"""

from __future__ import annotations

import sys
from pathlib import Path

import optax

sys.path.insert(0, str(Path(__file__).parent))
from _common import apply_fn, base_parser, evaluate, init_params, loss_fn, make_batches

from accelerate_tpu import Accelerator, DataLoaderShard, set_seed


def main() -> None:
    args = base_parser(lr=2e-2).parse_args()
    set_seed(args.seed)

    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    tx = optax.contrib.schedule_free_adamw(learning_rate=args.lr, warmup_steps=2)
    n_train = 4 if args.tiny else 12
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        (apply_fn, init_params(args.seed)),
        tx,
        DataLoaderShard(make_batches(n_train, args.batch_size)),
        DataLoaderShard(make_batches(4, args.batch_size, seed=1)),
    )
    step = accelerator.make_train_step(loss_fn)
    for epoch in range(args.num_epochs):
        for batch in train_dl:
            loss = step(batch)
        # schedule-free keeps train params (z) and eval params (x) distinct:
        # evaluate at the interpolated eval point
        import optax.contrib as contrib

        eval_params = contrib.schedule_free_eval_params(
            optimizer.opt_state, model.params
        )
        train_params = model.params
        model.load_state_dict(eval_params)
        acc = evaluate(accelerator, model, eval_dl)
        model.load_state_dict(train_params)
        accelerator.print(f"epoch {epoch}: loss={float(loss):.4f} accuracy={acc:.3f}")


if __name__ == "__main__":
    main()
