"""Feature: OOM-adaptive batch size (reference `by_feature/memory.py`).

`find_executable_batch_size` calls the training function with a starting batch
size and, on device-memory exhaustion (XLA RESOURCE_EXHAUSTED), halves it and
retries — each retry recompiles at the new static shape (reference
`utils/memory.py:111-168`).
"""

from __future__ import annotations

import sys
from pathlib import Path

import optax

sys.path.insert(0, str(Path(__file__).parent))
from _common import apply_fn, base_parser, evaluate, init_params, loss_fn, make_batches

from accelerate_tpu import Accelerator, DataLoaderShard, find_executable_batch_size, set_seed


def main() -> None:
    parser = base_parser()
    parser.add_argument("--starting_batch_size", type=int, default=256)
    args = parser.parse_args()
    set_seed(args.seed)
    accelerator = Accelerator(mixed_precision=args.mixed_precision)

    @find_executable_batch_size(starting_batch_size=args.starting_batch_size)
    def inner_training_loop(batch_size):
        accelerator.print(f"trying batch_size={batch_size}")
        accelerator.free_memory()  # reset prepared objects between attempts
        n_train = 4 if args.tiny else 12
        model, optimizer, train_dl, eval_dl = accelerator.prepare(
            (apply_fn, init_params(args.seed)),
            optax.adam(args.lr),
            DataLoaderShard(make_batches(n_train, batch_size)),
            DataLoaderShard(make_batches(4, batch_size, seed=1)),
        )
        step = accelerator.make_train_step(loss_fn)
        for _ in range(args.num_epochs):
            for batch in train_dl:
                loss = step(batch)
        return evaluate(accelerator, model, eval_dl), float(loss)

    acc, loss = inner_training_loop()
    accelerator.print(
        f"converged at batch_size={inner_training_loop.batch_size}: "
        f"loss={loss:.4f} accuracy={acc:.3f}"
    )


if __name__ == "__main__":
    main()
