"""Feature: GPT pretraining with tensor parallelism (reference
`by_feature/megatron_lm_gpt_pretraining.py`).

The reference rebuilds the model inside Megatron-LM for TP/PP; here TP is a
sharding rule set: `gpt2_sharding_rules()` annotates attention/MLP weights
Megatron-style (column-split QKV/up, row-split proj/down) over the `tensor` mesh
axis and XLA inserts the all-reduces (reference `utils/megatron_lm.py`,
`MegatronLMPlugin` tp_degree `utils/dataclasses.py:1910`).
"""

from __future__ import annotations

import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, str(Path(__file__).parent))
from _common import base_parser

from accelerate_tpu import Accelerator, DataLoaderShard, MegatronLMPlugin, set_seed
from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHead, gpt2_sharding_rules, lm_loss_fn


def lm_batches(n_batches, batch, seq, vocab, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {"input_ids": rng.integers(0, vocab, size=(batch, seq)).astype(np.int32)}
        for _ in range(n_batches)
    ]


def main() -> None:
    parser = base_parser(num_epochs=1)
    parser.add_argument("--tp_degree", type=int, default=2)
    parser.add_argument("--seq_len", type=int, default=64)
    args = parser.parse_args()
    set_seed(args.seed)

    # the reference's MegatronLMPlugin surface maps onto mesh axis sizes
    plugin = MegatronLMPlugin(tp_degree=args.tp_degree)
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        parallelism_config=plugin.to_parallelism_config(),
        sharding_rules=gpt2_sharding_rules(),
    )
    cfg = GPT2Config.tiny() if args.tiny else GPT2Config(
        vocab_size=1024, n_layer=2, n_head=4, n_embd=128, n_positions=args.seq_len
    )
    module = GPT2LMHead(cfg)
    seq = min(args.seq_len, cfg.n_positions)
    params = module.init_params(jax.random.key(args.seed), batch=args.batch_size, seq=seq)

    n_train = 4 if args.tiny else 8
    model, optimizer, train_dl = accelerator.prepare(
        (module, params),
        optax.adamw(args.lr),
        DataLoaderShard(lm_batches(n_train, args.batch_size, seq, cfg.vocab_size)),
    )
    # proof that TP engaged: model weights carry `tensor`-axis shardings
    specs = {s.spec for s in jax.tree.leaves(jax.tree.map(lambda p: p.sharding, model.params))}
    accelerator.print(f"mesh={dict(accelerator.mesh.shape)} param specs={specs}")

    step = accelerator.make_train_step(lm_loss_fn)
    for batch in train_dl:
        loss = step(batch)
    ppl = float(jnp.exp(jnp.minimum(loss, 20.0)))
    accelerator.print(f"loss={float(loss):.4f} perplexity={ppl:.1f} accuracy=n/a (LM)")


if __name__ == "__main__":
    main()
