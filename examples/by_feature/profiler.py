"""Feature: profiling (reference `by_feature/profiler.py`).

`accelerator.profile()` wraps `jax.profiler` tracing — one trace directory per
host, viewable in TensorBoard/Perfetto (reference wraps `torch.profiler.profile`
and exports Chrome traces, `accelerator.py:3449-3506`).
"""

from __future__ import annotations

import sys
import tempfile
from pathlib import Path

import optax

sys.path.insert(0, str(Path(__file__).parent))
from _common import apply_fn, base_parser, init_params, loss_fn, make_batches

from accelerate_tpu import Accelerator, DataLoaderShard, set_seed


def main() -> None:
    args = base_parser(num_epochs=1).parse_args()
    set_seed(args.seed)
    trace_dir = args.project_dir or tempfile.mkdtemp(prefix="profile_traces_")

    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    n_train = 4 if args.tiny else 8
    model, optimizer, train_dl = accelerator.prepare(
        (apply_fn, init_params(args.seed)),
        optax.adam(args.lr),
        DataLoaderShard(make_batches(n_train, args.batch_size)),
    )
    step = accelerator.make_train_step(loss_fn)

    # warm up outside the trace so compile time doesn't dominate the profile
    for batch in train_dl:
        loss = step(batch)

    with accelerator.profile(log_dir=trace_dir):
        for batch in train_dl:
            loss = step(batch)

    traces = list(Path(trace_dir).rglob("*"))
    accelerator.print(
        f"loss={float(loss):.4f}; wrote {sum(1 for t in traces if t.is_file())} "
        f"trace files under {trace_dir} (accuracy of profiling: view in TensorBoard)"
    )


if __name__ == "__main__":
    main()
