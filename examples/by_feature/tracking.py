"""Feature: experiment tracking (reference `by_feature/tracking.py`).

`init_trackers` starts every configured tracker (TensorBoard/WandB/MLflow/...;
"jsonl" is the dependency-free built-in), `log` records rank-0 metrics, and
`end_training` flushes (reference `tracking.py` + `accelerator.py:2645-2772`).
"""

from __future__ import annotations

import sys
import tempfile
from pathlib import Path

import numpy as np
import optax

sys.path.insert(0, str(Path(__file__).parent))
from _common import apply_fn, base_parser, evaluate, init_params, loss_fn, make_batches

from accelerate_tpu import Accelerator, DataLoaderShard, set_seed


def main() -> None:
    parser = base_parser()
    parser.add_argument("--log_with", default="jsonl", help="jsonl|tensorboard|wandb|...")
    args = parser.parse_args()
    set_seed(args.seed)
    project_dir = args.project_dir or tempfile.mkdtemp(prefix="tracking_example_")

    accelerator = Accelerator(
        mixed_precision=args.mixed_precision, log_with=args.log_with, project_dir=project_dir
    )
    accelerator.init_trackers("tracking_example", config=vars(args))

    n_train = 4 if args.tiny else 12
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        (apply_fn, init_params(args.seed)),
        optax.adam(args.lr),
        DataLoaderShard(make_batches(n_train, args.batch_size)),
        DataLoaderShard(make_batches(4, args.batch_size, seed=1)),
    )
    step = accelerator.make_train_step(loss_fn)
    global_step = 0
    for epoch in range(args.num_epochs):
        for batch in train_dl:
            loss = step(batch)
            accelerator.log({"train_loss": float(loss)}, step=global_step)
            global_step += 1
        acc = evaluate(accelerator, model, eval_dl)
        accelerator.log({"accuracy": acc, "epoch": epoch}, step=global_step)
        accelerator.print(f"epoch {epoch}: loss={float(loss):.4f} accuracy={acc:.3f}")
    # media logging: images + a summary table on every tracker that supports it
    if accelerator.is_main_process:
        heat = np.abs(np.asarray(model.params["w1"]))  # (features, hidden) heatmap
        for tracker in accelerator.trackers:
            try:
                tracker.log_images({"viz/weight_magnitude": heat / max(heat.max(), 1e-8)},
                                   step=global_step)
                tracker.log_table("final_metrics", columns=["metric", "value"],
                                  data=[["accuracy", acc], ["final_loss", float(loss)]],
                                  step=global_step)
            except NotImplementedError:
                pass
    accelerator.end_training()
    accelerator.print(f"metrics logged under {project_dir}")


if __name__ == "__main__":
    main()
