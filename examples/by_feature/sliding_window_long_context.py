"""Feature: long-context training with sliding-window (band) attention.

The reference has no long-context lever at all (SURVEY.md §5); this is the
TPU-native story: `LlamaConfig(sliding_window=W)` routes causal attention onto
the Pallas band grid, where only blocks inside the window exist as grid cells —
attention costs O(seq * W) instead of O(seq^2), so doubling the sequence at
fixed W doubles (not quadruples) attention time. GQA composes: grouped K/V are
read in place, never repeated in HBM. For sequences beyond one chip's memory,
add the `sequence` mesh axis + ring attention (`docs/long_context.md`).
"""

from __future__ import annotations

import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, str(Path(__file__).parent))
from _common import base_parser

from accelerate_tpu import Accelerator, DataLoaderShard, set_seed
from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def main():
    args = base_parser().parse_args()
    set_seed(args.seed)

    seq, window = 128, 32  # production: e.g. seq 32768, window 4096 (Mistral)
    cfg = LlamaConfig.tiny(
        dtype=jnp.float32,
        max_position_embeddings=seq,
        sliding_window=window,
        # 'flash' engages the Pallas band kernel on TPU (interpreted on CPU);
        # 'xla' computes the same masked attention without the kernel
        attention_impl="flash" if jax.devices()[0].platform in ("tpu", "axon") else "xla",
    )
    accelerator = Accelerator(mixed_precision=args.mixed_precision)

    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(args.seed)
    n_batches = 8 if args.tiny else 16
    # tokens drawn from a 32-token subset of the 256-token vocab: the LM
    # learns the restricted support, so the loss has room to fall from
    # ~ln(256) toward ~ln(32) (uniform over the FULL vocab would start at
    # the entropy floor with nothing to learn)
    ids = rng.integers(0, 32, (n_batches, 2, seq)).astype(np.int32)
    params = module.init(jax.random.key(0), ids[0])["params"]

    model, optimizer, loader = accelerator.prepare(
        (module, params), optax.adamw(args.lr),
        DataLoaderShard([{"input_ids": b} for b in ids]),
    )

    def loss_fn(m, batch):
        logits = m(batch["input_ids"])
        labels = jnp.roll(batch["input_ids"], -1, axis=1)
        logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        return -jnp.take_along_axis(logp, labels[:, :-1, None], axis=-1).mean()

    step = accelerator.make_train_step(loss_fn)
    losses = [float(step(batch)) for batch in loader]
    accelerator.print(
        f"sliding-window W={window} over seq={seq}: "
        f"loss {losses[0]:.3f} -> {losses[-1]:.3f}"
    )
    assert min(losses[1:]) < losses[0], losses


if __name__ == "__main__":
    main()
