"""Feature: pipeline-parallel TRAINING over the `stage` mesh axis (the
reference's Megatron-LM pp>1 training role, `utils/megatron_lm.py:1035-1057`
train_step — here one jitted SPMD program runs the GPipe microbatch schedule,
backward, gradient accumulation and the adamw tick; stage-sharded params and
optimizer state, replicated embedding/head).

Trains a tiny GPT-2 split into 4 stages on a dp2 x pp4 mesh (the 8-device CPU
rehearsal topology), with checkpoint save/restore mid-run. The same script on
a TPU pod shards stages across real chips — configuration, not code.
"""

from __future__ import annotations

import sys
import tempfile
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, str(Path(__file__).parent))
from _common import base_parser

from accelerate_tpu import Accelerator, set_seed
from accelerate_tpu.models.gpt2 import (
    GPT2Config,
    GPT2LMHead,
    gpt2_pipeline_parts,
    pipeline_lm_loss,
)
from accelerate_tpu.parallel.mesh import ParallelismConfig

STAGES = 4
MICROBATCHES = 4


def main() -> None:
    parser = base_parser(lr=1e-3, num_epochs=2, batch_size=8)
    args = parser.parse_args()
    set_seed(args.seed)

    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        parallelism_config=ParallelismConfig(data_parallel_size=-1, stage_size=STAGES),
    )
    accelerator.print(f"mesh: {dict(accelerator.mesh.shape)}")

    cfg = GPT2Config.tiny(n_layer=STAGES, dtype=jnp.float32)
    params = GPT2LMHead(cfg).init_params(jax.random.key(args.seed))
    stage_fn, per_stage, pre, post = gpt2_pipeline_parts(cfg, params, STAGES)

    model = accelerator.prepare_pipeline(
        stage_fn, per_stage, pre=pre, post=post, num_microbatches=MICROBATCHES
    )
    optimizer = accelerator.prepare_optimizer(optax.adamw(args.lr), model=model)
    step = accelerator.make_pipeline_train_step(
        stage_fn, pipeline_lm_loss, num_microbatches=MICROBATCHES,
        pre_fn=pre[0], post_fn=post[0], max_grad_norm=1.0,
    )

    rng = np.random.default_rng(args.seed)
    batches = [
        jnp.asarray(rng.integers(0, cfg.vocab_size, (args.batch_size, 32)), jnp.int32)
        for _ in range(4 if args.tiny else 8)
    ]
    with tempfile.TemporaryDirectory() as td:
        for epoch in range(args.num_epochs):
            for ids in batches:
                loss = step((ids, ids))
            accelerator.print(f"epoch {epoch}: loss={float(loss):.4f}")
            ckpt = accelerator.save_state(td + f"/epoch_{epoch}")
        # stage-sharded weights round-trip through orbax like any model
        accelerator.load_state(ckpt)
        loss = step((batches[0], batches[0]))
    trunk = jax.tree.leaves(model.params["stages"])[0]
    accelerator.print(
        f"final loss={float(loss):.4f} "
        f"trunk stage-sharded={not trunk.sharding.is_fully_replicated}"
    )


if __name__ == "__main__":
    main()
