"""Feature: k-fold cross-validation (reference `by_feature/cross_validation.py`).

Each fold trains on its own split; per-fold test logits are gathered with
`gather_for_metrics` and ensembled (averaged) for the final score, exactly the
reference's flow with datasets' k-fold splits.
"""

from __future__ import annotations

import sys
from pathlib import Path

import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, str(Path(__file__).parent))
from _common import apply_fn, base_parser, init_params, loss_fn, make_batches

from accelerate_tpu import Accelerator, DataLoaderShard, set_seed
from accelerate_tpu.state import AcceleratorState, GradientState


def main() -> None:
    parser = base_parser()
    parser.add_argument("--num_folds", type=int, default=3)
    args = parser.parse_args()
    set_seed(args.seed)

    n_train = 4 if args.tiny else 12
    folds = [make_batches(n_train, args.batch_size, seed=f) for f in range(args.num_folds)]
    test_batches = make_batches(4, args.batch_size, seed=99)

    fold_logits = []
    labels = None
    for fold_idx in range(args.num_folds):
        AcceleratorState._reset_state()
        GradientState._reset_state()
        accelerator = Accelerator(mixed_precision=args.mixed_precision)
        train = [b for i, f in enumerate(folds) if i != fold_idx for b in f]
        model, optimizer, train_dl, test_dl = accelerator.prepare(
            (apply_fn, init_params(args.seed + fold_idx)),
            optax.adam(args.lr),
            DataLoaderShard(train),
            DataLoaderShard(test_batches),
        )
        step = accelerator.make_train_step(loss_fn)
        for _ in range(args.num_epochs):
            for batch in train_dl:
                loss = step(batch)

        logits_all, labels_all = [], []
        for batch in test_dl:
            g = accelerator.gather_for_metrics(
                {"logits": model(batch["x"]), "labels": batch["labels"]}
            )
            logits_all.append(np.asarray(g["logits"]))
            labels_all.append(np.asarray(g["labels"]))
        fold_logits.append(np.concatenate(logits_all))
        labels = np.concatenate(labels_all)
        accelerator.print(f"fold {fold_idx}: loss={float(loss):.4f}")

    # ensemble: average fold logits (the reference's end-of-k-fold metric)
    preds = np.mean(fold_logits, axis=0).argmax(-1)
    accelerator.print(f"ensembled accuracy={float((preds == labels).mean()):.3f}")


if __name__ == "__main__":
    main()
