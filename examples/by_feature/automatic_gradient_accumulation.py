"""Feature: automatic gradient accumulation (reference
`by_feature/automatic_gradient_accumulation.py`).

Combines `find_executable_batch_size` with accumulation: when the per-device
batch must shrink to fit memory, the accumulation step count grows to keep the
OBSERVED (effective) batch size constant.
"""

from __future__ import annotations

import sys
from pathlib import Path

import optax

sys.path.insert(0, str(Path(__file__).parent))
from _common import apply_fn, base_parser, evaluate, init_params, loss_fn, make_batches

from accelerate_tpu import Accelerator, DataLoaderShard, find_executable_batch_size, set_seed

OBSERVED_BATCH_SIZE = 256  # the effective batch the optimizer should see


def main() -> None:
    args = base_parser().parse_args()
    set_seed(args.seed)
    accelerator = Accelerator(mixed_precision=args.mixed_precision)

    @find_executable_batch_size(starting_batch_size=OBSERVED_BATCH_SIZE)
    def inner_training_loop(batch_size):
        accum = OBSERVED_BATCH_SIZE // batch_size
        accelerator.print(f"batch_size={batch_size} x accumulation={accum}")
        accelerator.free_memory()
        accelerator.gradient_accumulation_steps = accum
        n_train = 2 * accum if args.tiny else 8 * accum
        model, optimizer, train_dl, eval_dl = accelerator.prepare(
            (apply_fn, init_params(args.seed)),
            optax.adam(args.lr),
            DataLoaderShard(make_batches(n_train, batch_size)),
            DataLoaderShard(make_batches(4, batch_size, seed=1)),
        )
        step = accelerator.make_train_step(loss_fn)
        for _ in range(args.num_epochs):
            for batch in train_dl:
                loss = step(batch)
        return evaluate(accelerator, model, eval_dl), float(loss)

    acc, loss = inner_training_loop()
    accelerator.print(f"loss={loss:.4f} accuracy={acc:.3f}")


if __name__ == "__main__":
    main()
