"""NLP example: BERT sequence classification with the full Accelerator flow.

Mirrors reference `examples/nlp_example.py` (BERT-base on GLUE/MRPC): prepare,
gradient accumulation, clipping, LR schedule, eval with gather_for_metrics,
tracking, checkpointing. With `datasets`+`transformers` available it trains on
real MRPC; otherwise it falls back to a synthetic separable text-pair task so the
example runs on any box (the reference tests do the same with a bundled sample).

Run:
    python examples/nlp_example.py                       # single host, all chips
    accelerate-tpu launch examples/nlp_example.py        # via the CLI
    python examples/nlp_example.py --mixed_precision bf16 --lr 2e-5
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

from accelerate_tpu import Accelerator, DataLoaderShard, OptaxSchedule, set_seed
from accelerate_tpu.models.bert import (
    BertConfig,
    BertForSequenceClassification,
    classification_loss_fn,
)

MAX_LEN = 64


def synthetic_mrpc(n: int, vocab: int, seed: int = 0):
    """Separable paraphrase-ish task: label 1 rows share a token prefix."""
    rng = np.random.default_rng(seed)
    ids = rng.integers(10, vocab, size=(n, MAX_LEN)).astype(np.int32)
    labels = rng.integers(0, 2, size=(n,)).astype(np.int32)
    ids[labels == 1, :8] = np.arange(2, 10)  # the signal
    mask = np.ones((n, MAX_LEN), dtype=np.int32)
    return ids, mask, labels


def get_dataloaders(batch_size: int, vocab: int, seed: int):
    ids, mask, labels = synthetic_mrpc(10 * batch_size, vocab, seed)
    n_train = 8 * batch_size

    def batches(lo, hi):
        out = []
        for i in range(lo, hi - batch_size + 1, batch_size):
            out.append(
                {
                    "input_ids": ids[i : i + batch_size],
                    "attention_mask": mask[i : i + batch_size],
                    "labels": labels[i : i + batch_size],
                }
            )
        return out

    return batches(0, n_train), batches(n_train, len(ids))


def training_function(args: argparse.Namespace) -> float:
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
        log_with="jsonl" if args.with_tracking else None,
        project_dir=args.project_dir,
    )
    if args.with_tracking:
        accelerator.init_trackers("nlp_example", config=vars(args))
    set_seed(args.seed)

    config = BertConfig.tiny() if args.tiny else BertConfig.base()
    module = BertForSequenceClassification(config)
    params = module.init_params(jax.random.key(args.seed))

    train_batches, eval_batches = get_dataloaders(args.batch_size, config.vocab_size, args.seed)
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, args.lr, warmup_steps=4, decay_steps=len(train_batches) * args.num_epochs
    )
    model, optimizer, train_dl, eval_dl, scheduler = accelerator.prepare(
        (module, params),
        optax.adamw(schedule),
        DataLoaderShard(train_batches),
        DataLoaderShard(eval_batches),
        OptaxSchedule(schedule),
    )

    step = accelerator.make_train_step(classification_loss_fn, max_grad_norm=args.max_grad_norm)
    for epoch in range(args.num_epochs):
        for batch in train_dl:
            loss = step(batch)
            scheduler.step()
        # evaluation with duplicate-tail-safe gathering
        correct = total = 0
        for batch in eval_dl:
            logits = model(batch["input_ids"], batch["attention_mask"])
            preds = jnp.argmax(logits, axis=-1)
            gathered = accelerator.gather_for_metrics({"preds": preds, "labels": batch["labels"]})
            correct += int((np.asarray(gathered["preds"]) == np.asarray(gathered["labels"])).sum())
            total += len(np.asarray(gathered["labels"]))
        acc = correct / max(total, 1)
        accelerator.print(f"epoch {epoch}: loss={float(loss):.4f} accuracy={acc:.3f}")
        if args.with_tracking:
            accelerator.log({"loss": float(loss), "accuracy": acc}, step=epoch)
    if args.checkpointing:
        accelerator.save_state()
    accelerator.end_training()
    return acc


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", default="no", choices=["no", "bf16", "fp16"])
    parser.add_argument("--lr", type=float, default=5e-4)
    parser.add_argument("--num_epochs", type=int, default=3)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--gradient_accumulation_steps", type=int, default=1)
    parser.add_argument("--max_grad_norm", type=float, default=1.0)
    parser.add_argument("--with_tracking", action="store_true")
    parser.add_argument("--checkpointing", action="store_true")
    parser.add_argument("--project_dir", default=None)
    parser.add_argument("--tiny", action="store_true", help="tiny config for smoke tests")
    args = parser.parse_args()
    training_function(args)


if __name__ == "__main__":
    main()
