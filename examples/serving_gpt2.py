"""GPT-2 continuous-batching serving (`docs/serving.md`): ragged requests with
per-request sampling params stream through one jitted decode step over a fixed
slot pool, with metrics logged through the standard tracker interface.

Runs on the host CPU in seconds:  JAX_PLATFORMS=cpu python examples/serving_gpt2.py
Swap in `GPT2Config.small()` + real weights and `kv_cache_dtype=jnp.int8`
(half the KV memory -> more slots per chip) for an actual deployment.
"""

import jax
import jax.numpy as jnp
import numpy as np

from accelerate_tpu import Request, SamplingParams, ServingEngine
from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from accelerate_tpu.tracking import JSONLTracker


def main():
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    module = GPT2LMHead(cfg)
    params = module.init_params(jax.random.key(0))

    tracker = JSONLTracker("serving_demo", logging_dir="/tmp")
    engine = ServingEngine(
        module, params,
        max_concurrency=4,           # decode batch width == resident requests
        prompt_buckets=(16, 32),     # admission pad targets (one compile each)
        eos_token_id=0,              # recycle a slot early on this token
        tracker=tracker, metrics_log_every=8,
    )

    # ragged prompts, mixed settings: greedy and seeded-sampled requests share
    # the same compiled step (params ride as [max_concurrency] data arrays)
    rng = np.random.default_rng(0)
    requests = [
        Request(prompt=rng.integers(1, cfg.vocab_size, (n,)).astype(np.int32).tolist(),
                params=p)
        for n, p in [
            (5, SamplingParams(max_new_tokens=12)),                      # greedy
            (11, SamplingParams(temperature=0.8, top_k=20, seed=7,
                                max_new_tokens=20)),
            (23, SamplingParams(temperature=1.0, seed=123, max_new_tokens=8)),
            (8, SamplingParams(max_new_tokens=30)),
            (17, SamplingParams(temperature=0.6, top_k=10, seed=1,
                                max_new_tokens=16)),
            (3, SamplingParams(max_new_tokens=6)),
        ]
    ]

    for out in engine.run(requests):
        print(f"req {out.request_id}: prompt_len={out.prompt_len:2d} "
              f"-> {len(out.tokens):2d} tokens ({out.finish_reason}): "
              f"{out.tokens[:8]}{'...' if len(out.tokens) > 8 else ''}")

    m = engine.metrics
    print(f"\n{m.requests_finished.value} requests, "
          f"{m.tokens_generated.value} tokens in {m.steps.value} steps; "
          f"mean slot occupancy {m.slot_occupancy.mean:.0%}; "
          f"metrics stream: {tracker.path}")


if __name__ == "__main__":
    main()
