"""Complete CV example: ResNet classification with every feature combined
(reference `examples/complete_cv_example.py`) — tracking, checkpoint/resume,
LR schedule, gradient accumulation, gathered metrics.

Run:
    python examples/complete_cv_example.py --tiny --with_tracking
"""

from __future__ import annotations

import argparse
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax

from accelerate_tpu import Accelerator, DataLoaderShard, OptaxSchedule, set_seed
from accelerate_tpu.accelerator import ProjectConfiguration
from accelerate_tpu.models.resnet import ResNet, ResNetConfig, image_classification_loss_fn


def synthetic_images(n: int, size: int, num_classes: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size=(n,)).astype(np.int32)
    imgs = rng.normal(size=(n, size, size, 3)).astype(np.float32)
    imgs += labels[:, None, None, None].astype(np.float32) * 0.5
    return imgs, labels


def training_function(args: argparse.Namespace) -> float:
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
        log_with="jsonl" if args.with_tracking else None,
        project_config=ProjectConfiguration(
            project_dir=args.project_dir or "complete_cv_out",
            automatic_checkpoint_naming=True,
            total_limit=2,
        ),
    )
    if args.with_tracking:
        accelerator.init_trackers("complete_cv_example", config=vars(args))
    set_seed(args.seed)

    config = ResNetConfig.tiny() if args.tiny else ResNetConfig.resnet50()
    size = 32 if args.tiny else 224
    module = ResNet(config)
    params = module.init_params(jax.random.key(args.seed), image_size=size)

    imgs, labels = synthetic_images(10 * args.batch_size, size, config.num_classes, args.seed)
    n_train = 8 * args.batch_size

    def batches(lo, hi):
        return [
            {"image": imgs[i : i + args.batch_size],
             "label": labels[i : i + args.batch_size]}
            for i in range(lo, hi - args.batch_size + 1, args.batch_size)
        ]

    schedule = optax.cosine_decay_schedule(args.lr, decay_steps=8 * args.num_epochs)
    model, optimizer, train_dl, eval_dl, scheduler = accelerator.prepare(
        (module, params),
        optax.sgd(schedule, momentum=0.9),
        DataLoaderShard(batches(0, n_train)),
        DataLoaderShard(batches(n_train, len(imgs))),
        OptaxSchedule(schedule),
    )
    if args.resume_from_checkpoint:
        accelerator.load_state(args.resume_from_checkpoint)

    step = accelerator.make_train_step(image_classification_loss_fn)
    for epoch in range(args.num_epochs):
        for batch in train_dl:
            loss = step(batch)
            scheduler.step()
        correct = total = 0
        for batch in eval_dl:
            logits = model(batch["image"])
            g = accelerator.gather_for_metrics(
                {"preds": jnp.argmax(logits, axis=-1), "labels": batch["label"]}
            )
            correct += int((np.asarray(g["preds"]) == np.asarray(g["labels"])).sum())
            total += len(np.asarray(g["labels"]))
        acc = correct / max(total, 1)
        accelerator.print(f"epoch {epoch}: loss={float(loss):.4f} accuracy={acc:.3f}")
        if args.with_tracking:
            accelerator.log({"loss": float(loss), "accuracy": acc}, step=epoch)
        if args.checkpointing:
            accelerator.save_state(
                os.path.join(accelerator.project_dir, "checkpoints", f"epoch_{epoch}")
            )
    accelerator.end_training()
    return acc


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", default="no", choices=["no", "bf16", "fp16"])
    parser.add_argument("--lr", type=float, default=3e-2)
    parser.add_argument("--num_epochs", type=int, default=2)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--gradient_accumulation_steps", type=int, default=1)
    parser.add_argument("--with_tracking", action="store_true")
    parser.add_argument("--checkpointing", action="store_true")
    parser.add_argument("--resume_from_checkpoint", default=None)
    parser.add_argument("--project_dir", default=None)
    parser.add_argument("--tiny", action="store_true")
    training_function(parser.parse_args())


if __name__ == "__main__":
    main()
