"""Complete NLP example: every feature in one script (reference
`examples/complete_nlp_example.py`) — tracking, checkpointing with epoch/step
granularity, mid-epoch resume, gradient accumulation, clipping, LR schedule,
and duplicate-tail-safe metric gathering. `examples/by_feature/*` each isolate
one of these; this script is the canonical combination.

Run:
    python examples/complete_nlp_example.py --with_tracking --checkpointing_steps epoch
    python examples/complete_nlp_example.py --resume_from_checkpoint <dir>
"""

from __future__ import annotations

import argparse
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax

from accelerate_tpu import Accelerator, DataLoaderShard, OptaxSchedule, set_seed, skip_first_batches
from accelerate_tpu.accelerator import ProjectConfiguration
from accelerate_tpu.models.bert import (
    BertConfig,
    BertForSequenceClassification,
    classification_loss_fn,
)

MAX_LEN = 64


def get_dataloaders(batch_size: int, vocab: int, seed: int):
    rng = np.random.default_rng(seed)
    n = 10 * batch_size
    ids = rng.integers(10, vocab, size=(n, MAX_LEN)).astype(np.int32)
    labels = rng.integers(0, 2, size=(n,)).astype(np.int32)
    ids[labels == 1, :8] = np.arange(2, 10)
    mask = np.ones((n, MAX_LEN), dtype=np.int32)
    n_train = 8 * batch_size

    def batches(lo, hi):
        return [
            {"input_ids": ids[i : i + batch_size], "attention_mask": mask[i : i + batch_size],
             "labels": labels[i : i + batch_size]}
            for i in range(lo, hi - batch_size + 1, batch_size)
        ]

    return batches(0, n_train), batches(n_train, n)


def training_function(args: argparse.Namespace) -> float:
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
        log_with="jsonl" if args.with_tracking else None,
        project_config=ProjectConfiguration(
            project_dir=args.project_dir or "complete_nlp_out",
            automatic_checkpoint_naming=True,
            total_limit=2,
        ),
    )
    if args.with_tracking:
        accelerator.init_trackers("complete_nlp_example", config=vars(args))
    set_seed(args.seed)

    config = BertConfig.tiny() if args.tiny else BertConfig.base()
    module = BertForSequenceClassification(config)
    params = module.init_params(jax.random.key(args.seed))

    train_batches, eval_batches = get_dataloaders(args.batch_size, config.vocab_size, args.seed)
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, args.lr, warmup_steps=4, decay_steps=len(train_batches) * args.num_epochs
    )
    model, optimizer, train_dl, eval_dl, scheduler = accelerator.prepare(
        (module, params),
        optax.adamw(schedule),
        DataLoaderShard(train_batches),
        DataLoaderShard(eval_batches),
        OptaxSchedule(schedule),
    )
    accelerator.register_for_checkpointing(scheduler)

    overall_step = 0
    starting_epoch = 0
    resume_step = None
    if args.resume_from_checkpoint:
        accelerator.load_state(args.resume_from_checkpoint)
        # checkpoint name encodes the position: epoch_<e> or step_<s>
        tag = os.path.basename(args.resume_from_checkpoint.rstrip("/"))
        if tag.startswith("epoch_"):
            starting_epoch = int(tag.split("_")[1]) + 1
        elif tag.startswith("step_"):
            overall_step = int(tag.split("_")[1])
            starting_epoch = overall_step // len(train_dl)
            resume_step = overall_step % len(train_dl)

    step = accelerator.make_train_step(classification_loss_fn, max_grad_norm=args.max_grad_norm)
    for epoch in range(starting_epoch, args.num_epochs):
        dl = train_dl
        if resume_step is not None and epoch == starting_epoch:
            dl = skip_first_batches(train_dl, resume_step)
            resume_step = None
        for batch in dl:
            loss = step(batch)
            scheduler.step()
            overall_step += 1
            if args.checkpointing_steps == "step" and overall_step % args.save_every == 0:
                accelerator.save_state(
                    os.path.join(accelerator.project_dir, "checkpoints", f"step_{overall_step}")
                )
        correct = total = 0
        for batch in eval_dl:
            logits = model(batch["input_ids"], batch["attention_mask"])
            g = accelerator.gather_for_metrics(
                {"preds": jnp.argmax(logits, axis=-1), "labels": batch["labels"]}
            )
            correct += int((np.asarray(g["preds"]) == np.asarray(g["labels"])).sum())
            total += len(np.asarray(g["labels"]))
        acc = correct / max(total, 1)
        accelerator.print(f"epoch {epoch}: loss={float(loss):.4f} accuracy={acc:.3f}")
        if args.with_tracking:
            accelerator.log({"loss": float(loss), "accuracy": acc, "epoch": epoch},
                            step=overall_step)
        if args.checkpointing_steps == "epoch":
            accelerator.save_state(
                os.path.join(accelerator.project_dir, "checkpoints", f"epoch_{epoch}")
            )
    accelerator.end_training()
    return acc


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", default="no", choices=["no", "bf16", "fp16"])
    parser.add_argument("--lr", type=float, default=5e-4)
    parser.add_argument("--num_epochs", type=int, default=3)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--gradient_accumulation_steps", type=int, default=1)
    parser.add_argument("--max_grad_norm", type=float, default=1.0)
    parser.add_argument("--with_tracking", action="store_true")
    parser.add_argument("--checkpointing_steps", default=None, choices=[None, "epoch", "step"])
    parser.add_argument("--save_every", type=int, default=10, help="steps between step-checkpoints")
    parser.add_argument("--resume_from_checkpoint", default=None)
    parser.add_argument("--project_dir", default=None)
    parser.add_argument("--tiny", action="store_true")
    training_function(parser.parse_args())


if __name__ == "__main__":
    main()
