"""Smoke-run a config template (reference `config_yaml_templates/run_me.py`
role): load the YAML, build the Accelerator it describes, print the resolved
topology, and take one tiny training step."""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

from accelerate_tpu import Accelerator
from accelerate_tpu.commands.config import LaunchConfig
from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHead, lm_loss_fn


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config_file", required=True)
    args = ap.parse_args()

    cfg = LaunchConfig.from_yaml(args.config_file)
    print(f"compute_environment={cfg.compute_environment} "
          f"mixed_precision={cfg.mixed_precision} "
          f"mesh: dp={cfg.data_parallel_size} fsdp={cfg.fsdp_size} "
          f"tp={cfg.tensor_size} pp={cfg.stage_size}")

    from accelerate_tpu.parallel.mesh import ParallelismConfig

    # the one-step smoke test runs unpipelined: a configured stage degree is
    # absorbed into the data axis so the mesh still covers every device
    # (pipeline training proper: examples/by_feature/pipeline_parallel_training.py)
    dp = cfg.data_parallel_size
    if cfg.stage_size > 1 and dp != -1:
        dp = dp * cfg.stage_size
    acc = Accelerator(
        mixed_precision=cfg.mixed_precision,
        parallelism_config=ParallelismConfig(
            data_parallel_size=dp,
            fsdp_size=cfg.fsdp_size,
            tensor_size=cfg.tensor_size,
        ),
        gradient_accumulation_steps=cfg.gradient_accumulation_steps,
    )
    print("mesh:", dict(acc.mesh.shape))

    mcfg = GPT2Config.tiny(dtype=jnp.float32)
    module = GPT2LMHead(mcfg)
    params = module.init_params(jax.random.key(0), batch=2, seq=16)
    model, _ = acc.prepare((module, params), optax.adamw(1e-3))
    step = acc.make_train_step(lm_loss_fn)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, mcfg.vocab_size, (8, 16)), jnp.int32)
    loss = step({"input_ids": ids})
    print("one step ok, loss =", float(loss))


if __name__ == "__main__":
    main()
