"""CV example: ResNet image classification, data-parallel over all chips.

Mirrors reference `examples/cv_example.py` (ResNet-50). Synthetic separable
images by default (each class has a distinct mean brightness) so the example
runs anywhere; point `--data_dir` at an image folder for real data.

Run:
    python examples/cv_example.py --tiny
    accelerate-tpu launch examples/cv_example.py -- --mixed_precision bf16
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

from accelerate_tpu import Accelerator, DataLoaderShard, set_seed
from accelerate_tpu.models.resnet import (
    ResNet,
    ResNetConfig,
    image_classification_loss_fn,
)


def synthetic_images(n: int, size: int, num_classes: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size=(n,)).astype(np.int32)
    base = labels[:, None, None, None] / num_classes
    images = (base + 0.1 * rng.normal(size=(n, size, size, 3))).astype(np.float32)
    return images, labels


def training_function(args: argparse.Namespace) -> float:
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
    )
    set_seed(args.seed)
    config = ResNetConfig.tiny() if args.tiny else ResNetConfig.resnet50(num_classes=args.num_classes)
    size = 32 if args.tiny else args.image_size
    module = ResNet(config)
    params = module.init_params(jax.random.key(args.seed), image_size=size)

    images, labels = synthetic_images(10 * args.batch_size, size, config.num_classes, args.seed)
    n_train = 8 * args.batch_size
    to_batches = lambda lo, hi: [
        {"image": images[i : i + args.batch_size], "label": labels[i : i + args.batch_size]}
        for i in range(lo, hi - args.batch_size + 1, args.batch_size)
    ]
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        (module, params),
        optax.sgd(args.lr, momentum=0.9),
        DataLoaderShard(to_batches(0, n_train)),
        DataLoaderShard(to_batches(n_train, len(images))),
    )
    step = accelerator.make_train_step(image_classification_loss_fn)
    for epoch in range(args.num_epochs):
        for batch in train_dl:
            loss = step(batch)
        correct = total = 0
        for batch in eval_dl:
            preds = jnp.argmax(model(batch["image"]), axis=-1)
            g = accelerator.gather_for_metrics({"p": preds, "l": batch["label"]})
            correct += int((np.asarray(g["p"]) == np.asarray(g["l"])).sum())
            total += len(np.asarray(g["l"]))
        acc = correct / max(total, 1)
        accelerator.print(f"epoch {epoch}: loss={float(loss):.4f} accuracy={acc:.3f}")
    return acc


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", default="no", choices=["no", "bf16", "fp16"])
    parser.add_argument("--lr", type=float, default=0.02)
    parser.add_argument("--num_epochs", type=int, default=3)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--image_size", type=int, default=224)
    parser.add_argument("--num_classes", type=int, default=1000)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--gradient_accumulation_steps", type=int, default=1)
    parser.add_argument("--tiny", action="store_true")
    args = parser.parse_args()
    training_function(args)


if __name__ == "__main__":
    main()
