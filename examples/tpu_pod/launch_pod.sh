#!/usr/bin/env bash
# Multi-host launch on a Cloud TPU pod slice — now a single CLI call:
# `accelerate-tpu launch --tpu_name ... --zone ...` runs the same launch on
# every pod VM via gcloud ssh --worker=all (jax.distributed autodetects the
# coordinator from TPU metadata). For a plain SSH cluster use
# `accelerate-tpu launch --workers host1,host2,... script.py` instead.
#
# Usage: ./launch_pod.sh <tpu-name> <zone> <script.py> [script args...]
set -euo pipefail

TPU_NAME=${1:?tpu name}
ZONE=${2:?gce zone}
SCRIPT=${3:?training script}
shift 3

exec accelerate-tpu launch --tpu_name "$TPU_NAME" --zone "$ZONE" "$SCRIPT" "$@"
