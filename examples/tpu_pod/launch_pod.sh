#!/usr/bin/env bash
# Multi-host launch on a Cloud TPU pod slice — the TPU-native analogue of the
# reference's examples/slurm/submit_multinode.sh (same role: show the exact
# incantation that turns N machines into one training job).
#
# One process per TPU VM host owns all of that host's chips (SPMD); there is
# no per-core forking and no RANK/MASTER_ADDR plumbing. On Cloud TPU,
# jax.distributed discovers the coordinator from the TPU metadata, so the env
# contract below is only needed off-GCP or to override.
#
# Usage: ./launch_pod.sh <tpu-name> <zone> <script.py> [script args...]
set -euo pipefail

TPU_NAME=${1:?tpu name}
ZONE=${2:?gce zone}
SCRIPT=${3:?training script}
shift 3

# `accelerate-tpu tpu-config` wraps: gcloud compute tpus tpu-vm ssh $TPU_NAME
#   --zone $ZONE --worker=all --command "accelerate-tpu launch $SCRIPT ..."
exec accelerate-tpu tpu-config \
  --tpu_name "$TPU_NAME" \
  --zone "$ZONE" \
  --command "cd \$(dirname $SCRIPT) && accelerate-tpu launch $SCRIPT $*"
