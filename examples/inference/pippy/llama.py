"""Llama pipeline-parallel inference (reference `examples/inference/pippy/llama.py`
role): the modern decoder stack (RMSNorm, RoPE, GQA, SwiGLU) through the same
blockwise -> prepare_pippy API as GPT-2. For real weights, map a HF checkpoint
with `params_from_hf_llama` or load safetensors shards."""

import jax
import jax.numpy as jnp
import numpy as np

from accelerate_tpu import prepare_pippy
from accelerate_tpu.models.llama import (
    LlamaConfig,
    LlamaForCausalLM,
    llama_blockwise,
    llama_blockwise_state_dict,
)
from accelerate_tpu.parallel.mesh import ParallelismConfig, build_mesh


def main():
    cfg = LlamaConfig.tiny(num_layers=4, dtype=jnp.float32, param_dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    params = module.init_params(jax.random.key(0))

    mesh = build_mesh(ParallelismConfig(data_parallel_size=2, stage_size=4))
    forward = prepare_pippy(
        llama_blockwise(cfg), llama_blockwise_state_dict(params), mesh=mesh
    )

    prompts = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 16)), jnp.int32
    )
    logits = forward(prompts)
    print(f"stages={forward.num_stages} logits={logits.shape}")
    print("greedy next tokens:", np.asarray(jnp.argmax(logits[:, -1], axis=-1)))


if __name__ == "__main__":
    main()
