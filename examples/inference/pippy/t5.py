"""T5 pipeline-parallel inference (reference `examples/inference/pippy/t5.py`
role): BOTH stacks pipelined over the stage axis. The decoder stage activation
is the pytree (hidden, encoder_out) — cross-attention reads the encoder output
stage-locally instead of via a send/recv graph."""

import jax
import jax.numpy as jnp
import numpy as np

from accelerate_tpu.models.t5 import (
    T5Config,
    T5ForConditionalGeneration,
    t5_pipeline_forward,
)
from accelerate_tpu.parallel.mesh import ParallelismConfig, build_mesh


def main():
    cfg = T5Config.tiny(num_layers=4, num_decoder_layers=4,
                        dtype=jnp.float32, param_dtype=jnp.float32)
    module = T5ForConditionalGeneration(cfg)
    params = module.init_params(jax.random.key(0))

    mesh = build_mesh(ParallelismConfig(data_parallel_size=2, stage_size=4))
    forward = t5_pipeline_forward(cfg, params, mesh=mesh)

    rng = np.random.default_rng(0)
    # batch 8 over 4 microbatches -> microbatch 2, divisible by dp=2 so each
    # data replica pipelines its own slice (no replicated-compute fallback)
    src = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 32)), jnp.int32)
    tgt = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 8)), jnp.int32)
    logits = forward(src, tgt)  # [8, 8, vocab]
    print(f"logits={logits.shape}")
    print("greedy next tokens:", np.asarray(jnp.argmax(logits[:, -1], axis=-1)))


if __name__ == "__main__":
    main()
