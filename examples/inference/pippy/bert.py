"""BERT pipeline-parallel inference (reference `examples/inference/pippy/bert.py`
role): an encoder pipeline whose last stage output feeds a non-LM head
(pooler + classifier). Pad-free batches — the PP path does not thread an
attention mask (same as the reference's traced example inputs)."""

import jax
import jax.numpy as jnp
import numpy as np

from accelerate_tpu import prepare_pippy
from accelerate_tpu.models.bert import (
    BertConfig,
    BertForSequenceClassification,
    bert_blockwise,
    bert_blockwise_state_dict,
)
from accelerate_tpu.parallel.mesh import ParallelismConfig, build_mesh


def main():
    cfg = BertConfig.tiny(num_layers=4, dtype=jnp.float32)
    module = BertForSequenceClassification(cfg)
    params = module.init_params(jax.random.key(0))

    mesh = build_mesh(ParallelismConfig(data_parallel_size=2, stage_size=4))
    forward = prepare_pippy(bert_blockwise(cfg), bert_blockwise_state_dict(params), mesh=mesh)

    ids = jnp.asarray(np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 32)), jnp.int32)
    logits = forward(ids)  # [8, num_labels]
    print(f"stages={forward.num_stages} class logits={logits.shape}")
    print("predictions:", np.asarray(jnp.argmax(logits, axis=-1)))


if __name__ == "__main__":
    main()
