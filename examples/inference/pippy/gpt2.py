"""GPT-2 pipeline-parallel inference (reference `examples/inference/pippy/gpt2.py`
role): split the trunk into 4 stages over the `stage` mesh axis, feed a batch,
read replicated logits on every device."""

import jax
import jax.numpy as jnp
import numpy as np

from accelerate_tpu import prepare_pippy
from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHead, gpt2_blockwise, gpt2_blockwise_state_dict
from accelerate_tpu.parallel.mesh import ParallelismConfig, build_mesh


def main():
    cfg = GPT2Config.tiny(n_layer=4, dtype=jnp.float32)
    module = GPT2LMHead(cfg)
    params = module.init_params(jax.random.key(0), batch=2, seq=32)

    mesh = build_mesh(ParallelismConfig(data_parallel_size=2, stage_size=4))
    forward = prepare_pippy(gpt2_blockwise(cfg), gpt2_blockwise_state_dict(params), mesh=mesh)

    ids = jnp.asarray(np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 32)), jnp.int32)
    logits = forward(ids)  # [4, 32, vocab], replicated on every device
    next_tokens = jnp.argmax(logits[:, -1], axis=-1)
    print(f"stages={forward.num_stages} microbatches={forward.num_microbatches}")
    print("greedy next tokens:", np.asarray(next_tokens))


if __name__ == "__main__":
    main()
