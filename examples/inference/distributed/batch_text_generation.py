"""Prompt-sharded batch text generation (reference
`examples/inference/distributed/phi2.py` role): each process takes its
`split_between_processes` slice of the prompt list, decodes with the jitted
KV-cache generate loop, and `gather_object` reassembles every completion
everywhere. Swap the toy GPT-2 for real weights via `params_from_hf_gpt2`."""

import jax
import jax.numpy as jnp
import numpy as np

from accelerate_tpu.models.generation import generate
from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from accelerate_tpu.state import PartialState
from accelerate_tpu.utils.operations import gather_object


def main():
    state = PartialState()
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    module = GPT2LMHead(cfg)
    params = module.init_params(jax.random.key(0), batch=1, seq=16)

    # 5 prompts over N processes: uneven split handled by split_between_processes
    prompts = [
        np.asarray([[2, 3, 5, 7, 11, 13, 17, 19]], np.int32),
        np.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], np.int32),
        np.asarray([[42, 42, 42, 42, 42, 42, 42, 42]], np.int32),
        np.asarray([[9, 8, 7, 6, 5, 4, 3, 2]], np.int32),
        np.asarray([[100, 101, 102, 103, 104, 105, 106, 107]], np.int32),
    ]

    completions = []
    with state.split_between_processes(prompts) as my_prompts:
        for ids in my_prompts:
            out = generate(module, params, jnp.asarray(ids), max_new_tokens=8)
            completions.append(np.asarray(out)[0].tolist())

    all_completions = gather_object(completions)
    if state.is_main_process:
        for i, toks in enumerate(all_completions):
            print(f"prompt {i}: +{toks}")


if __name__ == "__main__":
    main()
