"""Image-sharded batch classification (reference
`examples/inference/distributed/distributed_image_generation.py` /
`stable_diffusion.py` role, classification in place of diffusion): the image
batch splits across processes with padding so every process runs the same
static shape, each process runs ViT on its slice, predictions gather
everywhere and the padding is dropped."""

import jax
import jax.numpy as jnp
import numpy as np

from accelerate_tpu.models.vit import ViTConfig, ViTForImageClassification
from accelerate_tpu.state import PartialState
from accelerate_tpu.utils.operations import gather_object


def main():
    state = PartialState()
    cfg = ViTConfig.tiny()
    module = ViTForImageClassification(cfg)
    params = module.init_params(jax.random.key(0))

    n_images = 10  # deliberately uneven for multi-process runs
    images = np.random.default_rng(0).normal(  # NCHW, the torch conv layout
        size=(n_images, cfg.num_channels, cfg.image_size, cfg.image_size)
    ).astype(np.float32)

    # padding gives every process the same static shape to jit; each process
    # truncates its OWN padded tail before the gather (split_between_processes
    # pads every process with index >= n % num_processes, not just the last)
    base, extra = divmod(n_images, state.num_processes)
    my_real = base + (1 if state.process_index < extra else 0)
    with state.split_between_processes(images, apply_padding=True) as my_images:
        logits = module.apply({"params": params}, jnp.asarray(my_images))
        preds = np.asarray(jnp.argmax(logits, axis=-1)).tolist()[:my_real]

    all_preds = gather_object(preds)
    if state.is_main_process:
        print(f"{len(all_preds)} predictions from {state.num_processes} process(es):")
        print(all_preds)


if __name__ == "__main__":
    main()
