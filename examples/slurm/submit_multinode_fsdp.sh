#!/bin/bash
#SBATCH --job-name=accelerate-tpu-fsdp
#SBATCH --nodes=4
#SBATCH --ntasks-per-node=1
#SBATCH --cpus-per-task=32
#SBATCH --time=02:00:00
#SBATCH --output=%x_%j.out

# Parameter + optimizer-state sharding over every device in the job: the fsdp
# mesh axis absorbs all chips. Env contract: dp,fsdp,stage,sequence,tensor.
export ACCELERATE_TPU_MIXED_PRECISION=bf16
export ACCELERATE_TPU_PARALLELISM=1,-1,1,1,1

srun python examples/by_feature/fsdp_with_peak_mem_tracking.py
