#!/bin/bash
#SBATCH --job-name=accelerate-tpu-multinode
#SBATCH --nodes=4
#SBATCH --ntasks-per-node=1
#SBATCH --cpus-per-task=32
#SBATCH --time=02:00:00
#SBATCH --output=%x_%j.out

# One process per node; jax.distributed self-configures from the SLURM step
# (accelerate_tpu.state autodetects SLURM_NTASKS > 1 — no MASTER_ADDR plumbing).
export ACCELERATE_TPU_MIXED_PRECISION=bf16

srun python examples/complete_nlp_example.py --mixed_precision bf16
