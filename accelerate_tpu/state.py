"""Process/device state singletons.

Capability parity with reference `src/accelerate/state.py`:
  - ``PartialState``   (reference `state.py:115-813`)  — topology, rank accessors,
    barriers, process-slicing helpers, rank-gated execution.
  - ``AcceleratorState`` (reference `state.py:816-1131`) — adds mixed precision and
    the parallelism plan (here: the device mesh).
  - ``GradientState``  (reference `state.py:1134-1260`) — gradient-accumulation
    bookkeeping shared between Accelerator, dataloaders and optimizers.

TPU-native re-founding: there is no backend-selection matrix and no
``init_process_group`` rendezvous. A JAX process == one host; ``jax.distributed``
(coordinator on host 0, over DCN) replaces the TCP store; intra-host devices are
already visible. Collectives are either implicit (XLA inserts them from shardings
inside jit) or explicit host-level ops in `utils/operations.py`.
"""

from __future__ import annotations

import functools
import logging
import os
import threading
from contextlib import contextmanager
from typing import Any, Callable, Iterator

import jax
import numpy as np

from .parallel.mesh import ParallelismConfig, build_mesh, data_axes, mesh_axis_size
from .utils.environment import parse_choice_from_env, parse_flag_from_env

logger = logging.getLogger(__name__)


class DistributedType(str):
    """Topology descriptor. Unlike the reference (which needs one enum value per
    engine — DEEPSPEED/FSDP/MEGATRON_LM/XLA...), SPMD subsumes every strategy, so
    only the topology is distinguished."""

    NO = "NO"
    SPMD = "SPMD"  # >1 device, single host
    MULTI_HOST = "MULTI_HOST"  # >1 JAX process


def _sagemaker_env_to_contract() -> None:
    """Translate SageMaker's cluster env (SM_HOSTS JSON list + SM_CURRENT_HOST,
    set inside every training container) into the JAX_COORDINATOR/PROCESS_ID
    contract — JAX has no SageMaker autodetect, and without this a
    num_machines>1 job would run N duplicate single-process trainings
    (reference role: `utils/launch.py` SageMaker env plumbing)."""
    if os.environ.get("ACCELERATE_TPU_USE_SAGEMAKER") != "true":
        return
    hosts_raw, current = os.environ.get("SM_HOSTS"), os.environ.get("SM_CURRENT_HOST")
    if not hosts_raw or not current or os.environ.get("JAX_COORDINATOR_ADDRESS"):
        return
    import json as _json

    try:
        hosts = sorted(_json.loads(hosts_raw))
    except ValueError:
        logger.warning("SM_HOSTS is not JSON (%r); skipping cluster translation", hosts_raw)
        return
    if len(hosts) <= 1 or current not in hosts:
        return
    os.environ["JAX_COORDINATOR_ADDRESS"] = f"{hosts[0]}:8476"
    os.environ["JAX_NUM_PROCESSES"] = str(len(hosts))
    os.environ["JAX_PROCESS_ID"] = str(hosts.index(current))
    os.environ["ACCELERATE_TPU_NUM_PROCESSES"] = str(len(hosts))


def _in_multitask_slurm_step() -> bool:
    """True inside an `srun` task of a multi-task SLURM step (the only case
    where distributed init is needed and autodetectable). Discriminates on the
    STEP task count, not the allocation's: a plain `sbatch --ntasks=N` batch
    script also exports SLURM_NTASKS=N and SLURM_PROCID=0, but its single
    batch-step process would block forever waiting for N-1 peers."""
    if "SLURM_PROCID" not in os.environ or "SLURM_JOB_ID" not in os.environ:
        return False
    try:
        step_tasks = int(os.environ.get("SLURM_STEP_NUM_TASKS") or 1)
    except ValueError:
        return False
    return step_tasks > 1


def _maybe_init_distributed(initialization_timeout: int | None = None) -> None:
    """Initialize jax.distributed from the launcher env contract if present.

    Env contract (set by `commands/launch.py`): ``JAX_COORDINATOR_ADDRESS``,
    ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``. On Cloud TPU pods, plain
    ``jax.distributed.initialize()`` autodetects everything from metadata; the env
    vars only override. Mirrors the role of reference `state.py:212` init_process_group.
    ``initialization_timeout`` comes from ``InitProcessGroupKwargs.timeout_seconds``
    (reference `InitProcessGroupKwargs.timeout` -> init_process_group).
    """
    _sagemaker_env_to_contract()
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
    nproc = os.environ.get("JAX_NUM_PROCESSES") or os.environ.get("ACCELERATE_TPU_NUM_PROCESSES")
    if coord is None and nproc is None:
        if _in_multitask_slurm_step():
            # SLURM job step (reference: examples/slurm submit scripts feed
            # torch.distributed via MASTER_ADDR; here jax's built-in cluster
            # detection resolves coordinator/num_processes/process_id from the
            # SLURM_* env directly — no launcher arguments needed)
            if not jax.distributed.is_initialized():
                extra: dict[str, Any] = {}
                if initialization_timeout is not None:
                    extra["initialization_timeout"] = int(initialization_timeout)
                try:
                    jax.distributed.initialize(**extra)
                except (RuntimeError, ValueError) as e:
                    # the user explicitly ran a multi-task srun step; falling
                    # back to N duplicate single-process worlds is NOT benign —
                    # every task would claim main-process and write the same
                    # checkpoint/output paths. Refuse unless explicitly opted
                    # out (the opt-out keeps salvage-a-broken-cluster debugging
                    # possible).
                    from .utils.environment import parse_flag_from_env

                    if parse_flag_from_env("ACCELERATE_TPU_ALLOW_SLURM_FALLBACK"):
                        logger.warning(
                            "multi-task SLURM step detected but "
                            "jax.distributed.initialize failed (%s); "
                            "ACCELERATE_TPU_ALLOW_SLURM_FALLBACK=1 set — each "
                            "task now runs as an independent single-process "
                            "world", e,
                        )
                    else:
                        raise RuntimeError(
                            "multi-task SLURM step detected (SLURM_STEP_NUM_TASKS"
                            " > 1) but jax.distributed.initialize failed; "
                            "continuing would run N independent duplicate "
                            "single-process jobs that overwrite each other's "
                            "outputs. Set ACCELERATE_TPU_ALLOW_SLURM_FALLBACK=1 "
                            "to allow the single-process fallback anyway."
                        ) from e
        return
    # NOTE: must not touch jax.devices()/process_count() here — that would
    # initialize the backend single-process and make distributed init impossible
    if jax.distributed.is_initialized():
        return
    pid = os.environ.get("JAX_PROCESS_ID")
    extra: dict[str, Any] = {}
    if initialization_timeout is not None:
        extra["initialization_timeout"] = int(initialization_timeout)
    try:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(nproc) if nproc else None,
            process_id=int(pid) if pid is not None else None,
            **extra,
        )
    except (RuntimeError, ValueError) as e:  # already initialized or single-proc
        logger.debug("jax.distributed.initialize skipped: %s", e)


class PartialState:
    """Singleton holding topology facts and process-coordination primitives.

    Shared-state borg pattern (reference `SharedDict`, `state.py:83-110`): every
    instance shares one ``_shared_state`` dict, so constructing it anywhere returns
    the same initialized state.
    """

    _shared_state: dict[str, Any] = {}
    _lock = threading.Lock()

    def __init__(self, cpu: bool = False, **kwargs: Any):
        self.__dict__ = self._shared_state
        if self.initialized:
            return
        with self._lock:
            if self.initialized:
                return
            self._init(cpu=cpu, **kwargs)

    def _init(self, cpu: bool = False, initialization_timeout: int | None = None, **kwargs: Any) -> None:
        _maybe_init_distributed(initialization_timeout)
        self.debug = parse_flag_from_env("ACCELERATE_TPU_DEBUG_MODE")
        self._cpu = cpu
        self.devices = jax.devices()
        self.local_devices = jax.local_devices()
        self.process_index = jax.process_index()
        self.num_processes = jax.process_count()
        self.device = self.local_devices[0]
        self.fork_launched = parse_flag_from_env("FORK_LAUNCHED", False)
        if self.num_processes > 1:
            self.distributed_type = DistributedType.MULTI_HOST
        elif len(self.devices) > 1:
            self.distributed_type = DistributedType.SPMD
        else:
            self.distributed_type = DistributedType.NO

    # ------------------------------------------------------------------ topology
    @property
    def initialized(self) -> bool:
        return "devices" in self._shared_state

    @property
    def num_devices(self) -> int:
        return len(self.devices)

    @property
    def local_process_index(self) -> int:
        # one JAX process per host: local index is always 0 for the process itself
        return 0

    @property
    def use_distributed(self) -> bool:
        return self.num_devices > 1 or self.num_processes > 1

    @property
    def is_main_process(self) -> bool:
        return self.process_index == 0

    @property
    def is_local_main_process(self) -> bool:
        return True if self.num_processes == 1 else self.process_index == 0

    @property
    def is_last_process(self) -> bool:
        return self.process_index == self.num_processes - 1

    # ------------------------------------------------------------ coordination
    def wait_for_everyone(self) -> None:
        """Cross-host barrier (reference `state.py:343`). Implemented as a named
        sync over DCN; a no-op in single-process topologies (devices under one
        process are synchronized by the runtime)."""
        if self.num_processes > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("accelerate_tpu.wait_for_everyone")

    @contextmanager
    def main_process_first(self):
        """Main host runs the body first, others wait (reference `state.py:478`).
        Used for things like dataset preprocessing caches."""
        if not self.is_main_process:
            self.wait_for_everyone()
        yield
        if self.is_main_process:
            self.wait_for_everyone()

    @contextmanager
    def local_main_process_first(self):
        with self.main_process_first():
            yield

    @contextmanager
    def split_between_processes(
        self, inputs: list | tuple | dict | np.ndarray, apply_padding: bool = False
    ) -> Iterator[Any]:
        """Yield this process's slice of ``inputs`` (reference `state.py:389-476`).

        Lists/tuples/arrays are sliced on their first dimension; dicts are sliced
        per-value. With ``apply_padding`` the last process's share is padded (by
        repeating the final element) so all processes yield equal-length slices.
        """
        if self.num_processes == 1:
            yield inputs
            return

        def _slice(obj):
            length = len(obj)
            base, extra = divmod(length, self.num_processes)
            # first `extra` processes get one more element
            start = self.process_index * base + min(self.process_index, extra)
            stop = start + base + (1 if self.process_index < extra else 0)
            piece = obj[start:stop]
            if apply_padding and extra != 0:
                target = base + 1
                pad_n = target - len(piece)
                if pad_n > 0 and length > 0:
                    if isinstance(piece, np.ndarray):
                        piece = np.concatenate([piece, np.repeat(piece[-1:], pad_n, axis=0)])
                    else:
                        piece = list(piece) + [obj[-1]] * pad_n
            return piece

        if isinstance(inputs, dict):
            lengths = {len(v) for v in inputs.values()}
            if len(lengths) > 1:
                raise ValueError(f"All dict values must have equal length, got {lengths}.")
            yield {k: _slice(v) for k, v in inputs.items()}
        else:
            yield _slice(inputs)

    # ------------------------------------------------------------ rank gating
    def on_main_process(self, function: Callable) -> Callable:
        @functools.wraps(function)
        def wrapper(*args, **kwargs):
            if self.is_main_process:
                return function(*args, **kwargs)

        return wrapper

    def on_local_main_process(self, function: Callable) -> Callable:
        @functools.wraps(function)
        def wrapper(*args, **kwargs):
            if self.is_local_main_process:
                return function(*args, **kwargs)

        return wrapper

    def on_last_process(self, function: Callable) -> Callable:
        @functools.wraps(function)
        def wrapper(*args, **kwargs):
            if self.is_last_process:
                return function(*args, **kwargs)

        return wrapper

    def on_process(self, function: Callable | None = None, process_index: int = 0) -> Callable:
        if function is None:
            return functools.partial(self.on_process, process_index=process_index)

        @functools.wraps(function)
        def wrapper(*args, **kwargs):
            if self.process_index == process_index:
                return function(*args, **kwargs)

        return wrapper

    def on_local_process(
        self, function: Callable | None = None, local_process_index: int = 0
    ) -> Callable:
        """Run only on the given LOCAL process index (reference `state.py:641`).
        One JAX process per host means local index 0 is the only inhabitant,
        so this gates to "every host runs it" vs "no host does"."""
        if function is None:
            return functools.partial(
                self.on_local_process, local_process_index=local_process_index
            )

        @functools.wraps(function)
        def wrapper(*args, **kwargs):
            if self.local_process_index == local_process_index:
                return function(*args, **kwargs)

        return wrapper

    @property
    def default_device(self):
        """The device computation lands on by default (reference
        `state.py:682` picks MPS/CUDA/...; here it is jax's first device —
        TPU when attached, else CPU)."""
        import jax

        return jax.devices()[0]

    def print(self, *args, **kwargs) -> None:
        """Print once per job (main host only) — reference `state.py:677`."""
        if self.is_local_main_process:
            print(*args, **kwargs)

    def shutdown(self) -> None:
        """Teardown (reference `destroy_process_group`, `state.py:793-801`)."""
        if self.num_processes > 1:
            jax.distributed.shutdown()

    @classmethod
    def _reset_state(cls) -> None:
        """Clear the singleton (test isolation — reference `state.py:808`)."""
        cls._shared_state.clear()

    def __repr__(self) -> str:
        return (
            f"PartialState(distributed_type={self.distributed_type}, "
            f"num_processes={self.num_processes}, num_devices={self.num_devices}, "
            f"process_index={self.process_index})"
        )


class AcceleratorState:
    """PartialState + the training plan: mixed precision and the device mesh.

    Reference `state.py:816-1131` promotes DistributedType per plugin engine; here
    the "plugins" collapse into a `ParallelismConfig` whose axes configure one mesh.
    """

    _shared_state: dict[str, Any] = {}

    def __init__(
        self,
        mixed_precision: str | None = None,
        cpu: bool = False,
        parallelism_config: ParallelismConfig | None = None,
        **kwargs: Any,
    ):
        self.__dict__ = self._shared_state
        if self.initialized:
            return
        self._partial = PartialState(cpu=cpu, **kwargs)
        if mixed_precision is None:
            mixed_precision = parse_choice_from_env("ACCELERATE_TPU_MIXED_PRECISION", "no")
        self.mixed_precision_mode = mixed_precision.lower()
        self.parallelism_config = parallelism_config or ParallelismConfig()
        self.mesh = build_mesh(self.parallelism_config, self._partial.devices)
        self.initialized_cpu = cpu

    @property
    def initialized(self) -> bool:
        return "mesh" in self._shared_state

    @property
    def mixed_precision(self) -> str:
        return self.mixed_precision_mode

    # --- DeepSpeed plugin registry (reference `state.py` deepspeed_plugins +
    # get/select accessors). Plugins here only shape optax/mesh config
    # (utils/deepspeed.py); the registry preserves the multi-plugin selection
    # API so reference scripts that switch plugins keep working.
    @property
    def deepspeed_plugin(self):
        """The currently selected DeepSpeed plugin, or None (reference
        `AcceleratorState.deepspeed_plugin`)."""
        plugins = self._shared_state.get("deepspeed_plugins") or {}
        return plugins.get(self._shared_state.get("active_deepspeed_plugin"))

    def register_deepspeed_plugins(self, plugins) -> None:
        """Accept one plugin or a dict of named plugins; the first becomes
        active (reference multi-plugin constructor contract)."""
        if plugins is None:
            return
        if not isinstance(plugins, dict):
            plugins = {"default": plugins}
        self._shared_state["deepspeed_plugins"] = plugins
        # re-registering under different names must not leave a stale active
        # name pointing outside the new registry (deepspeed_plugin would
        # silently return None)
        if self._shared_state.get("active_deepspeed_plugin") not in plugins:
            self._shared_state["active_deepspeed_plugin"] = next(iter(plugins))

    def get_deepspeed_plugin(self, name: str):
        """Look up a registered plugin by name (reference `get_deepspeed_plugin`)."""
        plugins = self._shared_state.get("deepspeed_plugins") or {}
        if name not in plugins:
            raise ValueError(
                f"No DeepSpeed plugin named {name!r}; registered: {sorted(plugins)}"
            )
        return plugins[name]

    def select_deepspeed_plugin(self, name: str) -> None:
        """Make the named plugin active (reference `select_deepspeed_plugin`)."""
        self.get_deepspeed_plugin(name)  # raises with the registry listed
        self._shared_state["active_deepspeed_plugin"] = name

    # Delegate topology to PartialState
    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(PartialState(), name)

    @property
    def data_parallel_size(self) -> int:
        return mesh_axis_size(self.mesh, *data_axes(self.mesh))

    @property
    def batch_sharding(self):
        """NamedSharding for the global batch (leading dim over data+fsdp axes)."""
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self.mesh, PartitionSpec(data_axes(self.mesh)))

    @classmethod
    def _reset_state(cls, reset_partial_state: bool = False) -> None:
        cls._shared_state.clear()
        if reset_partial_state:
            PartialState._reset_state()

    def __repr__(self) -> str:
        return (
            f"AcceleratorState(mesh={dict(self.mesh.shape)}, "
            f"mixed_precision={self.mixed_precision_mode!r})"
        )


class GradientState:
    """Gradient-accumulation bookkeeping (reference `state.py:1134-1260`).

    Shared between the Accelerator (sets num_steps / sync schedule), prepared
    dataloaders (push/pop + end_of_dataloader), optimizers (skip while
    accumulating) and schedulers (step only on sync).
    """

    _shared_state: dict[str, Any] = {}

    def __init__(self, gradient_accumulation_steps: int | None = None, **plugin_kwargs: Any):
        self.__dict__ = self._shared_state
        if not self.initialized:
            self.sync_gradients = True
            self.active_dataloader = None
            self.dataloader_references: list[Any] = [None]
            self.num_steps = gradient_accumulation_steps or 1
            self.adjust_scheduler = plugin_kwargs.get("adjust_scheduler", True)
            self.sync_with_dataloader = plugin_kwargs.get("sync_with_dataloader", True)
            self.step = 0
        elif gradient_accumulation_steps is not None:
            self.num_steps = gradient_accumulation_steps

    @property
    def initialized(self) -> bool:
        return "sync_gradients" in self._shared_state

    @property
    def end_of_dataloader(self) -> bool:
        if not self.in_dataloader:
            return False
        return self.active_dataloader.end_of_dataloader

    @property
    def remainder(self) -> int:
        """Number of extra (duplicated) samples in the final global batch, used by
        gather_for_metrics to drop padding (reference `state.py:1196`)."""
        if not self.in_dataloader:
            return -1
        return self.active_dataloader.remainder

    @property
    def in_dataloader(self) -> bool:
        return self.active_dataloader is not None

    def _set_sync_gradients(self, sync: bool) -> None:
        self.sync_gradients = sync

    @property
    def is_xla_gradients_synced(self) -> bool:
        """Reference `GradientState.is_xla_gradients_synced`: whether the XLA
        gradient reduction already ran this step. Under SPMD the reduction is
        part of the compiled step itself, so this is exactly the sync
        boundary."""
        return self.sync_gradients

    def _add_dataloader(self, dataloader: Any) -> None:
        self.active_dataloader = dataloader
        self.dataloader_references.append(dataloader)

    def _remove_dataloader(self, dataloader: Any) -> None:
        if dataloader in self.dataloader_references:
            self.dataloader_references.remove(dataloader)
        self.active_dataloader = self.dataloader_references[-1]

    @classmethod
    def _reset_state(cls) -> None:
        cls._shared_state.clear()

    def __repr__(self) -> str:
        return (
            f"GradientState(num_steps={self.num_steps}, sync_gradients={self.sync_gradients}, "
            f"step={self.step})"
        )
