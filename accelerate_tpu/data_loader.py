"""Distributed data pipeline.

Capability parity: reference `src/accelerate/data_loader.py` (1321 LoC) —
`BatchSamplerShard`, `IterableDatasetShard`, `SeedableRandomSampler`,
`DataLoaderShard`, `DataLoaderDispatcher`, `prepare_data_loader`,
`skip_first_batches` (reference lines :103, :259, :68, :486, :680, :930, :1245).

TPU-native re-founding:
  - A "process" is a host; each host loads only its slice of the global batch and
    the loader assembles a single *global* `jax.Array` per leaf, sharded over the
    mesh's data axes (`jax.make_array_from_process_local_data`). Downstream, the
    jitted step consumes global arrays — there is no per-rank tensor plumbing.
  - XLA requires static shapes, so ragged final batches are padded *by wrapping
    samples from the batch start* (the reference's `even_batches` semantics) and
    the duplicate count is recorded in `remainder` for `gather_for_metrics` to
    drop (reference `accelerator.py:2487-2505`).
  - Host->device transfer is asynchronous in JAX; a one-batch lookahead both
    overlaps the copy and detects `end_of_dataloader` for gradient-sync
    bookkeeping (reference `data_loader.py:550-573`), replacing torch_xla's
    `MpDeviceLoader` background threads.

Works with torch `DataLoader`s (rebuilt around a sharded batch sampler, keeping
collate/workers) or with any python iterable yielding numpy/dict batches.
"""

from __future__ import annotations

import math
from collections.abc import Mapping
from typing import Any, Callable, Iterable, Iterator

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from .state import AcceleratorState, GradientState, PartialState
from .parallel.mesh import data_axes
from .utils.operations import (
    as_registered_pytree,
    broadcast_object_list,
    find_batch_size,
    recursively_apply,
)
from .utils.random import get_rng_key, synchronize_rng_states


def _leaf_to_numpy(t: Any) -> Any:
    """Convert a torch tensor / jax array leaf to numpy, pass others through."""
    if isinstance(t, np.ndarray):
        return t
    if isinstance(t, jax.Array):
        return np.asarray(t)
    # torch tensors, without importing torch eagerly
    if type(t).__module__.startswith("torch") and hasattr(t, "detach"):
        return t.detach().cpu().numpy()
    return t


def _is_arraylike(t: Any) -> bool:
    return (
        isinstance(t, (np.ndarray, jax.Array))
        or (type(t).__module__.startswith("torch") and hasattr(t, "detach"))
    )


class SeedableRandomSampler:
    """Deterministic, resumable shuffling sampler re-seeded per epoch
    (reference `data_loader.py:68-100`). Framework-agnostic: yields indices."""

    def __init__(self, data_source_len: int, seed: int = 0, epoch: int = 0):
        self.data_source_len = data_source_len
        self.seed = seed
        self.epoch = epoch

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __iter__(self) -> Iterator[int]:
        rng = np.random.default_rng(self.seed + self.epoch)
        yield from rng.permutation(self.data_source_len).tolist()
        self.epoch += 1

    def __len__(self) -> int:
        return self.data_source_len


class BatchSamplerShard:
    """Yield this process's share of a batch sampler's batches
    (reference `data_loader.py:103-257`). Two modes:

    - ``split_batches=True``: every underlying batch (the *global* batch) is cut
      into ``num_processes`` contiguous slices; this shard yields slice
      ``process_index``. The underlying batch size must divide evenly.
    - ``split_batches=False``: whole batches go round-robin; this shard takes
      batches ``process_index, process_index+P, ...``.

    With ``even_batches=True`` (default), sample indices wrap around to the
    dataset start so every process yields the same number of equally-sized
    batches — the static-shape guarantee the jitted step requires. With
    ``even_batches=False`` trailing batches may be smaller or missing.
    """

    def __init__(
        self,
        batch_sampler: Iterable[list[int]],
        num_processes: int,
        process_index: int,
        split_batches: bool = False,
        even_batches: bool = True,
    ):
        if not 0 <= process_index < num_processes:
            raise ValueError(f"process_index {process_index} out of range for {num_processes} processes")
        self.batch_sampler = batch_sampler
        self.num_processes = num_processes
        self.process_index = process_index
        self.split_batches = split_batches
        self.even_batches = even_batches
        self.batch_size = getattr(batch_sampler, "batch_size", None)
        if self.split_batches and self.batch_size is not None and self.batch_size % num_processes != 0:
            raise ValueError(
                f"split_batches requires batch size ({self.batch_size}) divisible by "
                f"num_processes ({num_processes})"
            )
        if self.batch_size is None and even_batches:
            # evening pads to the NOMINAL batch size; without one the pad target
            # is undefined (reference `data_loader.py:158-162` same rule)
            raise ValueError(
                "even_batches=True requires the batch sampler to expose `batch_size`; "
                "pass even_batches=False for samplers with variable batch sizes"
            )
        self.drop_last = getattr(batch_sampler, "drop_last", False)

    @property
    def total_length(self) -> int:
        return len(self.batch_sampler)

    def __len__(self) -> int:
        n = len(self.batch_sampler)
        if self.split_batches:
            return n
        if self.drop_last:
            # a trailing group with fewer than num_processes batches is dropped
            # entirely (reference `data_loader.py:199-205` length math)
            return n // self.num_processes
        if self.even_batches:
            return math.ceil(n / self.num_processes)
        # without evening, later processes may get one fewer batch
        base, extra = divmod(n, self.num_processes)
        return base + (1 if self.process_index < extra else 0)

    def __iter__(self) -> Iterator[list[int]]:
        if self.split_batches:
            yield from self._iter_split()
        else:
            yield from self._iter_round_robin()

    def _iter_split(self) -> Iterator[list[int]]:
        """Slice math is anchored on the NOMINAL batch size (reference
        `data_loader.py:189-209`): a ragged batch — including a first batch
        smaller than one global batch — is refilled by cycling the epoch's
        first batch, so every yielded slice has the static nominal/P shape."""
        nominal = self.batch_size
        if nominal is None:
            # no declared batch size (ctor forces even_batches=False): pure
            # exact partition — each batch sliced by its own ceil(len/P),
            # empty pieces skipped (reference batch_size-None role)
            for batch in self.batch_sampler:
                batch = list(batch)
                size = math.ceil(len(batch) / self.num_processes)
                piece = batch[size * self.process_index : size * (self.process_index + 1)]
                if piece:
                    yield piece
            return
        size = nominal // self.num_processes
        first: list[int] | None = None
        last: list[int] = []
        for batch in self.batch_sampler:
            batch = list(batch)
            if first is None:
                first = batch
            if last and len(last) != nominal:
                # the slice math assumes only the FINAL batch may be ragged
                # (torch BatchSampler invariant; the reference silently DROPS
                # mid-stream ragged batches — raise instead of losing samples)
                raise ValueError(
                    f"batch of {len(last)} followed by more batches; only the final "
                    f"batch may differ from the nominal size {nominal}"
                )
            last = batch
            if len(batch) == nominal:
                yield batch[size * self.process_index : size * (self.process_index + 1)]
        if first is None or len(last) == nominal or self.drop_last:
            return  # empty sampler, or no ragged tail, or tail dropped
        if not self.even_batches:
            piece = last[size * self.process_index : size * (self.process_index + 1)]
            if piece:
                yield piece
            return
        pool = list(first)
        while len(pool) < nominal:  # dataset smaller than one global batch
            pool = pool + pool
        refill = (last + pool)[:nominal]
        yield refill[size * self.process_index : size * (self.process_index + 1)]

    def _iter_round_robin(self) -> Iterator[list[int]]:
        """Whole batches go round-robin; a trailing group short of
        ``num_processes`` full batches is completed by wrapping already-seen
        indices (even_batches) or dropped whole (drop_last) — reference
        `data_loader.py:211-257` group semantics, static nominal shapes."""
        nominal = self.batch_size
        group: list[list[int]] = []
        seen: list[int] = []
        ragged_seen = False
        for batch in self.batch_sampler:
            batch = list(batch)
            if nominal is not None:
                if ragged_seen:
                    # padding math assumes only the FINAL batch may be ragged
                    # (torch BatchSampler invariant; the reference silently
                    # loses trailing batches here — raise instead)
                    raise ValueError(
                        "a ragged batch was followed by more batches; only the "
                        f"final batch may differ from the nominal size {nominal}"
                    )
                ragged_seen = len(batch) != nominal
            seen.extend(batch)
            group.append(batch)
            # without a declared batch size (even_batches=False ctor-enforced)
            # every complete group yields regardless of batch sizes
            if len(group) == self.num_processes and (
                nominal is None or len(group[-1]) == nominal
            ):
                yield group[self.process_index]
                group = []
        # trailing group: fewer than num_processes batches, or ragged last batch
        if not group:
            return
        if self.drop_last:
            # dropped whole, never wrapped — torch DataLoader drop_last
            # semantics extend to the process group
            return
        if not self.even_batches:
            if self.process_index < len(group):
                yield group[self.process_index]
            return
        # complete the group to num_processes full batches by cycling seen
        # indices; each process's refill continues where the previous stopped
        k = 0
        filled: list[list[int]] = []
        for i in range(self.num_processes):
            b = list(group[i]) if i < len(group) else []
            while len(b) < nominal:
                b.append(seen[k % len(seen)])
                k += 1
            filled.append(b)
        yield filled[self.process_index]


class IterableDatasetShard:
    """Shard an iterable (length-unknown) dataset across processes by buffering
    ``global_batch`` items and yielding this process's contiguous slice
    (reference `data_loader.py:259-356`). The final short buffer is completed by
    wrapping items from the first buffer unless ``drop_last``.
    """

    def __init__(
        self,
        dataset: Iterable,
        batch_size: int,
        num_processes: int,
        process_index: int,
        drop_last: bool = False,
        split_batches: bool = False,
        seed: int = 0,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.num_processes = num_processes
        self.process_index = process_index
        self.drop_last = drop_last
        self.split_batches = split_batches
        self.seed = seed
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def __iter__(self):
        # chunk = one global batch worth of items
        per_proc = self.batch_size // self.num_processes if self.split_batches else self.batch_size
        chunk_size = per_proc * self.num_processes
        first_chunk: list | None = None
        buffer: list = []
        for item in self.dataset:
            buffer.append(item)
            if len(buffer) == chunk_size:
                if first_chunk is None:
                    first_chunk = list(buffer)
                start = per_proc * self.process_index
                yield from buffer[start : start + per_proc]
                buffer = []
        if not buffer or self.drop_last:
            return
        if first_chunk is None:
            first_chunk = list(buffer)
        while len(buffer) < chunk_size:
            buffer.append(first_chunk[(len(buffer)) % len(first_chunk)])
        start = per_proc * self.process_index
        yield from buffer[start : start + per_proc]


class _PrefetchIterator:
    """One-batch lookahead so the consumer learns `end_of_dataloader` before the
    final step and H2D transfer overlaps compute (reference `data_loader.py:550-573`)."""

    def __init__(self, iterator: Iterator, on_last: Callable[[], None]):
        self._it = iterator
        self._on_last = on_last
        self._lookahead = None
        self._primed = False

    @property
    def in_flight(self) -> int:
        """Batches pulled from the underlying iterator but not yet yielded —
        checkpoint state surgery subtracts these (reference
        `data_loader.py:449` adjust_state_dict_for_prefetch)."""
        return 1 if self._lookahead is not None else 0

    def __iter__(self):
        return self

    def __next__(self):
        if not self._primed:
            self._lookahead = next(self._it)  # StopIteration propagates for empty loaders
            self._primed = True
        current = self._lookahead
        try:
            self._lookahead = next(self._it)
        except StopIteration:
            self._on_last()
            self._lookahead = None
            self._it = iter(())
            if current is None:
                raise
        if current is None:
            raise StopIteration
        return current


# counter keys a stateful loader snapshot uses for "already consumed", by UNIT:
# batch-unit keys (torchdata StatefulDataLoader's snapshot tree plus our own
# test fixtures) rewind by the in-flight batch count; sample-unit keys
# (sampler positions) rewind by in_flight × batch_size. Mixing the units would
# desync the sampler from the fetcher on resume.
_PREFETCH_BATCH_KEYS = frozenset(
    {"_snapshot_step", "_num_yielded", "_sampler_iter_yielded",
     "_num_batches_fetched", "num_batches_yielded"}
)
_PREFETCH_SAMPLE_KEYS = frozenset({"samples_yielded"})


def adjust_state_dict_for_prefetch(
    snapshot: Any, in_flight: int, batch_size: int | None = None
) -> Any:
    """Rewind every consumed-counter in a stateful loader's snapshot by the
    number of batches the prefetch chain has pulled ahead of the training step
    (reference `data_loader.py:449` ``adjust_state_dict_for_prefetch``). The
    walk is structural: nested mapping keys in the batch-unit set are
    decremented by ``in_flight``, sample-unit keys by
    ``in_flight * batch_size``, all clamped at 0, rest verbatim. When
    ``batch_size`` is unknown, sample-unit keys are left untouched and a
    warning explains the possible sampler desync."""
    sample_rewind = in_flight * batch_size if batch_size else None

    def _walk(node: Any) -> Any:
        if isinstance(node, Mapping):
            items = {}
            for k, v in node.items():
                if k in _PREFETCH_BATCH_KEYS and isinstance(v, int):
                    items[k] = max(v - in_flight, 0)
                elif k in _PREFETCH_SAMPLE_KEYS and isinstance(v, int):
                    if sample_rewind is None:
                        import warnings

                        warnings.warn(
                            f"stateful loader snapshot has sample-unit counter {k!r} "
                            "but the base loader exposes no batch_size; leaving it "
                            "unadjusted may desync the sampler by up to "
                            f"{in_flight} prefetched batch(es) on resume."
                        )
                        items[k] = v
                    else:
                        items[k] = max(v - sample_rewind, 0)
                else:
                    items[k] = _walk(v)
            try:
                return type(node)(items)
            except TypeError:  # Mapping subtypes w/o dict ctor (defaultdict, ...)
                return items
        if isinstance(node, (list, tuple)):
            walked = [_walk(v) for v in node]
            if hasattr(node, "_fields"):  # namedtuple: positional ctor
                return type(node)(*walked)
            return type(node)(walked)
        return node

    return _walk(snapshot)


class DataLoaderShard:
    """Per-process loader wrapper that yields *global, mesh-sharded* batches.

    Reference `data_loader.py:486-624` (+ the XLA `MpDeviceLoaderWrapper` role,
    `:627-677`, which JAX's async dispatch subsumes).
    """

    def __init__(
        self,
        base_loader: Iterable,
        device_placement: bool = True,
        mesh=None,
        rng_types: list[str] | None = None,
        synchronized_generator: SeedableRandomSampler | None = None,
        skip_batches: int = 0,
        total_dataset_length: int | None = None,
        total_batch_size: int | None = None,
        even_batches: bool = True,
        _drop_last: bool = False,
        prefetch: str = "none",
        prefetch_slot_bytes: int = 256 << 20,
    ):
        if prefetch not in ("none", "auto", "native"):
            raise ValueError(f"prefetch must be none|auto|native, got {prefetch!r}")
        self.prefetch = prefetch
        self.prefetch_slot_bytes = prefetch_slot_bytes
        self.base_loader = base_loader
        self.device_placement = device_placement
        self.mesh = mesh
        self.rng_types = rng_types
        self.synchronized_generator = synchronized_generator
        self.skip_batches = skip_batches
        self.total_dataset_length = total_dataset_length
        self._total_batch_size = total_batch_size
        self.even_batches = even_batches
        self._drop_last = _drop_last
        self.end_of_dataloader = False
        self.remainder = -1
        self.iteration = 0
        self.batches_seen_in_epoch = 0
        self.gradient_state = GradientState()
        if total_dataset_length is not None and total_batch_size:
            if not _drop_last and total_dataset_length % total_batch_size != 0:
                self.remainder = total_dataset_length % total_batch_size

    # ----------------------------------------------------------- properties
    @property
    def total_batch_size(self) -> int | None:
        return self._total_batch_size

    @property
    def dataset(self):
        return getattr(self.base_loader, "dataset", None)

    @property
    def batch_sampler(self):
        return getattr(self.base_loader, "batch_sampler", None)

    def set_epoch(self, epoch: int) -> None:
        self.iteration = epoch
        for obj in (self.batch_sampler, getattr(self.batch_sampler, "batch_sampler", None),
                    self.synchronized_generator, self.dataset):
            if obj is not None and hasattr(obj, "set_epoch"):
                obj.set_epoch(epoch)

    def __len__(self) -> int:
        return len(self.base_loader)

    # ------------------------------------------------------------- iteration
    def _data_sharding(self) -> NamedSharding:
        mesh = self.mesh if self.mesh is not None else AcceleratorState().mesh
        return NamedSharding(mesh, PartitionSpec(data_axes(mesh)))

    def _to_global(self, batch: Any) -> Any:
        """numpy/torch leaves -> one global jax.Array per leaf, sharded on the
        data axes. Pads a ragged leading dim by wrapping (static shapes for XLA).
        On the device-placement path, unregistered Mapping containers (HF
        BatchEncoding/UserDict) are normalized to plain dicts so the batch can
        cross the jit boundary; the host-only path keeps the user's container."""
        if not self.device_placement:
            return recursively_apply(_leaf_to_numpy, batch, test_type=_is_arraylike)
        batch = as_registered_pytree(batch)
        sharding = self._data_sharding()
        mesh = sharding.mesh
        shards = math.prod(mesh.shape[a] for a in data_axes(mesh))
        num_processes = PartialState().num_processes
        per_process_shards = max(shards // num_processes, 1)

        # A ragged final batch on ONE process is the whole global batch: record
        # how many samples are real so gather_for_metrics can drop the wrap
        # padding (sized datasets precompute this in __init__; iterables can't).
        if num_processes == 1 and self.end_of_dataloader and self.remainder < 0:
            bs = find_batch_size(batch)
            if bs is not None and bs % per_process_shards != 0:
                self.remainder = bs

        def _place(t):
            t = _leaf_to_numpy(t)
            if t.ndim >= 1 and t.shape[0] % per_process_shards != 0:
                target = math.ceil(t.shape[0] / per_process_shards) * per_process_shards
                reps = [t[i % t.shape[0]] for i in range(t.shape[0], target)]
                t = np.concatenate([t, np.stack(reps)], axis=0)
            if num_processes == 1:
                return jax.device_put(t, sharding)
            return jax.make_array_from_process_local_data(sharding, t)

        return recursively_apply(_place, batch, test_type=_is_arraylike)

    def __iter__(self):
        if self.rng_types is not None:
            synchronize_rng_states(self.rng_types)
        self.gradient_state._add_dataloader(self)
        self.end_of_dataloader = False
        self.batches_seen_in_epoch = 0
        try:
            def _mark_last():
                self.end_of_dataloader = True

            base_it = iter(self.base_loader)
            if self.prefetch in ("auto", "native"):
                # C++ staging ring: host batch assembly + aligned gather-copy of
                # batch i+1 overlap device compute on batch i (native/).
                # Wrapped INSIDE the lookahead iterator so end_of_dataloader
                # still flips exactly when the final batch is yielded.
                from .native import HostPrefetcher, is_native_available, native_unavailable_reason

                if is_native_available():
                    self._live_host_prefetcher = HostPrefetcher(
                        base_it, slot_bytes=self.prefetch_slot_bytes
                    )
                    base_it = iter(self._live_host_prefetcher)
                elif self.prefetch == "native":
                    raise RuntimeError(
                        f"prefetch='native' requested but {native_unavailable_reason()}"
                    )
            it = _PrefetchIterator(base_it, _mark_last)
            self._live_prefetch_it = it
            for idx, batch in enumerate(it):
                if idx < self.skip_batches:
                    continue
                self.batches_seen_in_epoch = idx + 1
                yield self._to_global(batch)
        finally:
            self.gradient_state._remove_dataloader(self)
            self.skip_batches = 0
            self._live_prefetch_it = None
            self._live_host_prefetcher = None

    def _in_flight_batches(self) -> int:
        """Batches the prefetch chain has consumed from ``base_loader`` beyond
        what this loader has yielded: the one-batch lookahead plus whatever the
        native staging ring holds."""
        n = 0
        if getattr(self, "_live_prefetch_it", None) is not None:
            n += self._live_prefetch_it.in_flight
        if getattr(self, "_live_host_prefetcher", None) is not None:
            n += self._live_host_prefetcher.in_flight
        return n

    # ----------------------------------------------------- checkpoint support
    def state_dict(self) -> dict[str, Any]:
        """Mid-epoch resumable state (reference StatefulDataLoader adapter,
        `data_loader.py:401-483`). When the wrapped loader is itself stateful
        (torchdata StatefulDataLoader), its snapshot — including worker /
        prefetched-batch state — is carried verbatim; the synchronized
        sampler's RNG state rides along so shuffling resumes identically."""
        state = {
            "iteration": self.iteration,
            "batches_seen_in_epoch": self.batches_seen_in_epoch,
            "end_of_dataloader": self.end_of_dataloader,
        }
        if hasattr(self.base_loader, "state_dict"):
            try:
                snapshot = self.base_loader.state_dict()
            except Exception:
                snapshot = None  # loader advertises state but can't produce it
            if snapshot is not None:
                # adjustment errors must propagate: swallowing them here would
                # silently drop the whole snapshot and restart the dataset
                in_flight = self._in_flight_batches()
                if in_flight:
                    snapshot = adjust_state_dict_for_prefetch(
                        snapshot, in_flight,
                        batch_size=getattr(self.base_loader, "batch_size", None),
                    )
                state["base_loader"] = snapshot
        sampler = self.synchronized_generator
        if sampler is not None and hasattr(sampler, "epoch"):
            state["sampler_epoch"] = sampler.epoch
        return state

    def load_state_dict(self, state: dict[str, Any]) -> None:
        self.iteration = state["iteration"]
        self.set_epoch(self.iteration)
        if "base_loader" in state and hasattr(self.base_loader, "load_state_dict"):
            try:
                self.base_loader.load_state_dict(state["base_loader"])
                return  # the base loader resumes mid-epoch itself: no re-skip
            except Exception:
                pass
        if "sampler_epoch" in state and self.synchronized_generator is not None:
            if hasattr(self.synchronized_generator, "set_epoch"):
                self.synchronized_generator.set_epoch(state["sampler_epoch"])
        if not state.get("end_of_dataloader", False):
            self.skip_batches = state.get("batches_seen_in_epoch", 0)


class DataLoaderDispatcher(DataLoaderShard):
    """Process-0-reads-everything mode (default for iterable datasets in the
    reference — `data_loader.py:680-908`): the main process fetches the full
    global batch and broadcasts it; every process slices its shard and the global
    array is assembled exactly as in `DataLoaderShard`.

    On TPU pods this trades DCN broadcast bandwidth for not needing a splittable
    dataset on every host — same trade the reference makes over NCCL.

    Ragged final batch: the reference completes it from ``first_batch`` under
    ``even_batches`` and yields uneven slices otherwise
    (`data_loader.py:812-850`). XLA shardings require equal shards, so here the
    batch is always completed (wrapping its own samples) and the real sample
    count is recorded in ``remainder`` — ``gather_for_metrics`` drops the
    duplicates, so metrics are dataset-exact either way and ``even_batches``
    has no separate meaning on this path.
    """

    def __iter__(self):
        state = PartialState()
        if state.num_processes == 1:
            yield from super().__iter__()
            return
        self.gradient_state._add_dataloader(self)
        self.end_of_dataloader = False
        try:
            if state.is_main_process:
                def _mark_last():
                    self.end_of_dataloader = True

                source = iter(self.base_loader)
                if self._drop_last:
                    # drop ONLY a trailing short batch, before the last-batch
                    # lookahead, so `last` lands on a batch that is actually
                    # yielded (the epoch-end sync boundary must be observed);
                    # mid-epoch size variation (bucketed samplers) passes through
                    def _full_only(it):
                        first_bs = None
                        prev = None
                        for b in it:
                            if prev is not None:
                                yield prev
                            if first_bs is None:
                                first_bs = find_batch_size(b)
                            prev = b
                        if prev is not None:
                            bs = find_batch_size(prev)
                            if not (bs is not None and first_bs is not None and bs < first_bs):
                                yield prev

                    source = _full_only(source)
                base_it = _PrefetchIterator(source, _mark_last)
            idx = 0
            while True:
                if state.is_main_process:
                    try:
                        batch = next(base_it)
                        payload = [
                            {
                                "stop": False,
                                "batch": recursively_apply(_leaf_to_numpy, batch, test_type=_is_arraylike),
                                "last": self.end_of_dataloader,
                            }
                        ]
                    except StopIteration:
                        payload = [{"stop": True}]
                else:
                    payload = [None]
                broadcast_object_list(payload, from_process=0)
                info = payload[0]
                if info["stop"]:
                    break
                self.end_of_dataloader = info["last"]
                # Slice this host's share of the global batch, completing a
                # ragged batch by wrapping so every process gets equal shapes.
                # The wrap target is aligned to per-process SHARD count too, so
                # downstream _to_global never pads mid-array — all padding sits
                # at the global tail and gather_for_metrics' [:remainder] is
                # exact.
                nproc = state.num_processes
                per_align = 1
                if self.device_placement:
                    mesh = self._data_sharding().mesh
                    shards = math.prod(mesh.shape[a] for a in data_axes(mesh))
                    per_align = max(shards // nproc, 1)
                bs = find_batch_size(info["batch"])
                per = max(-(-bs // nproc), 1) if bs else 0
                per = -(-per // per_align) * per_align
                if bs and per * nproc != bs:
                    if self.end_of_dataloader and self.remainder < 0:
                        self.remainder = bs
                    elif not self.end_of_dataloader and not getattr(self, "_warned_wrap", False):
                        import warnings

                        warnings.warn(
                            f"DataLoaderDispatcher: mid-epoch batch of {bs} samples "
                            f"wrapped to {per * nproc} to fill {nproc} process(es) x "
                            f"{per} per-process shard; the duplicates are NOT tracked "
                            "by gather_for_metrics (only the final batch's remainder "
                            "is). Use batch sizes divisible by the data-axis shard "
                            "count for exact metrics."
                        )
                        self._warned_wrap = True

                def _slice(t):
                    if t.shape[0] != per * nproc:
                        t = t[(np.arange(per * nproc) % t.shape[0])]
                    start = per * state.process_index
                    return t[start : start + per]

                local = recursively_apply(_slice, info["batch"], test_type=_is_arraylike)
                if idx >= self.skip_batches:
                    self.batches_seen_in_epoch = idx + 1
                    yield self._to_global(local)
                idx += 1
        finally:
            self.gradient_state._remove_dataloader(self)
            self.skip_batches = 0


# ------------------------------------------------------------------ factories
def _is_torch_loader(obj: Any) -> bool:
    return type(obj).__module__.startswith("torch.utils.data")


def prepare_data_loader(
    dataloader: Any,
    device_placement: bool = True,
    num_processes: int | None = None,
    process_index: int | None = None,
    split_batches: bool = False,
    put_on_device: bool = True,
    rng_types: list[str] | None = None,
    dispatch_batches: bool | None = None,
    even_batches: bool = True,
    use_seedable_sampler: bool = True,
    mesh=None,
    seed: int = 0,
) -> DataLoaderShard:
    """Shard a dataloader across processes and wrap it to emit global mesh-sharded
    arrays (reference `prepare_data_loader`, `data_loader.py:930-1179`).

    Accepts a torch `DataLoader` (rebuilt around `BatchSamplerShard`, preserving
    collate_fn/workers), or any iterable of batches (wrapped directly).
    """
    state = PartialState()
    num_processes = state.num_processes if num_processes is None else num_processes
    process_index = state.process_index if process_index is None else process_index

    synchronized_sampler: SeedableRandomSampler | None = None

    if _is_torch_loader(dataloader):
        import torch.utils.data as tud

        dataset = dataloader.dataset
        is_iterable = isinstance(dataset, tud.IterableDataset)
        if dispatch_batches is None:
            dispatch_batches = num_processes > 1 and is_iterable
        batch_size = dataloader.batch_size
        if batch_size is None and dataloader.batch_sampler is not None:
            batch_size = getattr(dataloader.batch_sampler, "batch_size", None)
        drop_last = getattr(dataloader, "drop_last", False)
        total_len = len(dataset) if hasattr(dataset, "__len__") else None

        common = dict(
            num_workers=dataloader.num_workers,
            collate_fn=dataloader.collate_fn,
            pin_memory=False,
            timeout=dataloader.timeout,
            worker_init_fn=dataloader.worker_init_fn,
        )

        if is_iterable:
            if num_processes > 1 and not dispatch_batches:
                dataset = IterableDatasetShard(
                    dataset,
                    batch_size=batch_size * num_processes if not split_batches else batch_size,
                    num_processes=num_processes,
                    process_index=process_index,
                    drop_last=drop_last,
                    split_batches=split_batches,
                    seed=seed,
                )
            new_loader = tud.DataLoader(dataset, batch_size=batch_size, drop_last=drop_last, **common)
        else:
            batch_sampler = dataloader.batch_sampler
            sampler = getattr(batch_sampler, "sampler", None)
            if use_seedable_sampler and isinstance(sampler, tud.RandomSampler):
                synchronized_sampler = SeedableRandomSampler(len(dataset), seed=seed)
                batch_sampler = tud.BatchSampler(
                    synchronized_sampler, batch_size=batch_size, drop_last=drop_last
                )
            if num_processes > 1:
                batch_sampler = BatchSamplerShard(
                    batch_sampler,
                    num_processes=num_processes,
                    process_index=process_index,
                    split_batches=split_batches,
                    even_batches=even_batches,
                )
            new_loader = tud.DataLoader(dataset, batch_sampler=batch_sampler, **common)

        per_host_batch = batch_size if (split_batches or num_processes == 1) else batch_size
        global_batch = batch_size if split_batches else (batch_size or 0) * num_processes
        cls = DataLoaderDispatcher if dispatch_batches else DataLoaderShard
        return cls(
            new_loader,
            device_placement=device_placement and put_on_device,
            mesh=mesh,
            rng_types=rng_types,
            synchronized_generator=synchronized_sampler,
            total_dataset_length=total_len,
            total_batch_size=global_batch or per_host_batch,
            even_batches=even_batches,
            _drop_last=drop_last,
        )

    # plain iterable of batches
    cls = DataLoaderDispatcher if dispatch_batches else DataLoaderShard
    return cls(
        dataloader,
        device_placement=device_placement and put_on_device,
        mesh=mesh,
        rng_types=rng_types,
        total_dataset_length=getattr(dataloader, "total_dataset_length", None),
        total_batch_size=getattr(dataloader, "total_batch_size", None),
        even_batches=even_batches,
    )


class SkipBatchSampler:
    """Wrap any batch sampler, skipping its first ``skip_batches`` batches
    (reference `SkipBatchSampler`, `data_loader.py:1221`): the sampler-level
    building block behind `skip_first_batches` for torch loaders whose
    sampler the caller manages directly."""

    def __init__(self, batch_sampler: Any, skip_batches: int = 0):
        self.batch_sampler = batch_sampler
        self.skip_batches = skip_batches
        # forward the nominal size so BatchSamplerShard keeps exact pad math
        self.batch_size = getattr(batch_sampler, "batch_size", None)
        self.drop_last = getattr(batch_sampler, "drop_last", False)

    def __iter__(self):
        for i, batch in enumerate(self.batch_sampler):
            if i >= self.skip_batches:
                yield batch

    @property
    def total_length(self) -> int:
        return len(self.batch_sampler)

    def __len__(self) -> int:
        return max(len(self.batch_sampler) - self.skip_batches, 0)


def get_sampler(dataloader: Any):
    """The index sampler driving a (possibly prepared/wrapped) dataloader
    (reference `get_sampler`, `data_loader.py:1199`)."""
    base = getattr(dataloader, "base_loader", dataloader)
    batch_sampler = getattr(base, "batch_sampler", None)
    while batch_sampler is not None and hasattr(batch_sampler, "batch_sampler"):
        batch_sampler = batch_sampler.batch_sampler  # unwrap shard/skip layers
    return getattr(batch_sampler, "sampler", getattr(base, "sampler", None))


def skip_first_batches(dataloader: Any, num_batches: int = 0) -> Any:
    """Resume mid-epoch by skipping the first ``num_batches`` batches
    (reference `data_loader.py:1245-1320`)."""
    if isinstance(dataloader, DataLoaderShard):
        dataloader.skip_batches = num_batches
        return dataloader
    return _SkipIterable(dataloader, num_batches)


class _SkipIterable:
    """Minimal skip wrapper for non-prepared iterables (reference `SkipDataLoader`)."""

    def __init__(self, base: Iterable, skip: int):
        self.base = base
        self.skip = skip

    def __iter__(self):
        for i, batch in enumerate(self.base):
            if i >= self.skip:
                yield batch

    def __len__(self):
        return max(len(self.base) - self.skip, 0)
