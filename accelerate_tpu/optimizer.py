"""Optimizer wrapper.

Capability parity: reference `src/accelerate/optimizer.py` (205 LoC) —
`AcceleratedOptimizer`: skip `step`/`zero_grad` while accumulating, fp16
skipped-step detection, device placement of optimizer state.

TPU-native re-founding: wraps an optax `GradientTransformation` instead of a torch
optimizer. Gradients arrive from `Accelerator.backward` already accumulated into a
buffer on this wrapper; `step()` runs one jitted, donated
``(params, opt_state, grads) -> (params, opt_state)`` update, sharded like the
params (ZeRO-style sharded optimizer state falls out of the params' shardings —
no hand-written partitioned update as in DeepSpeed).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

from .state import AcceleratorState, GradientState
from .utils.precision import DynamicGradScaler, GradScalerState


class AcceleratedOptimizer:
    def __init__(
        self,
        optimizer: optax.GradientTransformation,
        model: Any = None,
        scaler: DynamicGradScaler | None = None,
        opt_state_sharding: Any = None,
    ):
        if isinstance(optimizer, AcceleratedOptimizer):
            raise ValueError("Optimizer is already prepared.")
        self.optimizer = optimizer  # the optax transformation
        self.model = model  # PreparedModel holding the master params
        self.scaler = scaler
        self.scaler_state: GradScalerState | None = scaler.init() if scaler is not None else None
        self.gradient_state = GradientState()
        self.accelerator_state = AcceleratorState()
        self.opt_state = None
        self._opt_state_sharding = opt_state_sharding
        self._acc_grads = None  # accumulated gradient buffer (pytree like params)
        self._step_fn: Callable | None = None
        self._accumulate_fn: Callable | None = None
        self.step_was_skipped = False
        self._unscaled = False  # grads already unscaled this boundary
        self._num_updates = 0
        # fused-path fp16 bookkeeping: skipped boundaries accumulate as a lazy
        # device scalar so the hot loop never syncs; `num_updates` subtracts it
        self._skipped_updates = jnp.zeros((), jnp.int32)
        if model is not None:
            self._init_state()

    # ----------------------------------------------------------------- setup
    def attach_model(self, model: Any) -> None:
        self.model = model
        self._init_state()

    def _init_state(self) -> None:
        """Initialize optax state on-device; jit propagates the params' shardings
        into the param-shaped state leaves (mu/nu land sharded exactly like their
        params — the ZeRO property, for free)."""
        init = jax.jit(self.optimizer.init)
        self.opt_state = init(self.model.params)

    # ------------------------------------------------------- grad accumulation
    def _ensure_jits(self) -> None:
        if self._accumulate_fn is not None:
            return

        @jax.jit
        def _add(acc, grads):
            return jax.tree.map(jnp.add, acc, grads)

        def _apply(params, opt_state, grads):
            updates, new_opt_state = self.optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            return new_params, new_opt_state

        self._accumulate_fn = _add
        self._step_fn = jax.jit(_apply, donate_argnums=(0, 1))

    def accumulate_grads(self, grads: Any) -> None:
        """Add a (already 1/k-scaled) microbatch gradient into the buffer."""
        self._ensure_jits()
        if self._acc_grads is None:
            self._acc_grads = grads
        else:
            self._acc_grads = self._accumulate_fn(self._acc_grads, grads)

    @property
    def gradients(self) -> Any:
        return self._acc_grads

    @gradients.setter
    def gradients(self, value: Any) -> None:
        self._acc_grads = value

    # ------------------------------------------------------------------ torch-y API
    def zero_grad(self, set_to_none: bool = True) -> None:
        """Clear the accumulation buffer — a no-op while accumulating
        (reference `optimizer.py:111-121`)."""
        if self.gradient_state.sync_gradients:
            self._acc_grads = None

    def step(self, closure: Callable | None = None) -> None:
        """Apply the buffered gradient — a no-op while accumulating
        (reference `optimizer.py:154`). With fp16, unscale first and skip the
        update entirely on overflow (reference `:154-169`)."""
        if not self.gradient_state.sync_gradients:
            self.step_was_skipped = False
            return
        if self._acc_grads is None:
            raise RuntimeError("optimizer.step() called with no gradients; call accelerator.backward first.")
        self._ensure_jits()
        grads = self._acc_grads
        if self.scaler is not None:
            if self._unscaled:
                # explicit accelerator.unscale_gradients() already ran this
                # boundary (it set step_was_skipped on overflow); don't divide
                # by the scale a second time
                finite = not self.step_was_skipped
            else:
                grads, self.scaler_state, finite = self.scaler.unscale_and_update(
                    grads, self.scaler_state
                )
            self._unscaled = False
            if not bool(finite):
                self.step_was_skipped = True
                self._acc_grads = None
                return
        new_params, self.opt_state = self._step_fn(self.model.params, self.opt_state, grads)
        self.model.params = new_params
        self.step_was_skipped = False
        self._num_updates += 1

    # ------------------------------------------------------------- inspection
    @property
    def num_updates(self) -> int:
        """APPLIED updates (skipped fp16 boundaries excluded, both paths)."""
        return self._num_updates - int(self._skipped_updates)

    @property
    def learning_rate(self) -> float | None:
        """Current LR if the optax state exposes one (inject_hyperparams or
        scale_by_schedule patterns)."""
        def _find(state):
            if hasattr(state, "hyperparams") and "learning_rate" in state.hyperparams:
                return float(state.hyperparams["learning_rate"])
            return None

        for leaf in jax.tree.leaves(self.opt_state, is_leaf=lambda x: hasattr(x, "hyperparams")):
            lr = _find(leaf)
            if lr is not None:
                return lr
        return None

    # ------------------------------------------------------------ checkpointing
    def state_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "opt_state": self.opt_state,
            "num_updates": self._num_updates,
            "skipped_updates": int(self._skipped_updates),
        }
        if self.scaler_state is not None:
            out["scaler_state"] = self.scaler_state
        return out

    def load_state_dict(self, state: dict[str, Any]) -> None:
        self.opt_state = state["opt_state"]
        self._num_updates = int(state.get("num_updates", 0))
        self._skipped_updates = jnp.asarray(int(state.get("skipped_updates", 0)), jnp.int32)
        if "scaler_state" in state and self.scaler is not None:
            self.scaler_state = GradScalerState(*state["scaler_state"])
