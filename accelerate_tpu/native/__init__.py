"""Native runtime components (C++), with pure-Python fallbacks.

The reference's host-side data path rides torch's native machinery (worker
processes, pinned-memory copies — reference `data_loader.py:550-573` prefetch and
`MpDeviceLoaderWrapper`'s background loader threads). This package provides the
TPU-native equivalent as an in-tree C++ component: `prefetch_ring.cpp`, a
background gather-copy ring of 64-byte-aligned host staging buffers driven from
`HostPrefetcher` (host_prefetcher.py) and `DataLoaderShard(prefetch=...)`.

The shared library builds on first use with g++ (cached next to the source);
every consumer degrades gracefully to the Python path when no toolchain is
available, so the framework never hard-depends on the native build.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

_HERE = Path(__file__).resolve().parent
_SRC = _HERE / "prefetch_ring.cpp"
_LIB = _HERE / "libprefetch_ring.so"
_BUILD_LOCK = threading.Lock()
_LOAD_FAILURE: str | None = None
_lib: ctypes.CDLL | None = None


def _build() -> bool:
    # compile to a process-unique temp path, then rename atomically: concurrent
    # processes (multi-host launch, parallel tests) must never dlopen a
    # partially-written .so
    tmp = _LIB.with_suffix(f".so.tmp{os.getpid()}")
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        str(_SRC), "-o", str(tmp),
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        globals()["_LOAD_FAILURE"] = f"g++ unavailable: {e}"
        return False
    if proc.returncode != 0:
        globals()["_LOAD_FAILURE"] = f"native build failed: {proc.stderr[-500:]}"
        tmp.unlink(missing_ok=True)
        return False
    os.replace(tmp, _LIB)
    return True


def _load() -> ctypes.CDLL | None:
    global _lib, _LOAD_FAILURE
    if _lib is not None:
        return _lib
    if os.environ.get("ACCELERATE_TPU_DISABLE_NATIVE", "") not in ("", "0", "false"):
        _LOAD_FAILURE = "disabled via ACCELERATE_TPU_DISABLE_NATIVE"
        return None
    with _BUILD_LOCK:
        if _lib is not None:
            return _lib
        if _LOAD_FAILURE is not None:
            return None
        if not _LIB.exists() or _LIB.stat().st_mtime < _SRC.stat().st_mtime:
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(str(_LIB))
        except OSError as e:
            _LOAD_FAILURE = f"dlopen failed: {e}"
            return None
        lib.ring_create.restype = ctypes.c_void_p
        lib.ring_create.argtypes = [ctypes.c_int, ctypes.c_size_t]
        lib.ring_push_batch.restype = ctypes.c_long
        lib.ring_push_batch.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_size_t),
            ctypes.c_int,
        ]
        lib.ring_pop.restype = ctypes.c_void_p
        lib.ring_pop.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_size_t),
            ctypes.POINTER(ctypes.c_long),
        ]
        lib.ring_release.argtypes = [ctypes.c_void_p]
        lib.ring_stop.argtypes = [ctypes.c_void_p]
        lib.ring_completed.restype = ctypes.c_long
        lib.ring_completed.argtypes = [ctypes.c_void_p]
        lib.ring_destroy.argtypes = [ctypes.c_void_p]
        lib.ring_alignment.restype = ctypes.c_size_t
        _lib = lib
        return _lib


def is_native_available() -> bool:
    """True when the C++ prefetch ring built (or was already built) and loads."""
    return _load() is not None


def native_unavailable_reason() -> str | None:
    _load()
    return _LOAD_FAILURE


class PrefetchRing:
    """ctypes wrapper over one native ring (see prefetch_ring.cpp).

    ``push(arrays)`` enqueues an async gather-copy of the numpy arrays into one
    aligned slot and returns a job id; the caller must keep the sources alive
    until ``completed() > job_id``. ``pop()`` blocks for the oldest ready slot
    and returns 64-byte-aligned numpy views into it (zero-copy); ``release()``
    recycles the oldest popped slot once its views are dead.
    """

    def __init__(self, n_slots: int, slot_bytes: int):
        import numpy as np  # local: keep module import light

        self._np = np
        self._inflight: dict = {}
        self._inflight_mu = threading.Lock()
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native prefetch ring unavailable: {_LOAD_FAILURE}")
        self._lib = lib
        self._align = int(lib.ring_alignment())
        self._h = lib.ring_create(ctypes.c_int(n_slots), ctypes.c_size_t(slot_bytes))
        if not self._h:
            raise MemoryError("ring_create failed")
        self.slot_bytes = slot_bytes

    def push(self, arrays) -> int:
        np = self._np
        arrs = [np.ascontiguousarray(a) for a in arrays]
        n = len(arrs)
        srcs = (ctypes.c_void_p * n)(
            *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrs]
        )
        sizes = (ctypes.c_size_t * n)(*[a.nbytes for a in arrs])
        job = int(self._lib.ring_push_batch(self._h, srcs, sizes, ctypes.c_int(n)))
        if job == -1:
            raise ValueError(
                f"batch of {sum(a.nbytes for a in arrs)}B (aligned) exceeds slot "
                f"capacity {self.slot_bytes}B"
            )
        if job < 0:
            raise RuntimeError("ring is shutting down")
        # the ctypes arrays and arrs must outlive the async copy; the lock is
        # needed because push runs on the producer thread and _gc_inflight on
        # the consumer thread
        with self._inflight_mu:
            self._inflight[job] = (arrs, srcs, sizes)
        return job

    def _gc_inflight(self):
        done = int(self._lib.ring_completed(self._h))
        with self._inflight_mu:
            for job in [j for j in self._inflight if j < done]:
                del self._inflight[job]

    def pop(self, specs, copy: bool = True):
        """Blocking pop; ``specs`` is [(shape, dtype), ...] matching the pushed
        arrays. Returns (arrays, job_id).

        ``copy=True`` (default) returns owning arrays — always safe. With
        ``copy=False`` the arrays are zero-copy views into the slot, valid ONLY
        until the slot's `release()` (and never after `close()`); use it only
        when the consumer finishes with the data before releasing.
        """
        np = self._np
        nbytes = ctypes.c_size_t(0)
        job_id = ctypes.c_long(0)
        base = self._lib.ring_pop(self._h, ctypes.byref(nbytes), ctypes.byref(job_id))
        if not base:
            raise RuntimeError("ring is shutting down")
        self._gc_inflight()
        views = []
        off = 0
        for shape, dtype in specs:
            dt = np.dtype(dtype)
            count = int(np.prod(shape)) if len(shape) else 1
            seg = count * dt.itemsize
            buf = (ctypes.c_char * seg).from_address(base + off)
            v = np.frombuffer(buf, dtype=dt).reshape(shape)
            views.append(v.copy() if copy else v)
            off += -(-seg // self._align) * self._align
        return views, int(job_id.value)

    def release(self) -> None:
        self._lib.ring_release(self._h)

    def completed(self) -> int:
        return int(self._lib.ring_completed(self._h))

    def stop(self) -> None:
        """Unblock every thread waiting inside a ring call (push/pop return
        'shutting down'); the ring stays allocated until close()."""
        if getattr(self, "_h", None):
            self._lib.ring_stop(self._h)

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.ring_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


from .host_prefetcher import HostPrefetcher  # noqa: E402  (uses _load lazily)
