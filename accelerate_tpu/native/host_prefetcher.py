"""Batch prefetcher over the native staging ring.

A producer thread drains the base iterator (Python-side batch assembly) and
pushes each batch's leaves into the C++ ring, whose worker gather-copies them
into one aligned staging slot; the consumer pops slots FIFO and yields the batch
reconstructed as zero-copy views. Net effect: host batch assembly AND the
staging copy of batch i+1 overlap device compute on batch i — the reference gets
this from torch DataLoader workers + pinned-memory prefetch (reference
`data_loader.py:550-573`).

Popped batches are materialized as owning arrays (one fast memcpy out of the
aligned slot) and the slot recycles immediately — yielded batches have normal
numpy lifetimes, safe to hold past the iterator (JAX's async H2D may read them
any time later).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Iterable, Iterator

import jax
import numpy as np


def _flatten(batch: Any):
    """Pytree of arraylikes -> (numpy leaves, rebuild) via jax.tree. Returns
    (None, None) when any leaf is not a plain numeric/bool buffer (object
    dtypes hold PyObject pointers — memcpy'ing those would be garbage)."""
    raw, treedef = jax.tree.flatten(batch)
    leaves = []
    for leaf in raw:
        arr = np.asarray(leaf)
        if arr.dtype.hasobject:
            return None, None
        leaves.append(arr)
    return leaves, treedef.unflatten


class HostPrefetcher:
    """Iterate ``base`` through the native staging ring (see module docstring).

    Falls back to plain iteration when the native library is unavailable or a
    batch exceeds ``slot_bytes`` — identical output either way.
    """

    def __init__(
        self,
        base: Iterable,
        depth: int = 3,
        slot_bytes: int = 256 << 20,
    ):
        self.base = base
        self.depth = max(depth, 2)
        self.slot_bytes = slot_bytes
        self._consumed = 0  # producer thread: batches pulled from base
        self._yielded = 0  # consumer side: batches handed out

    @property
    def in_flight(self) -> int:
        """Batches staged in the ring but not yet yielded. Read between steps
        for checkpoint state surgery; the producer advances concurrently, so
        callers snapshot the base loader state BEFORE reading this (a late
        increment then only over-rewinds, replaying a batch rather than
        skipping one)."""
        return max(self._consumed - self._yielded, 0)

    def __iter__(self) -> Iterator[Any]:
        from . import PrefetchRing, is_native_available

        if not is_native_available():
            for batch in self.base:
                self._consumed += 1
                self._yielded += 1
                yield batch
            return

        ring = PrefetchRing(self.depth, self.slot_bytes)
        # bounded: bypass batches skip ring.push (the ring's own backpressure),
        # so without a maxsize a dataset of non-stageable batches would be
        # drained wholesale into memory ahead of the consumer
        meta: "queue.Queue" = queue.Queue(maxsize=self.depth + 2)
        _SENTINEL = object()
        error: list[BaseException] = []

        def producer():
            try:
                it = iter(self.base)
                while True:
                    # count BEFORE pulling: a preemption between the base
                    # loader advancing and the counter would otherwise
                    # under-count in_flight and make a concurrent checkpoint
                    # resume one batch too far (silent skip); over-counting
                    # merely replays a batch
                    self._consumed += 1
                    try:
                        batch = next(it)
                    except StopIteration:
                        self._consumed -= 1
                        break
                    leaves, rebuild = _flatten(batch)
                    if leaves is None:  # non-numeric leaves: not stageable
                        meta.put(("bypass", batch, None))
                        continue
                    total = sum(-(-a.nbytes // 64) * 64 for a in leaves)
                    if total > self.slot_bytes:
                        meta.put(("bypass", batch, None))
                        continue
                    ring.push(leaves)  # blocks when the ring is full
                    meta.put(("slot", [(a.shape, a.dtype) for a in leaves], rebuild))
            except BaseException as e:  # surface in the consumer
                error.append(e)
            finally:
                meta.put((_SENTINEL, None, None))

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                kind, payload, rebuild = meta.get()
                if kind is _SENTINEL:
                    break
                if kind == "bypass":
                    self._yielded += 1
                    yield payload
                    continue
                arrays, _ = ring.pop(payload, copy=True)
                ring.release()  # owning copies made; recycle the slot now
                self._yielded += 1
                yield rebuild(arrays)
            if error:
                raise error[0]
        finally:
            # stop first: the producer may be blocked inside ring_push_batch, and
            # destroying the ring under it would be a use-after-free
            ring.stop()
            # the producer may also be blocked on the bounded meta queue
            # (early consumer exit): drain until it can finish
            deadline = time.monotonic() + 5
            while t.is_alive() and time.monotonic() < deadline:
                try:
                    meta.get_nowait()
                except queue.Empty:
                    time.sleep(0.01)
            t.join(timeout=5)
            if t.is_alive():
                ring._h = None  # leak rather than free under a live thread
            else:
                ring.close()
