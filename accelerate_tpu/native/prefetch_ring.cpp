// Host-side prefetch ring: background gather-copy of batch buffers into
// 64-byte-aligned staging slots.
//
// Role in the framework (see native/__init__.py): the reference overlaps host
// batch preparation with device compute through torch DataLoader worker
// processes + pinned-memory copies (reference `data_loader.py:550-573` prefetch,
// `MpDeviceLoaderWrapper` background threads). Here the copy path is native: a
// worker thread drains a job queue, memcpy-gathers each batch's leaves into one
// contiguous aligned slot (releasing the Python GIL for the whole copy), and
// hands ready slots to the consumer FIFO. Alignment matters for the downstream
// host->device DMA and lets the CPU backend ingest buffers zero-copy.
//
// States per slot: FREE -> QUEUED -> READY -> POPPED -> FREE. Push blocks when
// every slot is in flight (backpressure = bounded prefetch depth). All calls are
// thread-safe; one consumer and any number of producers.

#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace {

constexpr size_t kAlign = 64;

enum SlotState : int { FREE = 0, QUEUED = 1, READY = 2, POPPED = 3 };

struct Segment {
  const void* src;
  size_t nbytes;
};

struct Job {
  int slot;
  long id;
  std::vector<Segment> segs;
};

struct Slot {
  uint8_t* buf = nullptr;
  size_t capacity = 0;
  size_t used = 0;
  long job_id = -1;
  int state = FREE;
};

struct Ring {
  std::vector<Slot> slots;
  std::queue<Job> jobs;
  std::queue<int> ready;
  std::queue<int> popped;
  std::mutex mu;
  std::condition_variable cv_job;    // worker waits for jobs
  std::condition_variable cv_ready;  // consumer waits for ready slots
  std::condition_variable cv_free;   // producer waits for a free slot
  std::thread worker;
  bool stopping = false;
  long next_job_id = 0;
  long completed = 0;  // jobs whose source buffers are no longer needed

  void run() {
    for (;;) {
      Job job;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_job.wait(lk, [&] { return stopping || !jobs.empty(); });
        if (stopping && jobs.empty()) return;
        job = std::move(jobs.front());
        jobs.pop();
      }
      Slot& slot = slots[job.slot];
      uint8_t* dst = slot.buf;
      size_t off = 0;
      for (const Segment& s : job.segs) {
        std::memcpy(dst + off, s.src, s.nbytes);
        // next segment starts at the next 64-byte boundary
        off += (s.nbytes + kAlign - 1) / kAlign * kAlign;
      }
      {
        std::lock_guard<std::mutex> lk(mu);
        slot.used = off;
        slot.job_id = job.id;
        slot.state = READY;
        ready.push(job.slot);
        completed = job.id + 1;
      }
      cv_ready.notify_all();
    }
  }
};

size_t aligned_total(const size_t* sizes, int count) {
  size_t total = 0;
  for (int i = 0; i < count; ++i) {
    total += (sizes[i] + kAlign - 1) / kAlign * kAlign;
  }
  return total;
}

}  // namespace

extern "C" {

void* ring_create(int n_slots, size_t slot_bytes) {
  if (n_slots < 1) return nullptr;
  Ring* r = new Ring();
  r->slots.resize(n_slots);
  for (Slot& s : r->slots) {
    s.capacity = slot_bytes;
    s.buf = static_cast<uint8_t*>(
        std::aligned_alloc(kAlign, (slot_bytes + kAlign - 1) / kAlign * kAlign));
    if (s.buf == nullptr) {
      for (Slot& t : r->slots) std::free(t.buf);
      delete r;
      return nullptr;
    }
  }
  r->worker = std::thread([r] { r->run(); });
  return r;
}

// Enqueue an async gather-copy of `count` segments into one slot. Returns the
// job id (>= 0), or -1 if the segments exceed the slot capacity. Source buffers
// must stay valid until ring_completed() > job id. Blocks while all slots are
// in flight.
long ring_push_batch(void* h, const void** srcs, const size_t* sizes, int count) {
  Ring* r = static_cast<Ring*>(h);
  if (aligned_total(sizes, count) > r->slots[0].capacity) return -1;
  Job job;
  job.segs.reserve(count);
  for (int i = 0; i < count; ++i) job.segs.push_back({srcs[i], sizes[i]});
  int slot_idx = -1;
  long job_id = -1;
  {
    std::unique_lock<std::mutex> lk(r->mu);
    r->cv_free.wait(lk, [&] {
      if (r->stopping) return true;
      for (size_t i = 0; i < r->slots.size(); ++i) {
        if (r->slots[i].state == FREE) {
          slot_idx = static_cast<int>(i);
          return true;
        }
      }
      return false;
    });
    if (r->stopping || slot_idx < 0) return -2;
    r->slots[slot_idx].state = QUEUED;
    job.slot = slot_idx;
    job.id = job_id = r->next_job_id++;
    r->jobs.push(std::move(job));
  }
  r->cv_job.notify_one();
  // job_id was captured under the lock: reading next_job_id here would race
  // with concurrent producers and return another producer's id
  return job_id;
}

// Block until a slot is ready; returns its base pointer and byte count. Slots
// come out in push order (FIFO). Returns nullptr if the ring is stopping.
const void* ring_pop(void* h, size_t* out_nbytes, long* out_job_id) {
  Ring* r = static_cast<Ring*>(h);
  std::unique_lock<std::mutex> lk(r->mu);
  r->cv_ready.wait(lk, [&] { return r->stopping || !r->ready.empty(); });
  if (r->ready.empty()) return nullptr;
  int idx = r->ready.front();
  r->ready.pop();
  Slot& s = r->slots[idx];
  s.state = POPPED;
  r->popped.push(idx);
  if (out_nbytes) *out_nbytes = s.used;
  if (out_job_id) *out_job_id = s.job_id;
  return s.buf;
}

// Free the oldest popped slot for reuse. The consumer must be done with every
// view into it.
void ring_release(void* h) {
  Ring* r = static_cast<Ring*>(h);
  {
    std::lock_guard<std::mutex> lk(r->mu);
    if (r->popped.empty()) return;
    int idx = r->popped.front();
    r->popped.pop();
    r->slots[idx].state = FREE;
  }
  r->cv_free.notify_one();
}

// Number of completed copy jobs: sources of jobs with id < this are reusable.
long ring_completed(void* h) {
  Ring* r = static_cast<Ring*>(h);
  std::lock_guard<std::mutex> lk(r->mu);
  return r->completed;
}

// Wake every blocked producer/consumer with a "shutting down" result WITHOUT
// freeing the ring. Call this, join any threads still inside ring_* calls, then
// ring_destroy — destroying while a call is blocked is a use-after-free.
void ring_stop(void* h) {
  Ring* r = static_cast<Ring*>(h);
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->stopping = true;
  }
  r->cv_job.notify_all();
  r->cv_ready.notify_all();
  r->cv_free.notify_all();
}

void ring_destroy(void* h) {
  Ring* r = static_cast<Ring*>(h);
  ring_stop(h);
  if (r->worker.joinable()) r->worker.join();
  for (Slot& s : r->slots) std::free(s.buf);
  delete r;
}

size_t ring_alignment() { return kAlign; }

}  // extern "C"
