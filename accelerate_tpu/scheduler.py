"""Learning-rate scheduler wrapper.

Capability parity: reference `src/accelerate/scheduler.py` (98 LoC) —
`AcceleratedScheduler` steps the wrapped scheduler only when gradients actually
synced, and not when the fp16 optimizer skipped its step.

TPU-native note: with one jitted SPMD step consuming the *global* batch, one
optimizer update corresponds to one scheduler step (the reference's
"step num_processes times" compensation exists only because its per-rank loops
each see 1/P of the data; that situation cannot arise here — equivalent to the
reference with ``split_batches=True``).

Works with (a) `OptaxSchedule` below, (b) any object exposing ``step()`` (torch
LR schedulers duck-type). optax optimizers whose transformation embeds a schedule
advance automatically with each update and need no wrapper at all.
"""

from __future__ import annotations

from typing import Any, Callable

from .state import GradientState


class OptaxSchedule:
    """Adapter giving an optax schedule function a torch-scheduler-shaped API
    (``step()`` / ``get_last_lr()`` / ``state_dict()``)."""

    def __init__(self, schedule_fn: Callable[[int], float]):
        self.schedule_fn = schedule_fn
        self.count = 0

    def step(self) -> None:
        self.count += 1

    def get_last_lr(self) -> list[float]:
        return [float(self.schedule_fn(self.count))]

    def state_dict(self) -> dict[str, Any]:
        return {"count": self.count}

    def load_state_dict(self, state: dict[str, Any]) -> None:
        self.count = int(state["count"])


class AcceleratedScheduler:
    def __init__(
        self,
        scheduler: Any,
        optimizers: list | None = None,
        step_with_optimizer: bool = True,
        split_batches: bool = False,
    ):
        self.scheduler = scheduler
        self.optimizers = optimizers or []
        self.step_with_optimizer = step_with_optimizer
        self.split_batches = split_batches
        self.gradient_state = GradientState()

    def step(self, *args: Any, **kwargs: Any) -> None:
        if not self.step_with_optimizer:
            self.scheduler.step(*args, **kwargs)
            return
        if not self.gradient_state.sync_gradients:
            return
        # don't advance past a skipped (overflowed) fp16 step — reference `scheduler.py:54-82`
        if any(getattr(opt, "step_was_skipped", False) for opt in self.optimizers):
            return
        self.scheduler.step(*args, **kwargs)

    def get_last_lr(self) -> list[float]:
        return self.scheduler.get_last_lr()

    def state_dict(self) -> dict[str, Any]:
        return self.scheduler.state_dict()

    def load_state_dict(self, state: dict[str, Any]) -> None:
        self.scheduler.load_state_dict(state)

    def __getattr__(self, name: str) -> Any:
        return getattr(self.scheduler, name)
