"""accelerate-tpu: TPU-native training orchestration with the HF Accelerate
capability surface, re-founded on JAX/XLA (see SURVEY.md for the mapping).

Public API parity: reference `src/accelerate/__init__.py`.
"""

__version__ = "0.1.0"

from .accelerator import (
    Accelerator,
    BoundModel,
    GradientAccumulationPlugin,
    PreparedModel,
    ProjectConfiguration,
)
from .data_loader import (
    BatchSamplerShard,
    DataLoaderDispatcher,
    DataLoaderShard,
    IterableDatasetShard,
    SeedableRandomSampler,
    SkipBatchSampler,
    get_sampler,
    prepare_data_loader,
    skip_first_batches,
)
from .inference import prepare_pippy
from .launchers import debug_launcher, notebook_launcher
from .logging import get_logger
from .memory import find_executable_batch_size, release_memory
from .optimizer import AcceleratedOptimizer
from .parallel.mesh import ParallelismConfig, build_mesh
from .parallel.pipeline import pipeline_apply, stack_stage_params
from .parallel.ring_attention import ring_attention, ring_attention_sharded
from .parallel.sharding import ShardingRules, infer_param_shardings
from .reliability import (
    FaultInjector,
    FaultSpec,
    PreemptionHandler,
    RetryError,
    RetryPolicy,
    install_preemption_handler,
)
from .scheduler import AcceleratedScheduler, OptaxSchedule
from .serving import (
    FIFOScheduler,
    Request,
    RequestOutput,
    SamplingParams,
    ServingEngine,
    ServingMetrics,
)
from .state import AcceleratorState, DistributedType, GradientState, PartialState
from .utils.operations import (
    broadcast,
    broadcast_object_list,
    concatenate,
    gather,
    gather_object,
    pad_across_processes,
    reduce,
    send_to_device,
)
from .ops.fp8 import DelayedScalingRecipe, Fp8Dense, adamw_fp8
from .utils.precision import DynamicGradScaler, PrecisionPolicy
from .utils.quantization import (
    QuantizationConfig,
    QuantizedTensor,
    dequantize_params,
    load_and_quantize_model,
    quantize_model,
    quantize_params,
)
from .parallel.compression import CommHookConfig, DDPCommunicationHookType
from .big_modeling import (
    BlockwiseModel,
    cpu_offload,
    cpu_offload_with_hook,
    disk_offload,
    dispatch_model,
    infer_auto_device_map,
    init_empty_weights,
    init_on_device,
    load_checkpoint_and_dispatch,
)
from .utils.imports import is_rich_available

if is_rich_available():  # optional extra: keep base import rich-free
    from .utils import rich
from .utils.deepspeed import DummyOptim, DummyScheduler
from .utils.dataclasses import (
    AutocastKwargs,
    DataLoaderConfiguration,
    DeepSpeedPlugin,
    DistributedDataParallelKwargs,
    FP8RecipeKwargs,
    FullyShardedDataParallelPlugin,
    GradScalerKwargs,
    InitProcessGroupKwargs,
    MegatronLMPlugin,
    ProfileKwargs,
)
from .utils.operations import ConvertOutputsToFp32, convert_outputs_to_fp32
from .utils.other import (
    convert_bytes,
    extract_model_from_parallel,
    get_pretty_name,
    load,
    save,
)
from .commands.config import write_basic_config
from .utils.random import set_seed, synchronize_rng_states
from .utils.safetensors_io import (
    load_checkpoint_in_model,
    load_safetensors_checkpoint,
    save_safetensors_checkpoint,
)
