"""Pipeline-parallel inference: the `prepare_pippy` capability.

Capability parity: reference `src/accelerate/inference.py` (184 LoC) — PiPPy /
`torch.distributed.pipelining`: auto split points weighted by module sizes
(`inference.py:31-55`), `ScheduleGPipe` microbatching (`:73-96`), rank-0 feeds /
last rank returns / output broadcast (`:99-121`, `operations.py:525`).

TPU-native re-founding: no per-rank send/recv program. The model's uniform trunk
blocks are grouped into contiguous stages; each stage's params are stacked on a
leading ``stage`` dim and the GPipe schedule runs as one SPMD program
(`parallel/pipeline.pipeline_apply` — `lax.ppermute` activation handoff inside
`shard_map`). The prologue (embedding) and epilogue (head) are tiny next to the
trunk and run replicated on every device, which also realizes the reference's
"broadcast the last stage's output to all ranks" step for free: every device
finishes with the full logits.

Serving: for request-level (rather than batch-level) inference, the
continuous-batching engine lives in `serving/` — `ServingEngine` (re-exported
here) multiplexes independent requests through one jitted decode step over a
fixed pool of KV-cache slots. See `docs/serving.md`.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .big_modeling import BlockwiseModel
from .parallel.pipeline import pipeline_apply
from .serving import ServingEngine  # noqa: F401  (re-export: serving entry point)
from .state import PartialState


def _trunk_split(names: Sequence[str], num_stages: int, split_points) -> list[list[str]]:
    """Group the uniform trunk blocks into contiguous, equal-sized stages.

    ``split_points="auto"`` mirrors the reference's size-weighted auto split
    (`inference.py:31-55`); trunk blocks are homogeneous so balanced == equal.
    An explicit list of block names marks the first block of stages 1..S-1, as
    the reference accepts explicit module-name split points.
    """
    n = len(names)
    if split_points == "auto":
        if n % num_stages:
            raise ValueError(
                f"{n} trunk blocks cannot split evenly into {num_stages} pipeline "
                f"stages; pick num_stages dividing {n} or pass explicit split_points."
            )
        per = n // num_stages
        return [list(names[i * per : (i + 1) * per]) for i in range(num_stages)]
    unknown = [p for p in split_points if p not in names]
    if unknown:
        raise ValueError(
            f"split_points {unknown} are not trunk blocks; valid split points "
            f"are {list(names)} (the prologue/epilogue cannot start a stage)."
        )
    bounds = [0] + [names.index(p) for p in split_points] + [n]
    groups = [list(names[a:b]) for a, b in zip(bounds[:-1], bounds[1:])]
    sizes = {len(g) for g in groups}
    if len(groups) != num_stages or len(sizes) != 1:
        raise ValueError(
            f"split_points {split_points} produce stage sizes "
            f"{[len(g) for g in groups]}; the SPMD pipeline needs {num_stages} "
            "equal stages (every device runs the same stage program)."
        )
    return groups


def prepare_pippy(
    model: BlockwiseModel,
    state_dict: dict[str, Any],
    mesh=None,
    num_microbatches: int | None = None,
    split_points: str | Sequence[str] = "auto",
    gather_output: bool = True,  # parity kwarg: outputs are always replicated
    axis_name: str = "stage",
) -> Callable:
    """Turn a blockwise model into a pipeline-parallel forward callable.

    ``model`` is a `BlockwiseModel` decomposition (prologue, uniform trunk
    blocks, epilogue — e.g. `models.gpt2.gpt2_blockwise`), ``state_dict`` its
    per-block params (e.g. `gpt2_blockwise_state_dict`). Returns
    ``forward(x) -> y`` running prologue -> staged GPipe trunk -> epilogue under
    one jit. Microbatch count defaults to the stage count (the reference's
    ``num_chunks`` defaults to the process count, `inference.py:124-160`).
    """
    if mesh is None:
        mesh = PartialState().mesh
    num_stages = mesh.shape.get(axis_name, 1)
    if num_stages <= 1:
        raise ValueError(
            f"prepare_pippy needs a mesh with a non-trivial '{axis_name}' axis; "
            "got stage size 1. Configure ParallelismConfig(stage_size=N)."
        )
    num_microbatches = num_microbatches or num_stages

    names = [n for n, _ in model.block_fns]
    fns = dict(model.block_fns)
    prologue_name, epilogue_name = names[0], names[-1]
    trunk = names[1:-1]
    if not trunk:
        raise ValueError("BlockwiseModel needs at least one trunk block between "
                         "prologue and epilogue to pipeline.")
    groups = _trunk_split(trunk, num_stages, split_points)
    per_stage = len(groups[0])
    block_fn = fns[trunk[0]]  # trunk blocks are uniform: one program, many params

    # params: stack trunk blocks on host -> (S, per, ...) -> place sharded over
    # the stage axis directly, so no single device ever holds the whole trunk
    # (each stage's slice streams to its own devices)
    trunk_trees = [state_dict[n] for g in groups for n in g]
    stage_sharding = NamedSharding(mesh, P(axis_name))

    def _stack_and_place(*leaves):
        host = np.stack([np.asarray(l) for l in leaves])
        host = host.reshape(num_stages, per_stage, *host.shape[1:])
        return jax.device_put(host, stage_sharding)

    stage_params = jax.tree.map(_stack_and_place, *trunk_trees)
    prologue_params = state_dict[prologue_name]
    epilogue_params = state_dict[epilogue_name]

    def stage_fn(sp, x):
        # one pipeline stage = scan over its slice of trunk blocks
        def body(h, lp):
            return block_fn(lp, h), None

        y, _ = jax.lax.scan(body, x, sp)
        return y

    def forward(prologue_p, stage_p, epilogue_p, x):
        h = fns[prologue_name](prologue_p, x)
        # data_axis=None: the pippy contract replicates outputs on every device
        # ("gather_output" for free) — dp-sharded compute would return sharded
        # outputs instead
        h = pipeline_apply(
            stage_fn, stage_p, h, mesh, num_microbatches, axis_name=axis_name,
            data_axis=None,
        )
        return fns[epilogue_name](epilogue_p, h)

    jitted = jax.jit(forward)

    def pp_forward(x, *args, **kwargs):
        if args or kwargs:
            raise TypeError(
                "pp_forward takes a single input array; extra forward arguments "
                f"are not threaded through the pipeline (got {len(args)} args, "
                f"{sorted(kwargs)} kwargs). Bake them into the block fns instead."
            )
        return jitted(prologue_params, stage_params, epilogue_params, x)

    pp_forward.num_stages = num_stages
    pp_forward.num_microbatches = num_microbatches
    pp_forward.stage_groups = groups
    return pp_forward
