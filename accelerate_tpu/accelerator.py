"""The `Accelerator` facade — the single user entry point.

Capability parity: reference `src/accelerate/accelerator.py` (3597 LoC): `prepare`,
`backward`, `accumulate`/`no_sync`, `clip_grad_norm_`, collectives facade
(`gather`, `gather_for_metrics`, `reduce`, `pad_across_processes`), checkpoint
orchestration (`save_state`/`load_state`), trackers, trigger, autocast/profile.

TPU-native re-founding (SURVEY.md §7): the reference spends most of its complexity
compensating for eager per-rank execution (DDP buckets, no_sync, grad scaler
plumbing, per-backend collectives, rank-0 dispatch). Here one jitted SPMD step +
`NamedSharding` subsumes DDP/FSDP/TP/SP; "backward" builds and caches a jitted
value-and-grad; gradient accumulation is a buffer add between jitted calls (or a
fused in-jit microbatch loop via `make_train_step`, the fast path). The imperative
call sequence — forward/backward/clip/step/zero_grad — is preserved so reference
users keep their training-loop shape.
"""

from __future__ import annotations

import contextlib
import functools
import os
import weakref
from collections.abc import Mapping
from dataclasses import dataclass
from typing import Any, Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec

from .data_loader import DataLoaderShard, prepare_data_loader, skip_first_batches
from .optimizer import AcceleratedOptimizer
from .parallel.mesh import ParallelismConfig, data_axes
from .parallel.sharding import ShardingRules, infer_param_shardings, shard_params
from .scheduler import AcceleratedScheduler
from .state import AcceleratorState, DistributedType, GradientState, PartialState
from .utils import operations
from .utils.operations import convert_to_fp32, recursively_apply
from .utils.precision import DynamicGradScaler, GradScalerState, PrecisionPolicy
from .utils.random import split_rng_key


def _is_optax_tx(obj: Any) -> bool:
    return hasattr(obj, "init") and hasattr(obj, "update") and not hasattr(obj, "apply")


def _is_flax_module(obj: Any) -> bool:
    return hasattr(obj, "apply") and hasattr(obj, "init") and hasattr(obj, "bind")


class BoundModel:
    """A model with params bound — what user ``loss_fn(model, batch)`` receives.
    Calling it runs the forward with those exact params, so gradients flow.

    When the model carries mutable non-param collections (``batch_stats``,
    ``fp8_meta``, …), each call threads them through and keeps the updated
    state on ``self.extra_state`` for the train step to collect."""

    __slots__ = ("apply_fn", "params", "extra_state")

    def __init__(self, apply_fn: Callable, params: Any, extra_state: Any = None):
        self.apply_fn = apply_fn
        self.params = params
        self.extra_state = extra_state

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        if self.extra_state is not None:
            out, self.extra_state = self.apply_fn(
                self.params, *args, extra_state=self.extra_state, **kwargs
            )
            return out
        return self.apply_fn(self.params, *args, **kwargs)


class PreparedModel:
    """Sharded, precision-managed model handle returned by `Accelerator.prepare`.

    Holds the *master* (fp32) parameter pytree placed on the mesh, the functional
    ``apply_fn(params, *args, **kwargs)``, and the sharding plan. Calling it runs
    an eagerly-jitted forward with the compute-dtype cast applied and outputs
    upcast to fp32 (the reference's autocast forward patch,
    `accelerator.py:1391-1402`, as a functional wrapper).
    """

    def __init__(
        self,
        apply_fn: Callable,
        params: Any,
        policy: PrecisionPolicy,
        mesh,
        shardings: Any,
        module: Any = None,
        extra_state: Any = None,
    ):
        self.apply_fn = apply_fn
        self.params = params
        self.policy = policy
        self.mesh = mesh
        self.shardings = shardings
        self.module = module  # the original user object, for unwrap_model
        self.extra_state = extra_state  # mutable non-param collections (replicated)
        self._acc_grads = None  # used only when no optimizer is prepared
        # keyed by (autocast_enabled, sorted static flag kwargs) — one compiled
        # forward per flag combination (see __call__)
        self._jit_forwards: dict[tuple, Callable] = {}
        self._hook = None  # hooks.ModelHook attachment point
        self.training = True

    @classmethod
    def _extract(cls, obj: Any) -> tuple[Callable, Any, Any, Any]:
        """Normalize user model objects to (apply_fn, params, extra_state, original).

        ``extra_state`` is non-None when a flax ``variables`` dict with mutable
        collections besides ``params`` (``batch_stats``, ``fp8_meta``, …) was
        passed; the returned apply_fn then accepts ``extra_state=`` and returns
        ``(out, new_extra_state)``.
        """
        if isinstance(obj, tuple) and len(obj) == 2:
            fn_or_module, params = obj
            if _is_flax_module(fn_or_module):
                module = fn_or_module
                extra_state = None
                if isinstance(params, Mapping) and "params" in params and len(params) > 1:
                    extra_state = {k: dict(v) if isinstance(v, Mapping) else v
                                   for k, v in params.items() if k != "params"}
                    params = params["params"]

                def apply_fn(p, *args, extra_state=None, **kwargs):
                    if extra_state is not None:
                        ins = dict(extra_state)
                        if "intermediates" in ins:
                            # write-only collection (flax sow convention): each
                            # call starts fresh so sown values never leak across
                            # steps when the state is threaded through
                            ins["intermediates"] = {}
                        out, mutated = module.apply(
                            {"params": p, **ins},
                            *args,
                            mutable=list(extra_state.keys()),
                            **kwargs,
                        )
                        return out, dict(mutated)
                    variables = {"params": p} if "params" not in p else p
                    return module.apply(variables, *args, **kwargs)

                return apply_fn, params, extra_state, module
            if callable(fn_or_module):
                if isinstance(params, Mapping) and "params" in params and len(params) > 1:
                    # plain-callable analogue of the flax mutable-collections
                    # contract: apply_fn(params, *args, extra_state=...) must
                    # return (out, new_extra_state). Used by the torch interop
                    # bridge for BN running stats + dropout rng.
                    extra_state = {k: v for k, v in params.items() if k != "params"}
                    return fn_or_module, params["params"], extra_state, fn_or_module
                return fn_or_module, params, None, fn_or_module
        raise TypeError(
            "Model must be a (flax_module, params) or (apply_fn, params) tuple, "
            f"got {type(obj)}. Initialize params first (module.init(key, sample))."
        )

    def bind(self, params: Any | None = None) -> BoundModel:
        return BoundModel(
            self.apply_fn, self.params if params is None else params, self.extra_state
        )

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        from .utils.precision import autocast_enabled

        cast = autocast_enabled()  # False inside autocast(AutocastKwargs(enabled=False))
        params = self.params
        if self._hook is not None:
            params, args, kwargs = self._hook.pre_forward(self, params, args, kwargs)
        # flag kwargs (deterministic=False, decode=True, return_hidden=True, …)
        # are Python control flow, not data: tracing them raises
        # TracerBoolConversionError inside the model. Route them around the jit
        # as part of the compilation key instead.
        static_kwargs = {
            k: v for k, v in kwargs.items() if isinstance(v, (bool, str)) or v is None
        }
        traced_kwargs = {k: v for k, v in kwargs.items() if k not in static_kwargs}
        key = (cast, tuple(sorted(static_kwargs.items())))
        if key not in self._jit_forwards:
            policy = self.policy
            has_state = self.extra_state is not None

            def fwd(params, state, args, kwargs, _cast=cast, _static=dict(static_kwargs)):
                p = policy.cast_to_compute(params) if _cast else params
                if has_state:
                    out, new_state = self.apply_fn(p, *args, extra_state=state, **kwargs, **_static)
                else:
                    out, new_state = self.apply_fn(p, *args, **kwargs, **_static), None
                return (policy.cast_to_output(out) if _cast else out), new_state

            self._jit_forwards[key] = jax.jit(fwd)
        out, new_state = self._jit_forwards[key](params, self.extra_state, args, traced_kwargs)
        if new_state is not None and self.training:
            # eval() forwards must be side-effect free: discard state mutations
            # (fp8 amax rolls, batch_stats updates) outside training mode
            self.extra_state = new_state
        if self._hook is not None:
            out = self._hook.post_forward(self, out)
        return out

    def eval(self) -> "PreparedModel":
        self.training = False
        return self

    def train(self, mode: bool = True) -> "PreparedModel":
        self.training = mode
        return self

    def state_dict(self) -> Any:
        return self.params

    def load_state_dict(self, params: Any) -> None:
        self.params = shard_params(params, self.shardings)


@dataclass
class ProjectConfiguration:
    """Where checkpoints/logs go (reference `utils/dataclasses.py:ProjectConfiguration`)."""

    project_dir: str | None = None
    logging_dir: str | None = None
    automatic_checkpoint_naming: bool = False
    total_limit: int | None = None
    iteration: int = 0
    # background disk writes for save_state: the call returns after the
    # device->host copy; bytes land before the next save/load/exit barrier
    # (SURVEY §7.6 async sharded save — beyond the reference's sync save)
    async_save: bool = False

    def __post_init__(self):
        if self.logging_dir is None:
            self.logging_dir = self.project_dir


@dataclass
class GradientAccumulationPlugin:
    """Reference `utils/dataclasses.py:GradientAccumulationPlugin`."""

    num_steps: int = 1
    adjust_scheduler: bool = True
    sync_with_dataloader: bool = True


class _RemovableHandle:
    """Deregistration handle for state pre-hooks (torch RemovableHandle role)."""

    _next_id = 0

    def __init__(self, registry: dict):
        self._registry = registry
        self.id = _RemovableHandle._next_id
        _RemovableHandle._next_id += 1

    def remove(self) -> None:
        self._registry.pop(self.id, None)


class Accelerator:
    def __init__(
        self,
        device_placement: bool = True,
        split_batches: bool = False,
        mixed_precision: str | None = None,
        gradient_accumulation_steps: int = 1,
        gradient_accumulation_plugin: GradientAccumulationPlugin | None = None,
        cpu: bool = False,
        parallelism_config: ParallelismConfig | None = None,
        sharding_rules: ShardingRules | None = None,
        log_with: str | list | None = None,
        project_dir: str | None = None,
        project_config: ProjectConfiguration | None = None,
        even_batches: bool = True,
        step_scheduler_with_optimizer: bool = True,
        rng_types: list[str] | None = None,
        dispatch_batches: bool | None = None,
        dataloader_config: Any = None,
        deepspeed_plugin: Any = None,
        fsdp_plugin: Any = None,
        megatron_lm_plugin: Any = None,
        kwargs_handlers: list[Any] | None = None,
        **kwargs: Any,
    ):
        self.project_configuration = project_config or ProjectConfiguration(project_dir=project_dir)
        # ---- engine plugins + kwargs handlers (reference accelerator.py:246-412):
        # resolve the migration-surface objects into the run plan BEFORE state
        # is built, so ds_config-derived precision/parallelism actually apply.
        (
            mixed_precision,
            gradient_accumulation_steps,
            parallelism_config,
            scaler_config,
            init_pg_timeout,
        ) = self._resolve_plugins(
            mixed_precision,
            gradient_accumulation_steps,
            parallelism_config,
            deepspeed_plugin,
            fsdp_plugin,
            megatron_lm_plugin,
            kwargs_handlers,
        )
        self._use_seedable_sampler = True
        self._use_stateful_dataloader = True
        if dataloader_config is not None:
            if split_batches or not even_batches or dispatch_batches is not None:
                raise ValueError(
                    "Pass dataloader behavior EITHER via dataloader_config= OR via the "
                    "split_batches/even_batches/dispatch_batches kwargs, not both."
                )
            split_batches = dataloader_config.split_batches
            even_batches = dataloader_config.even_batches
            dispatch_batches = dataloader_config.dispatch_batches
            self._use_seedable_sampler = dataloader_config.use_seedable_sampler
            self._use_stateful_dataloader = dataloader_config.use_stateful_dataloader
        if parallelism_config is None:
            # launcher env contract (commands/launch.py): dp,fsdp,stage,seq,tp
            env_par = os.environ.get("ACCELERATE_TPU_PARALLELISM")
            if env_par:
                dp, fsdp, stage, seq, tp = (int(x) for x in env_par.split(","))
                parallelism_config = ParallelismConfig(
                    data_parallel_size=dp, fsdp_size=fsdp, stage_size=stage,
                    sequence_size=seq, tensor_size=tp,
                )
        if gradient_accumulation_steps == 1:
            gradient_accumulation_steps = int(os.environ.get("ACCELERATE_TPU_GRAD_ACCUM_STEPS", 1))
        self.state = AcceleratorState(
            mixed_precision=mixed_precision,
            cpu=cpu,
            parallelism_config=parallelism_config,
            initialization_timeout=init_pg_timeout,
        )
        if self.deepspeed_plugin is not None:
            # reference keeps (possibly several, selectable) DS plugins on
            # AcceleratorState — preserve those accessors
            self.state.register_deepspeed_plugins(self.deepspeed_plugin)
        self.policy = PrecisionPolicy.from_mode(self.state.mixed_precision)
        if self.policy.requires_loss_scaling:
            self.scaler = DynamicGradScaler(**scaler_config) if scaler_config.pop("enabled", True) else None
        else:
            self.scaler = None
        if gradient_accumulation_plugin is not None:
            self.gradient_state = GradientState(
                gradient_accumulation_steps=gradient_accumulation_plugin.num_steps,
                adjust_scheduler=gradient_accumulation_plugin.adjust_scheduler,
                sync_with_dataloader=gradient_accumulation_plugin.sync_with_dataloader,
            )
        else:
            self.gradient_state = GradientState(gradient_accumulation_steps=gradient_accumulation_steps)
        self.device_placement = device_placement
        self.split_batches = split_batches
        self.even_batches = even_batches
        self.step_scheduler_with_optimizer = step_scheduler_with_optimizer
        self.rng_types = rng_types
        self.dispatch_batches = dispatch_batches
        self.sharding_rules = sharding_rules
        self.step = 0
        self.flag_tensor = None
        self._models: list[PreparedModel] = []
        self._save_state_pre_hooks: dict[int, Callable] = {}
        self._load_state_pre_hooks: dict[int, Callable] = {}
        self._optimizers: list[AcceleratedOptimizer] = []
        self._schedulers: list[AcceleratedScheduler] = []
        self._dataloaders: list[DataLoaderShard] = []
        self._custom_objects: list[Any] = []
        self._dummy_optim_map: dict[int, AcceleratedOptimizer] = {}
        # model -> (loss_fn -> jitted grad fn), both levels weakly keyed
        self._grad_fns: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
        self._train_steps: dict[tuple, Any] = {}
        self.trackers: list = []
        self._log_with = log_with

    def _resolve_plugins(
        self,
        mixed_precision,
        gradient_accumulation_steps,
        parallelism_config,
        deepspeed_plugin,
        fsdp_plugin,
        megatron_lm_plugin,
        kwargs_handlers,
    ):
        """Resolve engine plugins + kwargs handlers into the run plan — the
        reference ctor's plugin negotiation (`accelerator.py:246-412`), with the
        engines collapsed onto mesh axes. Env activation mirrors the reference's
        ``ACCELERATE_USE_DEEPSPEED``/``_FSDP``/``_MEGATRON_LM`` switches."""
        from .utils.dataclasses import (
            AutocastKwargs,
            DataLoaderConfiguration,
            DeepSpeedPlugin,
            DistributedDataParallelKwargs,
            FP8RecipeKwargs,
            FullyShardedDataParallelPlugin,
            GradScalerKwargs,
            InitProcessGroupKwargs,
            MegatronLMPlugin,
            ProfileKwargs,
        )
        from .utils.environment import parse_flag_from_env

        if deepspeed_plugin is None and parse_flag_from_env("ACCELERATE_TPU_USE_DEEPSPEED"):
            deepspeed_plugin = DeepSpeedPlugin(
                hf_ds_config=os.environ.get("ACCELERATE_TPU_DEEPSPEED_CONFIG_FILE") or None
            )
        if fsdp_plugin is None and parse_flag_from_env("ACCELERATE_TPU_USE_FSDP"):
            fsdp_plugin = FullyShardedDataParallelPlugin()
        if megatron_lm_plugin is None and parse_flag_from_env("ACCELERATE_TPU_USE_MEGATRON_LM"):
            megatron_lm_plugin = MegatronLMPlugin()
        engines = [p for p in (deepspeed_plugin, fsdp_plugin, megatron_lm_plugin) if p is not None]
        if len(engines) > 1:
            raise ValueError(
                "Pass at most one of deepspeed_plugin / fsdp_plugin / megatron_lm_plugin."
            )
        self.deepspeed_plugin = deepspeed_plugin
        self.fsdp_plugin = fsdp_plugin
        self.megatron_lm_plugin = megatron_lm_plugin

        self.ddp_handler = None
        self.profile_handler = None
        self.fp8_recipe_handler = None
        self.init_handler = None
        self.autocast_handler = None
        scaler_kwargs = None
        seen: set[type] = set()
        for handler in kwargs_handlers or []:
            if type(handler) in seen:
                raise ValueError(f"Duplicate kwargs handler of type {type(handler).__name__}.")
            seen.add(type(handler))
            if isinstance(handler, GradScalerKwargs):
                scaler_kwargs = handler
            elif isinstance(handler, DistributedDataParallelKwargs):
                self.ddp_handler = handler
            elif isinstance(handler, ProfileKwargs):
                self.profile_handler = handler
            elif isinstance(handler, FP8RecipeKwargs):
                self.fp8_recipe_handler = handler
            elif isinstance(handler, InitProcessGroupKwargs):
                self.init_handler = handler
            elif isinstance(handler, AutocastKwargs):
                self.autocast_handler = handler
            elif isinstance(handler, DataLoaderConfiguration):
                raise ValueError("Pass DataLoaderConfiguration as dataloader_config=, not a handler.")
            else:
                raise ValueError(f"Unsupported kwargs handler: {handler!r}")

        self.gradient_clipping = None
        if deepspeed_plugin is not None:
            if mixed_precision is None and getattr(deepspeed_plugin, "mixed_precision", None):
                mixed_precision = deepspeed_plugin.mixed_precision
            if gradient_accumulation_steps == 1 and deepspeed_plugin.gradient_accumulation_steps > 1:
                gradient_accumulation_steps = deepspeed_plugin.gradient_accumulation_steps
            if deepspeed_plugin.gradient_clipping is not None:
                self.gradient_clipping = deepspeed_plugin.gradient_clipping
            if parallelism_config is None:
                # stage >=3 -> fsdp over all devices; stages 0-2 -> the default
                # data mesh (opt-state sharding is a placement choice downstream)
                parallelism_config = deepspeed_plugin.to_parallelism_config(0)
        elif fsdp_plugin is not None and parallelism_config is None:
            parallelism_config = fsdp_plugin.to_parallelism_config()
        elif megatron_lm_plugin is not None and parallelism_config is None:
            parallelism_config = megatron_lm_plugin.to_parallelism_config()

        scaler_config: dict[str, Any] = {}
        if scaler_kwargs is not None:
            scaler_config = scaler_kwargs.to_dict()
        timeout = self.init_handler.timeout_seconds if self.init_handler is not None else None
        return mixed_precision, gradient_accumulation_steps, parallelism_config, scaler_config, timeout

    # ------------------------------------------------------------- topology
    @property
    def project_dir(self) -> str | None:
        """Reference `Accelerator.project_dir` (ProjectConfiguration passthrough)."""
        return self.project_configuration.project_dir

    @property
    def logging_dir(self) -> str | None:
        return self.project_configuration.logging_dir

    @property
    def partial_state(self) -> PartialState:
        return PartialState()

    @property
    def distributed_type(self) -> str:
        return self.partial_state.distributed_type

    @property
    def num_processes(self) -> int:
        return self.partial_state.num_processes

    @property
    def process_index(self) -> int:
        return self.partial_state.process_index

    @property
    def local_process_index(self) -> int:
        return self.partial_state.local_process_index

    @property
    def device(self):
        return self.partial_state.device

    @property
    def mesh(self):
        return self.state.mesh

    @property
    def num_devices(self) -> int:
        return self.partial_state.num_devices

    @property
    def is_main_process(self) -> bool:
        return self.partial_state.is_main_process

    @property
    def is_local_main_process(self) -> bool:
        return self.partial_state.is_local_main_process

    @property
    def is_last_process(self) -> bool:
        return self.partial_state.is_last_process

    @property
    def mixed_precision(self) -> str:
        return self.state.mixed_precision

    @property
    def sync_gradients(self) -> bool:
        return self.gradient_state.sync_gradients

    @property
    def gradient_accumulation_steps(self) -> int:
        return self.gradient_state.num_steps

    @gradient_accumulation_steps.setter
    def gradient_accumulation_steps(self, value: int) -> None:
        self.gradient_state.num_steps = value

    @property
    def use_distributed(self) -> bool:
        return self.partial_state.use_distributed

    # ------------------------------------------------------------ rank gating
    def on_main_process(self, function: Callable) -> Callable:
        return self.partial_state.on_main_process(function)

    def on_local_main_process(self, function: Callable) -> Callable:
        return self.partial_state.on_local_main_process(function)

    def on_last_process(self, function: Callable) -> Callable:
        return self.partial_state.on_last_process(function)

    def on_process(self, function: Callable | None = None, process_index: int = 0) -> Callable:
        return self.partial_state.on_process(function, process_index)

    def on_local_process(
        self, function: Callable | None = None, local_process_index: int = 0
    ) -> Callable:
        """Run only on processes with this LOCAL index (reference
        `accelerator.py` on_local_process). One process owns each host here, so
        every process has local index 0: index 0 runs everywhere (each host's
        sole process), other indices nowhere."""
        if function is None:
            return functools.partial(self.on_local_process, local_process_index=local_process_index)

        @functools.wraps(function)
        def wrapper(*args: Any, **kwargs: Any):
            if self.partial_state.local_process_index == local_process_index:
                return function(*args, **kwargs)

        return wrapper

    def print(self, *args: Any, **kwargs: Any) -> None:
        self.partial_state.print(*args, **kwargs)

    def wait_for_everyone(self) -> None:
        self.partial_state.wait_for_everyone()

    @contextlib.contextmanager
    def main_process_first(self):
        with self.partial_state.main_process_first():
            yield

    @contextlib.contextmanager
    def local_main_process_first(self):
        with self.partial_state.local_main_process_first():
            yield

    def split_between_processes(self, inputs: Any, apply_padding: bool = False):
        return self.partial_state.split_between_processes(inputs, apply_padding=apply_padding)

    # ---------------------------------------------------------------- prepare
    def prepare(self, *args: Any, device_placement: list[bool] | None = None) -> Any:
        """Prepare models/optimizers/dataloaders/schedulers in any order,
        returning them in the same order (reference `accelerator.py:1215`).

        Models are (module, params) or (apply_fn, params) tuples; optimizers are
        optax GradientTransformations; dataloaders are torch DataLoaders or batch
        iterables; schedulers expose ``step()``.
        """
        from .utils.deepspeed import DummyOptim, DummyScheduler

        result: list[Any] = [None] * len(args)
        model_indices: list[int] = []
        for obj in args:
            if self.verify_device_map(obj):
                raise ValueError(
                    "You can't train a model that has been loaded with a "
                    "multi-entry device map (big-model inference dispatch); "
                    "prepare the underlying params on a mesh instead."
                )
        # pass 1: models and dataloaders
        for i, obj in enumerate(args):
            if isinstance(obj, (DummyOptim, DummyScheduler)):
                continue  # passes 2/3
            if isinstance(obj, PreparedModel):
                result[i] = obj
                model_indices.append(i)
            elif _is_optax_tx(obj) or isinstance(obj, AcceleratedOptimizer):
                continue  # pass 2 (checked before the tuple case: an optax
                # GradientTransformation is itself a (init, update) namedtuple)
            elif (
                isinstance(obj, tuple)
                and len(obj) == 2
                and (callable(obj[0]) or _is_flax_module(obj[0]))
                and not callable(obj[1])
            ):
                result[i] = self.prepare_model(obj)
                model_indices.append(i)
            elif hasattr(obj, "step") and not hasattr(obj, "__iter__"):
                continue  # pass 3
            elif hasattr(obj, "__iter__"):
                result[i] = self.prepare_data_loader(obj)
            else:
                result[i] = obj
        # pass 2: optimizers attach to the (single) model. A DummyOptim's
        # sibling DummyScheduler (same prepare call) supplies the warmup/total
        # step counts for 'auto' resolution, matching the reference's joint
        # engine build (`accelerator.py:1741-1803`).
        dummy_sched = next((o for o in args if isinstance(o, DummyScheduler)), None)
        for i, obj in enumerate(args):
            if result[i] is not None:
                continue
            if isinstance(obj, DummyOptim):
                model = result[model_indices[0]] if model_indices else None
                result[i] = self._prepare_dummy_optim(obj, dummy_sched, model=model)
            elif _is_optax_tx(obj) or isinstance(obj, AcceleratedOptimizer):
                model = result[model_indices[0]] if model_indices else None
                result[i] = self.prepare_optimizer(obj, model=model)
        # pass 3: schedulers attach to optimizers
        for i, obj in enumerate(args):
            if result[i] is None:
                result[i] = self.prepare_scheduler(obj)
        return result[0] if len(result) == 1 else tuple(result)

    def prepare_model(self, model: Any, device_placement: bool | None = None) -> PreparedModel:
        """Shard+place parameters per the parallelism plan (reference
        `prepare_model`, `accelerator.py:1351-1593`, minus all engine wrapping)."""
        if isinstance(model, PreparedModel):
            return model
        apply_fn, params, extra_state, module = PreparedModel._extract(model)
        params = self.policy.cast_to_param(params)
        shardings = infer_param_shardings(
            params,
            self.mesh,
            rules=self.sharding_rules,
            shard_params_on_fsdp=self.state.parallelism_config.fsdp_size > 1
            or self.state.parallelism_config.tensor_size > 1,
        )
        if device_placement if device_placement is not None else self.device_placement:
            params = shard_params(params, shardings)
        prepared = PreparedModel(
            apply_fn,
            params,
            policy=self.policy,
            mesh=self.mesh,
            shardings=shardings,
            module=module,
            extra_state=extra_state,
        )
        self._models.append(prepared)
        return prepared

    def prepare_optimizer(
        self, optimizer: Any, model: PreparedModel | None = None, device_placement: bool | None = None
    ) -> AcceleratedOptimizer:
        if isinstance(optimizer, AcceleratedOptimizer):
            if optimizer.model is None and model is not None:
                optimizer.attach_model(model)
            self._optimizers.append(optimizer)
            return optimizer
        if model is None:
            if len(self._models) != 1:
                raise ValueError(
                    "prepare_optimizer needs `model=` when zero or multiple models are prepared."
                )
            model = self._models[0]
        if getattr(self.fp8_recipe_handler, "opt_level", "O1") == "O2":
            # a user-supplied optax transformation cannot be rewritten into the
            # fp8-state form — say so instead of silently ignoring the recipe
            from .ops.fp8 import ScaleByAdamFp8State  # noqa: F401

            probe = jax.eval_shape(optimizer.init, {"w": jnp.zeros((1,))})
            if not any(
                isinstance(s, ScaleByAdamFp8State)
                for s in jax.tree.leaves(
                    probe, is_leaf=lambda s: isinstance(s, ScaleByAdamFp8State)
                )
            ):
                import warnings

                warnings.warn(
                    "FP8RecipeKwargs(opt_level='O2') is configured, but the "
                    "optimizer passed to prepare() does not carry fp8 state. "
                    "Construct it with accelerate_tpu.adamw_fp8(..., "
                    "opt_level='O2') (or define it in a ds_config and use "
                    "DummyOptim) to get the low-precision moments."
                )
        prepared = AcceleratedOptimizer(optimizer, model=model, scaler=self.scaler)
        self._optimizers.append(prepared)
        return prepared

    def _prepare_dummy_optim(
        self, dummy, dummy_sched=None, model: PreparedModel | None = None
    ) -> AcceleratedOptimizer:
        """Compile a `DummyOptim` (+ sibling `DummyScheduler`) against the
        deepspeed_plugin's ds_config sections (reference swaps placeholders for
        engine-built objects in `_prepare_deepspeed`, `accelerator.py:1741-1803`)."""
        from .utils.deepspeed import build_ds_optimizer, build_ds_schedule

        plugin = self.deepspeed_plugin
        if plugin is None:
            raise ValueError(
                "DummyOptim requires a deepspeed_plugin (its optimizer comes from "
                "the ds_config 'optimizer' section)."
            )
        opt_cfg = getattr(plugin, "optimizer_config", None)
        sched_cfg = getattr(plugin, "scheduler_config", None)
        base_lr = dummy.lr
        if opt_cfg:
            p = opt_cfg.get("params", {})
            lr = p.get("lr")
            if lr is not None and lr != "auto":
                base_lr = float(lr)
        schedule_fn = build_ds_schedule(sched_cfg, dummy_sched, base_lr)
        fp8_opt_level = getattr(self.fp8_recipe_handler, "opt_level", "O1") or "O1"
        tx = build_ds_optimizer(opt_cfg, dummy, schedule_fn, fp8_opt_level=fp8_opt_level)
        prepared = self.prepare_optimizer(tx, model=model)
        prepared._ds_schedule_fn = schedule_fn
        prepared._ds_base_lr = base_lr  # the lr the optimizer actually uses
        self._dummy_optim_map[id(dummy)] = prepared
        return prepared

    def prepare_data_loader(self, data_loader: Any, device_placement: bool | None = None) -> DataLoaderShard:
        if isinstance(data_loader, DataLoaderShard):
            self._dataloaders.append(data_loader)
            return data_loader
        prepared = prepare_data_loader(
            data_loader,
            device_placement=device_placement if device_placement is not None else self.device_placement,
            split_batches=self.split_batches,
            rng_types=self.rng_types,
            dispatch_batches=self.dispatch_batches,
            even_batches=self.even_batches,
            use_seedable_sampler=self._use_seedable_sampler,
            mesh=self.mesh,
        )
        self._dataloaders.append(prepared)
        return prepared

    def prepare_scheduler(self, scheduler: Any) -> AcceleratedScheduler:
        if isinstance(scheduler, AcceleratedScheduler):
            return scheduler
        from .utils.deepspeed import DeepSpeedSchedulerView, DummyScheduler

        if isinstance(scheduler, DummyScheduler):
            opt = self._dummy_optim_map.get(id(scheduler.optimizer))
            if opt is None:
                opt = self._optimizers[-1] if self._optimizers else None
            if opt is None:
                raise ValueError(
                    "DummyScheduler must be prepared together with (or after) its "
                    "DummyOptim — the schedule is embedded in the built optimizer."
                )
            schedule_fn = getattr(opt, "_ds_schedule_fn", None)
            if schedule_fn is None:
                # constant-lr config: report the ds_config-RESOLVED lr the
                # optimizer actually runs at, not the placeholder's field
                base = getattr(opt, "_ds_base_lr", None)
                if base is None:
                    base = getattr(scheduler.optimizer, "lr", 0.0) if scheduler.optimizer else 0.0
                schedule_fn = lambda _count, _base=base: _base  # noqa: E731
            scheduler = DeepSpeedSchedulerView(schedule_fn, opt)
        prepared = AcceleratedScheduler(
            scheduler,
            optimizers=self._optimizers,
            step_with_optimizer=self.step_scheduler_with_optimizer,
            split_batches=self.split_batches,
        )
        self._schedulers.append(prepared)
        return prepared

    # ------------------------------------------------------- gradient machinery
    def _do_sync(self) -> None:
        if self.gradient_state.sync_with_dataloader and self.gradient_state.end_of_dataloader:
            self.step = 0
            self.gradient_state._set_sync_gradients(True)
        else:
            self.step += 1
            self.gradient_state._set_sync_gradients(
                (self.step % self.gradient_state.num_steps) == 0
            )

    @contextlib.contextmanager
    def accumulate(self, *models: Any):
        """Gradient-accumulation context (reference `accelerator.py:1050`):
        decides whether this batch is a sync boundary; `backward` scales the loss
        by 1/num_steps and `optimizer.step()`/`zero_grad()` no-op off-boundary."""
        self._do_sync()
        yield

    @contextlib.contextmanager
    def no_sync(self, model: Any = None):
        """Force-suppress gradient application inside the context (reference
        `no_sync`, `accelerator.py:935`). There is no per-rank allreduce to skip
        under SPMD; this only gates the optimizer."""
        prev = self.gradient_state.sync_gradients
        self.gradient_state._set_sync_gradients(False)
        try:
            yield
        finally:
            self.gradient_state._set_sync_gradients(prev)

    def trigger_sync_in_backward(self, model: Any = None) -> None:
        """Make the NEXT backward apply gradients even though the step count
        says we're mid-accumulation (reference `trigger_sync_in_backward`,
        `accelerator.py:977`: sets DDP's require_backward_grad_sync after
        forwards under no_sync). Under SPMD there is no allreduce to re-arm —
        the equivalent observable effect is forcing the optimizer boundary."""
        self.gradient_state._set_sync_gradients(True)

    @contextlib.contextmanager
    def join_uneven_inputs(self, joinables: list, even_batches: bool | None = None):
        """API parity with DDP's Join (reference `accelerator.py:1095-1182`).
        Uneven inputs cannot reach the jitted step (the loader pads to static
        shapes), so Join itself is coordination-free — but the ``even_batches``
        override IS honored: prepared loaders (and their shard samplers) run
        with the overridden value for the duration of the context, exactly like
        the reference's temporary `dl.batch_sampler.even_batches` swap."""
        overridden: list[tuple[Any, bool]] = []
        if even_batches is not None:
            for dl in self._dataloaders:
                for target in (dl, getattr(dl, "batch_sampler", None)):
                    if target is None or not hasattr(target, "even_batches"):
                        continue
                    if even_batches and getattr(target, "batch_size", 0) is None:
                        # same invariant as the BatchSamplerShard constructor:
                        # even_batches needs a declared batch_size to pad to —
                        # overriding past it would crash the trailing-group
                        # refill mid-iteration
                        import warnings

                        warnings.warn(
                            "join_uneven_inputs(even_batches=True) skipped a "
                            "loader whose batch sampler exposes no batch_size; "
                            "it keeps even_batches=False.",
                            stacklevel=2,
                        )
                        continue
                    overridden.append((target, target.even_batches))
                    target.even_batches = even_batches
            if not overridden:
                import warnings

                warnings.warn(
                    "join_uneven_inputs(even_batches=...) found no prepared "
                    "dataloaders to override; the argument has no effect.",
                    stacklevel=2,
                )
        try:
            yield
        finally:
            for target, prev in overridden:
                target.even_batches = prev

    def _get_grad_fn(self, loss_fn: Callable, model: PreparedModel) -> Callable:
        # Keyed on live object identity via weak references: an id()-keyed dict
        # can silently hand a new function a dead function's compiled program
        # after GC reuses the address. The cached value must NOT strongly
        # reference loss_fn (the key) — a value→key edge would pin the entry
        # forever — so `compute` closes over a weakref and the dict entry is
        # evicted by the weakref callback when loss_fn dies.
        per_model = self._grad_fns.get(model)
        if per_model is None:
            per_model = self._grad_fns[model] = {}
        try:
            probe = weakref.ref(loss_fn)
            cached = per_model.get(probe)  # hashes the referent — may also raise
        except TypeError:  # not weakref-able or not hashable: recompile each call
            probe, cached = None, None
        if cached is not None:
            return cached
        policy = self.policy
        apply_fn = model.apply_fn
        loss_ref = probe if probe is not None else (lambda fn=loss_fn: fn)

        def compute(params, mstate, batch, inner_scale, outer_scale):
            live_loss_fn = loss_ref()
            if live_loss_fn is None:  # pragma: no cover - entry evicted before call
                raise RuntimeError("loss_fn was garbage-collected before the step ran")

            def fwd(p):
                bound = BoundModel(apply_fn, policy.cast_to_compute(p), mstate)
                out = live_loss_fn(bound, batch)
                if isinstance(out, tuple):
                    loss, aux = out[0], out[1:]
                else:
                    loss, aux = out, ()
                # inner_scale rides INSIDE the reduced-precision backward (fp16
                # underflow protection, capped fp16-safe so a healthy cotangent
                # chain can't trip 65504); the outer remainder is applied to
                # the fp32 grads below. See DynamicGradScaler.split_scale.
                return (loss.astype(jnp.float32) * inner_scale, (loss, aux, bound.extra_state))

            (_, (loss, aux, new_mstate)), grads = jax.value_and_grad(fwd, has_aux=True)(params)
            grads = jax.tree.map(lambda g: g * outer_scale, grads)
            return convert_to_fp32(loss), aux, grads, new_mstate

        fn = jax.jit(compute)
        if probe is not None:
            key = weakref.ref(loss_fn, lambda ref, d=per_model: d.pop(ref, None))
            per_model[key] = fn
        return fn

    def backward(self, loss_fn: Callable, batch: Any = None, model: PreparedModel | None = None, **kwargs: Any):
        """Compute gradients of ``loss_fn(model, batch)`` and accumulate them.

        The reference's ``accelerator.backward(loss)`` rides torch's implicit
        tape; JAX has no tape, so the facade takes the loss *function* and returns
        the loss value. Gradients are scaled by 1/gradient_accumulation_steps
        (reference `accelerator.py:2199-2231`) and by the dynamic fp16 scale when
        active — applied to the fp32 grads after the backward, so the scaler's
        multiplier can never itself overflow the fp16 cotangent chain.
        """
        if model is None:
            if len(self._models) != 1:
                raise ValueError("backward() needs `model=` when zero or multiple models are prepared.")
            model = self._models[0]
        grad_fn = self._get_grad_fn(loss_fn, model)
        inv_k = 1.0 / self.gradient_state.num_steps
        inner = jnp.asarray(1.0, dtype=jnp.float32)
        outer = jnp.asarray(inv_k, dtype=jnp.float32)
        if self.scaler is not None:
            opt = self._optimizer_for(model)
            if opt is not None and opt.scaler_state is not None:
                inner, rest = self.scaler.split_scale(opt.scaler_state.scale)
                outer = rest * inv_k
        loss, aux, grads, new_mstate = grad_fn(
            model.params, model.extra_state, batch, inner, outer
        )
        model.extra_state = new_mstate
        opt = self._optimizer_for(model)
        if opt is not None:
            opt.accumulate_grads(grads)
        else:
            if model._acc_grads is None:
                model._acc_grads = grads
            else:
                model._acc_grads = jax.tree.map(jnp.add, model._acc_grads, grads)
        return (loss, *aux) if aux else loss

    def _optimizer_for(self, model: PreparedModel) -> AcceleratedOptimizer | None:
        for opt in self._optimizers:
            if opt.model is model:
                return opt
        return None

    def unscale_gradients(self, optimizer: AcceleratedOptimizer | None = None) -> None:
        """Explicit fp16 unscale (reference `accelerator.py:2293-2325`); normally
        `optimizer.step()` does this itself. Idempotent within one boundary —
        the optimizer's next real step clears the unscaled mark."""
        opts = [optimizer] if optimizer is not None else self._optimizers
        for opt in opts:
            if opt.scaler is not None and opt._acc_grads is not None and not opt._unscaled:
                grads, opt.scaler_state, finite = opt.scaler.unscale_and_update(
                    opt._acc_grads, opt.scaler_state
                )
                opt._acc_grads = grads
                opt.step_was_skipped = not bool(finite)
                opt._unscaled = True

    def clip_grad_norm_(self, parameters: Any = None, max_norm: float = 1.0, norm_type: float = 2.0):
        """Clip accumulated gradients by global norm, returning the pre-clip norm
        (reference `accelerator.py:2327-2382`). Unscales fp16 gradients first
        (reference behavior), computes ONE norm over every prepared optimizer's
        gradients together, and scales them all by the same factor. Runs jitted
        over the sharded grad pytrees — the cross-device reduction is XLA's."""
        if norm_type != 2.0:
            raise NotImplementedError("Only L2 global-norm clipping is supported.")
        self.unscale_gradients()
        with_grads = [opt for opt in self._optimizers if opt._acc_grads is not None]
        if not with_grads:
            return None
        clipped, total_norm = _clip_tree(
            tuple(opt._acc_grads for opt in with_grads), max_norm
        )
        for opt, tree in zip(with_grads, clipped):
            opt._acc_grads = tree
        return total_norm

    def clip_grad_value_(self, parameters: Any = None, clip_value: float = 1.0) -> None:
        for opt in self._optimizers:
            if opt._acc_grads is None:
                continue
            opt._acc_grads = jax.jit(
                lambda g: jax.tree.map(lambda x: jnp.clip(x, -clip_value, clip_value), g)
            )(opt._acc_grads)

    # ----------------------------------------------------- fused fast path
    def make_train_step(
        self,
        loss_fn: Callable,
        model: PreparedModel | None = None,
        optimizer: AcceleratedOptimizer | None = None,
        max_grad_norm: float | None = None,
        donate: bool = True,
        comm_hook: Any = None,
    ) -> Callable:
        """Build the fused jitted train step — the performance path.

        Returns ``step(batch) -> loss``. Internally: per-microbatch gradient
        computation with an in-buffer add, and on each sync boundary a single
        donated jitted update (grads mean + optional global-norm clip + optax
        update + apply). One device program per call; params/opt-state buffers are
        donated so HBM holds a single copy.

        ``comm_hook`` is the reference's DDP comm-hook analogue
        (`utils/dataclasses.py:117-213`): a `CommHookConfig` (or hook-name string:
        "fp16"/"bf16"/"power_sgd"/"batched_power_sgd") that compresses the
        cross-replica gradient reduction. Data-parallel only, like DDP comm hooks.
        With gradient accumulation the hook reduces every microbatch (DDP-without-
        no_sync semantics); the common ``k == 1`` path matches DDP exactly.

        fp16 note: overflow skip/backoff state stays on-device (no per-step
        sync), but a prepared *scheduler* must read ``step_was_skipped`` each
        boundary to mirror torch's skip-aware LR stepping — fp16 + scheduler
        therefore pays one host sync per boundary (torch's GradScaler does
        too); bf16 never does.
        """
        if model is None:
            model = self._models[0]
        if optimizer is None:
            optimizer = self._optimizer_for(model)
        if max_grad_norm is None:
            # ds_config gradient_clipping (reference applies it inside the engine)
            max_grad_norm = self.gradient_clipping
        if comm_hook is None and self.ddp_handler is not None:
            comm_hook = self.ddp_handler.to_comm_hook_config()
        policy = self.policy
        tx = optimizer.optimizer
        # NOTE: gradient_accumulation_steps is read LIVE from gradient_state at
        # every boundary (as a traced scalar, so changing it never recompiles) —
        # freezing it at build time silently mis-scaled the loss if the user
        # changed it after building the step.

        hook_cfg = None
        if comm_hook is not None:
            from .parallel.compression import CommHookConfig, init_comm_state, reduce_gradients

            if hasattr(comm_hook, "to_comm_hook_config"):  # DistributedDataParallelKwargs
                comm_hook = comm_hook.to_comm_hook_config()
            hook_cfg = CommHookConfig(comm_hook) if isinstance(comm_hook, str) else comm_hook
            if hook_cfg is not None and hook_cfg.comm_hook == "no":
                hook_cfg = None
        mesh = self.mesh
        n_replicas = 1
        if hook_cfg is not None:
            if mesh is None or mesh.shape.get("data", 1) <= 1:
                hook_cfg = None  # single replica: nothing to compress
            else:
                other = [a for a, s in mesh.shape.items() if a != "data" and s > 1]
                if other:
                    raise ValueError(
                        "comm_hook gradient compression is a data-parallel feature "
                        f"(like DDP comm hooks); mesh also shards axes {other}."
                    )
                n_replicas = mesh.shape["data"]

        scaler = optimizer.scaler if optimizer is not None else None

        def loss_and_grads(params, mstate, batch, inner):
            # mstate = mutable non-param collections (batch_stats/fp8_meta/…),
            # threaded through as value_and_grad aux — None for pure models.
            # ``inner`` is the fp16 loss-scale factor applied INSIDE the
            # reduced-precision backward (see DynamicGradScaler.split_scale);
            # 1.0 when no scaler is active.
            def f(p):
                bound = BoundModel(model.apply_fn, policy.cast_to_compute(p), mstate)
                out = loss_fn(bound, batch)
                loss = out[0] if isinstance(out, tuple) else out
                loss = loss.astype(jnp.float32)
                return loss * inner, (loss, bound.extra_state)

            (_, (loss, new_mstate)), grads = jax.value_and_grad(f, has_aux=True)(params)
            return loss, grads, new_mstate

        # lgr signature: (params, mstate, batch, comm_rep, comm_err, inner) ->
        #                (loss, grads, mstate, comm_rep, comm_err)
        def lgr_plain(params, mstate, batch, comm_rep, comm_err, inner):
            loss, grads, mstate = loss_and_grads(params, mstate, batch, inner)
            return loss, grads, mstate, comm_rep, comm_err

        lgr_hooked = None
        if hook_cfg is not None:
            from jax import shard_map
            from jax.sharding import PartitionSpec as P

            def _local(params, mstate, batch, comm_rep, comm_err, inner):
                # per-replica gradients; the only cross-replica traffic is the
                # compressed reduction + scalar loss pmean. Error-feedback buffers
                # (comm_err) stay worker-local: leading axis sharded over "data".
                loss, grads, mstate = loss_and_grads(params, mstate, batch, inner)
                grads, comm_rep, comm_err = reduce_gradients(
                    grads, comm_rep, comm_err, "data", hook_cfg
                )
                loss = jax.lax.pmean(loss, "data")
                # mutable collections are computed from the local shard; average
                # the floating leaves so the declared-replicated output is well
                # defined (SyncBN-style cross-replica statistics)
                if mstate is not None:
                    mstate = jax.tree.map(
                        lambda x: jax.lax.pmean(x, "data")
                        if jnp.issubdtype(x.dtype, jnp.floating)
                        else x,
                        mstate,
                    )
                return loss, grads, mstate, comm_rep, comm_err

            lgr_hooked = shard_map(
                _local,
                mesh=mesh,
                in_specs=(P(), P(), P("data"), P(), P("data"), P()),
                out_specs=(P(), P(), P(), P(), P("data")),
                check_vma=False,
            )

        # Pin gradients and updated params to the params' own shardings so the
        # whole fused step (grad -> clip -> optax update -> apply) carries ONE
        # consistent spec per leaf. Without this XLA is free to re-infer specs
        # in the backward, which on dp×fsdp×tp meshes produced involuntary full
        # rematerialization (VERDICT r1: spmd_partitioner warnings).
        param_shardings = getattr(model, "shardings", None)

        def constrain_like_params(tree):
            if param_shardings is None or tree is None:
                return tree
            return jax.tree.map(jax.lax.with_sharding_constraint, tree, param_shardings)

        def _split(scaler_state):
            # (inner loss-scale for the fp16 backward, its inverse factor) —
            # derived INSIDE the jit from the threaded scaler state, so there is
            # exactly one source of truth for both scaling and policy updates
            if scaler is None or scaler_state is None:
                return jnp.asarray(1.0, jnp.float32)
            inner, _ = scaler.split_scale(scaler_state.scale)
            return inner

        def make_micro(lgr):
            # acc / mstate / comm_err are consumed and replaced every call:
            # donating them keeps ONE gradient accumulator in HBM instead of
            # old+new copies during each microbatch.
            # NOTE: persistent comm-hook state is overflow-guarded per leaf
            # INSIDE reduce_gradients (compression._powersgd_leaf), so
            # non-finite microbatches can't poison it on ANY path and the
            # donated error buffers keep per-leaf lifetimes.
            @functools.partial(jax.jit, donate_argnums=(1, 2, 5) if donate else ())
            def micro_step(params, mstate, acc, batch, comm_rep, comm_err, scaler_state):
                inner = _split(scaler_state)
                loss, grads, mstate, comm_rep, comm_err = lgr(
                    params, mstate, batch, comm_rep, comm_err, inner
                )
                grads = constrain_like_params(grads)
                acc = grads if acc is None else jax.tree.map(jnp.add, acc, grads)
                return acc, mstate, loss, comm_rep, comm_err

            return micro_step

        def make_update(lgr):
            def _update(params, opt_state, mstate, acc, batch, comm_rep, comm_err, inv_k, scaler_state):
                inner = _split(scaler_state)
                loss, grads, mstate, comm_rep, comm_err = lgr(
                    params, mstate, batch, comm_rep, comm_err, inner
                )
                if acc is not None:
                    grads = jax.tree.map(jnp.add, acc, grads)
                # undo the inner loss scale and the accumulation factor in fp32
                grads = jax.tree.map(lambda g: g * (inv_k / inner), grads)
                grads = constrain_like_params(grads)
                finite = jnp.asarray(True)
                if scaler is not None:
                    finite = scaler.all_finite(grads)
                if max_grad_norm is not None:
                    grads, _ = _clip_tree(grads, max_grad_norm)
                updates, new_opt_state = tx.update(grads, opt_state, params)
                new_params = constrain_like_params(optax.apply_updates(params, updates))
                if scaler is not None:
                    # skip the update on overflow; torch-GradScaler growth/backoff
                    # (persistent comm-hook state is guarded inside the hook)
                    new_params = jax.tree.map(
                        lambda new, old: jnp.where(finite, new, old), new_params, params
                    )
                    new_opt_state = jax.tree.map(
                        lambda new, old: jnp.where(finite, new, old), new_opt_state, opt_state
                    )
                    scaler_state = scaler.update_state(scaler_state, finite)
                return new_params, new_opt_state, mstate, loss, comm_rep, comm_err, scaler_state, finite

            return jax.jit(_update, donate_argnums=(0, 1, 2, 3, 6) if donate else ())

        micro_plain, update_plain = make_micro(lgr_plain), make_update(lgr_plain)
        micro_hooked = update_hooked = None
        if hook_cfg is not None:
            micro_hooked, update_hooked = make_micro(lgr_hooked), make_update(lgr_hooked)
            comm_rep0, comm_err0 = init_comm_state(
                model.params, hook_cfg, n_replicas, mesh=mesh, axis="data"
            )
        else:
            comm_rep0 = comm_err0 = None
        warmup = hook_cfg.warmup_updates if hook_cfg is not None else 0
        state_box = {"acc": None, "count": 0, "rep": comm_rep0, "err": comm_err0}

        def step(batch: Any) -> jax.Array:
            self._do_sync()
            hooked = hook_cfg is not None and optimizer._num_updates >= warmup
            if self.gradient_state.sync_gradients:
                upd = update_hooked if hooked else update_plain
                inv_k = jnp.asarray(1.0 / self.gradient_state.num_steps, dtype=jnp.float32)
                (
                    params, opt_state, mstate, loss,
                    state_box["rep"], state_box["err"], new_scaler_state, finite,
                ) = upd(
                    model.params,
                    optimizer.opt_state,
                    model.extra_state,
                    state_box["acc"],
                    batch,
                    state_box["rep"],
                    state_box["err"],
                    inv_k,
                    optimizer.scaler_state,
                )
                model.params = params
                optimizer.opt_state = opt_state
                model.extra_state = mstate
                if scaler is not None:
                    optimizer.scaler_state = new_scaler_state
                    # lazy device scalars: reading (bool()/int()) syncs,
                    # assigning doesn't — skipped boundaries never count as
                    # applied updates (imperative-path semantics)
                    optimizer.step_was_skipped = jnp.logical_not(finite)
                    optimizer._skipped_updates = (
                        optimizer._skipped_updates + jnp.logical_not(finite).astype(jnp.int32)
                    )
                # boundary count: drives comm-hook warmup; `num_updates`
                # subtracts the device-tracked skips on read
                optimizer._num_updates += 1
                state_box["acc"] = None
                state_box["count"] = 0
            else:
                micro = micro_hooked if hooked else micro_plain
                state_box["acc"], model.extra_state, loss, state_box["rep"], state_box["err"] = (
                    micro(
                        model.params,
                        model.extra_state,
                        state_box["acc"],
                        batch,
                        state_box["rep"],
                        state_box["err"],
                        optimizer.scaler_state,
                    )
                )
                state_box["count"] += 1
            return loss

        return step

    # -------------------------------------------------------- pipeline training
    def prepare_pipeline(
        self,
        stage_fn: Callable,
        per_stage_params: Any,
        *,
        pre: tuple[Callable, Any] | None = None,
        post: tuple[Callable, Any] | None = None,
        num_microbatches: int = 1,
        axis_name: str = "stage",
    ) -> PreparedModel:
        """Prepare a GPipe pipeline model over the mesh's ``stage`` axis.

        ``per_stage_params`` is a list of per-stage param pytrees (one per
        pipeline stage, all for the same homogeneous ``stage_fn``) or an
        already-stacked tree with a leading stage dim. ``pre``/``post`` are
        optional ``(fn, params)`` pairs for the replicated embedding/head
        around the pipelined trunk. The returned `PreparedModel` carries
        stage-axis shardings, so `save_state`/`load_state` round-trip the
        stage-sharded weights through orbax like any other model, and a
        prepared optimizer's state lands stage-sharded for free.

        Reference role: Megatron-LM model prep (`utils/megatron_lm.py` pp>1
        model partitioning) — here a sharding annotation, not an engine.
        """
        from .parallel.pipeline import pipeline_apply
        from .parallel.pipeline_train import build_pipeline_params, stage_shardings

        if self.mesh is None or self.mesh.shape.get(axis_name, 1) <= 1:
            raise ValueError(
                f"prepare_pipeline needs a mesh with a non-trivial {axis_name!r} axis "
                "(ParallelismConfig(stage_size=...))."
            )
        pre_fn, pre_params = pre if pre is not None else (None, None)
        post_fn, post_params = post if post is not None else (None, None)
        stage_size = self.mesh.shape[axis_name]
        if isinstance(per_stage_params, list) and len(per_stage_params) != stage_size:
            raise ValueError(
                f"got {len(per_stage_params)} per-stage param trees for a mesh "
                f"with {axis_name} axis size {stage_size}; pipeline stages must "
                "match the mesh one-to-one."
            )
        params = build_pipeline_params(per_stage_params, pre_params, post_params)
        params = self.policy.cast_to_param(params)
        shardings = stage_shardings(params, self.mesh, axis_name)
        if self.device_placement:
            params = shard_params(params, shardings)
        mesh = self.mesh

        def apply_fn(p, x):
            h = pre_fn(p["pre"], x) if pre_fn is not None else x
            y = pipeline_apply(
                stage_fn, p["stages"], h, mesh, num_microbatches, axis_name=axis_name
            )
            return post_fn(p["post"], y) if post_fn is not None else y

        prepared = PreparedModel(
            apply_fn,
            params,
            policy=self.policy,
            mesh=mesh,
            shardings=shardings,
            module=stage_fn,
        )
        self._models.append(prepared)
        return prepared

    def make_pipeline_train_step(
        self,
        stage_fn: Callable,
        loss_fn: Callable,
        model: PreparedModel | None = None,
        optimizer: AcceleratedOptimizer | None = None,
        *,
        num_microbatches: int,
        pre_fn: Callable | None = None,
        post_fn: Callable | None = None,
        max_grad_norm: float | None = None,
        donate: bool = True,
        axis_name: str = "stage",
    ) -> Callable:
        """`make_train_step` sibling for a `prepare_pipeline` model: one jitted
        SPMD program runs the GPipe microbatch schedule, backward, gradient
        accumulation and the optimizer tick over the ``stage`` mesh axis
        (reference Megatron train_step role, `utils/megatron_lm.py:1035-1057`).
        ``step(batch) -> loss`` with ``batch = (x, targets)``."""
        from .parallel.pipeline_train import make_pipeline_train_step

        return make_pipeline_train_step(
            self,
            stage_fn,
            loss_fn,
            model,
            optimizer,
            num_microbatches=num_microbatches,
            pre_fn=pre_fn,
            post_fn=post_fn,
            max_grad_norm=max_grad_norm,
            donate=donate,
            axis_name=axis_name,
        )

    # ------------------------------------------------------------- collectives
    def gather(self, tensor: Any) -> Any:
        return operations.gather(tensor)

    def gather_for_metrics(self, input_data: Any, use_gather_object: bool = False) -> Any:
        """Gather eval outputs and drop the duplicated tail of the final ragged
        batch (reference `accelerator.py:2443-2505` + GradientState.remainder)."""
        if use_gather_object or not _all_tensors(input_data):
            data = operations.gather_object(
                input_data if isinstance(input_data, list) else [input_data]
            )
        else:
            data = operations.gather(input_data)
        try:
            on_last = self.gradient_state.end_of_dataloader
            remainder = self.gradient_state.remainder
        except Exception:
            return data
        if on_last and remainder > 0:
            data = operations.recursively_apply(lambda t: t[:remainder], data)
        return data

    def reduce(self, tensor: Any, reduction: str = "sum", scale: float = 1.0) -> Any:
        return operations.reduce(tensor, reduction=reduction, scale=scale)

    def pad_across_processes(self, tensor: Any, dim: int = 0, pad_index: int = 0, pad_first: bool = False):
        return operations.pad_across_processes(tensor, dim=dim, pad_index=pad_index, pad_first=pad_first)

    def broadcast(self, tensor: Any, from_process: int = 0) -> Any:
        return operations.broadcast(tensor, from_process=from_process)

    # -------------------------------------------------------------- triggers
    def set_trigger(self) -> None:
        """Set a breakpoint flag visible to all processes (reference
        `accelerator.py:2233-2290` — coordinated early-stop)."""
        self.flag_tensor = np.array([1], dtype=np.int64)

    def check_trigger(self) -> bool:
        flag = self.flag_tensor if self.flag_tensor is not None else np.array([0], dtype=np.int64)
        total = operations.reduce(flag, reduction="sum")
        if int(np.asarray(total)[0]) > 0:
            self.flag_tensor = None
            return True
        return False

    # -------------------------------------------------------------- contexts
    def save(self, obj: Any, f: str, safe_serialization: bool = False) -> None:
        """Rank-gated serialization of any object (reference `Accelerator.save`
        -> `utils/other.py:save`): array pytrees go to safetensors when
        ``safe_serialization`` (interchange format), anything else to pickle
        with array leaves converted to host numpy. Main process writes; other
        ranks no-op."""
        from .utils.other import save as _save

        _save(obj, f, safe_serialization=safe_serialization)

    @property
    def optimizer_step_was_skipped(self) -> bool:
        """True when any prepared optimizer skipped its last step (fp16
        overflow) — reference `Accelerator.optimizer_step_was_skipped`."""
        return any(bool(opt.step_was_skipped) for opt in self._optimizers)

    @property
    def use_seedable_sampler(self) -> bool:
        return self._use_seedable_sampler

    @property
    def non_blocking(self) -> bool:
        """Device transfers are asynchronous by nature in JAX (reference flag
        parity: always True)."""
        return True

    @property
    def use_stateful_dataloader(self) -> bool:
        """Echoes ``DataLoaderConfiguration.use_stateful_dataloader``. Prepared
        loaders here support state_dict/load_state_dict regardless (no
        torchdata dependency); the flag records the user's intent for
        reference-code compatibility."""
        return self._use_stateful_dataloader

    @property
    def save_iteration(self) -> int:
        """Next automatic checkpoint index (reference `save_iteration`)."""
        return self.project_configuration.iteration

    @property
    def fp8_backend(self) -> str | None:
        """'NATIVE' when fp8 training is configured (XLA-native delayed-scaling
        path, `ops/fp8.py`) — the reference reports TE/MSAMP here."""
        if self.mixed_precision == "fp8" or self.fp8_recipe_handler is not None:
            return "NATIVE"
        return None

    def verify_device_map(self, model: Any) -> bool:
        """True when ``model`` carries a multi-entry big-model device map;
        `prepare` calls this and refuses such models (reference
        `accelerator.py` verify_device_map role)."""
        device_map = getattr(model, "device_map", None)
        return isinstance(device_map, dict) and len(device_map) > 1

    def register_save_state_pre_hook(self, hook: Callable) -> "_RemovableHandle":
        """``hook(models, weights, output_dir)`` runs at the top of
        `save_state` (reference `accelerator.py` register_save_state_pre_hook);
        mutate ``weights`` in place to customize what is persisted."""
        handle = _RemovableHandle(self._save_state_pre_hooks)
        self._save_state_pre_hooks[handle.id] = hook
        return handle

    def register_load_state_pre_hook(self, hook: Callable) -> "_RemovableHandle":
        """``hook(models, input_dir)`` runs at the top of `load_state`."""
        handle = _RemovableHandle(self._load_state_pre_hooks)
        self._load_state_pre_hooks[handle.id] = hook
        return handle

    @contextlib.contextmanager
    def autocast(self, autocast_handler: Any = None):
        """Reference `accelerator.py:3422`. Precision is a functional cast
        policy applied inside prepared forwards, so *enabling* is the ambient
        state; the context's real lever is ``AutocastKwargs(enabled=False)``,
        which makes eager `PreparedModel` calls inside the block skip the
        compute-dtype cast (numerically sensitive regions run in the fp32
        master dtype)."""
        from .utils.precision import reset_autocast_enabled, set_autocast_enabled

        handler = autocast_handler or self.autocast_handler
        enabled = handler.enabled if handler is not None else True
        token = set_autocast_enabled(enabled)
        try:
            yield
        finally:
            reset_autocast_enabled(token)

    @contextlib.contextmanager
    def profile(self, profile_handler: Any = None, log_dir: str | None = None):
        """jax.profiler trace context, one trace per host (reference
        `accelerator.py:3449-3506` / torch.profiler). ``profile_handler``
        defaults to the ProfileKwargs passed via ``kwargs_handlers``."""
        handler = profile_handler or self.profile_handler
        target = log_dir or (
            (handler.output_trace_dir if handler is not None else None)
            or self.project_configuration.logging_dir
            or "profile_traces"
        )
        jax.profiler.start_trace(
            target,
            create_perfetto_link=bool(handler.create_perfetto_link) if handler is not None else False,
        )
        try:
            yield
        finally:
            jax.profiler.stop_trace()

    # ---------------------------------------------------------- model export
    def unwrap_model(self, model: PreparedModel, keep_fp32_wrapper: bool = True) -> Any:
        """Return the original module the user handed to prepare (reference
        `extract_model_from_parallel`, `utils/other.py:64-133`)."""
        from .utils.other import extract_model_from_parallel

        return extract_model_from_parallel(model, keep_fp32_wrapper=keep_fp32_wrapper)

    def get_state_dict(self, model: PreparedModel, unwrap: bool = True, main_process_only: bool = False) -> Any:
        """Fully-gathered (unsharded) parameter pytree on host (reference
        `accelerator.py:3329-3383` — FSDP FULL_STATE_DICT / ZeRO-3 consolidation).

        Leaves stream to host one at a time. With ``main_process_only`` the
        rank0-only consolidation semantics apply: non-main processes receive
        ``None`` leaves and never hold a full replica (the safe mode for
        big models — every process must still make the call, it is collective)."""
        return operations.consolidate_on_main(model.params, keep_on_all=not main_process_only)

    def free_memory(self, *objects: Any) -> tuple:
        """Drop references to prepared objects and clear compiled caches
        (reference `accelerator.py:3257-3289`)."""
        self._models.clear()
        self._optimizers.clear()
        self._schedulers.clear()
        self._dataloaders.clear()
        self._grad_fns.clear()
        self._train_steps.clear()
        self.step = 0
        jax.clear_caches()
        return objects

    def clear(self, *objects: Any) -> tuple:
        return self.free_memory(*objects)

    # ----------------------------------------------------------- checkpointing
    def register_for_checkpointing(self, *objects: Any) -> None:
        """Track custom stateful objects for save_state/load_state (reference
        `accelerator.py:3385`). Objects must expose state_dict/load_state_dict."""
        invalid = [o for o in objects if not (hasattr(o, "state_dict") and hasattr(o, "load_state_dict"))]
        if invalid:
            raise ValueError(f"Objects lack state_dict/load_state_dict: {invalid}")
        self._custom_objects.extend(objects)

    def save_state(
        self, output_dir: str | None = None, async_save: bool | None = None, **save_model_kwargs: Any
    ) -> str:
        """``async_save`` (default: ``ProjectConfiguration.async_save``) returns
        once device arrays are copied to host; disk writes complete in the
        background and are barriered at the next save/load/`wait_for_checkpoint`/exit."""
        from .checkpointing import get_checkpoint_dir, save_accelerator_state

        resolved = str(get_checkpoint_dir(self, output_dir))  # hooks see the real dir
        weights = [m.params for m in self._models]
        for hook in self._save_state_pre_hooks.values():
            hook(self._models, weights, resolved)  # hooks may replace entries
        if async_save is None:
            async_save = self.project_configuration.async_save
        return save_accelerator_state(self, resolved, weights=weights, async_save=async_save)

    def wait_for_checkpoint(self) -> None:
        """Block until every async save_state has fully landed on disk."""
        from .checkpointing import wait_for_checkpoint_saves

        wait_for_checkpoint_saves()

    def load_state(self, input_dir: str | None = None, **load_model_kwargs: Any) -> str:
        """With ``input_dir=None``, recovery walks the complete-checkpoint
        chain newest-first and restores from the first directory that loads
        cleanly (a corrupt latest checkpoint falls back instead of failing) —
        pre-hooks observe the newest candidate. Returns the directory actually
        restored."""
        from .checkpointing import latest_checkpoint_dir, load_accelerator_state

        resolved = str(latest_checkpoint_dir(self)) if input_dir is None else str(input_dir)
        for hook in self._load_state_pre_hooks.values():
            hook(self._models, resolved)
        return load_accelerator_state(self, input_dir)

    def save_model(
        self,
        model: PreparedModel,
        save_directory: str,
        max_shard_size: str | int = "10GB",
        safe_serialization: bool = True,
    ) -> None:
        from .checkpointing import save_model_weights

        save_model_weights(
            self.get_state_dict(model, main_process_only=True),
            save_directory,
            max_shard_size=max_shard_size,
            safe_serialization=safe_serialization,
        )

    # ---------------------------------------------------------------- tracking
    def init_trackers(self, project_name: str, config: dict | None = None, init_kwargs: dict | None = None):
        from .tracking import filter_trackers

        self.trackers = filter_trackers(
            self._log_with, self.project_configuration.logging_dir, project_name, config,
            init_kwargs or {},
        )

    def log(self, values: dict, step: int | None = None, log_kwargs: dict | None = None) -> None:
        if not self.is_main_process:
            return
        for tracker in self.trackers:
            tracker.log(values, step=step, **(log_kwargs or {}).get(tracker.name, {}))

    def get_tracker(self, name: str, unwrap: bool = False):
        for tracker in self.trackers:
            if tracker.name == name:
                return tracker.tracker if unwrap else tracker
        raise ValueError(f"Tracker {name} not initialized (have: {[t.name for t in self.trackers]})")

    def end_training(self) -> None:
        for tracker in self.trackers:
            tracker.finish()
        self.wait_for_everyone()

    # ------------------------------------------------------------- loader utils
    def skip_first_batches(self, dataloader: Any, num_batches: int = 0) -> Any:
        return skip_first_batches(dataloader, num_batches)

    def __repr__(self) -> str:
        return (
            f"Accelerator(mesh={dict(self.mesh.shape)}, mixed_precision={self.mixed_precision!r}, "
            f"grad_accum={self.gradient_state.num_steps})"
        )


def _all_tensors(data: Any) -> bool:
    ok = True

    def _check(t):
        nonlocal ok
        return t

    flat = jax.tree.leaves(data)
    return all(hasattr(leaf, "shape") and hasattr(leaf, "dtype") for leaf in flat)


@jax.jit
def _clip_tree(grads: Any, max_norm: float):
    norm = optax.global_norm(grads)
    factor = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree.map(lambda g: g * factor, grads), norm


def _clip_by_global_norm(grads: Any, max_norm: float):
    return _clip_tree(grads, max_norm)
