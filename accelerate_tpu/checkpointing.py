"""Checkpoint save/load orchestration.

Capability parity: reference `src/accelerate/checkpointing.py` (306 LoC) +
`Accelerator.save_state/load_state` (`accelerator.py:2953-3255`): rotating
``checkpoints/checkpoint_<i>`` directories with ``total_limit`` pruning, per-object
model/optimizer/scheduler/dataloader/RNG/custom-object state, and model-only
consolidated export (`save_model`, `accelerator.py:2804-2919`).

TPU-native re-founding: sharded arrays are written with orbax (tensorstore under
the hood) — every host writes only its own shards in parallel and restore re-places
them onto the mesh; this natively covers what the reference needs
`SHARDED_STATE_DICT` + `merge_fsdp_weights` machinery for. Host-side state (RNG,
sampler positions, step counters) is written by process 0 only.
"""

from __future__ import annotations

import atexit
import os
import pickle
import shutil
from pathlib import Path
from typing import Any

import jax
import numpy as np

from .state import PartialState
from .utils.constants import (
    CHECKPOINT_DIR_PREFIX,
    CUSTOM_STATE_NAME,
    DATALOADER_STATE_NAME,
    MODEL_NAME,
    OPTIMIZER_NAME,
    RNG_STATE_NAME,
    SCHEDULER_NAME,
    STEP_STATE_NAME,
)
from .utils.random import capture_rng_state, restore_rng_state


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


# In-flight async savers (orbax ``StandardCheckpointer`` IS an
# ``AsyncCheckpointer``: ``save`` copies device arrays to host synchronously —
# so training may immediately mutate/donate params — then persists to disk in a
# background thread; ``close`` joins it). SURVEY §7.6 async sharded save.
_PENDING_SAVES: list[Any] = []


def wait_for_checkpoint_saves() -> None:
    """Barrier: block until every scheduled async save has fully landed on disk.

    Called automatically before the next save (so directory rotation can't
    delete a checkpoint mid-write), before any restore, and at process exit —
    the reference's synchronous ``save_state`` semantics are thus preserved at
    every point where they are observable."""
    while _PENDING_SAVES:
        ckptr = _PENDING_SAVES.pop()
        try:
            ckptr.wait_until_finished()
        finally:
            ckptr.close()


atexit.register(wait_for_checkpoint_saves)


def _save_pytree(path: Path, tree: Any, async_save: bool = False) -> None:
    ocp = _ocp()
    if async_save:
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(path.absolute(), tree)
        _PENDING_SAVES.append(ckptr)
        return
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path.absolute(), tree)


def _restore_pytree(path: Path, target: Any | None = None) -> Any:
    from jax.sharding import NamedSharding, PartitionSpec

    from .state import AcceleratorState

    wait_for_checkpoint_saves()
    ocp = _ocp()
    mesh = AcceleratorState().mesh if AcceleratorState._shared_state else None

    def _sharding_for(x):
        s = getattr(x, "sharding", None)
        if isinstance(s, NamedSharding) or mesh is None:
            return s
        # Leaves that never went through shard_params (e.g. optax step counters
        # created by tx.init) live uncommitted on the default device; jit mixes
        # them freely with mesh-placed params. Orbax restores them COMMITTED to
        # one device, which jit then rejects next to 8-device params — so
        # restore such leaves replicated on the mesh instead.
        return NamedSharding(mesh, PartitionSpec())

    with ocp.StandardCheckpointer() as ckptr:
        if target is None:
            return ckptr.restore(path.absolute())
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=_sharding_for(x))
            if hasattr(x, "shape")
            else x,
            target,
        )
        return ckptr.restore(path.absolute(), abstract)


def _restore_pytree_host(path: Path) -> Any:
    """Topology-independent restore: rebuild the abstract tree from the
    checkpoint's own metadata with NO shardings, so a checkpoint written by an
    N-process mesh consolidates on a single host — the merge-weights path
    (reference `utils/fsdp_utils.py:274` merge_fsdp_weights role). A plain
    ``restore(path)`` would try to re-materialize the saved device topology
    and fail off-cluster."""
    wait_for_checkpoint_saves()
    ocp = _ocp()
    host = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    with ocp.StandardCheckpointer() as ckptr:
        meta = ckptr.metadata(path.absolute()).item_metadata.tree
        abstract = jax.tree.map(
            lambda m: jax.ShapeDtypeStruct(m.shape, np.dtype(m.dtype), sharding=host)
            if hasattr(m, "shape")
            else m,
            meta,
        )
        return ckptr.restore(path.absolute(), abstract)


def _save_host_state(path: Path, obj: Any) -> None:
    if PartialState().is_main_process:
        with open(path, "wb") as f:
            pickle.dump(obj, f)


def _load_host_state(path: Path) -> Any:
    with open(path, "rb") as f:
        return pickle.load(f)


def get_checkpoint_dir(accelerator, output_dir: str | None) -> Path:
    """Resolve (and rotate) the checkpoint directory (reference
    `accelerator.py:2991-3016` automatic naming + total_limit pruning)."""
    pc = accelerator.project_configuration
    if output_dir is not None:
        return Path(output_dir)
    base = Path(pc.project_dir or ".") / "checkpoints"
    base.mkdir(parents=True, exist_ok=True)
    if pc.automatic_checkpoint_naming:
        # rotation may delete a directory a previous async save is still
        # writing — land all in-flight bytes before pruning
        wait_for_checkpoint_saves()
        existing = sorted(
            (
                d
                for d in base.iterdir()
                if d.name.startswith(CHECKPOINT_DIR_PREFIX + "_")
                and d.name.rsplit("_", 1)[1].isdigit()
            ),
            key=lambda d: int(d.name.rsplit("_", 1)[1]),
        )
        if pc.total_limit is not None and len(existing) + 1 > pc.total_limit:
            for stale in existing[: len(existing) + 1 - pc.total_limit]:
                if PartialState().is_main_process:
                    shutil.rmtree(stale, ignore_errors=True)
        target = base / f"{CHECKPOINT_DIR_PREFIX}_{pc.iteration}"
        pc.iteration += 1
        return target
    return base


def _is_complete_checkpoint(d: Path) -> bool:
    """A preemption/SIGKILL between an async save_state returning and its
    background writes committing leaves orbax's atomic-rename temp dirs
    (``*.orbax-checkpoint-tmp-*``) next to — instead of — the final array
    dirs. Such a directory must not be offered to load_state(None): automatic
    recovery should fall back to the previous intact checkpoint."""
    try:
        entries = list(d.iterdir())
    except OSError:
        return False
    return bool(entries) and not any("orbax-checkpoint-tmp" in e.name for e in entries)


def latest_checkpoint_dir(accelerator) -> Path:
    """Most recent COMPLETE automatic checkpoint directory (for load_state(None));
    directories left incomplete by a crash mid-async-write are skipped."""
    wait_for_checkpoint_saves()  # our own in-flight saves must not look crashed
    pc = accelerator.project_configuration
    base = Path(pc.project_dir or ".") / "checkpoints"
    candidates = sorted(
        (
            d
            for d in base.iterdir()
            if d.name.startswith(CHECKPOINT_DIR_PREFIX + "_")
            and d.name.rsplit("_", 1)[1].isdigit()
            and _is_complete_checkpoint(d)
        ),
        key=lambda d: int(d.name.rsplit("_", 1)[1]),
    ) if base.exists() else []
    if not candidates:
        raise FileNotFoundError(f"No complete checkpoints under {base}")
    return candidates[-1]


def save_accelerator_state(
    accelerator,
    output_dir: str | None = None,
    weights: list | None = None,
    async_save: bool = False,
) -> str:
    """Serialize every prepared object's state (reference `checkpointing.py:53-162`).
    ``weights`` (from the save-state pre-hooks) overrides what is persisted per
    model, without touching the live params.

    With ``async_save`` the array pytrees are copied to host synchronously but
    written to disk in background threads: the call returns as soon as the
    host-side state is down, and the bytes are guaranteed on disk by the next
    save/restore/rotation or ``wait_for_checkpoint_saves()``/process exit."""
    wait_for_checkpoint_saves()  # at most one in-flight checkpoint generation
    out = get_checkpoint_dir(accelerator, output_dir)
    state = PartialState()
    out.mkdir(parents=True, exist_ok=True)

    for i, model in enumerate(accelerator._models):
        _save_pytree(
            out / f"{MODEL_NAME}_{i}",
            weights[i] if weights is not None else model.params,
            async_save=async_save,
        )
        if getattr(model, "extra_state", None) is not None:
            _save_pytree(out / f"{MODEL_NAME}_{i}.extra", model.extra_state, async_save=async_save)
    for i, opt in enumerate(accelerator._optimizers):
        sd = opt.state_dict()
        _save_pytree(out / f"{OPTIMIZER_NAME}_{i}", sd["opt_state"], async_save=async_save)
        meta = {k: v for k, v in sd.items() if k != "opt_state"}
        meta["scaler_state"] = (
            jax.tree.map(lambda x: np.asarray(x), meta["scaler_state"]) if "scaler_state" in meta else None
        )
        _save_host_state(out / f"{OPTIMIZER_NAME}_{i}.meta.pkl", meta)
    for i, sched in enumerate(accelerator._schedulers):
        _save_host_state(out / f"{SCHEDULER_NAME}_{i}.pkl", sched.state_dict())
    for i, dl in enumerate(accelerator._dataloaders):
        _save_host_state(out / f"{DATALOADER_STATE_NAME}_{i}.pkl", dl.state_dict())
    for i, obj in enumerate(accelerator._custom_objects):
        _save_host_state(out / f"{CUSTOM_STATE_NAME}_{i}.pkl", obj.state_dict())
    _save_host_state(out / f"{RNG_STATE_NAME}.pkl", capture_rng_state())
    _save_host_state(out / f"{STEP_STATE_NAME}.pkl", {"step": accelerator.step})
    state.wait_for_everyone()
    return str(out)


def load_accelerator_state(accelerator, input_dir: str | None = None) -> None:
    """Restore every prepared object (reference `checkpointing.py:165-286`).
    Sharded arrays are re-placed directly onto their mesh positions."""
    if input_dir is None:
        input_dir = str(latest_checkpoint_dir(accelerator))
    src = Path(input_dir)

    for i, model in enumerate(accelerator._models):
        model.params = _restore_pytree(src / f"{MODEL_NAME}_{i}", target=model.params)
        extra_path = src / f"{MODEL_NAME}_{i}.extra"
        if extra_path.exists() and getattr(model, "extra_state", None) is not None:
            model.extra_state = _restore_pytree(extra_path, target=model.extra_state)
    for i, opt in enumerate(accelerator._optimizers):
        opt_state = _restore_pytree(src / f"{OPTIMIZER_NAME}_{i}", target=opt.opt_state)
        meta_path = src / f"{OPTIMIZER_NAME}_{i}.meta.pkl"
        meta = _load_host_state(meta_path) if meta_path.exists() else {}
        opt.load_state_dict({"opt_state": opt_state, **{k: v for k, v in meta.items() if v is not None}})
    for i, sched in enumerate(accelerator._schedulers):
        sched.load_state_dict(_load_host_state(src / f"{SCHEDULER_NAME}_{i}.pkl"))
    for i, dl in enumerate(accelerator._dataloaders):
        dl.load_state_dict(_load_host_state(src / f"{DATALOADER_STATE_NAME}_{i}.pkl"))
    for i, obj in enumerate(accelerator._custom_objects):
        obj.load_state_dict(_load_host_state(src / f"{CUSTOM_STATE_NAME}_{i}.pkl"))
    rng_path = src / f"{RNG_STATE_NAME}.pkl"
    if rng_path.exists():
        restore_rng_state(_load_host_state(rng_path))
    step_path = src / f"{STEP_STATE_NAME}.pkl"
    if step_path.exists():
        accelerator.step = _load_host_state(step_path)["step"]


def save_custom_state(obj: Any, path: str | os.PathLike, index: int = 0) -> str:
    """Persist ONE registered custom object (reference `save_custom_state`,
    `checkpointing.py:240`): anything exposing ``state_dict()``, written by
    process 0 as `custom_checkpoint_<index>.pkl`."""
    target = Path(path) / f"{CUSTOM_STATE_NAME}_{index}.pkl"
    _save_host_state(target, obj.state_dict())
    return str(target)


def load_custom_state(obj: Any, path: str | os.PathLike, index: int = 0) -> None:
    """Restore ONE custom object saved by `save_custom_state` (reference
    `load_custom_state`, `checkpointing.py:252`)."""
    obj.load_state_dict(_load_host_state(Path(path) / f"{CUSTOM_STATE_NAME}_{index}.pkl"))


def save_model_weights(
    state_dict: Any,
    save_directory: str,
    max_shard_size: str | int = "10GB",
    safe_serialization: bool = True,
) -> list[str]:
    """Consolidated (unsharded) model export for interchange (reference
    `save_model`, `accelerator.py:2804-2919`), written by process 0:
    sharded ``.safetensors`` + index with tied-weight dedup by default, or flax
    msgpack with ``safe_serialization=False``. Counterpart of the sharded orbax
    layout above.

    Quantized (``QuantizedTensor``) leaves are dequantized to dense arrays on
    export — the interchange format is dense weights, matching how quantized
    models re-enter through ``quantize_params`` at load."""
    if not PartialState().is_main_process:
        return []
    from .utils.quantization import QuantizedTensor, dequantize_params

    if any(isinstance(l, QuantizedTensor)
           for l in jax.tree.leaves(state_dict,
                                    is_leaf=lambda l: isinstance(l, QuantizedTensor))):
        state_dict = dequantize_params(state_dict)
    os.makedirs(save_directory, exist_ok=True)
    if safe_serialization:
        from .utils.safetensors_io import save_safetensors_checkpoint

        return save_safetensors_checkpoint(state_dict, save_directory, max_shard_size=max_shard_size)
    from flax import serialization

    as_np = jax.tree.map(lambda x: np.asarray(x) if hasattr(x, "shape") else x, state_dict)
    payload = serialization.msgpack_serialize(as_np)
    out = Path(save_directory) / "model.msgpack"
    with open(out, "wb") as f:
        f.write(payload)
    return [str(out)]


def load_model_weights(save_directory: str) -> Any:
    """Load a consolidated export — safetensors (sharded or single) or msgpack,
    whichever is present."""
    directory = Path(save_directory)
    if not (directory / "model.msgpack").exists():
        from .utils.safetensors_io import load_safetensors_checkpoint

        return load_safetensors_checkpoint(directory, nested=True)
    from flax import serialization

    with open(directory / "model.msgpack", "rb") as f:
        return serialization.msgpack_restore(f.read())
