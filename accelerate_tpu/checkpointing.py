"""Checkpoint save/load orchestration.

Capability parity: reference `src/accelerate/checkpointing.py` (306 LoC) +
`Accelerator.save_state/load_state` (`accelerator.py:2953-3255`): rotating
``checkpoints/checkpoint_<i>`` directories with ``total_limit`` pruning, per-object
model/optimizer/scheduler/dataloader/RNG/custom-object state, and model-only
consolidated export (`save_model`, `accelerator.py:2804-2919`).

TPU-native re-founding: sharded arrays are written with orbax (tensorstore under
the hood) — every host writes only its own shards in parallel and restore re-places
them onto the mesh; this natively covers what the reference needs
`SHARDED_STATE_DICT` + `merge_fsdp_weights` machinery for. Host-side state (RNG,
sampler positions, step counters) is written by process 0 only.
"""

from __future__ import annotations

import atexit
import os
import pickle
import shutil
import warnings
from pathlib import Path
from typing import Any

import jax
import numpy as np

from .reliability.faults import (
    SCOPE_CHECKPOINT_RESTORE,
    SCOPE_CHECKPOINT_SAVE,
    fault_point,
)
from .reliability.retry import RetryPolicy
from .state import PartialState
from .utils.constants import (
    CHECKPOINT_COMPLETE_MARKER,
    CHECKPOINT_DIR_PREFIX,
    CUSTOM_STATE_NAME,
    DATALOADER_STATE_NAME,
    MODEL_NAME,
    OPTIMIZER_NAME,
    RNG_STATE_NAME,
    SCHEDULER_NAME,
    STEP_STATE_NAME,
)
from .utils.random import capture_rng_state, restore_rng_state


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


# In-flight async savers (orbax ``StandardCheckpointer`` IS an
# ``AsyncCheckpointer``: ``save`` copies device arrays to host synchronously —
# so training may immediately mutate/donate params — then persists to disk in a
# background thread; ``close`` joins it). SURVEY §7.6 async sharded save.
_PENDING_SAVES: list[Any] = []

# Checkpoint dirs awaiting their _COMPLETE commit marker: an async generation
# is committed only once wait_for_checkpoint_saves() has joined every writer
# without error. At most one generation is in flight (save barriers at entry).
_PENDING_COMMITS: list[Path] = []

# Transient-I/O retry for every save/restore touchpoint (docs/reliability.md).
# Module-level so deployments can swap in a tighter/looser policy.
CHECKPOINT_RETRY_POLICY = RetryPolicy(max_attempts=3, base_delay_s=0.05,
                                      max_delay_s=1.0, retryable=(OSError,))


class CheckpointSaveError(Exception):
    """One or more async checkpoint writers failed; ``errors`` holds every
    underlying exception (the whole pending list is drained regardless)."""

    def __init__(self, errors: list[BaseException]):
        super().__init__(
            f"{len(errors)} async checkpoint writer(s) failed: "
            + "; ".join(repr(e) for e in errors)
        )
        self.errors = errors


class CheckpointRestoreError(Exception):
    """Every complete checkpoint in the fallback chain failed to restore;
    ``errors`` holds the per-checkpoint failures newest-first."""

    def __init__(self, errors: list[BaseException]):
        super().__init__(
            f"all {len(errors)} complete checkpoint(s) failed to restore: "
            + "; ".join(repr(e) for e in errors)
        )
        self.errors = errors


def _commit_checkpoint(d: Path) -> None:
    """Land the `_COMPLETE` marker — the crash-consistency line: a directory
    without it is treated as torn and skipped by `latest_checkpoint_dir`."""
    if PartialState().is_main_process:
        (d / CHECKPOINT_COMPLETE_MARKER).write_text("complete\n")


def wait_for_checkpoint_saves() -> None:
    """Barrier: block until every scheduled async save has fully landed on disk.

    Called automatically before the next save (so directory rotation can't
    delete a checkpoint mid-write), before any restore, and at process exit —
    the reference's synchronous ``save_state`` semantics are thus preserved at
    every point where they are observable.

    The WHOLE pending list is drained and every saver closed even when one
    ``wait_until_finished`` raises (a partial drain would leak writer threads
    and orphan savers); failures re-raise aggregated as `CheckpointSaveError`.
    Only after an error-free drain are pending generations committed with
    their `_COMPLETE` marker."""
    errors: list[BaseException] = []
    while _PENDING_SAVES:
        ckptr = _PENDING_SAVES.pop()
        try:
            ckptr.wait_until_finished()
        except BaseException as exc:
            errors.append(exc)
        finally:
            try:
                ckptr.close()
            except BaseException as exc:
                errors.append(exc)
    if errors:
        # the in-flight generation may be torn — leave it uncommitted so
        # recovery falls back to the previous intact checkpoint
        _PENDING_COMMITS.clear()
        if len(errors) == 1:
            raise errors[0]
        raise CheckpointSaveError(errors)
    while _PENDING_COMMITS:
        _commit_checkpoint(_PENDING_COMMITS.pop())


atexit.register(wait_for_checkpoint_saves)


def _save_pytree(path: Path, tree: Any, async_save: bool = False) -> None:
    ocp = _ocp()
    if async_save:
        ckptr = ocp.StandardCheckpointer()

        def _schedule():
            fault_point(SCOPE_CHECKPOINT_SAVE)
            ckptr.save(path.absolute(), tree)

        try:
            # retries cover the synchronous device->host + scheduling half of
            # the async save; background-write failures surface (aggregated)
            # at the next wait_for_checkpoint_saves() barrier
            CHECKPOINT_RETRY_POLICY.call(_schedule)
        except BaseException:
            ckptr.close()
            raise
        _PENDING_SAVES.append(ckptr)
        return

    def _save():
        fault_point(SCOPE_CHECKPOINT_SAVE)
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(path.absolute(), tree)

    CHECKPOINT_RETRY_POLICY.call(_save)


def _restore_pytree(path: Path, target: Any | None = None) -> Any:
    from jax.sharding import NamedSharding, PartitionSpec

    from .state import AcceleratorState

    wait_for_checkpoint_saves()
    ocp = _ocp()
    mesh = AcceleratorState().mesh if AcceleratorState._shared_state else None

    def _sharding_for(x):
        s = getattr(x, "sharding", None)
        if isinstance(s, NamedSharding) or mesh is None:
            return s
        # Leaves that never went through shard_params (e.g. optax step counters
        # created by tx.init) live uncommitted on the default device; jit mixes
        # them freely with mesh-placed params. Orbax restores them COMMITTED to
        # one device, which jit then rejects next to 8-device params — so
        # restore such leaves replicated on the mesh instead.
        return NamedSharding(mesh, PartitionSpec())

    def _restore():
        fault_point(SCOPE_CHECKPOINT_RESTORE)
        with ocp.StandardCheckpointer() as ckptr:
            if target is None:
                return ckptr.restore(path.absolute())
            abstract = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=_sharding_for(x))
                if hasattr(x, "shape")
                else x,
                target,
            )
            return ckptr.restore(path.absolute(), abstract)

    return CHECKPOINT_RETRY_POLICY.call(_restore)


def _restore_pytree_host(path: Path) -> Any:
    """Topology-independent restore: rebuild the abstract tree from the
    checkpoint's own metadata with NO shardings, so a checkpoint written by an
    N-process mesh consolidates on a single host — the merge-weights path
    (reference `utils/fsdp_utils.py:274` merge_fsdp_weights role). A plain
    ``restore(path)`` would try to re-materialize the saved device topology
    and fail off-cluster."""
    wait_for_checkpoint_saves()
    ocp = _ocp()
    host = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    with ocp.StandardCheckpointer() as ckptr:
        meta = ckptr.metadata(path.absolute()).item_metadata.tree
        abstract = jax.tree.map(
            lambda m: jax.ShapeDtypeStruct(m.shape, np.dtype(m.dtype), sharding=host)
            if hasattr(m, "shape")
            else m,
            meta,
        )
        return ckptr.restore(path.absolute(), abstract)


def _save_host_state(path: Path, obj: Any) -> None:
    if not PartialState().is_main_process:
        return

    def _write():
        fault_point(SCOPE_CHECKPOINT_SAVE)
        with open(path, "wb") as f:
            pickle.dump(obj, f)

    CHECKPOINT_RETRY_POLICY.call(_write)


def _load_host_state(path: Path) -> Any:
    def _read():
        fault_point(SCOPE_CHECKPOINT_RESTORE)
        with open(path, "rb") as f:
            return pickle.load(f)

    return CHECKPOINT_RETRY_POLICY.call(_read)


def get_checkpoint_dir(accelerator, output_dir: str | None) -> Path:
    """Resolve (and rotate) the checkpoint directory (reference
    `accelerator.py:2991-3016` automatic naming + total_limit pruning)."""
    pc = accelerator.project_configuration
    if output_dir is not None:
        return Path(output_dir)
    base = Path(pc.project_dir or ".") / "checkpoints"
    base.mkdir(parents=True, exist_ok=True)
    if pc.automatic_checkpoint_naming:
        # rotation may delete a directory a previous async save is still
        # writing — land all in-flight bytes before pruning
        wait_for_checkpoint_saves()
        existing = sorted(
            (
                d
                for d in base.iterdir()
                if d.name.startswith(CHECKPOINT_DIR_PREFIX + "_")
                and d.name.rsplit("_", 1)[1].isdigit()
            ),
            key=lambda d: int(d.name.rsplit("_", 1)[1]),
        )
        if pc.total_limit is not None and len(existing) + 1 > pc.total_limit:
            for stale in existing[: len(existing) + 1 - pc.total_limit]:
                if PartialState().is_main_process:
                    shutil.rmtree(stale, ignore_errors=True)
            # non-main processes must not proceed (and possibly start reading
            # a checkpoint for restore) while main's rmtree is mid-deletion
            PartialState().wait_for_everyone()
        target = base / f"{CHECKPOINT_DIR_PREFIX}_{pc.iteration}"
        pc.iteration += 1
        return target
    return base


def _is_complete_checkpoint(d: Path) -> bool:
    """A preemption/SIGKILL anywhere between a save_state starting and its
    last background write committing leaves a torn directory: orbax's
    atomic-rename temp dirs (``*.orbax-checkpoint-tmp-*``) next to — instead
    of — the final array dirs, or host-state pickles with no array dirs at
    all. The `_COMPLETE` marker is written strictly AFTER every array and
    host write has landed, so its presence (plus the absence of temp dirs)
    is the commit line: anything else must not be offered to
    load_state(None) — automatic recovery falls back to the previous intact
    checkpoint."""
    try:
        entries = list(d.iterdir())
    except OSError:
        return False
    if not entries or any("orbax-checkpoint-tmp" in e.name for e in entries):
        return False
    return (d / CHECKPOINT_COMPLETE_MARKER).exists()


def complete_checkpoint_dirs(accelerator) -> list[Path]:
    """Every COMPLETE automatic checkpoint directory, newest first — the
    restore fallback chain for load_accelerator_state(None). Torn directories
    (crashed mid-write, see `_is_complete_checkpoint`) are excluded; bit-rot a
    completeness scan cannot see (e.g. a truncated array file) is caught when
    the restore itself fails and the chain walks to the next entry."""
    wait_for_checkpoint_saves()  # our own in-flight saves must not look crashed
    pc = accelerator.project_configuration
    base = Path(pc.project_dir or ".") / "checkpoints"
    if not base.exists():
        return []
    return sorted(
        (
            d
            for d in base.iterdir()
            if d.name.startswith(CHECKPOINT_DIR_PREFIX + "_")
            and d.name.rsplit("_", 1)[1].isdigit()
            and _is_complete_checkpoint(d)
        ),
        key=lambda d: int(d.name.rsplit("_", 1)[1]),
        reverse=True,
    )


def latest_checkpoint_dir(accelerator) -> Path:
    """Most recent COMPLETE automatic checkpoint directory (for load_state(None));
    directories left incomplete by a crash mid-async-write are skipped."""
    candidates = complete_checkpoint_dirs(accelerator)
    if not candidates:
        base = Path(accelerator.project_configuration.project_dir or ".") / "checkpoints"
        raise FileNotFoundError(f"No complete checkpoints under {base}")
    return candidates[0]


def save_accelerator_state(
    accelerator,
    output_dir: str | None = None,
    weights: list | None = None,
    async_save: bool = False,
) -> str:
    """Serialize every prepared object's state (reference `checkpointing.py:53-162`).
    ``weights`` (from the save-state pre-hooks) overrides what is persisted per
    model, without touching the live params.

    With ``async_save`` the array pytrees are copied to host synchronously but
    written to disk in background threads: the call returns as soon as the
    host-side state is down, and the bytes are guaranteed on disk by the next
    save/restore/rotation or ``wait_for_checkpoint_saves()``/process exit."""
    wait_for_checkpoint_saves()  # at most one in-flight checkpoint generation
    out = get_checkpoint_dir(accelerator, output_dir)
    state = PartialState()
    out.mkdir(parents=True, exist_ok=True)

    for i, model in enumerate(accelerator._models):
        _save_pytree(
            out / f"{MODEL_NAME}_{i}",
            weights[i] if weights is not None else model.params,
            async_save=async_save,
        )
        if getattr(model, "extra_state", None) is not None:
            _save_pytree(out / f"{MODEL_NAME}_{i}.extra", model.extra_state, async_save=async_save)
    for i, opt in enumerate(accelerator._optimizers):
        sd = opt.state_dict()
        _save_pytree(out / f"{OPTIMIZER_NAME}_{i}", sd["opt_state"], async_save=async_save)
        meta = {k: v for k, v in sd.items() if k != "opt_state"}
        meta["scaler_state"] = (
            jax.tree.map(lambda x: np.asarray(x), meta["scaler_state"]) if "scaler_state" in meta else None
        )
        _save_host_state(out / f"{OPTIMIZER_NAME}_{i}.meta.pkl", meta)
    for i, sched in enumerate(accelerator._schedulers):
        _save_host_state(out / f"{SCHEDULER_NAME}_{i}.pkl", sched.state_dict())
    for i, dl in enumerate(accelerator._dataloaders):
        _save_host_state(out / f"{DATALOADER_STATE_NAME}_{i}.pkl", dl.state_dict())
    for i, obj in enumerate(accelerator._custom_objects):
        _save_host_state(out / f"{CUSTOM_STATE_NAME}_{i}.pkl", obj.state_dict())
    _save_host_state(out / f"{RNG_STATE_NAME}.pkl", capture_rng_state())
    _save_host_state(out / f"{STEP_STATE_NAME}.pkl", {"step": accelerator.step})
    state.wait_for_everyone()
    if async_save:
        # the generation commits (gets its _COMPLETE marker) only when the
        # background writers are joined error-free at the next barrier
        _PENDING_COMMITS.append(out)
    else:
        _commit_checkpoint(out)
    return str(out)


def load_accelerator_state(accelerator, input_dir: str | None = None) -> str:
    """Restore every prepared object (reference `checkpointing.py:165-286`).
    Sharded arrays are re-placed directly onto their mesh positions.

    With ``input_dir=None`` this is the crash-recovery entry point: it walks
    the complete-checkpoint chain newest-first and restores from the first
    directory that loads cleanly — a latest checkpoint corrupted past what
    the completeness scan can see (truncated array file, unreadable pickle)
    is skipped instead of killing recovery. Returns the directory actually
    restored from."""
    if input_dir is None:
        candidates = complete_checkpoint_dirs(accelerator)
        if not candidates:
            base = Path(accelerator.project_configuration.project_dir or ".") / "checkpoints"
            raise FileNotFoundError(f"No complete checkpoints under {base}")
        failures: list[BaseException] = []
        for candidate in candidates:
            try:
                return _load_accelerator_state_from(accelerator, candidate)
            except Exception as exc:  # corrupt/unreadable: walk back one
                failures.append(exc)
                warnings.warn(
                    f"checkpoint {candidate} failed to restore ({exc!r}); "
                    "falling back to the previous complete checkpoint",
                    stacklevel=2,
                )
        raise CheckpointRestoreError(failures)
    return _load_accelerator_state_from(accelerator, Path(input_dir))


def _load_accelerator_state_from(accelerator, src: Path) -> str:
    for i, model in enumerate(accelerator._models):
        model.params = _restore_pytree(src / f"{MODEL_NAME}_{i}", target=model.params)
        extra_path = src / f"{MODEL_NAME}_{i}.extra"
        if extra_path.exists() and getattr(model, "extra_state", None) is not None:
            model.extra_state = _restore_pytree(extra_path, target=model.extra_state)
    for i, opt in enumerate(accelerator._optimizers):
        opt_state = _restore_pytree(src / f"{OPTIMIZER_NAME}_{i}", target=opt.opt_state)
        meta_path = src / f"{OPTIMIZER_NAME}_{i}.meta.pkl"
        meta = _load_host_state(meta_path) if meta_path.exists() else {}
        opt.load_state_dict({"opt_state": opt_state, **{k: v for k, v in meta.items() if v is not None}})
    for i, sched in enumerate(accelerator._schedulers):
        sched.load_state_dict(_load_host_state(src / f"{SCHEDULER_NAME}_{i}.pkl"))
    for i, dl in enumerate(accelerator._dataloaders):
        dl.load_state_dict(_load_host_state(src / f"{DATALOADER_STATE_NAME}_{i}.pkl"))
    for i, obj in enumerate(accelerator._custom_objects):
        obj.load_state_dict(_load_host_state(src / f"{CUSTOM_STATE_NAME}_{i}.pkl"))
    rng_path = src / f"{RNG_STATE_NAME}.pkl"
    if rng_path.exists():
        restore_rng_state(_load_host_state(rng_path))
    step_path = src / f"{STEP_STATE_NAME}.pkl"
    if step_path.exists():
        accelerator.step = _load_host_state(step_path)["step"]
    return str(src)


def save_custom_state(obj: Any, path: str | os.PathLike, index: int = 0) -> str:
    """Persist ONE registered custom object (reference `save_custom_state`,
    `checkpointing.py:240`): anything exposing ``state_dict()``, written by
    process 0 as `custom_checkpoint_<index>.pkl`."""
    target = Path(path) / f"{CUSTOM_STATE_NAME}_{index}.pkl"
    _save_host_state(target, obj.state_dict())
    return str(target)


def load_custom_state(obj: Any, path: str | os.PathLike, index: int = 0) -> None:
    """Restore ONE custom object saved by `save_custom_state` (reference
    `load_custom_state`, `checkpointing.py:252`)."""
    obj.load_state_dict(_load_host_state(Path(path) / f"{CUSTOM_STATE_NAME}_{index}.pkl"))


def save_model_weights(
    state_dict: Any,
    save_directory: str,
    max_shard_size: str | int = "10GB",
    safe_serialization: bool = True,
) -> list[str]:
    """Consolidated (unsharded) model export for interchange (reference
    `save_model`, `accelerator.py:2804-2919`), written by process 0:
    sharded ``.safetensors`` + index with tied-weight dedup by default, or flax
    msgpack with ``safe_serialization=False``. Counterpart of the sharded orbax
    layout above.

    Quantized (``QuantizedTensor``) leaves are dequantized to dense arrays on
    export — the interchange format is dense weights, matching how quantized
    models re-enter through ``quantize_params`` at load."""
    if not PartialState().is_main_process:
        return []
    from .utils.quantization import QuantizedTensor, dequantize_params

    if any(isinstance(l, QuantizedTensor)
           for l in jax.tree.leaves(state_dict,
                                    is_leaf=lambda l: isinstance(l, QuantizedTensor))):
        state_dict = dequantize_params(state_dict)
    os.makedirs(save_directory, exist_ok=True)
    if safe_serialization:
        from .utils.safetensors_io import save_safetensors_checkpoint

        return save_safetensors_checkpoint(state_dict, save_directory, max_shard_size=max_shard_size)
    from flax import serialization

    as_np = jax.tree.map(lambda x: np.asarray(x) if hasattr(x, "shape") else x, state_dict)
    payload = serialization.msgpack_serialize(as_np)
    out = Path(save_directory) / "model.msgpack"
    with open(out, "wb") as f:
        f.write(payload)
    return [str(out)]


def load_model_weights(save_directory: str) -> Any:
    """Load a consolidated export — safetensors (sharded or single) or msgpack,
    whichever is present."""
    directory = Path(save_directory)
    if not (directory / "model.msgpack").exists():
        from .utils.safetensors_io import load_safetensors_checkpoint

        return load_safetensors_checkpoint(directory, nested=True)
    from flax import serialization

    with open(directory / "model.msgpack", "rb") as f:
        return serialization.msgpack_restore(f.read())
