"""Model hooks: pre/post-forward interception on prepared models.

Capability parity: reference `src/accelerate/hooks.py` (720 LoC) — `ModelHook`,
`SequentialHook`, `add_hook_to_module`, `AlignDevicesHook` (move weights to the
execution device before forward, offload after).

TPU-native re-founding: the reference monkey-patches ``module.forward``; here a
hook wraps the *functional* call — `PreparedModel.__call__` consults its attached
hook, and `pre_forward` may substitute the parameter pytree itself (which is how
offloaded weights stream in: the hook hands back device-placed params, and
`post_forward` drops them). No nn.Module surgery, no pickling hazards.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np


class ModelHook:
    """Base hook (reference `hooks.py:37`). ``no_grad`` is meaningless under
    functional transforms and omitted."""

    def init_hook(self, model: Any) -> Any:
        return model

    def pre_forward(self, model: Any, params: Any, args: tuple, kwargs: dict):
        """Return possibly-substituted (params, args, kwargs)."""
        return params, args, kwargs

    def post_forward(self, model: Any, output: Any) -> Any:
        return output

    def detach_hook(self, model: Any) -> Any:
        return model


class SequentialHook(ModelHook):
    """Compose several hooks in order (reference `hooks.py:100`)."""

    def __init__(self, *hooks: ModelHook):
        self.hooks = list(hooks)

    def init_hook(self, model):
        for h in self.hooks:
            model = h.init_hook(model)
        return model

    def pre_forward(self, model, params, args, kwargs):
        for h in self.hooks:
            params, args, kwargs = h.pre_forward(model, params, args, kwargs)
        return params, args, kwargs

    def post_forward(self, model, output):
        for h in self.hooks:
            output = h.post_forward(model, output)
        return output

    def detach_hook(self, model):
        for h in self.hooks:
            model = h.detach_hook(model)
        return model


def add_hook_to_module(model: Any, hook: ModelHook, append: bool = False) -> Any:
    """Attach (or chain) a hook onto a PreparedModel-like object (reference
    `hooks.py:124`)."""
    existing = getattr(model, "_hook", None)
    if append and existing is not None:
        hook = SequentialHook(existing, hook)
    model._hook = hook
    return hook.init_hook(model)


def remove_hook_from_module(model: Any, recurse: bool = False) -> Any:
    hook = getattr(model, "_hook", None)
    if hook is not None:
        model = hook.detach_hook(model)
        model._hook = None
    return model


class AlignDevicesHook(ModelHook):
    """Stream weights to the execution device for the forward, release after
    (reference `hooks.py:220`). ``weights_map`` is any mapping name->host array
    (e.g. `OffloadedWeightsLoader`); restores device placement lazily per call."""

    def __init__(
        self,
        execution_device: Any = None,
        offload: bool = True,
        weights_map: Any = None,
        sharding: Any = None,
    ):
        self.execution_device = execution_device
        self.offload = offload
        self.weights_map = weights_map
        self.sharding = sharding

    def pre_forward(self, model, params, args, kwargs):
        if self.weights_map is not None:
            from .utils.modeling import unflatten_params

            params = unflatten_params({k: self.weights_map[k] for k in self.weights_map})
        target = self.sharding if self.sharding is not None else self.execution_device
        if target is not None:
            params = jax.tree.map(lambda p: jax.device_put(p, target), params)
        self._live_params = params
        return params, args, kwargs

    def post_forward(self, model, output):
        if self.offload:
            # drop device copies; host masters stay in weights_map
            params = getattr(self, "_live_params", None)
            if params is not None:
                jax.tree.map(
                    lambda p: p.delete() if isinstance(p, jax.Array) and not p.is_deleted() else None,
                    params,
                    is_leaf=lambda x: isinstance(x, jax.Array),
                )
            self._live_params = None
        return output
