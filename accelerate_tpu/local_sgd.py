"""Local SGD: reduce synchronization frequency by averaging parameters
periodically instead of synchronizing gradients every step.

Capability parity: reference `src/accelerate/local_sgd.py` (103 LoC).

TPU-native re-founding: the reference wraps `no_sync` to skip DDP's per-step
allreduce, then `reduce(mean)`s params every N steps. Under one global SPMD step
gradients are *always* globally averaged inside jit, so the comm-saving variant
needs per-replica parameter islands: `make_local_train_step` builds a
`shard_map` over the data axes in which each replica runs its own optimizer
locally (no cross-replica traffic), and every ``local_sgd_steps`` the host calls
`sync()` for one `pmean` over params + optimizer state. The `LocalSGD` context
manager drives the cadence with the reference's API shape.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from .parallel.mesh import data_axes


def make_local_train_step(
    loss_fn: Callable,
    apply_fn: Callable,
    tx: optax.GradientTransformation,
    mesh,
):
    """Build (local_step, sync, replicate) for local-SGD training.

    - ``replicate(params)`` -> per-replica param/opt-state islands (params get a
      leading replica axis sharded over the data axes).
    - ``local_step(island, batch)`` -> (island, loss): per-replica fwd/bwd/update
      with NO cross-replica collectives.
    - ``sync(island)`` -> island with params/opt-state pmean-averaged.
    """
    from jax import shard_map

    axes = data_axes(mesh)
    n_rep = 1
    for a in axes:
        n_rep *= mesh.shape[a]

    def _stack(tree):
        return jax.tree.map(lambda p: jnp.broadcast_to(p[None], (n_rep, *p.shape)), tree)

    island_spec = lambda tree: jax.tree.map(lambda _: P(axes), tree)

    def replicate(params):
        params_r = _stack(params)
        opt_r = _stack(tx.init(params))
        island = {"params": params_r, "opt": opt_r}
        shardings = jax.tree.map(lambda _: NamedSharding(mesh, P(axes)), island)
        return jax.tree.map(jax.device_put, island, shardings)

    def _local_step(island, batch):
        # leading replica dim is size 1 locally
        params = jax.tree.map(lambda p: p[0], island["params"])
        opt_state = jax.tree.map(lambda p: p[0], island["opt"])

        def loss_of(p):
            from .accelerator import BoundModel

            return loss_fn(BoundModel(apply_fn, p), batch)

        loss, grads = jax.value_and_grad(loss_of)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        island = {
            "params": jax.tree.map(lambda p: p[None], params),
            "opt": jax.tree.map(lambda p: p[None], opt_state),
        }
        return island, loss[None]

    def _sync_fn(island):
        return jax.tree.map(lambda p: jax.lax.pmean(p, axes), island)

    batch_spec = P(axes)
    local_step = jax.jit(
        shard_map(
            _local_step,
            mesh=mesh,
            in_specs=(island_spec({"params": 0, "opt": 0}), batch_spec),
            out_specs=(island_spec({"params": 0, "opt": 0}), P(axes)),
            check_vma=False,
        )
    )
    sync = jax.jit(
        shard_map(
            _sync_fn, mesh=mesh,
            in_specs=(island_spec({"params": 0, "opt": 0}),),
            out_specs=island_spec({"params": 0, "opt": 0}),
            check_vma=False,
        )
    )

    def unreplicate(island):
        return jax.tree.map(lambda p: p[0], jax.device_get(island["params"]))

    return local_step, sync, replicate, unreplicate


class LocalSGD:
    """Context manager driving the sync cadence (reference `local_sgd.py:84`):

        with LocalSGD(sync_fn, local_sgd_steps=8) as lsgd:
            for batch in dl:
                island, loss = local_step(island, batch)
                island = lsgd.step(island)
    """

    def __init__(self, sync_fn: Callable | None = None, local_sgd_steps: int = 8, enabled: bool = True):
        self.sync_fn = sync_fn
        self.local_sgd_steps = local_sgd_steps
        self.enabled = enabled
        self.num_steps = 0

    def __enter__(self) -> "LocalSGD":
        self.num_steps = 0
        return self

    def __exit__(self, *exc) -> None:
        return None

    def step(self, island: Any) -> Any:
        self.num_steps += 1
        if not self.enabled:
            return island
        if self.num_steps % self.local_sgd_steps == 0:
            return self.sync_fn(island)
        return island
