"""Torch nn.Module interop: run (and train) torch models on TPU via JAX.

This is the reference's core promise — `accelerator.prepare(model)` for a torch
`nn.Module` — and SURVEY.md §7's #1-ranked hard part. The reference keeps torch
as the executor; here the module must become a *pure JAX function* so it can be
jitted/sharded/differentiated on TPU. Strategy:

  1. `torch.fx.symbolic_trace` captures the module's forward as an op graph
     (HF transformers models trace via `transformers.utils.fx`).
  2. Parameters/buffers are pulled out of the module into a numpy pytree
     (dot-path keys), convertible to sharded jax arrays.
  3. A graph interpreter replays the fx graph with JAX ops: an op table maps
     `call_module` leaf types (Linear/LayerNorm/Embedding/Conv2d/...),
     `call_function` (torch.add/matmul/F.gelu/...) and `call_method`
     (view/permute/transpose/...) onto jnp equivalents.

The resulting ``apply_fn(params, *args)`` is a first-class citizen: it works
with `Accelerator.prepare`, `backward`, `make_train_step`, sharding rules, and
checkpointing. Coverage is the standard layer vocabulary — exotic custom ops
raise `UnsupportedTorchOp` with the node context so users know exactly what to
port.

Known limits: HuggingFace transformers models are not fx-traceable with some
torch/transformers version combinations (their tracer's mask utilities vmap over
proxies); for those, use the per-architecture weight mappers instead
(`models.gpt2.params_from_hf_gpt2`) — same capability the reference's
checkpoint-ingestion path provides, with a TPU-native model body.
"""

from __future__ import annotations

import math
import operator
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np


class UnsupportedTorchOp(NotImplementedError):
    pass


def _t2n(t) -> np.ndarray:
    # copy: .numpy() shares memory with the torch tensor, so torch-side
    # in-place mutation (BN running stats, optimizer steps) would leak into
    # the captured pytree
    return np.array(t.detach().cpu().numpy())


def extract_params(module) -> tuple[dict[str, np.ndarray], dict[str, np.ndarray]]:
    """(parameters, buffers) as flat dot-path dicts (reference analogue: the
    state_dict the reference moves device-to-device; here it leaves torch)."""
    params = {name: _t2n(p) for name, p in module.named_parameters()}
    buffers = {name: _t2n(b) for name, b in module.named_buffers()}
    return params, buffers


# --------------------------------------------------------------- module table
def _linear(mod, params, x):
    w = params["weight"]  # [out, in] torch layout
    y = jnp.matmul(x, w.T)
    if params.get("bias") is not None:
        y = y + params["bias"]
    return y


def _embedding(mod, params, idx):
    return params["weight"][idx]


def _layer_norm(mod, params, x):
    axes = tuple(range(-len(mod.normalized_shape), 0))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + mod.eps)
    if params.get("weight") is not None:
        y = y * params["weight"]
    if params.get("bias") is not None:
        y = y + params["bias"]
    return y


def _convnd(mod, params, x):
    """Conv1d/Conv2d: torch NC<spatial> / OI<spatial> layouts, any rank."""
    spatial = "HW"[: x.ndim - 2] if x.ndim <= 4 else "HWD"[: x.ndim - 2]
    spec = "NC" + spatial
    dn = jax.lax.conv_dimension_numbers(
        x.shape, params["weight"].shape, (spec, "OI" + spatial, spec)
    )
    pad = mod.padding if isinstance(mod.padding, str) else [(p, p) for p in mod.padding]
    y = jax.lax.conv_general_dilated(
        x, params["weight"], window_strides=mod.stride, padding=pad,
        rhs_dilation=mod.dilation, dimension_numbers=dn, feature_group_count=mod.groups,
    )
    if params.get("bias") is not None:
        y = y + params["bias"].reshape((1, -1) + (1,) * (x.ndim - 2))
    return y


def _group_norm(mod, params, x):
    n, c = x.shape[:2]
    g = mod.num_groups
    xg = x.reshape(n, g, c // g, *x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    y = ((xg - mean) * jax.lax.rsqrt(var + mod.eps)).reshape(x.shape)
    if params.get("weight") is not None:
        shape = (1, c) + (1,) * (x.ndim - 2)
        y = y * params["weight"].reshape(shape) + params["bias"].reshape(shape)
    return y


def _batch_norm(mod, params, x):
    # inference semantics: running statistics (buffers)
    shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    mean = params["running_mean"].reshape(shape)
    var = params["running_var"].reshape(shape)
    y = (x - mean) * jax.lax.rsqrt(var + mod.eps)
    if params.get("weight") is not None:
        y = y * params["weight"].reshape(shape) + params["bias"].reshape(shape)
    return y


def _batch_norm_train(mod, params, x):
    """Training semantics: normalize by BATCH statistics and return updated
    running stats (torch's exact update: biased var normalizes, unbiased var
    feeds the running buffer, momentum default 0.1)."""
    axes = (0,) + tuple(range(2, x.ndim))
    n = math.prod(x.shape[i] for i in axes)
    mean = jnp.mean(x, axis=axes)
    var = jnp.var(x, axis=axes)  # biased
    shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    y = (x - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + mod.eps)
    if params.get("weight") is not None:
        y = y * params["weight"].reshape(shape) + params["bias"].reshape(shape)
    unbiased = var * (n / max(n - 1, 1))
    if mod.momentum is None:
        # torch semantics: cumulative moving average, factor 1/num_batches
        # int32: JAX truncates int64 without x64 mode anyway (torch stores this
        # counter as int64, but 2^31 batches is out of reach)
        nbt = params.get("num_batches_tracked", jnp.zeros((), jnp.int32)) + 1
        m = 1.0 / nbt.astype(jnp.float32)
    else:
        m = mod.momentum
    new_mean = (1 - m) * params["running_mean"] + m * mean
    new_var = (1 - m) * params["running_var"] + m * unbiased
    updates = {"running_mean": new_mean, "running_var": new_var}
    if "num_batches_tracked" in params:
        updates["num_batches_tracked"] = params["num_batches_tracked"] + 1
    return y, updates


def _max_pool2d(mod, params, x):
    k = mod.kernel_size if isinstance(mod.kernel_size, tuple) else (mod.kernel_size,) * 2
    s = mod.stride if isinstance(mod.stride, tuple) else (mod.stride or mod.kernel_size,) * 2
    p = mod.padding if isinstance(mod.padding, tuple) else (mod.padding,) * 2
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 1, *k), (1, 1, *s),
        [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])],
    )


def _avg_pool2d(mod, params, x):
    k = mod.kernel_size if isinstance(mod.kernel_size, tuple) else (mod.kernel_size,) * 2
    s = mod.stride if isinstance(mod.stride, tuple) else (mod.stride or mod.kernel_size,) * 2
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, (1, 1, *k), (1, 1, *s), "VALID")
    return summed / (k[0] * k[1])


def _adaptive_avg_pool2d(mod, params, x):
    out = mod.output_size if isinstance(mod.output_size, tuple) else (mod.output_size,) * 2
    if out == (1, 1):
        return jnp.mean(x, axis=(2, 3), keepdims=True)
    raise UnsupportedTorchOp(f"AdaptiveAvgPool2d{out}")


def _dropout(mod, params, x, *a, **k):
    return x  # eval semantics


def _identity(mod, params, x):
    return x


def _mha(mod, params, q, k, v, **kwargs):
    raise UnsupportedTorchOp("nn.MultiheadAttention: use explicit q/k/v layers")


def _conv_transpose2d(mod, params, x):
    if any(getattr(mod, "output_padding", (0, 0))):
        raise UnsupportedTorchOp("ConvTranspose2d with output_padding")
    if getattr(mod, "groups", 1) != 1:
        raise UnsupportedTorchOp("ConvTranspose2d with groups > 1")
    if any(d != 1 for d in getattr(mod, "dilation", (1, 1))):
        raise UnsupportedTorchOp("ConvTranspose2d with dilation")
    # torch weight layout is (in, out/groups, kh, kw) = "IOHW"
    dn = jax.lax.conv_dimension_numbers(
        x.shape, tuple(params["weight"].shape[i] for i in (1, 0, 2, 3)), ("NCHW", "OIHW", "NCHW")
    )
    pad = [(p, p) for p in mod.padding]
    y = jax.lax.conv_transpose(
        x, params["weight"], strides=mod.stride, padding=pad,
        dimension_numbers=dn, transpose_kernel=True,
    )
    if params.get("bias") is not None:
        y = y + params["bias"][None, :, None, None]
    return y


def _lerp_axis(x, out_len, axis):
    """1-D linear resample along ``axis`` with align_corners=True index mapping
    (output i samples input i*(in-1)/(out-1))."""
    in_len = x.shape[axis]
    if out_len == 1 or in_len == 1:
        idx = jnp.zeros((out_len,), jnp.float32)
    else:
        idx = jnp.linspace(0.0, in_len - 1.0, out_len)
    lo = jnp.floor(idx).astype(jnp.int32)
    hi = jnp.clip(lo + 1, 0, in_len - 1)
    w = (idx - lo).reshape([out_len if a == axis else 1 for a in range(x.ndim)])
    return jnp.take(x, lo, axis=axis) * (1 - w) + jnp.take(x, hi, axis=axis) * w


def _upsample(mod, params, x):
    mode = getattr(mod, "mode", "nearest")
    if mod.size is not None:
        size = mod.size if isinstance(mod.size, (tuple, list)) else (mod.size,) * (x.ndim - 2)
    else:
        sf = mod.scale_factor
        sf = sf if isinstance(sf, (tuple, list)) else (sf,) * (x.ndim - 2)
        size = tuple(int(d * f) for d, f in zip(x.shape[2:], sf))
    if mode in ("bilinear", "linear") and getattr(mod, "align_corners", None):
        y = x
        for i, s in enumerate(size):
            y = _lerp_axis(y, s, 2 + i)
        return y
    method = {"nearest": "nearest", "bilinear": "bilinear", "linear": "bilinear"}.get(mode)
    if method is None or getattr(mod, "align_corners", None):
        raise UnsupportedTorchOp(f"Upsample(mode={mode!r}, align_corners=True)")
    return jax.image.resize(x, (*x.shape[:2], *size), method=method)


MODULE_TABLE: dict[str, Callable] = {
    "Linear": _linear,
    "Embedding": _embedding,
    "LayerNorm": _layer_norm,
    "Conv2d": _convnd,
    "GroupNorm": _group_norm,
    "BatchNorm1d": _batch_norm,
    "BatchNorm2d": _batch_norm,
    "MaxPool2d": _max_pool2d,
    "AvgPool2d": _avg_pool2d,
    "AdaptiveAvgPool2d": _adaptive_avg_pool2d,
    "Dropout": _dropout,
    "Identity": _identity,
    "ReLU": lambda m, p, x: jax.nn.relu(x),
    "GELU": lambda m, p, x: jax.nn.gelu(x, approximate=getattr(m, "approximate", "none") != "none"),
    "SiLU": lambda m, p, x: jax.nn.silu(x),
    "Sigmoid": lambda m, p, x: jax.nn.sigmoid(x),
    "Tanh": lambda m, p, x: jnp.tanh(x),
    "Softmax": lambda m, p, x: jax.nn.softmax(x, axis=m.dim if m.dim is not None else -1),
    "Flatten": lambda m, p, x: x.reshape(*x.shape[: m.start_dim], -1),
    "MultiheadAttention": _mha,
    "Conv1d": _convnd,
    "ConvTranspose2d": _conv_transpose2d,
    "Upsample": _upsample,
    "UpsamplingNearest2d": _upsample,
    "UpsamplingBilinear2d": _upsample,
    "LeakyReLU": lambda m, p, x: jax.nn.leaky_relu(x, m.negative_slope),
    "ELU": lambda m, p, x: jax.nn.elu(x, m.alpha),
    "ReLU6": lambda m, p, x: jnp.clip(x, 0, 6),
    "Hardtanh": lambda m, p, x: jnp.clip(x, m.min_val, m.max_val),
    "Hardswish": lambda m, p, x: jax.nn.hard_swish(x),
    "Mish": lambda m, p, x: x * jnp.tanh(jax.nn.softplus(x)),
    "Softplus": lambda m, p, x: jax.nn.softplus(m.beta * x) / m.beta,
    "LogSoftmax": lambda m, p, x: jax.nn.log_softmax(x, axis=m.dim if m.dim is not None else -1),
}


# ------------------------------------------------------------- function table
def _fn_softmax(x, dim=-1, **kw):
    return jax.nn.softmax(x, axis=dim)


def _fn_gelu(x, approximate="none"):
    return jax.nn.gelu(x, approximate=approximate != "none")


def _fn_split(x, split_size_or_sections, dim=0):
    """torch.split: int chunk size OR explicit per-section sizes."""
    if isinstance(split_size_or_sections, (list, tuple)):
        bounds, acc = [], 0
        for s in split_size_or_sections[:-1]:
            acc += s
            bounds.append(acc)
        return tuple(jnp.split(x, bounds, axis=dim))
    size = split_size_or_sections
    return tuple(jnp.split(x, list(range(size, x.shape[dim], size)), axis=dim))


def _fn_chunk(x, chunks, dim=0):
    """torch.chunk: ceil-sized chunks (may return FEWER than requested) —
    array_split's even distribution differs."""
    length = x.shape[dim]
    size = -(-length // chunks)
    return tuple(jnp.split(x, list(range(size, length, size)), axis=dim))


def _fn_var_std(fn):
    """torch.var/std: legacy (input, dim, unbiased, keepdim) AND new
    (input, dim, *, correction, keepdim) signatures."""

    def wrapped(x, dim=None, unbiased=None, keepdim=False, correction=None, **kw):
        if correction is None:
            correction = 1 if unbiased is None else int(bool(unbiased))
        return fn(x, axis=dim, keepdims=keepdim, ddof=correction)

    return wrapped


def _build_function_table():
    import torch
    import torch.nn.functional as F

    return {
        torch.add: jnp.add, operator.add: operator.add,
        operator.gt: operator.gt, operator.lt: operator.lt,
        operator.ge: operator.ge, operator.le: operator.le,
        operator.eq: operator.eq, operator.ne: operator.ne,
        operator.neg: operator.neg, operator.mod: operator.mod,
        torch.gt: jnp.greater, torch.lt: jnp.less,
        torch.ge: jnp.greater_equal, torch.le: jnp.less_equal,
        torch.eq: jnp.equal, torch.ne: jnp.not_equal,
        torch.logical_and: jnp.logical_and, torch.logical_or: jnp.logical_or,
        torch.logical_not: jnp.logical_not,
        torch.sub: jnp.subtract, operator.sub: operator.sub,
        torch.mul: jnp.multiply, operator.mul: operator.mul,
        torch.div: jnp.divide, operator.truediv: operator.truediv,
        operator.floordiv: operator.floordiv,
        torch.matmul: jnp.matmul, operator.matmul: jnp.matmul,
        torch.bmm: jnp.matmul,
        torch.pow: jnp.power, operator.pow: operator.pow,
        torch.exp: jnp.exp, torch.log: jnp.log, torch.sqrt: jnp.sqrt,
        torch.rsqrt: jax.lax.rsqrt,
        torch.tanh: jnp.tanh, torch.sigmoid: jax.nn.sigmoid,
        torch.relu: lambda x, **k: jax.nn.relu(x),
        F.relu: lambda x, inplace=False, **k: jax.nn.relu(x),
        F.gelu: _fn_gelu,
        F.silu: lambda x, inplace=False, **k: jax.nn.silu(x),
        F.sigmoid: jax.nn.sigmoid,
        F.softmax: _fn_softmax, torch.softmax: _fn_softmax,
        F.dropout: lambda x, *a, **k: x,
        torch.cat: lambda tensors, dim=0: jnp.concatenate(tensors, axis=dim),
        torch.stack: lambda tensors, dim=0: jnp.stack(tensors, axis=dim),
        torch.transpose: lambda x, a, b: jnp.swapaxes(x, a, b),
        torch.permute: lambda x, dims: jnp.transpose(x, dims),
        torch.reshape: lambda x, shape: jnp.reshape(x, shape),
        torch.flatten: lambda x, start_dim=0, end_dim=-1: _flatten(x, start_dim, end_dim),
        torch.mean: _reduce(jnp.mean), torch.sum: _reduce(jnp.sum),
        torch.max: lambda x, dim=None, **k: jnp.max(x, axis=dim),
        torch.min: lambda x, dim=None, **k: jnp.min(x, axis=dim),
        torch.unsqueeze: lambda x, dim: jnp.expand_dims(x, dim),
        torch.squeeze: lambda x, dim=None: jnp.squeeze(x, axis=dim),
        operator.getitem: _getitem,
        torch.arange: lambda *a, **k: jnp.arange(*a),
        torch.ones: lambda *a, **k: jnp.ones(a[0] if len(a) == 1 else a),
        torch.zeros: lambda *a, **k: jnp.zeros(a[0] if len(a) == 1 else a),
        torch.where: jnp.where,
        torch.einsum: jnp.einsum,
        F.linear: lambda x, w, b=None: jnp.matmul(x, w.T) + (b if b is not None else 0),
        F.embedding: lambda idx, w, *a, **k: w[idx],
        F.layer_norm: _fn_layer_norm,
        F.scaled_dot_product_attention: _fn_sdpa,
        F.cross_entropy: _fn_cross_entropy,
        F.nll_loss: _fn_nll_loss,
        F.mse_loss: _fn_mse_loss,
        F.binary_cross_entropy_with_logits: _fn_bce_with_logits,
        F.log_softmax: _fn_log_softmax, torch.log_softmax: _fn_log_softmax,
        F.leaky_relu: lambda x, negative_slope=0.01, **k: jax.nn.leaky_relu(x, negative_slope),
        F.elu: lambda x, alpha=1.0, **k: jax.nn.elu(x, alpha),
        F.relu6: lambda x, **k: jnp.clip(x, 0, 6),
        F.hardtanh: lambda x, min_val=-1.0, max_val=1.0, **k: jnp.clip(x, min_val, max_val),
        F.softplus: lambda x, beta=1.0, **k: jax.nn.softplus(beta * x) / beta,
        F.mish: lambda x, **k: x * jnp.tanh(jax.nn.softplus(x)),
        F.hardswish: lambda x, **k: jax.nn.hard_swish(x),
        F.pad: _fn_pad,
        torch.clamp: lambda x, min=None, max=None, **k: jnp.clip(x, min, max),
        torch.abs: jnp.abs,
        torch.erf: jax.scipy.special.erf,
        torch.split: _fn_split,
        torch.chunk: _fn_chunk,
        torch.var: _fn_var_std(jnp.var),
        torch.std: _fn_var_std(jnp.std),
        getattr: getattr,
    }


def _flatten(x, start_dim=0, end_dim=-1):
    nd = x.ndim
    end = end_dim % nd
    shape = x.shape[:start_dim] + (-1,) + x.shape[end + 1 :]
    return x.reshape(shape)


def _reduce(fn):
    def wrapped(x, dim=None, keepdim=False, **kw):
        return fn(x, axis=dim, keepdims=keepdim)

    return wrapped


def _getitem(obj, idx):
    def fix(i):
        if type(i).__module__.startswith("torch") and hasattr(i, "detach"):
            return jnp.asarray(_t2n(i))
        return i

    if isinstance(idx, tuple):
        idx = tuple(fix(i) for i in idx)
    else:
        idx = fix(idx)
    return obj[idx]


def _fn_log_softmax(x, dim=-1, **kw):
    return jax.nn.log_softmax(x, axis=dim)


def _apply_reduction(per_elem, reduction):
    if reduction == "mean":
        return per_elem.mean()
    if reduction == "sum":
        return per_elem.sum()
    if reduction == "none":
        return per_elem
    raise UnsupportedTorchOp(f"reduction={reduction!r}")


def _flatten_class_dim(input, target):
    """[N, C, d1...] logits + [N, d1...] targets -> [N*d1..., C] / [N*d1...]."""
    if input.ndim > 2:
        c = input.shape[1]
        input = jnp.moveaxis(input, 1, -1).reshape(-1, c)
        target = target.reshape(-1)
    return input, target


def _weighted_nll(logp, target, weight, ignore_index, reduction, label_smoothing=0.0):
    """Shared core of F.cross_entropy / F.nll_loss over log-probabilities,
    matching torch exactly: per-sample loss
    (1-ls) * (-w[y] logp[y]) + ls * (-sum_c w_c logp_c / C), mean reduction
    divides by sum of w[y] over valid rows."""
    valid = target != ignore_index
    safe = jnp.where(valid, target, 0)
    picked = -jnp.take_along_axis(logp, safe[:, None], axis=-1)[:, 0]
    n_classes = logp.shape[-1]
    if weight is not None:
        wy = weight[safe]
        picked = picked * wy
        smooth = -(logp * weight[None, :]).sum(axis=-1) / n_classes
        denom = (wy * valid).sum()
    else:
        smooth = -logp.sum(axis=-1) / n_classes
        denom = valid.sum()
    per = (1.0 - label_smoothing) * picked + label_smoothing * smooth
    per = jnp.where(valid, per, 0.0)
    if reduction == "mean":
        return per.sum() / jnp.maximum(denom, 1e-9)
    return _apply_reduction(per, reduction)


def _fn_cross_entropy(input, target, weight=None, ignore_index=-100,
                      reduction="mean", label_smoothing=0.0, **kw):
    """torch.nn.functional.cross_entropy for int class targets ([N, C, ...]
    logits vs [N, ...] indices), incl. ignore_index, per-class weight, and
    label smoothing (torch's exact weighted-smoothing formula)."""
    if target.dtype not in (jnp.int32, jnp.int64):
        raise UnsupportedTorchOp("F.cross_entropy with probability targets")
    input, target = _flatten_class_dim(input, target)
    logp = jax.nn.log_softmax(input, axis=-1)
    return _weighted_nll(logp, target, weight, ignore_index, reduction, label_smoothing)


def _fn_nll_loss(input, target, weight=None, ignore_index=-100, reduction="mean", **kw):
    """F.nll_loss over log-probabilities — cross_entropy minus the log_softmax;
    spatial [N, C, d...] inputs flatten like cross_entropy."""
    input, target = _flatten_class_dim(input, target)
    return _weighted_nll(input, target, weight, ignore_index, reduction)


def _fn_mse_loss(input, target, reduction="mean", **kw):
    return _apply_reduction((input - target) ** 2, reduction)


def _fn_bce_with_logits(input, target, weight=None, pos_weight=None, reduction="mean", **kw):
    logp = jax.nn.log_sigmoid(input)
    lognotp = jax.nn.log_sigmoid(-input)
    if pos_weight is not None:
        per = -(pos_weight * target * logp + (1.0 - target) * lognotp)
    else:
        per = -(target * logp + (1.0 - target) * lognotp)
    if weight is not None:
        per = per * weight
    return _apply_reduction(per, reduction)


def _fn_pad(x, pad, mode="constant", value=0.0):
    """torch F.pad: flat (before, after) pairs starting from the LAST dim."""
    if mode != "constant":
        raise UnsupportedTorchOp(f"F.pad(mode={mode!r})")
    pairs = [(0, 0)] * x.ndim
    for i in range(len(pad) // 2):
        pairs[x.ndim - 1 - i] = (pad[2 * i], pad[2 * i + 1])
    return jnp.pad(x, pairs, constant_values=value)


def _fn_layer_norm(x, normalized_shape, weight=None, bias=None, eps=1e-5):
    axes = tuple(range(-len(normalized_shape), 0))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight
    if bias is not None:
        y = y + bias
    return y


def _fn_sdpa(q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False, scale=None, **kw):
    d = q.shape[-1]
    scale = 1.0 / math.sqrt(d) if scale is None else scale
    logits = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    if is_causal:
        s_q, s_k = logits.shape[-2:]
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool))
        logits = jnp.where(mask, logits, -1e30)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            logits = jnp.where(attn_mask, logits, -1e30)
        else:
            logits = logits + attn_mask
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", w, v)


# --------------------------------------------------------------- method table
METHOD_TABLE: dict[str, Callable] = {
    "view": lambda x, *shape: x.reshape(shape[0] if len(shape) == 1 and isinstance(shape[0], (tuple, list)) else shape),
    "reshape": lambda x, *shape: x.reshape(shape[0] if len(shape) == 1 and isinstance(shape[0], (tuple, list)) else shape),
    "permute": lambda x, *dims: jnp.transpose(x, dims[0] if len(dims) == 1 and isinstance(dims[0], (tuple, list)) else dims),
    "transpose": lambda x, a, b: jnp.swapaxes(x, a, b),
    "contiguous": lambda x: x,
    "flatten": _flatten,
    "size": lambda x, dim=None: x.shape if dim is None else x.shape[dim],
    "shape": lambda x: x.shape,
    "mean": _reduce(jnp.mean),
    "sum": _reduce(jnp.sum),
    "softmax": lambda x, dim=-1: jax.nn.softmax(x, axis=dim),
    "unsqueeze": lambda x, dim: jnp.expand_dims(x, dim),
    "squeeze": lambda x, dim=None: jnp.squeeze(x, axis=dim),
    "expand": lambda x, *sizes: jnp.broadcast_to(x, tuple(x.shape[i] if s == -1 else s for i, s in enumerate(sizes))),
    "masked_fill": lambda x, mask, value: jnp.where(mask, value, x),
    "to": lambda x, *a, **k: x,
    "float": lambda x: x.astype(jnp.float32),
    "type_as": lambda x, other: x.astype(other.dtype),
    "split": _fn_split,
    "chunk": _fn_chunk,
    "pow": jnp.power,
    "clamp": lambda x, min=None, max=None: jnp.clip(x, min, max),
    "repeat": lambda x, *reps: jnp.tile(x, reps),
    "t": lambda x: x.T,
    "bool": lambda x: x.astype(bool),
    "long": lambda x: x.astype(jnp.int32),
    "detach": lambda x: jax.lax.stop_gradient(x),
    "item": lambda x: x,
    "mul": jnp.multiply, "add": jnp.add, "sub": jnp.subtract, "div": jnp.divide,
    "matmul": jnp.matmul,
}


def convert_torch_module(
    module, example_args: tuple = (), train: bool = False, seed: int = 0
) -> tuple[Callable, Any]:
    """Trace a torch nn.Module and return ``(apply_fn, variables)`` ready for
    `Accelerator.prepare((apply_fn, variables))`.

    Inference (``train=False``): ``variables`` is the flat param dict; buffers
    are captured as constants and ``apply_fn(params, *inputs)`` is pure.

    Training (``train=True`` — reference capability: training arbitrary
    ``nn.Module``s, `accelerator.py:1351+`): the graph is traced in train mode, and
    ``variables`` is ``{"params": ..., "torch_state": {"buffers": ...,
    "rng": seed}}`` — the mutable collections contract: ``apply_fn(params,
    *inputs, extra_state=...)`` returns ``(out, new_extra_state)``. BatchNorm
    normalizes by batch statistics and updates its running buffers through the
    state; Dropout draws from a per-step PRNG key folded per call site.
    `PreparedModel.eval()` gives inference behavior at run time (state
    mutations discarded, but the traced train-mode graph still drops out —
    re-convert with ``train=False`` for serving).
    """
    import torch
    import torch.nn.functional as F

    module = module.train() if train else module.eval()
    # Loss functionals contain tensor-dependent python checks (e.g. mse_loss's
    # size-mismatch warning) that fx cannot trace through; keep them as leaf
    # call_function nodes — the function table maps them whole.
    autowrap = (
        F.mse_loss, F.cross_entropy, F.nll_loss, F.binary_cross_entropy_with_logits,
    )
    try:
        tracer = torch.fx.Tracer(autowrap_functions=autowrap)
        graph = tracer.trace(module)
        gm = torch.fx.GraphModule(tracer.root, graph)
    except Exception:
        from transformers.utils import fx as hf_fx  # HF models need their tracer

        gm = hf_fx.symbolic_trace(module)
    params, buffers = extract_params(module)
    fn_table = _build_function_table()
    submodules = dict(gm.named_modules())

    stateful = train and (
        bool(buffers)
        or any(type(m).__name__ == "Dropout" and m.p > 0 for m in submodules.values())
    )

    def apply_fn(params: dict, *args: Any, extra_state: Any = None) -> Any:
        env: dict[str, Any] = {}
        arg_iter = iter(args)
        state_in = (extra_state or {}).get("torch_state", {}) if stateful else {}
        live_buffers = dict(state_in.get("buffers", buffers))
        buffer_updates: dict[str, Any] = {}
        rng_box = {"key": None, "calls": 0}
        if stateful and "rng" in state_in:
            rng_box["key"] = jax.random.fold_in(
                jax.random.PRNGKey(seed), state_in["rng"].astype(jnp.uint32)
            )

        def next_dropout_key():
            rng_box["calls"] += 1
            if rng_box["key"] is None:
                raise RuntimeError(
                    "This module was converted with train=True and contains active "
                    "Dropout: call apply_fn(params, *args, extra_state=...) with the "
                    "'torch_state' collection (Accelerator.prepare threads it "
                    "automatically), or re-convert with train=False for inference."
                )
            return jax.random.fold_in(rng_box["key"], rng_box["calls"])

        def lookup(prefix: str, store: dict) -> dict:
            out = {}
            for key, value in store.items():
                if key.startswith(prefix + ".") and "." not in key[len(prefix) + 1 :]:
                    out[key[len(prefix) + 1 :]] = value
                elif prefix == "" and "." not in key:
                    out[key] = value
            return out

        def materialize(node_arg):
            if isinstance(node_arg, torch.fx.Node):
                return env[node_arg.name]
            if isinstance(node_arg, (list, tuple)):
                return type(node_arg)(materialize(a) for a in node_arg)
            if isinstance(node_arg, dict):
                return {k: materialize(v) for k, v in node_arg.items()}
            if type(node_arg).__module__.startswith("torch") and hasattr(node_arg, "detach"):
                return jnp.asarray(_t2n(node_arg))
            return node_arg

        for node in gm.graph.nodes:
            if node.op == "placeholder":
                try:
                    env[node.name] = next(arg_iter)
                except StopIteration:
                    env[node.name] = materialize(node.args[0]) if node.args else None
            elif node.op == "get_attr":
                target = node.target
                if target in params:
                    env[node.name] = params[target]
                elif target in live_buffers:
                    env[node.name] = jnp.asarray(live_buffers[target])
                else:  # torch constants stored on the module
                    obj = gm
                    for part in target.split("."):
                        obj = getattr(obj, part)
                    env[node.name] = materialize(obj)
            elif node.op == "call_module":
                sub = submodules[node.target]
                cls = type(sub).__name__
                sub_params = {
                    **{k: jnp.asarray(v) for k, v in lookup(node.target, live_buffers).items()},
                    **lookup(node.target, params),
                }
                margs = [materialize(a) for a in node.args]
                if stateful and cls in ("BatchNorm1d", "BatchNorm2d", "BatchNorm3d"):
                    y, updates = _batch_norm_train(sub, sub_params, *margs)
                    for k, v in updates.items():
                        buffer_updates[f"{node.target}.{k}"] = v
                    env[node.name] = y
                elif stateful and cls == "Dropout" and sub.p > 0:
                    key = next_dropout_key()
                    (x_in,) = margs
                    keep = jax.random.bernoulli(key, 1.0 - sub.p, x_in.shape)
                    env[node.name] = jnp.where(keep, x_in / (1.0 - sub.p), 0.0)
                else:
                    handler = MODULE_TABLE.get(cls)
                    if handler is None:
                        raise UnsupportedTorchOp(f"module {cls} at {node.target}")
                    env[node.name] = handler(sub, sub_params, *margs)
            elif node.op == "call_function":
                handler = fn_table.get(node.target)
                if handler is None:
                    raise UnsupportedTorchOp(f"function {node.target}")
                margs = [materialize(a) for a in node.args]
                mkwargs = {k: materialize(v) for k, v in node.kwargs.items()}
                mkwargs.pop("dtype", None)
                mkwargs.pop("device", None)
                env[node.name] = handler(*margs, **mkwargs)
            elif node.op == "call_method":
                handler = METHOD_TABLE.get(node.target)
                if handler is None:
                    raise UnsupportedTorchOp(f"method .{node.target}()")
                margs = [materialize(a) for a in node.args]
                mkwargs = {k: materialize(v) for k, v in node.kwargs.items()}
                env[node.name] = handler(*margs, **mkwargs)
            elif node.op == "output":
                out = materialize(node.args[0])
                if extra_state is not None and stateful:
                    new_buffers = {
                        k: buffer_updates.get(k, jnp.asarray(v)) for k, v in live_buffers.items()
                    }
                    new_state = {
                        "torch_state": {
                            "buffers": new_buffers,
                            "rng": state_in.get("rng", jnp.zeros((), jnp.uint32)) + 1,
                        }
                    }
                    return out, new_state
                return out
        raise RuntimeError("fx graph had no output node")

    if stateful:
        variables = {
            "params": params,
            "torch_state": {"buffers": buffers, "rng": np.zeros((), np.uint32)},
        }
        return apply_fn, variables
    return apply_fn, params
