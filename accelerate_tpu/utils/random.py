"""Seeding and cross-process RNG synchronization.

Capability parity: reference `src/accelerate/utils/random.py` (set_seed,
synchronize_rng_states). TPU-native: JAX randomness is an explicit threefry key, so
the framework keeps one root key per job (split per step/host as needed) instead of
mutating hidden per-device generator state. Host-side RNG (python/numpy, used by
samplers and data augmentation) is synchronized by broadcasting from process 0 over
DCN, mirroring reference `random.py:66-128`.
"""

from __future__ import annotations

import random as _py_random
from typing import Any, Iterable

import jax
import numpy as np

from ..state import PartialState
from .operations import broadcast_object_list

_ROOT_KEY: jax.Array | None = None


def set_seed(seed: int, device_specific: bool = False) -> None:
    """Seed python, numpy and the framework's root JAX key (reference `random.py:31`).

    With ``device_specific`` each process offsets the seed by its index so
    augmentation streams differ per host while remaining deterministic.
    """
    global _ROOT_KEY
    if device_specific:
        seed += PartialState().process_index
    _py_random.seed(seed)
    np.random.seed(seed % (2**32))
    _ROOT_KEY = jax.random.key(seed)


def get_rng_key() -> jax.Array:
    """The job's current root PRNG key (auto-seeded to 0 if set_seed never ran)."""
    global _ROOT_KEY
    if _ROOT_KEY is None:
        _ROOT_KEY = jax.random.key(0)
    return _ROOT_KEY


def split_rng_key(num: int = 2) -> tuple[jax.Array, ...]:
    """Split the root key, advancing it (functional analogue of generator state)."""
    global _ROOT_KEY
    keys = jax.random.split(get_rng_key(), num + 1)
    _ROOT_KEY = keys[0]
    return tuple(keys[1:])


def capture_rng_state() -> dict[str, Any]:
    """Snapshot all host+framework RNG state for checkpointing
    (reference `checkpointing.py:144-161`)."""
    key = get_rng_key()
    return {
        "python": _py_random.getstate(),
        "numpy": np.random.get_state(),
        "jax_key_data": np.asarray(jax.random.key_data(key)),
    }


def restore_rng_state(state: dict[str, Any]) -> None:
    global _ROOT_KEY
    _py_random.setstate(state["python"])
    np.random.set_state(state["numpy"])
    _ROOT_KEY = jax.random.wrap_key_data(np.asarray(state["jax_key_data"]))


def synchronize_rng_state() -> None:
    """Broadcast process 0's host RNG state to all processes so samplers shuffle
    identically everywhere (reference `random.py:66-128`)."""
    state = PartialState()
    if state.num_processes == 1:
        return
    payload = [capture_rng_state()]
    broadcast_object_list(payload, from_process=0)
    restore_rng_state(payload[0])


def synchronize_rng_states(rng_types: Iterable[str] | None = None) -> None:
    """API-compatible alias (the reference takes a list of generator types; here all
    host RNG state travels together)."""
    synchronize_rng_state()
