"""Rematerialization policies: the HBM <-> FLOPs dial.

The reference exposes activation checkpointing as engine flags (FSDP
`activation_checkpointing`, `accelerator.py:1531-1540`; DeepSpeed config;
Megatron `--recompute-*`). TPU-native this is `jax.checkpoint` with a
save-policy; the named policies below pick what XLA keeps in HBM across the
forward pass:

- ``"full"``     — save nothing, recompute everything in backward (max memory
                   savings, ~33% more FLOPs).
- ``"dots"``     — save matmul outputs only (`checkpoint_dots`): elementwise/
                   norm ops recompute, the MXU work does not. Usually the best
                   throughput-per-byte trade on TPU.
- ``"dots_no_batch"`` — `dots_with_no_batch_dims_saveable`: like "dots" but
                   batched matmuls (attention scores) also recompute.
- ``"nothing"``  — alias of "full".
"""

from __future__ import annotations

from typing import Any, Callable

import jax

_POLICIES: dict[str, Any] = {
    "full": None,
    "nothing": None,
    "dots": jax.checkpoint_policies.checkpoint_dots,
    "dots_no_batch": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
}


def resolve_remat_policy(name: str | None) -> Any:
    """Map a policy name to a `jax.checkpoint` policy callable (None = save
    nothing). Accepts a callable directly for custom policies."""
    if name is None or callable(name):
        return name
    try:
        return _POLICIES[name]
    except KeyError:
        raise ValueError(
            f"Unknown remat policy {name!r}; choose from {sorted(_POLICIES)} "
            "or pass a jax.checkpoint_policies callable."
        ) from None


def remat_block(block_cls, policy_name: str | None = None, static_argnums: tuple = ()):
    """nn.remat a flax block class under the named policy.

    ``static_argnums`` indexes the block's ``__call__`` positional args with the
    module instance at 0 — Python-bool flags like ``deterministic``/``decode``
    MUST be listed or flax traces them and `if flag:` raises
    TracerBoolConversionError."""
    import flax.linen as nn

    return nn.remat(
        block_cls,
        prevent_cse=False,
        policy=resolve_remat_policy(policy_name),
        static_argnums=static_argnums,
    )
