"""Environment-variable parsing and host introspection.

Capability parity: reference `src/accelerate/utils/environment.py` (str_to_bool,
parse_flag_from_env, CPU topology probing). TPU-native: the launcher <-> library
contract uses ``ACCELERATE_TPU_*`` variables plus JAX's own coordinator variables
(``JAX_COORDINATOR_ADDRESS``/``JAX_PROCESS_ID``/``JAX_NUM_PROCESSES``) instead of
torch.distributed's ``RANK``/``WORLD_SIZE``/``MASTER_ADDR`` rendezvous contract.
"""

from __future__ import annotations

import contextlib
import os
from typing import Any


def on_tpu_platform() -> bool:
    """True on real TPU or the axon tunnel — THE platform probe (kernels pick
    compiled-vs-interpret and dispatchers pick flash-vs-xla off this)."""
    import jax

    return jax.devices()[0].platform in ("tpu", "axon")


def str_to_bool(value: str) -> int:
    """Convert a truthy/falsy string to 1/0 (raises on anything else)."""
    value = value.lower()
    if value in ("y", "yes", "t", "true", "on", "1"):
        return 1
    if value in ("n", "no", "f", "false", "off", "0"):
        return 0
    raise ValueError(f"invalid truth value {value!r}")


def parse_flag_from_env(key: str, default: bool = False) -> bool:
    value = os.environ.get(key, None)
    if value is None:
        return default
    try:
        return bool(str_to_bool(value))
    except ValueError:
        raise ValueError(f"If set, {key} must be yes or no, got {value!r}.")


def parse_choice_from_env(key: str, default: str = "no") -> str:
    return os.environ.get(key, str(default))


def parse_int_from_env(key: str, default: int) -> int:
    """Integer env knob; empty/whitespace values fall back to the default
    (kernel block sizes, sweep knobs)."""
    raw = os.environ.get(key, "").strip()
    return int(raw) if raw else default


def get_int_from_env(keys: list[str], default: int) -> int:
    """Return the first set integer among ``keys`` (reference: same helper for PMI/OMPI)."""
    for key in keys:
        value = os.environ.get(key, None)
        if value is not None:
            return int(value)
    return default


@contextlib.contextmanager
def patch_environment(**kwargs: Any):
    """Temporarily set environment variables inside the context, restoring after.

    Mirrors reference `utils/other.py:patch_environment`. Keys are upper-cased.
    """
    existing: dict[str, str] = {}
    for key, value in kwargs.items():
        key = key.upper()
        if key in os.environ:
            existing[key] = os.environ[key]
        os.environ[key] = str(value)
    try:
        yield
    finally:
        for key in kwargs:
            key = key.upper()
            if key in existing:
                os.environ[key] = existing[key]
            else:
                os.environ.pop(key, None)


@contextlib.contextmanager
def clear_environment():
    """Temporarily empty os.environ inside the context (reference `utils/other.py:clear_environment`)."""
    saved = dict(os.environ)
    os.environ.clear()
    try:
        yield
    finally:
        os.environ.clear()
        os.environ.update(saved)


def are_we_under_multihost_env() -> bool:
    """True when launcher-provided multi-host coordinates are present."""
    return "JAX_COORDINATOR_ADDRESS" in os.environ or "ACCELERATE_TPU_NUM_PROCESSES" in os.environ
