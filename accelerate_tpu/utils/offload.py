"""Disk-offloaded weight storage.

Capability parity: reference `src/accelerate/utils/offload.py` (213 LoC) —
numpy-memmap weight store with an ``index.json`` manifest, plus a dict-like
loader that pulls from memory or disk transparently.
"""

from __future__ import annotations

import json
import os
from collections.abc import Mapping
from pathlib import Path
from typing import Any, Iterator

import numpy as np


def offload_weight(weight: np.ndarray, weight_name: str, offload_folder: str, index: dict | None = None) -> dict:
    """Write one array to a .dat memmap and record it in the index
    (reference `offload.py:25`)."""
    weight = np.asarray(weight)
    os.makedirs(offload_folder, exist_ok=True)
    dtype = str(weight.dtype)
    if weight.dtype == np.dtype("bfloat16"):  # numpy can't memmap bf16: store as uint16 bits
        weight = weight.view(np.uint16)
        dtype = "bfloat16"
    path = Path(offload_folder) / f"{weight_name.replace('/', '--')}.dat"
    mm = np.memmap(path, dtype=weight.dtype, mode="w+", shape=weight.shape or (1,))
    mm[:] = weight if weight.shape else weight.reshape(1)
    mm.flush()
    if index is not None:
        index[weight_name] = {"dtype": dtype, "shape": list(weight.shape)}
    return index if index is not None else {}


def save_offload_index(index: dict, offload_folder: str) -> None:
    with open(Path(offload_folder) / "index.json", "w") as f:
        json.dump(index, f, indent=2)


def load_offload_index(offload_folder: str) -> dict:
    with open(Path(offload_folder) / "index.json") as f:
        return json.load(f)


def load_offloaded_weight(offload_folder: str, weight_name: str, info: dict) -> np.ndarray:
    shape = tuple(info["shape"]) or (1,)
    dtype = info["dtype"]
    storage_dtype = np.uint16 if dtype == "bfloat16" else np.dtype(dtype)
    path = Path(offload_folder) / f"{weight_name.replace('/', '--')}.dat"
    mm = np.memmap(path, dtype=storage_dtype, mode="r", shape=shape)
    arr = np.asarray(mm)
    if dtype == "bfloat16":
        import jax.numpy as jnp

        arr = arr.view(jnp.bfloat16)
    if not info["shape"]:
        arr = arr.reshape(())
    return arr


class OffloadedWeightsLoader(Mapping):
    """Dict-like view over in-memory weights + a disk offload folder
    (reference `OffloadedWeightsLoader`, `offload.py:127`)."""

    def __init__(self, state_dict: dict[str, np.ndarray] | None = None, save_folder: str | None = None):
        if state_dict is None and save_folder is None:
            raise ValueError("Need at least one of state_dict or save_folder.")
        self.state_dict = dict(state_dict or {})
        self.save_folder = save_folder
        self.index = load_offload_index(save_folder) if save_folder else {}
        self.all_keys = list(self.state_dict) + [k for k in self.index if k not in self.state_dict]

    def __getitem__(self, key: str) -> np.ndarray:
        if key in self.state_dict:
            return self.state_dict[key]
        return load_offloaded_weight(self.save_folder, key, self.index[key])

    def __iter__(self) -> Iterator[str]:
        return iter(self.all_keys)

    def __len__(self) -> int:
        return len(self.all_keys)


def offload_state_dict(save_dir: str, state_dict: dict[str, Any]) -> None:
    """Offload a flat state dict to disk (reference `offload_state_dict`)."""
    index: dict = {}
    for name, value in state_dict.items():
        index = offload_weight(np.asarray(value), name, save_dir, index)
    save_offload_index(index, save_dir)
