"""Small cross-cutting utilities (reference `utils/other.py` role — the
backend-free subset that has TPU meaning; engine unwrap/save paths collapse
into `Accelerator.unwrap_model`/`save`)."""

from __future__ import annotations

import pickle
from typing import Any

import jax
import numpy as np


def convert_bytes(size: float) -> str:
    """Human-readable byte size (reference `utils/other.py:convert_bytes`)."""
    for unit in ("B", "KB", "MB", "GB"):
        if abs(size) < 1024.0:
            return f"{size:.2f} {unit}"
        size /= 1024.0
    return f"{size:.2f} TB"


def get_pretty_name(obj: Any) -> str:
    """Best display name for an object (reference `utils/other.py`)."""
    if hasattr(obj, "__qualname__"):
        return obj.__qualname__
    if hasattr(obj, "__name__"):
        return obj.__name__
    return type(obj).__qualname__


def extract_model_from_parallel(model: Any, keep_fp32_wrapper: bool = True) -> Any:
    """Unwrap a prepared model back to the user object (reference
    `extract_model_from_parallel` — DDP/FSDP/compiled unwrapping collapses to
    returning the original module/apply_fn captured at prepare time). With
    ``keep_fp32_wrapper`` and an active compute-cast policy, a callable
    original is returned wrapped so outputs still upcast to fp32 (the
    reference keeps the autocast forward patch)."""
    from ..accelerator import PreparedModel
    from .operations import ConvertOutputsToFp32

    if not isinstance(model, PreparedModel):
        return model
    original = model.module
    if (
        keep_fp32_wrapper
        and model.policy.enabled
        and callable(original)
        and not hasattr(original, "apply")  # wrapping a flax module would hide
        # its .apply/.init API; plain forward functions are what the
        # reference's fp32 forward patch wraps
    ):
        return ConvertOutputsToFp32(original)
    return original


def save(obj: Any, f: str, save_on_each_node: bool = False, safe_serialization: bool = False) -> None:
    """Rank-gated object serialization (reference `utils/other.py:save`).
    ``save_on_each_node`` writes from every process (shared-filesystem-free
    clusters); default is main-process-only."""
    from ..state import PartialState

    state = PartialState()
    should_write = state.is_local_main_process if save_on_each_node else state.is_main_process
    if not should_write:
        return
    host = jax.tree.map(
        lambda x: np.asarray(jax.device_get(x)) if hasattr(x, "shape") else x, obj
    )
    if safe_serialization:
        from safetensors.numpy import save_file

        from .safetensors_io import flatten_state_dict

        save_file(flatten_state_dict(host), f)
        return
    with open(f, "wb") as fh:
        pickle.dump(host, fh)


def load(f: str, map_location: Any = None, **kwargs: Any) -> Any:
    """Counterpart of `save` (reference `utils/other.py:load`); safetensors
    files load via the interchange reader, anything else unpickles."""
    if _is_safetensors_file(f):
        from .safetensors_io import load_safetensors_checkpoint

        return load_safetensors_checkpoint(f, nested=True)
    with open(f, "rb") as fh:
        return pickle.load(fh)


def _is_safetensors_file(f: str) -> bool:
    """Sniff the safetensors header (8-byte little-endian length + '{') so
    `load` round-trips whatever `save(..., safe_serialization=True)` wrote,
    regardless of extension."""
    if str(f).endswith(".safetensors"):
        return True
    import os

    try:
        size = os.path.getsize(f)
        with open(f, "rb") as fh:
            head = fh.read(9)
    except OSError:
        return False
    if len(head) < 9:
        return False
    n = int.from_bytes(head[:8], "little")
    return head[8:9] == b"{" and 0 < n <= size - 8
