"""Host-level collective operations over nested pytrees.

Capability parity: reference `src/accelerate/utils/operations.py` (871 LoC) —
``gather``/``gather_object``/``broadcast``/``reduce``/``pad_across_processes``/
``concatenate``/``send_to_device``/``recursively_apply`` plus debug-mode shape
verification (`operations.py:359-421`).

TPU-native re-founding. Two different things hide behind "gather" in the reference:
  (a) device-level collectives inside the step — on JAX these are *implicit*: XLA
      inserts all-reduce/all-gather from shardings under jit, so no wrapper exists;
  (b) host-level, eager collectives for metrics/objects between processes — that is
      what this module provides, built on `jax.experimental.multihost_utils`
      (gRPC/DCN) instead of a torch.distributed TCP store.
A sharded `jax.Array` is already "the gathered batch" viewed globally, so `gather`
on one simply materializes it host-locally (replicating across hosts when needed).
"""

from __future__ import annotations

import functools
import pickle
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from ..state import PartialState

TensorTypes = (jax.Array, np.ndarray)


class DistributedOperationException(Exception):
    """Raised in debug mode when an operation's inputs disagree across processes
    (reference `utils/operations.py:359` / `utils/dataclasses.py` exception)."""


# --------------------------------------------------------------------- pytrees
def is_tensor(x: Any) -> bool:
    return isinstance(x, TensorTypes)


def recursively_apply(
    func: Callable,
    data: Any,
    *args: Any,
    test_type: Callable[[Any], bool] = is_tensor,
    error_on_other_type: bool = False,
    **kwargs: Any,
) -> Any:
    """Map ``func`` over every tensor leaf of a nested structure, leaving other
    leaves untouched (capability of reference `operations.py:85-134`, realized as
    a shim over the pytree machinery: ``jax.tree.map`` handles sequences,
    namedtuples and registered custom nodes). Mappings — including plain dicts —
    are descended by hand instead, because (a) JAX's dict flattening sorts keys,
    which would silently reorder user batches and crash on non-comparable mixed
    key types, and (b) Mapping subclasses like HF's BatchEncoding aren't
    registered pytree nodes at all. ``test_type`` doubles as ``is_leaf`` so
    callers can stop descent at custom aggregate types."""

    def on_leaf(x: Any) -> Any:
        if test_type(x):
            return func(x, *args, **kwargs)
        if isinstance(x, Mapping):
            return type(x)(
                {
                    k: recursively_apply(
                        func, v, *args, test_type=test_type,
                        error_on_other_type=error_on_other_type, **kwargs,
                    )
                    for k, v in x.items()
                }
            )
        if error_on_other_type:
            raise TypeError(
                f"Unsupported type {type(x)} passed: only nested containers of arrays are handled."
            )
        return x

    return jax.tree.map(
        on_leaf, data, is_leaf=lambda x: test_type(x) or isinstance(x, Mapping)
    )


def as_registered_pytree(data: Any) -> Any:
    """Convert Mapping subclasses that are NOT plain dicts (HF BatchEncoding /
    ModelOutput, UserDict, …) into dicts, recursively — a jitted step can only
    trace containers the pytree registry knows. Everything else passes through."""
    if isinstance(data, Mapping):
        return {k: as_registered_pytree(v) for k, v in data.items()}
    if isinstance(data, tuple) and hasattr(data, "_fields"):  # namedtuple
        return type(data)(*(as_registered_pytree(v) for v in data))
    if isinstance(data, (list, tuple)):
        return type(data)(as_registered_pytree(v) for v in data)
    return data


def send_to_device(tensor: Any, device: Any = None, non_blocking: bool = False) -> Any:
    """Place every array leaf on ``device`` (a jax.Device or NamedSharding) —
    reference `operations.py:136-191`. `jax.device_put` is asynchronous by nature,
    so ``non_blocking`` is the default behavior and the flag is accepted only for
    API compatibility."""

    def _send(t):
        return jax.device_put(t, device)

    return recursively_apply(_send, tensor)


def get_data_structure(data: Any) -> Any:
    """Shape/dtype skeleton of a pytree (used for broadcast negotiation)."""
    return recursively_apply(lambda t: (tuple(t.shape), np.dtype(t.dtype).name), data)


def slice_tensors(data: Any, tensor_slice: slice) -> Any:
    """Slice every leaf (reference `operations.py:585`)."""
    return recursively_apply(lambda t: t[tensor_slice], data)


def concatenate(data: list, dim: int = 0) -> Any:
    """Concatenate a list of same-structure pytrees leafwise (capability of
    reference `operations.py:605`; here one multi-tree ``jax.tree.map``)."""

    def _cat(*leaves: Any) -> Any:
        if isinstance(leaves[0], Mapping):  # descended by hand: see recursively_apply
            return type(leaves[0])(
                {k: concatenate([l[k] for l in leaves], dim=dim) for k in leaves[0].keys()}
            )
        if not is_tensor(leaves[0]):
            raise TypeError(f"Can only concatenate containers of arrays, got {type(leaves[0])}.")
        if isinstance(leaves[0], np.ndarray):
            return np.concatenate(leaves, axis=dim)
        return jnp.concatenate(leaves, axis=dim)

    return jax.tree.map(_cat, *data, is_leaf=lambda x: isinstance(x, Mapping))


# ---------------------------------------------------------------- debug verify
def verify_operation(function: Callable) -> Callable:
    """In debug mode, pre-gather every rank's pytree shapes before the collective
    and raise `DistributedOperationException` listing per-process shapes on
    mismatch — catching desyncs before they deadlock (reference `operations.py:359-421`)."""

    @functools.wraps(function)
    def wrapper(*args, **kwargs):
        state = PartialState()
        if not state.debug or state.num_processes == 1:
            return function(*args, **kwargs)
        tensor = kwargs.get("tensor", args[0] if args else None)
        shapes = get_data_structure(tensor)
        all_shapes = gather_object([shapes])
        if any(s != all_shapes[0] for s in all_shapes):
            operation = f"{function.__module__}.{function.__name__}"
            raise DistributedOperationException(
                f"Cannot apply {operation}: input structure/shape differs across processes.\n"
                + "\n".join(f"  - process {i}: {s}" for i, s in enumerate(all_shapes))
            )
        return function(*args, **kwargs)

    return wrapper


# ------------------------------------------------------------------- collectives
def _materialize(t: jax.Array | np.ndarray) -> np.ndarray | jax.Array:
    """Make a (possibly sharded, possibly multi-host) array host-materializable.

    Fully-addressable arrays just transfer; arrays with non-addressable shards
    (multi-host) are replicated via a process-level all-gather of local shards.
    """
    if isinstance(t, np.ndarray):
        return t
    if getattr(t, "is_fully_addressable", True):
        return np.asarray(jax.device_get(t))
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(t, tiled=True))


@verify_operation
def gather(tensor: Any) -> Any:
    """Return the full, job-global value of ``tensor`` on every process
    (reference `operations.py:423` — there: concat over ranks; here: a sharded
    global array is already the concatenation, so gathering means materializing
    it; per-host numpy data is all-gathered over DCN)."""
    state = PartialState()

    def _gather(t):
        if isinstance(t, jax.Array):
            return _materialize(t)
        if state.num_processes > 1:
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(np.asarray(t), tiled=True))
        return np.asarray(t)

    return recursively_apply(_gather, tensor)


def consolidate_on_main(tree: Any, keep_on_all: bool = False) -> Any:
    """Stream-consolidate a (possibly sharded) pytree to host numpy, one leaf at
    a time, keeping the result only on the main process by default (other
    processes get ``None`` leaves).

    This is the host-memory- and DCN-safe export path for big models
    (reference `accelerator.py:3329-3383` — FSDP FULL_STATE_DICT with
    rank0-only consolidation): peak host usage is the full tree on host 0 but
    only ONE leaf anywhere else, instead of `gather`'s full replica per host.
    Every process must call it — materializing a non-addressable (multi-host)
    leaf is a collective."""
    state = PartialState()
    leaves, treedef = jax.tree.flatten(tree)
    out = []
    for leaf in leaves:
        if not (hasattr(leaf, "shape") and hasattr(leaf, "dtype")):
            out.append(leaf)
            continue
        keep = keep_on_all or state.is_main_process
        if isinstance(leaf, jax.Array) and not getattr(leaf, "is_fully_addressable", True):
            val = _materialize(leaf)  # collective: all processes participate
            out.append(val if keep else None)
        else:
            out.append(_materialize(leaf) if keep else None)
    return jax.tree.unflatten(treedef, out)


def gather_object(object: Any) -> list:
    """All-gather arbitrary picklable python objects across processes
    (reference `operations.py:449`). Objects are pickled to byte arrays, padded to
    the max length, exchanged over DCN, and unpickled. Expects a list and returns
    the concatenation of every process's list."""
    state = PartialState()
    if not isinstance(object, list):
        raise TypeError(f"gather_object expects a list, got {type(object)}")
    if state.num_processes == 1:
        return list(object)
    from jax.experimental import multihost_utils

    payload = np.frombuffer(pickle.dumps(object), dtype=np.uint8)
    sizes = multihost_utils.process_allgather(np.array([payload.size], dtype=np.int64))
    max_size = int(np.max(sizes))
    padded = np.zeros((max_size,), dtype=np.uint8)
    padded[: payload.size] = payload
    gathered = multihost_utils.process_allgather(padded)  # [num_processes, max_size]
    out: list = []
    for i in range(state.num_processes):
        out.extend(pickle.loads(gathered[i, : int(sizes[i])].tobytes()))
    return out


@verify_operation
def broadcast(tensor: Any, from_process: int = 0) -> Any:
    """Broadcast every leaf from ``from_process`` to all (reference `operations.py:476`).
    multihost broadcast is one-to-all from process 0; for other sources the value
    is rotated to process 0 first via a process all-gather."""
    state = PartialState()
    if state.num_processes == 1:
        return tensor
    from jax.experimental import multihost_utils

    def _bcast(t):
        t = np.asarray(jax.device_get(t)) if isinstance(t, jax.Array) else np.asarray(t)
        if from_process == 0:
            return np.asarray(multihost_utils.broadcast_one_to_all(t))
        gathered = multihost_utils.process_allgather(t)
        return np.asarray(gathered[from_process])

    return recursively_apply(_bcast, tensor)


def broadcast_object_list(object_list: list, from_process: int = 0) -> list:
    """Broadcast a list of picklable objects from one process (reference `operations.py:564`)."""
    state = PartialState()
    if state.num_processes == 1:
        return object_list
    from jax.experimental import multihost_utils

    payload = np.frombuffer(pickle.dumps(object_list), dtype=np.uint8)
    size_arr = np.array([payload.size], dtype=np.int64)
    if from_process != 0:
        sizes = multihost_utils.process_allgather(size_arr)
        size = int(sizes[from_process])
        padded = np.zeros((int(np.max(sizes)),), dtype=np.uint8)
        padded[: payload.size] = payload
        gathered = multihost_utils.process_allgather(padded)
        data = gathered[from_process, :size]
    else:
        size = int(multihost_utils.broadcast_one_to_all(size_arr)[0])
        padded = np.zeros((size,), dtype=np.uint8)
        if PartialState().process_index == 0:
            padded[:] = payload[:size]
        data = np.asarray(multihost_utils.broadcast_one_to_all(padded))
    result = pickle.loads(data.tobytes())
    object_list[:] = result
    return object_list


@verify_operation
def pad_across_processes(tensor: Any, dim: int = 0, pad_index: int = 0, pad_first: bool = False) -> Any:
    """Pad every process's leaf to the max size along ``dim`` so a later gather is
    rectangular (reference `operations.py:632`). XLA requires static shapes, so this
    also serves as the pad-to-bucket primitive for ragged final batches."""
    state = PartialState()

    def _pad(t):
        t = np.asarray(jax.device_get(t)) if isinstance(t, jax.Array) else np.asarray(t)
        if dim >= t.ndim:
            return t
        size = t.shape[dim]
        if state.num_processes > 1:
            from jax.experimental import multihost_utils

            sizes = multihost_utils.process_allgather(np.array([size], dtype=np.int64))
            max_size = int(np.max(sizes))
        else:
            max_size = size
        if max_size == size:
            return t
        pad_widths = [(0, 0)] * t.ndim
        pad_widths[dim] = (max_size - size, 0) if pad_first else (0, max_size - size)
        return np.pad(t, pad_widths, constant_values=pad_index)

    return recursively_apply(_pad, tensor)


def pad_input_tensors(tensor: Any, batch_size: int, num_processes: int, dim: int = 0) -> Any:
    """Pad a batch so it divides evenly across processes by repeating trailing
    samples (reference `operations.py:687`)."""

    def _pad(t):
        t = np.asarray(t)
        remainder = t.shape[dim] % num_processes
        if remainder == 0:
            return t
        pad_n = num_processes - remainder
        idx = [slice(None)] * t.ndim
        idx[dim] = slice(t.shape[dim] - 1, t.shape[dim])
        last = np.repeat(t[tuple(idx)], pad_n, axis=dim)
        return np.concatenate([t, last], axis=dim)

    return recursively_apply(_pad, tensor)


@verify_operation
def reduce(tensor: Any, reduction: str = "mean", scale: float = 1.0) -> Any:
    """Sum/mean every leaf across processes (reference `operations.py:728`)."""
    state = PartialState()

    def _reduce(t):
        t = np.asarray(jax.device_get(t)) if isinstance(t, jax.Array) else np.asarray(t)
        if state.num_processes > 1:
            from jax.experimental import multihost_utils

            stacked = multihost_utils.process_allgather(t)
            t = stacked.sum(axis=0)
            if reduction == "mean":
                t = t / state.num_processes
        return t * scale

    return recursively_apply(_reduce, tensor)


# ----------------------------------------------------------------- dtype casts
def convert_to_fp32(tensor: Any) -> Any:
    """Upcast every floating leaf to float32 (reference `operations.py:769` —
    used on model outputs under mixed precision so user-side metric math is fp32)."""

    def _upcast(t):
        if jnp.issubdtype(t.dtype, jnp.floating) and t.dtype != jnp.float32:
            return t.astype(jnp.float32)
        return t

    return recursively_apply(_upcast, tensor)


def convert_outputs_to_fp32(model_forward: Callable) -> Callable:
    """Function form of `ConvertOutputsToFp32` (reference `operations.py:769`)."""
    return ConvertOutputsToFp32(model_forward)


class ConvertOutputsToFp32:
    """Picklable callable wrapper that upcasts a function's outputs to fp32
    (reference `ConvertOutputsToFp32`, `operations.py:790-828`)."""

    def __init__(self, model_forward: Callable):
        self.model_forward = model_forward
        functools.update_wrapper(self, model_forward)

    def __call__(self, *args, **kwargs):
        return convert_to_fp32(self.model_forward(*args, **kwargs))

    def __getstate__(self):
        return {"model_forward": self.model_forward}

    def __setstate__(self, state):
        self.__init__(state["model_forward"])


def find_batch_size(data: Any) -> int | None:
    """First dimension of the first array leaf found (reference `operations.py`)."""
    if isinstance(data, (tuple, list)):
        for d in data:
            bs = find_batch_size(d)
            if bs is not None:
                return bs
        return None
    if isinstance(data, Mapping):
        for v in data.values():
            bs = find_batch_size(v)
            if bs is not None:
                return bs
        return None
    # any array-like with a leading dim counts (torch tensors included — the
    # loaders call this on raw user batches before leaf conversion)
    shape = getattr(data, "shape", None)
    if shape is not None and len(shape) >= 1:
        return int(shape[0])
    return None


def listify(data: Any) -> Any:
    """Convert array leaves to plain python lists (for logging/tracking)."""
    return recursively_apply(lambda t: np.asarray(t).tolist(), data)
