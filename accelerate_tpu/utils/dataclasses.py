"""Typed configuration plugins and kwargs handlers.

Capability parity: reference `src/accelerate/utils/dataclasses.py` (2535 LoC) —
the plugin dataclass family consumed by `Accelerator(...)`. Under SPMD most
engine-specific plugins collapse into `ParallelismConfig` (mesh axes); what
remains here are the genuinely orthogonal knobs: dataloader behavior, profiling,
fp8 recipes, grad-scaler settings, compilation, and `KwargsHandler` plumbing.

Engine-plugin mapping (for users migrating from the reference):
  - DistributedDataParallelKwargs -> nothing to configure: XLA fuses/schedules
    gradient reductions itself (bucketing knobs have no analogue).
  - FullyShardedDataParallelPlugin -> `FullyShardedDataParallelPlugin` below: a
    thin alias filling ParallelismConfig.fsdp_size + sharding rules.
  - DeepSpeedPlugin zero_stage -> fsdp_size (stage 3) / zero1 opt-state sharding.
  - MegatronLMPlugin tp/pp/sp degrees -> tensor/stage/sequence sizes.
  - TorchDynamoPlugin -> `CompilationConfig` (jit options; XLA always compiles).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Callable

from ..parallel.mesh import ParallelismConfig
from ..parallel.sharding import ShardingRules


class KwargsHandler:
    """Base for typed kwargs containers (reference `dataclasses.py:51-70`)."""

    def to_dict(self) -> dict:
        return copy.deepcopy(self.__dict__)


@dataclass
class GradScalerKwargs(KwargsHandler):
    """fp16 dynamic loss-scale settings (reference `GradScalerKwargs`)."""

    init_scale: float = 2.0**15
    growth_factor: float = 2.0
    backoff_factor: float = 0.5
    growth_interval: int = 2000
    enabled: bool = True


@dataclass
class DataLoaderConfiguration(KwargsHandler):
    """Dataloader behavior knobs (reference `DataLoaderConfiguration`)."""

    split_batches: bool = False
    dispatch_batches: bool | None = None
    even_batches: bool = True
    use_seedable_sampler: bool = True
    non_blocking: bool = True  # JAX transfers are always async
    # parity with reference use_stateful_dataloader: loaders here are ALWAYS
    # mid-epoch resumable (state_dict/load_state_dict), no torchdata needed
    use_stateful_dataloader: bool = True


@dataclass
class ProfileKwargs(KwargsHandler):
    """Profiler configuration (reference `ProfileKwargs`, `dataclasses.py:406`).
    Maps onto `jax.profiler.trace`: traces include XLA/TPU activity by default;
    per-rank Chrome/Perfetto output lands under ``output_trace_dir``."""

    output_trace_dir: str | None = None
    create_perfetto_link: bool = False
    host_tracer_level: int = 2
    python_tracer_level: int = 0

    def build(self):
        import jax

        class _Ctx:
            def __init__(self, kw: "ProfileKwargs"):
                self.kw = kw

            def __enter__(self):
                jax.profiler.start_trace(
                    self.kw.output_trace_dir or "profile_traces",
                    create_perfetto_link=self.kw.create_perfetto_link,
                )
                return self

            def __exit__(self, *exc):
                jax.profiler.stop_trace()

        return _Ctx(self)


@dataclass
class CompilationConfig(KwargsHandler):
    """jit/compile options (role of reference `TorchDynamoPlugin` — everything is
    always compiled under XLA; these tune how)."""

    donate_buffers: bool = True
    scan_layers: bool = False
    remat: bool = False
    remat_policy: str | None = None  # e.g. 'dots_saveable', 'nothing_saveable'


@dataclass
class FP8RecipeKwargs(KwargsHandler):
    """fp8 recipe (reference `FP8RecipeKwargs`): delayed-scaling parameters for
    the fp8 matmul path in ops/fp8.py."""

    margin: int = 0
    interval: int = 16
    fp8_format: str = "HYBRID"  # E4M3 fwd / E5M2 bwd
    amax_history_len: int = 1024
    amax_compute_algo: str = "max"
    backend: str = "native"  # "native" fp8-storage dot | "qdq" rounding simulation
    # MS-AMP-role optimizer level (reference accelerator.py:2015-2057):
    # "O1" fp32 optimizer state; "O2" e4m3 mu + scaled-fp16 nu (ops/fp8.py:adamw_fp8)
    opt_level: str = "O1"

    def to_recipe(self):
        from ..ops.fp8 import DelayedScalingRecipe

        return DelayedScalingRecipe(
            margin=self.margin,
            amax_history_len=self.amax_history_len,
            fp8_format=self.fp8_format,
            backend=self.backend,
        )


@dataclass
class FullyShardedDataParallelPlugin(KwargsHandler):
    """FSDP surface (reference `dataclasses.py:1404`): resolves to mesh config +
    sharding rules; `state_dict_type` picks checkpoint layout (orbax-sharded vs
    consolidated)."""

    fsdp_size: int = -1  # -1: all devices
    reshard_after_forward: bool = True  # ZeRO-3 semantics (XLA schedules this)
    state_dict_type: str = "SHARDED_STATE_DICT"
    min_weight_size_to_shard: int = 2**10

    def to_parallelism_config(self) -> ParallelismConfig:
        return ParallelismConfig(data_parallel_size=1 if self.fsdp_size == -1 else -1,
                                 fsdp_size=self.fsdp_size if self.fsdp_size != -1 else -1)


@dataclass
class DeepSpeedPlugin(KwargsHandler):
    """ZeRO-stage surface for migrating DeepSpeed users (reference
    `dataclasses.py:974`): stages map to sharding placement, not an engine."""

    zero_stage: int = 2
    gradient_accumulation_steps: int = 1
    gradient_clipping: float | None = None
    offload_optimizer_device: str | None = None  # 'cpu' -> host-offloaded opt state
    hf_ds_config: str | None = None  # path to a ds_config.json ('auto' values OK)
    # raw ds_config optimizer/scheduler sections, kept verbatim ('auto' intact)
    # for DummyOptim/DummyScheduler compilation (reference utils/deepspeed.py:245-291)
    optimizer_config: dict | None = None
    scheduler_config: dict | None = None

    def __post_init__(self):
        if self.hf_ds_config:
            self._apply_ds_config(self.hf_ds_config)

    def _apply_ds_config(self, path: str) -> None:
        """Ingest a DeepSpeed JSON config file (the reference accepts the same
        file via `DeepSpeedPlugin(hf_ds_config=...)` / `HfDeepSpeedConfig`,
        `utils/deepspeed.py:44-170`). 'auto' entries keep this plugin's
        defaults, as the reference's auto-fill does; engine-only knobs
        (comm backends, AIO, launcher) are ignored — XLA owns those here."""
        import json

        with open(path) as f:
            cfg = json.load(f)

        def _real(v):
            return v is not None and v != "auto"

        zero = cfg.get("zero_optimization", {})
        if _real(zero.get("stage")):
            self.zero_stage = int(zero["stage"])
        off = zero.get("offload_optimizer", {})
        if _real(off.get("device")) and off.get("device") != "none":
            self.offload_optimizer_device = off["device"]
        if _real(cfg.get("gradient_accumulation_steps")):
            self.gradient_accumulation_steps = int(cfg["gradient_accumulation_steps"])
        if _real(cfg.get("gradient_clipping")):
            self.gradient_clipping = float(cfg["gradient_clipping"])
        self.mixed_precision = None
        if cfg.get("bf16", {}).get("enabled") is True:
            self.mixed_precision = "bf16"
        elif cfg.get("fp16", {}).get("enabled") is True:
            self.mixed_precision = "fp16"
        if cfg.get("optimizer"):
            self.optimizer_config = cfg["optimizer"]
        if cfg.get("scheduler"):
            self.scheduler_config = cfg["scheduler"]

    def to_parallelism_config(self, num_devices: int) -> ParallelismConfig:
        if self.zero_stage >= 3:
            return ParallelismConfig(data_parallel_size=1, fsdp_size=-1)
        return ParallelismConfig()  # stages 0-2: replicated params; opt-state
        # sharding is a placement choice made by the optimizer wrapper


@dataclass
class MegatronLMPlugin(KwargsHandler):
    """TP/PP/SP degrees (reference `dataclasses.py:1814`)."""

    tp_degree: int = 1
    pp_degree: int = 1
    sequence_parallelism: bool = False
    sp_degree: int = 1

    def to_parallelism_config(self) -> ParallelismConfig:
        return ParallelismConfig(
            tensor_size=self.tp_degree,
            stage_size=self.pp_degree,
            sequence_size=self.sp_degree if self.sequence_parallelism else 1,
        )


@dataclass
class AutocastKwargs(KwargsHandler):
    """Customize `Accelerator.autocast` (reference `utils/dataclasses.py`
    AutocastKwargs). Under jit, mixed precision is a functional cast applied
    inside prepared forwards, so the ONE meaningful lever is ``enabled=False``:
    eager `PreparedModel` calls inside the context skip the compute-dtype cast
    and run in the master (fp32) dtype — the reference's
    "disable autocast for a numerically sensitive region" use case.
    ``cache_enabled`` is accepted for API compatibility (torch's autocast
    weight-cast cache has no JAX analogue — XLA caches compiled programs)."""

    enabled: bool = True
    cache_enabled: bool | None = None


@dataclass
class InitProcessGroupKwargs(KwargsHandler):
    """Distributed-init knobs (reference `InitProcessGroupKwargs`): mapped to
    jax.distributed.initialize timeouts."""

    timeout_seconds: int = 1800


@dataclass
class DistributedDataParallelKwargs(KwargsHandler):
    """Data-parallel knobs (reference `DistributedDataParallelKwargs`,
    `dataclasses.py:117-213`). Bucketing/broadcast knobs have no analogue — XLA
    schedules gradient reductions itself; what carries over is the comm-hook
    family (fp16/bf16/PowerSGD gradient compression, see
    `parallel/compression.py`), exposed here as `comm_hook` + state options and
    consumed by `Accelerator.make_train_step(comm_hook=...)`.
    """

    bucket_cap_mb: int = 25  # accepted for parity; XLA ignores it
    find_unused_parameters: bool = False  # meaningless under whole-graph autodiff
    static_graph: bool = False  # jit is always a static graph
    comm_hook: str = "no"  # no | fp16 | bf16 | power_sgd | batched_power_sgd
    matrix_approximation_rank: int = 1
    start_powerSGD_iter: int = 2

    def to_comm_hook_config(self):
        from ..parallel.compression import CommHookConfig

        # DDPCommunicationHookType is a str Enum: "no" comparison and the
        # CommHookConfig ctor (which normalizes in __post_init__) handle it
        if self.comm_hook == "no":
            return None
        return CommHookConfig(
            comm_hook=self.comm_hook,
            matrix_approximation_rank=self.matrix_approximation_rank,
            start_powerSGD_iter=self.start_powerSGD_iter,
        )
