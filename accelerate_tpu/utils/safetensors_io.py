"""safetensors interchange — torch-free, both directions.

Capability parity:
  - export: reference `Accelerator.save_model` (`accelerator.py:2804-2919`) —
    sharded ``.safetensors`` + ``model.safetensors.index.json`` with tied-weight
    deduplication and a ``total_size`` header.
  - import: reference `load_checkpoint_in_model` / safetensors device-direct
    read (`utils/modeling.py:1611-1834`, `:1425-1518`) — stream HF sharded
    safetensors checkpoints into a numpy state dict WITHOUT torch, ready for
    the per-architecture ``params_from_hf_*`` mappers or direct pytree reshape.

TPU-native notes: exported keys are "."-joined flat paths (the HF ecosystem
convention) so files round-trip through `safetensors.numpy` and load in
`transformers` unchanged; bfloat16 leaves are written natively (safetensors
has first-class BF16; numpy doesn't, so bf16 crosses via ml_dtypes' view).
"""

from __future__ import annotations

import json
import os
import re
from pathlib import Path
from typing import Any, Callable, Mapping

import jax
import numpy as np

SAFE_WEIGHTS_NAME = "model.safetensors"
SAFE_WEIGHTS_INDEX_NAME = "model.safetensors.index.json"


def _flatten_leaves(tree: Any, sep: str = ".") -> dict[str, Any]:
    """Nested pytree -> flat {dotted_key: ORIGINAL leaf} (no host conversion —
    aliasing between leaves must survive for tied-weight detection)."""
    flat: dict[str, Any] = {}

    def _walk(node: Any, prefix: str) -> None:
        if isinstance(node, Mapping):
            for k, v in node.items():
                _walk(v, f"{prefix}{sep}{k}" if prefix else str(k))
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                _walk(v, f"{prefix}{sep}{i}" if prefix else str(i))
        elif node is None:
            return
        else:
            flat[prefix] = node

    _walk(tree, "")
    return flat


def flatten_state_dict(tree: Any, sep: str = ".") -> dict[str, np.ndarray]:
    """Nested pytree -> flat {dotted_key: numpy array}."""
    return {k: np.asarray(jax.device_get(v)) for k, v in _flatten_leaves(tree, sep).items()}


def unflatten_state_dict(flat: Mapping[str, Any], sep: str = ".") -> dict:
    """Flat {dotted_key: array} -> nested dict pytree."""
    out: dict = {}
    for key, value in flat.items():
        parts = key.split(sep)
        node = out
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return out


def _parse_size(size: str | int) -> int:
    if isinstance(size, int):
        return size
    m = re.fullmatch(r"\s*(\d+(?:\.\d+)?)\s*([KMGT]?B)\s*", size, re.IGNORECASE)
    if not m:
        raise ValueError(f"Unparseable max_shard_size {size!r}")
    mult = {"B": 1, "KB": 10**3, "MB": 10**6, "GB": 10**9, "TB": 10**12}
    return int(float(m.group(1)) * mult[m.group(2).upper()])


def find_tied_weights(flat: Mapping[str, Any]) -> dict[str, str]:
    """{alias_key: canonical_key} for entries that are the SAME view of the
    same buffer (reference `find_tied_parameters`, `utils/modeling.py:605`).

    Must run on ORIGINAL leaves: numpy views key on (data pointer, shape,
    strides, dtype) — two DIFFERENT views of one buffer (q/k/v slices of a
    fused qkv) are NOT tied, deduplicating them would corrupt the checkpoint —
    and device arrays (jax.Array) key on object identity, since device_get
    would copy each path into a distinct host buffer and erase the aliasing.
    First occurrence is canonical."""
    seen: dict[tuple, str] = {}
    tied: dict[str, str] = {}
    for k, v in flat.items():
        if isinstance(v, np.ndarray):
            ident = (v.__array_interface__["data"][0], v.shape, v.strides, str(v.dtype))
        else:
            ident = (id(v), getattr(v, "shape", None), None, str(getattr(v, "dtype", "")))
        if ident in seen:
            tied[k] = seen[ident]
        else:
            seen[ident] = k
    return tied


def save_safetensors_checkpoint(
    state_dict: Any,
    save_directory: str | os.PathLike,
    max_shard_size: str | int = "10GB",
    metadata: dict[str, str] | None = None,
) -> list[str]:
    """Write a (possibly nested) state dict as sharded safetensors with an HF
    index. Returns the list of files written. Tied (aliased) tensors are saved
    once and recorded under ``metadata.tied_weights`` in the index, mirroring
    the reference's duplicate removal (`accelerator.py:2846-2880`)."""
    from safetensors.numpy import save_file

    save_directory = Path(save_directory)
    save_directory.mkdir(parents=True, exist_ok=True)
    raw = dict(state_dict) if _is_flat(state_dict) else _flatten_leaves(state_dict)
    tied = find_tied_weights(raw)  # on ORIGINAL leaves, before host copies
    flat = {k: _to_numpy(v) for k, v in raw.items() if k not in tied}

    limit = _parse_size(max_shard_size)
    shards: list[dict[str, np.ndarray]] = [{}]
    sizes = [0]
    for k, v in flat.items():
        nbytes = v.nbytes
        if sizes[-1] + nbytes > limit and shards[-1]:
            shards.append({})
            sizes.append(0)
        shards[-1][k] = v
        sizes[-1] += nbytes

    written: list[str] = []
    base_meta = dict(metadata or {})
    if tied:
        base_meta["tied_weights"] = json.dumps(tied)
    if len(shards) == 1:
        path = save_directory / SAFE_WEIGHTS_NAME
        save_file(shards[0], str(path), metadata={"format": "np", **base_meta})
        return [str(path)]

    n = len(shards)
    weight_map: dict[str, str] = {}
    for i, shard in enumerate(shards):
        name = f"model-{i + 1:05d}-of-{n:05d}.safetensors"
        save_file(shard, str(save_directory / name), metadata={"format": "np", **base_meta})
        written.append(str(save_directory / name))
        for k in shard:
            weight_map[k] = name
    index = {
        "metadata": {"total_size": int(sum(sizes)), **base_meta},
        "weight_map": weight_map,
    }
    index_path = save_directory / SAFE_WEIGHTS_INDEX_NAME
    index_path.write_text(json.dumps(index, indent=2, sort_keys=True))
    written.append(str(index_path))
    return written


def load_safetensors_checkpoint(
    checkpoint: str | os.PathLike,
    *,
    nested: bool = False,
    dtype: Any = None,
) -> dict[str, Any]:
    """Stream a safetensors checkpoint (single file, sharded dir with index, or
    HF model dir) into a flat numpy state dict — no torch anywhere. Tied
    aliases recorded by `save_safetensors_checkpoint` are re-materialized as
    references to the canonical array. ``nested=True`` returns the dotted keys
    unflattened into a pytree; ``dtype`` optionally casts floating leaves."""
    path = Path(checkpoint)
    files: list[Path]
    tied: dict[str, str] = {}
    if path.is_file():
        files = [path]
    elif (path / SAFE_WEIGHTS_INDEX_NAME).exists():
        index = json.loads((path / SAFE_WEIGHTS_INDEX_NAME).read_text())
        files = [path / name for name in sorted(set(index["weight_map"].values()))]
        if "tied_weights" in index.get("metadata", {}):
            tied = json.loads(index["metadata"]["tied_weights"])
    elif (path / SAFE_WEIGHTS_NAME).exists():
        files = [path / SAFE_WEIGHTS_NAME]
    else:
        found = sorted(path.glob("*.safetensors")) if path.is_dir() else []
        if not found:
            raise FileNotFoundError(f"No safetensors checkpoint at {checkpoint}")
        files = found

    flat: dict[str, Any] = {}
    if len(files) > 1:
        # shard reads are IO-bound memcpys that release the GIL: loading the
        # shards concurrently overlaps disk/page-cache reads (reference
        # load-time table is the benchmark this feeds — BASELINE.md)
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=min(len(files), 8)) as pool:
            for part in pool.map(lambda f: _load_one(f, dtype), files):
                flat.update(part)
    else:
        flat.update(_load_one(files[0], dtype))
    for f in files:
        if not tied:
            meta = _read_metadata(f)
            if "tied_weights" in meta:
                tied = json.loads(meta["tied_weights"])
    for alias, canonical in tied.items():
        if canonical in flat:
            flat[alias] = flat[canonical]
    return unflatten_state_dict(flat) if nested else flat


def load_checkpoint_in_model(
    model: Any,
    checkpoint: str | os.PathLike,
    mapper: Callable[[dict], dict] | None = None,
    strict: bool = True,
) -> Any:
    """Load a safetensors checkpoint into a prepared model / param pytree
    (role of reference `load_checkpoint_in_model`, `utils/modeling.py:1611`).

    ``model`` may be a PreparedModel (params replaced in place, resharded by
    its plan) or a plain param pytree (returns the new pytree). ``mapper``
    adapts foreign layouts — e.g. ``params_from_hf_gpt2`` consuming the flat
    HF state dict this loader produces.
    """
    flat = load_safetensors_checkpoint(checkpoint)
    params = mapper(flat) if mapper is not None else unflatten_state_dict(flat)
    if hasattr(model, "load_state_dict"):  # PreparedModel
        if strict:
            _check_structure(model.params, params)
        model.load_state_dict(params)
        return model
    if strict and hasattr(model, "keys"):
        _check_structure(model, params)
    return params


# ----------------------------------------------------------------- internals
def _is_flat(tree: Any) -> bool:
    return isinstance(tree, Mapping) and all(
        not isinstance(v, (Mapping, list, tuple)) for v in tree.values()
    )


def _to_numpy(v: Any) -> np.ndarray:
    # bf16 leaves arrive as ml_dtypes bfloat16 arrays, which safetensors
    # writes natively — no special-casing needed
    return np.asarray(jax.device_get(v))


def _load_one(path: Path, dtype: Any) -> dict[str, np.ndarray]:
    from safetensors import safe_open

    out: dict[str, np.ndarray] = {}
    with safe_open(str(path), framework="np") as f:
        for k in f.keys():
            arr = f.get_tensor(k)
            if dtype is not None and np.issubdtype(np.asarray(arr).dtype, np.floating):
                arr = np.asarray(arr).astype(dtype)
            out[k] = arr
    return out


def _read_metadata(path: Path) -> dict[str, str]:
    from safetensors import safe_open

    with safe_open(str(path), framework="np") as f:
        return dict(f.metadata() or {})


def _check_structure(expected: Any, got: Any) -> None:
    # key-set comparison only: _flatten_leaves never device_gets the weights
    exp = set(_flatten_leaves(expected).keys())
    new = set(_flatten_leaves(got).keys())
    missing, unexpected = exp - new, new - exp
    if missing or unexpected:
        raise ValueError(
            f"Checkpoint structure mismatch: missing={sorted(missing)[:8]} "
            f"unexpected={sorted(unexpected)[:8]}"
        )
