"""Shared constants for accelerate_tpu.

Capability parity: reference `src/accelerate/utils/constants.py` (checkpoint file
names, option lists). Values here are TPU-native (orbax/msgpack layouts instead of
torch .bin/.safetensors) but serve the same roles.
"""

# Checkpoint layout (see checkpointing.py)
MODEL_NAME = "model"
OPTIMIZER_NAME = "optimizer"
SCHEDULER_NAME = "scheduler"
SAMPLER_NAME = "sampler"
DATALOADER_STATE_NAME = "dataloader"
RNG_STATE_NAME = "rng_state"
CUSTOM_STATE_NAME = "custom_checkpoint"
STEP_STATE_NAME = "step"
CHECKPOINT_DIR_PREFIX = "checkpoint"
# commit marker written only after every array/host write of a checkpoint
# generation has landed on disk; its absence marks a crashed/in-flight save
CHECKPOINT_COMPLETE_MARKER = "_COMPLETE"

# Profile trace filename pattern (one per host), mirrors reference PROFILE_PATTERN_NAME
PROFILE_PATTERN_NAME = "profile_{suffix}"

# Mesh axis names, ordered outermost (slowest, DCN-friendly) to innermost (ICI-friendly).
# data: pure data parallel replicas
# fsdp: parameter/optimizer-state sharding axis (ZeRO-3 analogue)
# tensor: tensor (Megatron-style) model parallelism
# sequence: sequence/context parallelism (ring attention)
# stage: pipeline stages
MESH_AXIS_NAMES = ("data", "fsdp", "stage", "sequence", "tensor")

# Environment variable namespace (launcher <-> library contract)
ENV_PREFIX = "ACCELERATE_TPU_"

# Default config file location
DEFAULT_CONFIG_DIR_ENV = "ACCELERATE_TPU_CONFIG_DIR"
DEFAULT_CONFIG_NAME = "default_config.yaml"

# Scheduler/optimizer semantics
FSDP_STATE_DICT_TYPES = ["FULL_STATE_DICT", "SHARDED_STATE_DICT"]

# Mixed-precision choices
MIXED_PRECISION_CHOICES = ["no", "bf16", "fp16", "fp8"]
