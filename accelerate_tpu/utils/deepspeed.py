"""DeepSpeed migration surface: DummyOptim / DummyScheduler placeholders.

Capability parity: reference `utils/deepspeed.py:245-291`. Scripts written for
DeepSpeed keep the conventional training-loop shape even when the *real*
optimizer/scheduler are defined in the ds_config JSON — they construct
`DummyOptim`/`DummyScheduler` placeholders and `accelerator.prepare(...)`
swaps in the engine-built objects. TPU-native re-founding: there is no engine;
the ds_config ``optimizer``/``scheduler`` sections are compiled directly to an
optax `GradientTransformation` with the LR schedule *embedded* (optax folds the
schedule into the update, advancing with each optimizer tick exactly like
DeepSpeed's engine-internal scheduler — the reference's
`DeepSpeedSchedulerWrapper.step()` is a no-op for the same reason).

'auto' entries resolve from the placeholder's own fields (lr, weight_decay,
warmup/total steps), mirroring the reference's auto-fill contract
(`utils/deepspeed.py:44-170`).
"""

from __future__ import annotations

from typing import Any, Callable


class DummyOptim:
    """Placeholder optimizer for ds_config-defined optimizers (reference
    `utils/deepspeed.py:245-265`). ``params`` is accepted for signature parity
    but unused — optax transformations are parameter-free until `init`."""

    def __init__(self, params: Any = None, lr: float = 0.001, weight_decay: float = 0.0, **kwargs: Any):
        self.params = params
        self.lr = lr
        self.weight_decay = weight_decay
        self.kwargs = kwargs


class DummyScheduler:
    """Placeholder scheduler for ds_config-defined schedulers (reference
    `utils/deepspeed.py:267-291`). ``lr_scheduler_callable`` (an
    ``optimizer -> schedule_fn`` factory, or a plain ``step -> lr`` optax
    schedule) overrides the ds_config section when given."""

    def __init__(
        self,
        optimizer: Any = None,
        total_num_steps: int | None = None,
        warmup_num_steps: int = 0,
        lr_scheduler_callable: Callable | None = None,
        **kwargs: Any,
    ):
        self.optimizer = optimizer
        self.total_num_steps = total_num_steps
        self.warmup_num_steps = warmup_num_steps
        self.lr_scheduler_callable = lr_scheduler_callable
        self.kwargs = kwargs


def _resolve(value: Any, fallback: Any) -> Any:
    return fallback if value is None or value == "auto" else value


def build_ds_schedule(
    scheduler_config: dict | None,
    dummy_scheduler: DummyScheduler | None,
    base_lr: float,
) -> Callable[[int], float] | None:
    """Compile a ds_config ``scheduler`` section to an optax schedule fn.

    Supported types (DeepSpeed's scheduler zoo): WarmupLR (linear warmup then
    constant), WarmupDecayLR (warmup then linear decay to 0 at
    total_num_steps), WarmupCosineLR (warmup then cosine to ``cos_min_ratio``).
    A `DummyScheduler.lr_scheduler_callable` takes precedence over the section.
    Returns None when there is nothing to schedule (constant lr).
    """
    import optax

    ds = dummy_scheduler
    if ds is not None and ds.lr_scheduler_callable is not None:
        fn = ds.lr_scheduler_callable
        try:  # reference contract: callable(optimizer); optax users pass step->lr
            candidate = fn(ds.optimizer)
        except TypeError:
            candidate = fn
        return candidate if callable(candidate) else fn
    if not scheduler_config:
        return None
    stype = scheduler_config.get("type", "WarmupLR")
    p = scheduler_config.get("params", {})
    warmup = int(_resolve(p.get("warmup_num_steps"), ds.warmup_num_steps if ds else 0))
    max_lr = float(_resolve(p.get("warmup_max_lr"), base_lr))
    min_lr = float(_resolve(p.get("warmup_min_lr"), 0.0))
    total = _resolve(p.get("total_num_steps"), ds.total_num_steps if ds else None)
    if stype == "WarmupLR":
        if warmup == 0:  # DeepSpeed semantics: no warmup = constant max_lr
            return optax.schedules.constant_schedule(max_lr)
        return optax.schedules.join_schedules(
            [optax.schedules.linear_schedule(min_lr, max_lr, warmup),
             optax.schedules.constant_schedule(max_lr)],
            [warmup],
        )
    if stype == "WarmupDecayLR":
        if total is None:
            raise ValueError("WarmupDecayLR needs total_num_steps (ds_config or DummyScheduler)")
        decay = optax.schedules.linear_schedule(max_lr, 0.0, max(int(total) - warmup, 1))
        if warmup == 0:
            return decay
        return optax.schedules.join_schedules(
            [optax.schedules.linear_schedule(min_lr, max_lr, warmup), decay],
            [warmup],
        )
    if stype == "WarmupCosineLR":
        if total is None:
            raise ValueError("WarmupCosineLR needs total_num_steps (ds_config or DummyScheduler)")
        cos_min = float(_resolve(p.get("cos_min_ratio"), 1e-4)) * max_lr
        if warmup == 0:
            return optax.schedules.cosine_decay_schedule(
                init_value=max_lr, decay_steps=int(total), alpha=cos_min / max_lr
            )
        return optax.schedules.warmup_cosine_decay_schedule(
            init_value=min_lr, peak_value=max_lr, warmup_steps=warmup,
            decay_steps=int(total), end_value=cos_min,
        )
    raise ValueError(
        f"Unsupported ds_config scheduler type {stype!r}; supported: WarmupLR, "
        "WarmupDecayLR, WarmupCosineLR (or pass lr_scheduler_callable)."
    )


def build_ds_optimizer(
    optimizer_config: dict | None,
    dummy_optim: DummyOptim,
    schedule_fn: Callable[[int], float] | None = None,
    fp8_opt_level: str = "O1",
):
    """Compile a ds_config ``optimizer`` section (+ optional embedded schedule)
    to an optax `GradientTransformation`.

    Supported types: Adam, AdamW (adam_w_mode), SGD, Lamb. 'auto' params fall
    back to the `DummyOptim`'s fields (reference auto-fill semantics).
    ``fp8_opt_level="O2"`` (from `FP8RecipeKwargs.opt_level`) builds Adam-family
    optimizers with fp8/fp16-carried moments (`ops/fp8.adamw_fp8`, MS-AMP role).
    """
    import optax

    cfg = optimizer_config or {"type": "AdamW", "params": {}}
    otype = cfg.get("type", "AdamW")
    p = cfg.get("params", {})
    lr = float(_resolve(p.get("lr"), dummy_optim.lr))
    wd = float(_resolve(p.get("weight_decay"), dummy_optim.weight_decay))
    learning_rate = schedule_fn if schedule_fn is not None else lr
    betas = _resolve(p.get("betas"), dummy_optim.kwargs.get("betas", (0.9, 0.999)))
    eps = float(_resolve(p.get("eps"), dummy_optim.kwargs.get("eps", 1e-8)))
    name = otype.lower()
    if fp8_opt_level == "O2" and name in ("adam", "adamw"):
        from ..ops.fp8 import adamw_fp8

        return adamw_fp8(
            learning_rate, b1=betas[0], b2=betas[1], eps=eps, weight_decay=wd,
            opt_level="O2",
        )
    if name == "adam" and not cfg.get("adam_w_mode", False):
        # DeepSpeed 'Adam' couples weight decay into the gradient (L2), unlike AdamW
        tx = optax.adam(learning_rate, b1=betas[0], b2=betas[1], eps=eps)
        if wd:
            tx = optax.chain(optax.add_decayed_weights(wd), tx)
        return tx
    if name in ("adamw", "adam"):  # adam with adam_w_mode=True is AdamW
        return optax.adamw(learning_rate, b1=betas[0], b2=betas[1], eps=eps, weight_decay=wd)
    if name == "sgd":
        momentum = float(_resolve(p.get("momentum"), dummy_optim.kwargs.get("momentum", 0.0)))
        tx = optax.sgd(learning_rate, momentum=momentum or None)
        if wd:
            tx = optax.chain(optax.add_decayed_weights(wd), tx)
        return tx
    if name == "lamb":
        return optax.lamb(learning_rate, b1=betas[0], b2=betas[1], eps=eps, weight_decay=wd)
    raise ValueError(
        f"Unsupported ds_config optimizer type {otype!r}; supported: Adam, AdamW, SGD, Lamb."
    )


class DeepSpeedSchedulerView:
    """Torch-scheduler-shaped view over a schedule embedded in the optax
    optimizer (reference `DeepSpeedSchedulerWrapper`: ``step()`` is a no-op
    because the engine — here, the optimizer update itself — advances the
    schedule; `get_last_lr` reads the live update count)."""

    def __init__(self, schedule_fn: Callable[[int], float], optimizer: Any):
        self.schedule_fn = schedule_fn
        self.optimizer = optimizer  # AcceleratedOptimizer

    def step(self, *args: Any, **kwargs: Any) -> None:
        pass  # the optax update advances the embedded schedule

    def get_last_lr(self) -> list[float]:
        return [float(self.schedule_fn(int(self.optimizer.num_updates)))]

    def state_dict(self) -> dict:
        return {}  # the count lives in (and restores with) the optimizer state

    def load_state_dict(self, state: dict) -> None:
        pass
