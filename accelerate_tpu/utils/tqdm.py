"""Main-process-only progress bars (reference `utils/tqdm.py`)."""

from __future__ import annotations

from .imports import is_tqdm_available


def tqdm(*args, main_process_only: bool = True, **kwargs):
    """Drop-in tqdm that renders only on the main process."""
    if not is_tqdm_available():
        raise ImportError("tqdm is not installed; `pip install tqdm`.")
    from tqdm import auto

    from ..state import PartialState

    disable = kwargs.pop("disable", False)
    if main_process_only and not PartialState().is_main_process:
        disable = True
    return auto.tqdm(*args, disable=disable, **kwargs)
