"""Weight-only quantization: blockwise int8 and 4-bit (nf4 / fp4).

Capability position: the reference delegates quantization to bitsandbytes —
`load_and_quantize_model` (`utils/bnb.py:44-195`) swaps `nn.Linear` for CUDA
`Linear8bitLt`/`Linear4bit` modules (`replace_with_bnb_layers`,
`utils/bnb.py:274`) driven by a `BnbQuantizationConfig`.

TPU-native design: no layer swap and no custom kernels. Quantization is a
*pytree transform*: `quantize_params` rewrites eligible weight leaves into
`QuantizedTensor` pytree nodes (packed integer payload + blockwise fp32
absmax scales — that is what lives in HBM), and `quantize_model` wraps a
model's apply_fn so quantized leaves are dequantized to the compute dtype on
entry. The dequant runs *inside jit*, so XLA fuses the unpack/scale into the
consuming matmul and the bf16 materialization is transient — the steady-state
memory is the packed payload, matching bitsandbytes' storage story without
device-specific kernels.

4-bit uses the NF4 codebook (information-theoretically optimal for normal
weights, per the QLoRA paper) or the FP4 e2m1 value set; two 4-bit codes are
packed per uint8. int8 is symmetric absmax per block.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

# NF4: the 16 quantiles of a standard normal scaled to [-1, 1] (QLoRA).
NF4_CODE = np.array(
    [
        -1.0, -0.6961928009986877, -0.5250730514526367, -0.39491748809814453,
        -0.28444138169288635, -0.18477343022823334, -0.09105003625154495, 0.0,
        0.07958029955625534, 0.16093020141124725, 0.24611230194568634,
        0.33791524171829224, 0.44070982933044434, 0.5626170039176941,
        0.7229568362236023, 1.0,
    ],
    dtype=np.float32,
)

# FP4 (e2m1): sign x {0, .0625, 8, 12, 4, 6, 2, 3} / 12 — bitsandbytes' value set.
FP4_CODE = np.array(
    [
        0.0, 0.0052, 0.6667, 1.0, 0.3333, 0.5, 0.1667, 0.25,
        -0.0, -0.0052, -0.6667, -1.0, -0.3333, -0.5, -0.1667, -0.25,
    ],
    dtype=np.float32,
)


@dataclass
class QuantizationConfig:
    """Mirror of the reference's `BnbQuantizationConfig` (`utils/bnb.py` ctor args).

    load_in_8bit / load_in_4bit pick the payload width; `quant_type` selects the
    4-bit codebook ("nf4" or "fp4"); `block_size` is the absmax granularity;
    `skip_modules` / `keep_in_fp32_modules` exclude leaves by substring of their
    flattened path (the reference's `llm_int8_skip_modules` equivalent).
    """

    load_in_8bit: bool = False
    load_in_4bit: bool = False
    quant_type: str = "nf4"
    block_size: int = 64
    compute_dtype: Any = jnp.bfloat16
    skip_modules: list = field(default_factory=list)
    keep_in_fp32_modules: list = field(default_factory=list)
    min_weight_size: int = 4096  # leaves smaller than this stay unquantized

    def __post_init__(self):
        if self.load_in_8bit and self.load_in_4bit:
            raise ValueError("Pick one of load_in_8bit / load_in_4bit, not both")
        if not (self.load_in_8bit or self.load_in_4bit):
            raise ValueError("One of load_in_8bit / load_in_4bit must be set")
        if self.quant_type not in ("nf4", "fp4"):
            raise ValueError(f"quant_type must be nf4 or fp4, got {self.quant_type}")

    @property
    def bits(self) -> int:
        return 8 if self.load_in_8bit else 4


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """A quantized weight leaf: packed payload + blockwise scales.

    Registered as a pytree node so it flows through jit/device_put/tree maps;
    `shape`/`bits`/`quant_type`/`compute_dtype` ride in the static aux data.
    """

    # _plane_pack: host-side kernel-layout cache (ops/nf4_matmul.plane_pack);
    # never flattened into the pytree
    __slots__ = ("data", "scales", "shape", "bits", "quant_type", "compute_dtype", "_plane_pack")

    def __init__(self, data, scales, shape, bits, quant_type, compute_dtype):
        self.data = data
        self.scales = scales
        self.shape = tuple(shape)
        self.bits = bits
        self.quant_type = quant_type
        self.compute_dtype = compute_dtype
        self._plane_pack = None

    def tree_flatten(self):
        return (self.data, self.scales), (self.shape, self.bits, self.quant_type, self.compute_dtype)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], *aux)

    @property
    def nbytes(self) -> int:
        return int(self.data.size * self.data.dtype.itemsize + self.scales.size * self.scales.dtype.itemsize)

    @property
    def dtype(self):
        return self.compute_dtype

    @property
    def ndim(self) -> int:
        return len(self.shape)

    def __repr__(self) -> str:
        kind = "int8" if self.bits == 8 else self.quant_type
        return f"QuantizedTensor({kind}, shape={self.shape}, blocks={self.scales.shape[0]})"


@functools.partial(jax.jit, static_argnums=(1, 2), donate_argnums=0)
def _quantize_leaf_device(a: jax.Array, block: int, kind: str):
    """Blockwise quantize ONE leaf on the accelerator — one fused pass over
    the weights (cast/absmax/normalize/codebook-argmin/nibble-pack), so a 7B
    load never serializes through a single host core. Donation frees the
    source fp16 buffer as soon as the packed payload exists, keeping peak HBM
    at ~one model copy during a quantized load."""
    flat = a.reshape(-1)
    pad = (-flat.size) % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    blocks = flat.reshape(-1, block).astype(jnp.float32)
    absmax = jnp.abs(blocks).max(axis=1)
    scales = jnp.where(absmax > 0, absmax, 1.0)
    normed = blocks / scales[:, None]
    if kind == "int8":
        q = jnp.clip(jnp.round(normed * 127.0), -127, 127).astype(jnp.int8)
        return q.reshape(-1), scales
    code = jnp.asarray(NF4_CODE if kind == "nf4" else FP4_CODE)
    idx = jnp.argmin(jnp.abs(normed[..., None] - code), axis=-1).astype(jnp.uint8).reshape(-1)
    return (idx[0::2] << 4) | idx[1::2], scales


def quantize(arr: Any, config: QuantizationConfig, on_device: bool = False) -> QuantizedTensor:
    """Blockwise-quantize one array. ``on_device=True`` runs the jitted pass
    on the accelerator (the array should already be device-resident); default
    is the host numpy path (runs once at load)."""
    if on_device:
        kind = "int8" if config.bits == 8 else config.quant_type
        arr = jnp.asarray(arr)
        payload, scales = _quantize_leaf_device(arr, config.block_size, kind)
        return QuantizedTensor(
            payload, scales, tuple(arr.shape),
            config.bits, config.quant_type, config.compute_dtype,
        )
    a = np.asarray(jax.device_get(arr), dtype=np.float32)
    shape = a.shape
    flat = a.reshape(-1)
    block = config.block_size
    pad = (-flat.size) % block
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    blocks = flat.reshape(-1, block)
    absmax = np.abs(blocks).max(axis=1)
    scales = np.where(absmax > 0, absmax, 1.0).astype(np.float32)
    normed = blocks / scales[:, None]

    if config.bits == 8:
        q = np.clip(np.round(normed * 127.0), -127, 127).astype(np.int8)
        payload = q.reshape(-1)
    else:
        code = NF4_CODE if config.quant_type == "nf4" else FP4_CODE
        # nearest codebook entry via binary search over the decision midpoints
        # of the SORTED codebook (fp4's bit-pattern order is unsorted — map
        # back through argsort): O(log 16) per element with no [*, 16] temp,
        # ~10x faster than the brute-force distance argmin on a 7B load
        order = np.argsort(code).astype(np.uint8)
        sorted_code = code[order]
        mids = (sorted_code[1:] + sorted_code[:-1]) * 0.5
        pos = np.searchsorted(mids, normed.reshape(-1))
        idx = order[pos]
        payload = (idx[0::2] << 4) | idx[1::2]  # two nibbles per byte

    return QuantizedTensor(
        jnp.asarray(payload),
        jnp.asarray(scales),
        shape,
        config.bits,
        config.quant_type,
        config.compute_dtype,
    )


def dequantize(qt: QuantizedTensor, dtype: Any | None = None) -> jax.Array:
    """Rebuild the dense array — jit-friendly, fuses into the consuming matmul."""
    out_dtype = dtype if dtype is not None else qt.compute_dtype
    n_blocks = qt.scales.shape[0]
    if qt.bits == 8:
        vals = qt.data.astype(jnp.float32).reshape(n_blocks, -1) / 127.0
    else:
        hi = (qt.data >> 4).astype(jnp.int32)
        lo = (qt.data & 0xF).astype(jnp.int32)
        idx = jnp.stack([hi, lo], axis=-1).reshape(-1)
        code = jnp.asarray(NF4_CODE if qt.quant_type == "nf4" else FP4_CODE)
        vals = code[idx].reshape(n_blocks, -1)
    dense = (vals * qt.scales[:, None]).reshape(-1)
    size = int(np.prod(qt.shape)) if qt.shape else 1
    return dense[:size].reshape(qt.shape).astype(out_dtype)


def _flat_path(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", getattr(p, "name", getattr(p, "idx", None)))
        parts.append(str(key))
    return "/".join(parts)


def quantize_params(params: Any, config: QuantizationConfig, on_device: bool = False) -> Any:
    """Rewrite eligible weight leaves to QuantizedTensor.

    Eligible = floating, ndim >= 2, size >= min_weight_size, and path not
    matched by skip_modules / keep_in_fp32_modules (substring match on the
    flattened "a/b/c" path, like the reference's module-name matching).

    ``on_device=True``: leaves are (or are moved) device-resident and the
    blockwise pass runs as one fused jit per leaf with the source buffer
    donated — the load path for accelerator-attached hosts, where a 7B
    host-side quantize would serialize minutes of numpy through few cores.
    """
    skip = list(config.skip_modules) + list(config.keep_in_fp32_modules)

    def _maybe_quantize(path, leaf):
        if isinstance(leaf, QuantizedTensor):
            return leaf
        if not hasattr(leaf, "ndim") or leaf.ndim < 2:
            return leaf
        # read dtype off the leaf itself — jnp.asarray here would device-put
        # the whole array just to inspect it
        if not jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf
        if leaf.size < config.min_weight_size:
            return leaf
        name = _flat_path(path)
        if any(s in name for s in skip):
            return leaf
        return quantize(leaf, config, on_device=on_device)

    # threads overlap the numpy passes (they release the GIL) on multi-core
    # hosts; degrade to a plain loop on single-core boxes where a pool only
    # adds overhead. The on_device path dispatches async jits — also serial.
    import os as _os

    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(params)
    workers = min(8, _os.cpu_count() or 1)
    if on_device or workers <= 1:
        new_leaves = [_maybe_quantize(p, l) for p, l in paths_leaves]
    else:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=workers) as pool:
            new_leaves = list(pool.map(lambda pl: _maybe_quantize(*pl), paths_leaves))
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def dequantize_params(params: Any, dtype: Any | None = None) -> Any:
    """Inverse transform: QuantizedTensor leaves back to dense arrays."""
    return jax.tree.map(
        lambda l: dequantize(l, dtype) if isinstance(l, QuantizedTensor) else l,
        params,
        is_leaf=lambda l: isinstance(l, QuantizedTensor),
    )


def quantized_nbytes(params: Any) -> int:
    """Steady-state HBM footprint of a (possibly partially) quantized tree."""
    total = 0
    for leaf in jax.tree.leaves(params, is_leaf=lambda l: isinstance(l, QuantizedTensor)):
        if isinstance(leaf, QuantizedTensor):
            total += leaf.nbytes
        elif hasattr(leaf, "nbytes"):
            total += int(leaf.nbytes)
    return total


def quantize_model(model: Any, config: QuantizationConfig):
    """Quantize a prepared model's weights in place and patch its apply path.

    The analogue of the reference's layer swap (`replace_with_bnb_layers`): the
    model's params tree is rewritten and its apply_fn wrapped so quantized
    leaves are dequantized (inside jit) right before the original forward.
    Accepts an `accelerator.PreparedModel` or an `(apply_fn, params)` tuple;
    returns the same kind of object.
    """
    from accelerate_tpu.accelerator import PreparedModel

    if isinstance(model, tuple) and len(model) == 2:
        apply_fn, params = model
        qparams = quantize_params(params, config)

        def q_apply(p, *args, **kwargs):
            return apply_fn(dequantize_params(p), *args, **kwargs)

        return q_apply, qparams

    if isinstance(model, PreparedModel):
        inner = model.apply_fn
        qparams = quantize_params(model.params, config)
        if getattr(model, "shardings", None) is not None:
            # re-place on the mesh: quantization round-trips through the host, so
            # without this every leaf would land unsharded on the default device.
            # Dense (skipped) leaves keep their original sharding; packed leaves
            # have different shapes than their spec described, so they replicate
            # (the payload is 4-8x smaller than the dense bf16 weight).
            from jax.sharding import NamedSharding, PartitionSpec

            def place(q, s):
                if isinstance(q, QuantizedTensor):
                    return jax.device_put(q, NamedSharding(s.mesh, PartitionSpec()))
                return jax.device_put(q, s)

            qparams = jax.tree.map(
                place, qparams, model.shardings, is_leaf=lambda x: isinstance(x, QuantizedTensor)
            )
        model.params = qparams

        def q_apply(p, *args, **kwargs):
            return inner(dequantize_params(p), *args, **kwargs)

        model.apply_fn = q_apply
        model._jit_forwards = {}  # drop any forward compiled against dense params
        return model

    raise TypeError(f"Cannot quantize object of type {type(model)}")


class QuantizedModule:
    """Flax-module shim for quantized weights in jitted pipelines (the
    `Linear4bit` role for the *generation* path): `apply` dequantizes
    `QuantizedTensor` leaves on entry — inside jit, so XLA fuses the
    unpack+scale into the consuming matmuls and HBM holds only the packed
    payload. Hashable by identity, so it works as a jit static argument
    (e.g. `models.generation.generate(QuantizedModule(m), qparams, ...)`)."""

    def __init__(self, module: Any):
        self.module = module

    def init(self, *args: Any, **kwargs: Any) -> Any:
        return self.module.init(*args, **kwargs)

    def apply(self, variables: Any, *args: Any, **kwargs: Any) -> Any:
        variables = dict(variables)
        if "params" in variables:
            variables["params"] = dequantize_params(variables["params"])
        return self.module.apply(variables, *args, **kwargs)

    def __getattr__(self, name: str) -> Any:
        return getattr(self.module, name)


def load_and_quantize_model(
    module: Any,
    weights_location: str,
    quantization_config: QuantizationConfig,
):
    """Load a safetensors/orbax checkpoint and return quantized (apply_fn, params).

    Mirror of the reference's `load_and_quantize_model` (`utils/bnb.py:44`):
    weights stream from disk and only the packed payload stays resident.
    """
    from accelerate_tpu.checkpointing import load_model_weights

    params = load_model_weights(weights_location)
    qparams = quantize_params(params, quantization_config)
    if hasattr(module, "apply"):  # flax module
        def apply_fn(p, *args, **kwargs):
            dense = dequantize_params(p)
            variables = {"params": dense} if "params" not in dense else dense
            return module.apply(variables, *args, **kwargs)
    elif callable(module):
        def apply_fn(p, *args, **kwargs):
            return module(dequantize_params(p), *args, **kwargs)
    else:
        raise TypeError(f"module must be a flax module or callable, got {type(module)}")
    return apply_fn, qparams
