"""Version comparison gates (reference `utils/versions.py:1-56`)."""

from __future__ import annotations

import importlib.metadata
import operator

_OPS = {
    "<": operator.lt, "<=": operator.le, "==": operator.eq,
    "!=": operator.ne, ">=": operator.ge, ">": operator.gt,
}


def _parse(v: str) -> tuple:
    """Numeric components from leading digits, padded, plus a final marker that
    ranks pre-releases ("0.4.30rc1") below their release ("0.4.30"). A PEP 440
    local segment ("2.1.0+cu118") is dropped before parsing — local builds
    satisfy the same bounds as their public release, they are not pre-releases."""
    v = v.split("+", 1)[0]
    parts = []
    prerelease = False
    for p in v.split("."):
        i = 0
        while i < len(p) and p[i].isdigit():
            i += 1
        parts.append(int(p[:i]) if i else 0)
        if i < len(p):
            prerelease = True
    while len(parts) < 4:
        parts.append(0)
    parts.append(0 if prerelease else 1)
    return tuple(parts)


def compare_versions(library_or_version: str, operation: str, requirement_version: str) -> bool:
    """compare_versions("jax", ">=", "0.4") or compare_versions("0.4.30", "<", "0.5")."""
    if operation not in _OPS:
        raise ValueError(f"operation must be one of {sorted(_OPS)}, got {operation}")
    try:
        version = importlib.metadata.version(library_or_version)
    except importlib.metadata.PackageNotFoundError:
        version = library_or_version  # treat as a literal version string
    return _OPS[operation](_parse(version), _parse(requirement_version))


def is_jax_version(operation: str, version: str) -> bool:
    return compare_versions("jax", operation, version)
