"""Feature-availability probes.

Capability parity: reference `src/accelerate/utils/imports.py` (~50 ``is_*_available``
probes). The TPU-native build needs far fewer: the compute stack is always JAX; the
optional pieces are trackers, torch interop, and checkpoint backends.
"""

from __future__ import annotations

import importlib.util
from functools import lru_cache


@lru_cache
def _package_available(name: str) -> bool:
    return importlib.util.find_spec(name) is not None


def is_torch_available() -> bool:
    return _package_available("torch")


def is_tensorboard_available() -> bool:
    return _package_available("tensorboardX") or _package_available("tensorboard")


def is_wandb_available() -> bool:
    return _package_available("wandb")


def is_mlflow_available() -> bool:
    return _package_available("mlflow")


def is_comet_ml_available() -> bool:
    return _package_available("comet_ml")


def is_clearml_available() -> bool:
    return _package_available("clearml")


def is_aim_available() -> bool:
    return _package_available("aim")


def is_dvclive_available() -> bool:
    return _package_available("dvclive")


def is_orbax_available() -> bool:
    return _package_available("orbax")


def is_transformers_available() -> bool:
    return _package_available("transformers")


def is_datasets_available() -> bool:
    return _package_available("datasets")


def is_rich_available() -> bool:
    return _package_available("rich")


def is_tqdm_available() -> bool:
    return _package_available("tqdm")


def is_pandas_available() -> bool:
    return _package_available("pandas")


@lru_cache
def is_tpu_available() -> bool:
    """True when the default JAX backend exposes TPU devices."""
    import jax

    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except RuntimeError:
        return False
