"""Mixed-precision policies and the dynamic loss scaler.

Capability parity: reference AMP integration (`accelerator.py:472-510`, GradScaler
factory `utils/modeling.py:1876-1907`, fp8 recipes `utils/dataclasses.py:283-404`).

TPU-native re-founding: instead of autocast context managers patched onto
``model.forward``, precision is a *functional cast policy* applied around the jitted
step: master params stay fp32, compute runs in bf16 (the MXU's native input dtype),
outputs upcast to fp32. bf16 needs no loss scaling on TPU; the fp16 dynamic scaler
exists for API/capability parity and for the rare fp16 workload, implemented as
explicit state threaded through the step (no hidden mutable scaler object).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


def _cast_floating(tree: Any, dtype) -> Any:
    def _cast(t):
        if hasattr(t, "dtype") and jnp.issubdtype(t.dtype, jnp.floating):
            return t.astype(dtype)
        return t

    return jax.tree.map(_cast, tree)


# ---- autocast context: consulted by PreparedModel at call time -------------
# contextvar (not a module global) so nested/async usage stays correct
import contextvars

_AUTOCAST_ENABLED = contextvars.ContextVar("accelerate_tpu_autocast_enabled", default=True)


def autocast_enabled() -> bool:
    """Whether prepared forwards should apply the compute-dtype cast
    (False inside `Accelerator.autocast(AutocastKwargs(enabled=False))`)."""
    return _AUTOCAST_ENABLED.get()


def set_autocast_enabled(enabled: bool):
    """Returns a reset token for the enclosing context manager."""
    return _AUTOCAST_ENABLED.set(bool(enabled))


def reset_autocast_enabled(token) -> None:
    _AUTOCAST_ENABLED.reset(token)


@dataclass(frozen=True)
class PrecisionPolicy:
    """What dtype each tensor class lives in. ``param_dtype`` is the master copy;
    ``compute_dtype`` is what the forward/backward runs in; ``output_dtype`` is
    what user-visible outputs are cast to (reference `convert_outputs_to_fp32`)."""

    mode: str = "no"
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.float32
    output_dtype: Any = jnp.float32

    @classmethod
    def from_mode(cls, mode: str | None) -> "PrecisionPolicy":
        mode = (mode or "no").lower()
        if mode in ("no", "fp32", "none"):
            return cls(mode="no")
        if mode == "bf16":
            return cls(mode="bf16", compute_dtype=jnp.bfloat16)
        if mode == "fp16":
            return cls(mode="fp16", compute_dtype=jnp.float16)
        if mode == "fp8":
            # fp8 matmul inputs ride XLA's native fp8 support; master/compute
            # bookkeeping stays bf16 and per-tensor scaling is handled in ops/fp8.py
            return cls(mode="fp8", compute_dtype=jnp.bfloat16)
        raise ValueError(f"Unknown mixed_precision mode {mode!r}")

    @property
    def enabled(self) -> bool:
        return self.mode != "no"

    @property
    def requires_loss_scaling(self) -> bool:
        return self.mode == "fp16"

    def cast_to_compute(self, tree: Any) -> Any:
        if not self.enabled:
            return tree
        return _cast_floating(tree, self.compute_dtype)

    def cast_to_param(self, tree: Any) -> Any:
        return _cast_floating(tree, self.param_dtype)

    def cast_to_output(self, tree: Any) -> Any:
        return _cast_floating(tree, self.output_dtype)


class GradScalerState(NamedTuple):
    """Dynamic loss-scale state (functional analogue of torch GradScaler —
    reference `get_grad_scaler`, `utils/modeling.py:1876`)."""

    scale: jax.Array
    growth_tracker: jax.Array  # consecutive finite steps


@dataclass
class DynamicGradScaler:
    """Doubles the scale every ``growth_interval`` finite steps, halves on overflow,
    and reports whether the step must be skipped — identical policy to torch's
    GradScaler, but as explicit state so it lives inside the jitted step."""

    init_scale: float = 2.0**15
    growth_factor: float = 2.0
    backoff_factor: float = 0.5
    growth_interval: int = 2000
    # The total scale S is split: min(S, max_inner_scale) multiplies the loss
    # INSIDE the reduced-precision backward (underflow protection; small enough
    # that healthy cotangent chains stay under fp16's 65504), and the remainder
    # S/inner is applied to the fp32 grads outside. Overflow backoff stays a
    # real feedback loop — sustained non-finite steps halve S until the inner
    # factor itself shrinks and the fp16 cotangents come back in range.
    max_inner_scale: float = 2.0**10
    # Ceiling on S: the outer factor is numerically exact in fp32 (powers of
    # two), so growth on a long healthy run must not walk S toward fp32 inf.
    max_scale: float = 2.0**24

    def split_scale(self, scale: jax.Array) -> tuple[jax.Array, jax.Array]:
        """(inner, outer) with inner*outer == scale and inner fp16-safe."""
        inner = jnp.minimum(scale, self.max_inner_scale)
        return inner, scale / inner

    def init(self) -> GradScalerState:
        return GradScalerState(
            scale=jnp.asarray(self.init_scale, dtype=jnp.float32),
            growth_tracker=jnp.zeros((), dtype=jnp.int32),
        )

    def scale_loss(self, loss: jax.Array, state: GradScalerState) -> jax.Array:
        return loss * state.scale

    @staticmethod
    def all_finite(grads: Any) -> jax.Array:
        """Scalar bool: every leaf of ``grads`` is finite."""
        finite = jnp.asarray(True)
        for leaf in jax.tree.leaves(grads):
            finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(leaf)))
        return finite

    def update_state(self, state: GradScalerState, finite: jax.Array) -> GradScalerState:
        """One torch-GradScaler policy step: grow after ``growth_interval``
        finite boundaries (capped at max_scale), back off on overflow. The ONE
        implementation shared by the imperative and fused training paths."""
        tracker = jnp.where(finite, state.growth_tracker + 1, 0)
        grow = tracker >= self.growth_interval
        new_scale = jnp.where(
            finite,
            jnp.where(grow, jnp.minimum(state.scale * self.growth_factor, self.max_scale), state.scale),
            state.scale * self.backoff_factor,
        )
        return GradScalerState(scale=new_scale, growth_tracker=jnp.where(grow, 0, tracker))

    def unscale_and_update(self, grads: Any, state: GradScalerState):
        """Unscale grads; detect non-finite values; return
        (unscaled_grads, new_state, is_finite)."""
        inv = 1.0 / state.scale
        grads = jax.tree.map(lambda g: (g * inv).astype(g.dtype), grads)
        finite = self.all_finite(grads)
        return grads, self.update_state(state, finite), finite
