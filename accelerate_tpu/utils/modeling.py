"""Model introspection: sizes, memory budgets, tied weights, flattening.

Capability parity: reference `src/accelerate/utils/modeling.py` (1907 LoC) — the
pieces that aren't torch-specific: `compute_module_sizes`, `calculate_maximum_sizes`
(estimate-memory backend), `find_tied_parameters`, `get_max_memory`, and the
flat <-> nested param-tree converters the offload/dispatch stack uses.
"""

from __future__ import annotations

import math
import re
from typing import Any

import jax
import numpy as np


def dtype_byte_size(dtype: Any) -> float:
    if hasattr(dtype, "itemsize"):
        return dtype.itemsize
    return np.dtype(dtype).itemsize


def flatten_params(params: Any, prefix: str = "", sep: str = "/") -> dict[str, Any]:
    """Nested pytree -> {'a/b/c': leaf} flat dict."""
    flat: dict[str, Any] = {}
    if isinstance(params, dict):
        for k, v in params.items():
            flat.update(flatten_params(v, f"{prefix}{k}{sep}", sep))
    else:
        flat[prefix[: -len(sep)]] = params
    return flat


def unflatten_params(flat: dict[str, Any], sep: str = "/") -> Any:
    nested: dict[str, Any] = {}
    for key, value in flat.items():
        parts = key.split(sep)
        node = nested
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return nested


def named_module_tensors(params: Any) -> list[tuple[str, Any]]:
    return sorted(flatten_params(params).items())


def compute_module_sizes(params: Any, dtype: Any | None = None) -> dict[str, int]:
    """Bytes per module path, aggregated up the tree (reference
    `compute_module_sizes`). Key "" is the total."""
    sizes: dict[str, int] = {}
    for name, leaf in named_module_tensors(params):
        nbytes = int(math.prod(getattr(leaf, "shape", ()) or (1,))) * int(
            dtype_byte_size(dtype or leaf.dtype)
        )
        parts = name.split("/")
        for i in range(len(parts) + 1):
            sizes["/".join(parts[:i])] = sizes.get("/".join(parts[:i]), 0) + nbytes
    return sizes


def calculate_maximum_sizes(params: Any) -> tuple[int, tuple[int, str]]:
    """(total bytes, (largest leaf bytes, its name)) — reference
    `calculate_maximum_sizes` used by estimate-memory."""
    total = 0
    largest = (0, "")
    for name, leaf in named_module_tensors(params):
        nbytes = int(math.prod(getattr(leaf, "shape", ()) or (1,))) * int(dtype_byte_size(leaf.dtype))
        total += nbytes
        if nbytes > largest[0]:
            largest = (nbytes, name)
    return total, largest


def find_tied_parameters(params: Any) -> list[list[str]]:
    """Groups of parameter names sharing the same underlying buffer (reference
    `find_tied_parameters`, `modeling.py:605`). In JAX pytrees ties show up as
    identical array objects (same id) appearing at several paths."""
    by_id: dict[int, list[str]] = {}
    for name, leaf in named_module_tensors(params):
        if hasattr(leaf, "shape"):
            by_id.setdefault(id(leaf), []).append(name)
    return [names for names in by_id.values() if len(names) > 1]


def get_max_memory(max_memory: dict | None = None) -> dict[str, int]:
    """Memory budget per tier: each accelerator device's free HBM, host RAM, disk
    (reference `get_max_memory`, `modeling.py:797`)."""
    if max_memory is not None:
        return dict(max_memory)
    out: dict[str, int] = {}
    for i, dev in enumerate(jax.local_devices()):
        try:
            stats = dev.memory_stats()
            free = stats["bytes_limit"] - stats["bytes_in_use"]
        except Exception:
            free = 8 * 1024**3
        out[f"device:{i}"] = int(free * 0.9)
    try:
        with open("/proc/meminfo") as f:
            meminfo = f.read()
        avail_kb = int(re.search(r"MemAvailable:\s+(\d+)", meminfo).group(1))
        out["cpu"] = avail_kb * 1024 // 2
    except Exception:
        out["cpu"] = 8 * 1024**3
    out["disk"] = 1 << 62
    return out


def get_balanced_memory(
    params: Any,
    num_devices: int | None = None,
    no_split_module_classes: Any = None,
    low_zero: bool = False,
) -> dict[str, int]:
    """Per-device budgets that spread the model evenly (reference
    `get_balanced_memory`, `modeling.py:951`): each device gets at least the
    largest indivisible block (else the fit degenerates to first-fill), and
    ``low_zero`` reserves device 0 for activations/generation by halving its
    share, as the reference does for generate-heavy workloads."""
    total, (largest_leaf, _) = calculate_maximum_sizes(params)
    sizes = compute_module_sizes(params)
    top_blocks = [v for k, v in sizes.items() if k and "/" not in k]
    largest_block = max(top_blocks, default=largest_leaf)
    n = num_devices or len(jax.local_devices())
    per = max(-(-total // n), largest_block)
    per = int(per * 1.1)  # fit slack, as in the reference's buffer margin
    budget = {f"device:{i}": per for i in range(n)}
    if low_zero and n > 1:
        budget["device:0"] = per // 2
    budget["cpu"] = get_max_memory()["cpu"]
    budget["disk"] = 1 << 62
    return budget
