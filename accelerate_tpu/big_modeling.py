"""Big-model inference: load and run models larger than device memory.

Capability parity: reference `src/accelerate/big_modeling.py` (633 LoC) +
`utils/modeling.py` device-map machinery: `init_empty_weights` (meta init),
`infer_auto_device_map` (greedy first-fit onto device/cpu/disk budgets),
`dispatch_model` + `AlignDevicesHook` (per-submodule weight streaming),
`load_checkpoint_and_dispatch`, `cpu_offload`, `disk_offload`.

TPU-native re-founding:
  - "meta device" = `jax.eval_shape`: abstract param trees with zero allocation.
  - placement tiers are {device, cpu, disk}; "device" means *the mesh* — a block
    resident on-device is sharded over all chips (NamedSharding), not pinned to
    one GPU as in the reference's per-GPU maps.
  - instead of monkey-patched forward hooks, a `BlockwiseModel` runs its blocks
    sequentially; offloaded blocks stream host->HBM just-in-time with the *next*
    block's transfer launched before the current block computes (JAX async
    dispatch gives the overlap for free — the role of the reference's
    prefetching AlignDevicesHook).
"""

from __future__ import annotations

import contextlib
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

import jax
import numpy as np

from .utils.modeling import (
    compute_module_sizes,
    flatten_params,
    get_max_memory,
    unflatten_params,
)
from .utils.offload import OffloadedWeightsLoader, offload_state_dict


@contextlib.contextmanager
def init_empty_weights(include_buffers: bool = False):
    """Context marker for meta initialization (reference `big_modeling.py:57`).

    JAX needs no patching: yield a helper whose ``.init(module, *args)`` returns
    an *abstract* parameter tree via `jax.eval_shape` — no memory is touched.
    """

    class _Meta:
        @staticmethod
        def init(module: Any, rngs: Any, *args: Any, **kwargs: Any) -> Any:
            out = jax.eval_shape(lambda: module.init(rngs, *args, **kwargs))
            return out["params"] if isinstance(out, dict) and "params" in out else out

    yield _Meta()


def init_on_device(device: Any):
    """Place subsequent inits directly on ``device`` (reference `init_on_device`)."""

    return jax.default_device(device)


def infer_auto_device_map(
    params: Any,
    max_memory: dict[str, int] | None = None,
    no_split_module_classes: Sequence[str] | None = None,
    dtype: Any | None = None,
    clean_result: bool = True,
) -> dict[str, str]:
    """Fit a param tree onto ordered {device(s), cpu, disk} tiers
    (reference `utils/modeling.py:1096-1398`), with the reference solver's
    load-bearing behaviors re-founded on pytrees:

      - **per-device budgets**: ``max_memory`` keys may be ``device:i`` (or the
        legacy pooled ``device``), filled in execution order — a block placed on
        ``device:1`` runs after everything on ``device:0`` (offload streaming
        preserves block order, so this is the reference's sequential pipeline).
      - **tied weights placed together**: blocks sharing an aliased leaf (the
        reference's `find_tied_parameters` at `:605`) are fused into one
        placement unit whose size counts the shared buffer once, so a tied
        embedding/head pair can never straddle tiers.
      - **no-split modules**: a block whose *key* matches an entry of
        ``no_split_module_classes`` (module classes have no meaning in a param
        tree; keys are the unit of structure) is moved whole to the next tier
        when it doesn't fit. Other oversized blocks are split into their
        children and re-fitted (the reference's recursive descent).
      - ``clean_result`` merges children that all landed on one tier back into
        their parent entry (reference `clean_device_map`).
    """
    budgets = get_max_memory(max_memory)
    # ordered tiers: devices in index order, then cpu, then disk (unbounded)
    tiers: list[list[Any]] = []
    if "device" in budgets:  # legacy pooled budget
        tiers.append(["device", budgets["device"]])
    tiers.extend(
        [k, budgets[k]]
        for k in sorted(
            (k for k in budgets if k.startswith("device:")),
            key=lambda k: int(k.split(":")[1]),
        )
    )
    tiers.append(["cpu", budgets.get("cpu", 0)])
    tiers.append(["disk", 1 << 62])
    no_split = tuple(no_split_module_classes or ())
    sizes = compute_module_sizes(params, dtype=dtype)
    from .utils.modeling import find_tied_parameters

    tied_groups = find_tied_parameters(params)

    def block_of(leaf_path: str) -> str:
        return leaf_path.split("/", 1)[0]

    # union top-level blocks linked by tied leaves into single placement units;
    # iterate in the state dict's insertion order — that IS execution order for
    # blockwise models, and the fit must follow it (sizes' keys are sorted)
    from collections.abc import Mapping as _Mapping

    if isinstance(params, _Mapping):
        top_order = [str(k) for k in params.keys()]
    else:
        top_order = [k for k in sizes if k and "/" not in k]
    parent: dict[str, str] = {b: b for b in top_order if b in sizes}

    def find(x: str) -> str:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    shared_bytes: dict[tuple[str, ...], int] = {}
    for group in tied_groups:
        blocks = sorted({block_of(p) for p in group})
        for a, b in zip(blocks, blocks[1:]):
            parent[find(a)] = find(b)
        if len(blocks) > 1:
            # the shared buffer is counted once per block in `sizes`; remember
            # the duplicate bytes so the fused unit's size is physical
            leaf = group[0]
            dup = sizes.get(leaf, 0) * (len(group) - 1)
            shared_bytes[tuple(blocks)] = shared_bytes.get(tuple(blocks), 0) + dup

    units: list[tuple[list[str], int]] = []  # ([block names], bytes), in tree order
    seen_roots: dict[str, int] = {}
    for b in parent:
        root = find(b)
        if root not in seen_roots:
            seen_roots[root] = len(units)
            units.append(([b], sizes[b]))
        else:
            names, total = units[seen_roots[root]]
            names.append(b)
            units[seen_roots[root]] = (names, total + sizes[b])
    for blocks, dup in shared_bytes.items():
        for i, (names, total) in enumerate(units):
            if set(blocks) <= set(names):
                units[i] = (names, total - dup)
                break

    device_map: dict[str, str] = {}
    queue: list[tuple[list[str], int]] = list(units)
    cursor = 0  # tiers only advance: blocks execute in order, so a later block
    # may never land on an EARLIER device than its predecessor (the sequential
    # offload pipeline the reference solver preserves — no backfill)
    while queue:
        names, size = queue.pop(0)
        placed = False
        for ti in range(cursor, len(tiers)):
            tier_name, budget = tiers[ti]
            if size <= budget:
                for n in names:
                    device_map[n] = tier_name
                tiers[ti][1] = budget - size
                cursor = ti
                placed = True
                break
            # try splitting a single oversized, splittable block on the first
            # tier that can't hold it whole (reference's recursive descent)
            if (
                tier_name != "disk"
                and len(names) == 1
                and not any(pat in names[0].rsplit("/", 1)[-1] for pat in no_split)
            ):
                children = [k for k in sizes if k.startswith(names[0] + "/") and k.count("/") == names[0].count("/") + 1]
                if children:
                    queue = [([c], sizes[c]) for c in children] + queue
                    placed = True
                    break
        if not placed:
            for n in names:
                device_map[n] = "disk"

    if clean_result:
        device_map = clean_device_map(device_map)
    return device_map


def clean_device_map(device_map: dict[str, str], module_prefix: str = "") -> dict[str, str]:
    """Merge child entries that share one placement back into the parent
    (reference `clean_device_map`)."""
    prefixes = {k.rsplit("/", 1)[0] for k in device_map if "/" in k}
    for prefix in sorted(prefixes, key=lambda p: -p.count("/")):
        children = {k: v for k, v in device_map.items() if k.startswith(prefix + "/")}
        if children and len(set(children.values())) == 1 and prefix not in device_map:
            for k in children:
                del device_map[k]
            device_map[prefix] = next(iter(children.values()))
    return device_map


@dataclass
class BlockwiseModel:
    """Sequential block decomposition of a model — the unit of offload streaming.

    ``blocks`` maps block name -> ``fn(block_params, x) -> x`` applied in order;
    ``prologue``/``epilogue`` handle embedding / final head with their own param
    blocks. The param tree's first-level keys must cover all block names.
    """

    block_fns: list[tuple[str, Callable]]
    params: Any = None  # per-block: jax tree (resident) or numpy tree (offloaded)
    device_map: dict[str, str] = field(default_factory=dict)
    offload_loader: OffloadedWeightsLoader | None = None
    sharding: Any = None  # NamedSharding for resident/streamed placement
    # cpu_offload_with_hook mode: streamed blocks STAY on device across calls
    # until the user hook's offload() evicts them (multi-model pipelines)
    cache_resident: bool = False
    _cache: dict = field(default_factory=dict, repr=False)
    _prev_hook: Any = None

    def _evict_cache(self) -> None:
        for _params, transient in self._cache.values():
            for p in transient:
                if not p.is_deleted():
                    p.delete()
        self._cache.clear()

    def _place_host(self, host: Any) -> Any:
        return jax.tree.map(
            lambda p: jax.device_put(p, self.sharding) if self.sharding is not None else jax.device_put(p),
            host,
        )

    def _fetch_entry(self, key: str, tier: str) -> tuple[Any, list]:
        """(placed subtree for device_map entry ``key``, transient leaves to
        evict after the block runs — empty for resident device entries)."""
        if tier.startswith("device"):  # "device" or per-chip "device:i"
            return self.params[key], []
        if tier == "cpu":
            host = self.params[key]
        elif key in self.offload_loader:  # disk, split down to a single leaf
            host = self.offload_loader[key]
        else:  # disk subtree
            flat = {
                k[len(key) + 1 :]: self.offload_loader[k]
                for k in self.offload_loader
                if k.startswith(key + "/")
            }
            host = unflatten_params(flat)
        placed = self._place_host(host)
        return placed, [p for p in jax.tree.leaves(placed) if isinstance(p, jax.Array)]

    def _block_params(self, name: str) -> tuple[Any, list]:
        if name in self.device_map or not self.device_map:
            return self._fetch_entry(name, self.device_map.get(name, "device"))
        # block was SPLIT by the solver: assemble from its child entries
        sub: dict[str, Any] = {}
        transient: list = []
        for key, tier in self.device_map.items():
            if not key.startswith(name + "/"):
                continue
            part, part_tr = self._fetch_entry(key, tier)
            transient.extend(part_tr)
            node = sub
            rel = key[len(name) + 1 :].split("/")
            for p in rel[:-1]:
                node = node.setdefault(p, {})
            node[rel[-1]] = part
        if not sub:
            raise KeyError(f"no device_map entry covers block {name!r}")
        return sub, transient

    def _block_params_cached(self, name: str) -> tuple[Any, list]:
        if name not in self._cache:
            self._cache[name] = self._block_params(name)
        return self._cache[name][0], []  # nothing transient: eviction is manual

    def __call__(self, x: Any) -> Any:
        if self._prev_hook is not None:
            # multi-model pipeline: entering this model evicts the previous
            # one's device-resident weights (reference cpu_offload_with_hook)
            self._prev_hook.offload()
        fetch = self._block_params_cached if self.cache_resident else self._block_params
        names = [n for n, _ in self.block_fns]
        fns = dict(self.block_fns)
        # prefetch pipeline: launch block i+1's H2D before computing block i
        next_params, next_transient = fetch(names[0])
        for i, name in enumerate(names):
            cur, cur_transient = next_params, next_transient
            if i + 1 < len(names):
                next_params, next_transient = fetch(names[i + 1])
            x = fns[name](cur, x)
            for p in cur_transient:  # free streamed HBM, keep resident parts
                if not p.is_deleted():
                    p.delete()
        return x


def dispatch_model(
    model: BlockwiseModel,
    device_map: dict[str, str],
    state_dict: Any,
    offload_dir: str | None = None,
    sharding: Any = None,
) -> BlockwiseModel:
    """Place each block per the device map (reference `big_modeling.py:306`).

    With ``sharding`` (a NamedSharding over the mesh), every device-tier block
    lands SHARDED across all chips — the TPU-native reading of "on device",
    where capacity is the pooled HBM. Without it, per-chip tiers ``device:i``
    are honored literally: the block is pinned to ``jax.local_devices()[i]``,
    matching the per-device budgets the solver computed. cpu blocks stay as
    numpy, disk blocks are memmap-offloaded."""
    placed: dict[str, Any] = {}
    disk_flat: dict[str, np.ndarray] = {}
    local = jax.local_devices()

    def _resolve(path: str) -> Any:
        node = state_dict
        for part in path.split("/"):
            node = node[part]
        return node

    for name, tier in device_map.items():
        block = _resolve(name)  # name may be a nested path from a split block
        if tier.startswith("device"):  # "device" or per-chip "device:i"
            if sharding is not None:
                target = sharding
            elif ":" in tier:
                idx = int(tier.split(":")[1])
                if idx >= len(local):
                    raise ValueError(
                        f"device_map entry {name!r} -> {tier!r} but only "
                        f"{len(local)} local devices exist — the map was solved "
                        "for a different topology; re-run infer_auto_device_map."
                    )
                target = local[idx]
            else:
                target = None
            placed[name] = jax.tree.map(
                lambda p, t=target: jax.device_put(p, t) if t is not None else jax.device_put(p),
                block,
            )
        elif tier == "cpu":
            placed[name] = jax.tree.map(np.asarray, block)
        else:
            for k, v in flatten_params({name: block}).items():
                disk_flat[k] = np.asarray(v)
    loader = None
    if disk_flat:
        if offload_dir is None:
            raise ValueError("disk offload requires offload_dir")
        offload_state_dict(offload_dir, disk_flat)
        loader = OffloadedWeightsLoader(save_folder=offload_dir)
    model.params = placed
    model.device_map = dict(device_map)
    model.offload_loader = loader
    model.sharding = sharding
    return model


def cpu_offload(model: BlockwiseModel, state_dict: Any) -> BlockwiseModel:
    """Everything on host, streamed per block (reference `big_modeling.py:170`)."""
    device_map = {name: "cpu" for name, _ in model.block_fns}
    return dispatch_model(model, device_map, state_dict)


class UserCpuOffloadHook:
    """Manual offload control returned by `cpu_offload_with_hook` (reference
    `big_modeling.py:259` / `hooks.py` UserCpuOffloadHook): ``offload()`` frees
    this model's device-resident streamed weights; ``remove()`` also turns the
    stay-resident behavior off."""

    def __init__(self, model: BlockwiseModel):
        self.model = model

    def offload(self) -> None:
        self.model._evict_cache()

    def remove(self) -> None:
        self.model.cache_resident = False
        self.model._prev_hook = None
        self.model._evict_cache()


def cpu_offload_with_hook(
    model: BlockwiseModel,
    state_dict: Any = None,
    prev_module_hook: "UserCpuOffloadHook | None" = None,
) -> tuple[BlockwiseModel, UserCpuOffloadHook]:
    """CPU-offload ``model`` but keep its weights on device across calls until
    the returned hook's ``offload()`` — the multi-model-pipeline pattern
    (reference `big_modeling.py:259`): pass the previous model's hook as
    ``prev_module_hook`` and invoking this model evicts that one first."""
    if state_dict is not None:
        model = cpu_offload(model, state_dict)
    model.cache_resident = True
    model._prev_hook = prev_module_hook
    return model, UserCpuOffloadHook(model)


def disk_offload(model: BlockwiseModel, state_dict: Any, offload_dir: str) -> BlockwiseModel:
    device_map = {name: "disk" for name, _ in model.block_fns}
    return dispatch_model(model, device_map, state_dict, offload_dir=offload_dir)


def load_checkpoint_and_dispatch(
    model: BlockwiseModel,
    checkpoint: str,
    device_map: dict[str, str] | str = "auto",
    max_memory: dict[str, int] | None = None,
    offload_folder: str | None = None,
    sharding: Any = None,
) -> BlockwiseModel:
    """Load a consolidated export and dispatch per the (possibly inferred) map
    (reference `big_modeling.py:504`)."""
    from .checkpointing import load_model_weights

    state_dict = load_model_weights(checkpoint)
    if device_map == "auto":
        device_map = infer_auto_device_map(state_dict, max_memory=max_memory)
    return dispatch_model(model, device_map, state_dict, offload_dir=offload_folder, sharding=sharding)
