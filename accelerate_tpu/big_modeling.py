"""Big-model inference: load and run models larger than device memory.

Capability parity: reference `src/accelerate/big_modeling.py` (633 LoC) +
`utils/modeling.py` device-map machinery: `init_empty_weights` (meta init),
`infer_auto_device_map` (greedy first-fit onto device/cpu/disk budgets),
`dispatch_model` + `AlignDevicesHook` (per-submodule weight streaming),
`load_checkpoint_and_dispatch`, `cpu_offload`, `disk_offload`.

TPU-native re-founding:
  - "meta device" = `jax.eval_shape`: abstract param trees with zero allocation.
  - placement tiers are {device, cpu, disk}; "device" means *the mesh* — a block
    resident on-device is sharded over all chips (NamedSharding), not pinned to
    one GPU as in the reference's per-GPU maps.
  - instead of monkey-patched forward hooks, a `BlockwiseModel` runs its blocks
    sequentially; offloaded blocks stream host->HBM just-in-time with the *next*
    block's transfer launched before the current block computes (JAX async
    dispatch gives the overlap for free — the role of the reference's
    prefetching AlignDevicesHook).
"""

from __future__ import annotations

import contextlib
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

import jax
import numpy as np

from .utils.modeling import (
    compute_module_sizes,
    flatten_params,
    get_max_memory,
    unflatten_params,
)
from .utils.offload import OffloadedWeightsLoader, offload_state_dict


@contextlib.contextmanager
def init_empty_weights(include_buffers: bool = False):
    """Context marker for meta initialization (reference `big_modeling.py:57`).

    JAX needs no patching: yield a helper whose ``.init(module, *args)`` returns
    an *abstract* parameter tree via `jax.eval_shape` — no memory is touched.
    """

    class _Meta:
        @staticmethod
        def init(module: Any, rngs: Any, *args: Any, **kwargs: Any) -> Any:
            out = jax.eval_shape(lambda: module.init(rngs, *args, **kwargs))
            return out["params"] if isinstance(out, dict) and "params" in out else out

    yield _Meta()


def init_on_device(device: Any):
    """Place subsequent inits directly on ``device`` (reference `init_on_device`)."""

    return jax.default_device(device)


def infer_auto_device_map(
    params: Any,
    max_memory: dict[str, int] | None = None,
    no_split_module_classes: Sequence[str] | None = None,
    dtype: Any | None = None,
) -> dict[str, str]:
    """Greedy first-fit of top-level blocks onto {device, cpu, disk}
    (reference `utils/modeling.py:1096`). Blocks are the first-level keys of the
    param tree (a transformer's embedding / layer_i / head), which are exactly
    the reference's no-split modules."""
    budgets = get_max_memory(max_memory)
    device_budget = sum(v for k, v in budgets.items() if k.startswith("device"))
    cpu_budget = budgets.get("cpu", 0)
    sizes = compute_module_sizes(params, dtype=dtype)
    top_blocks = [k for k in sizes if k and "/" not in k]
    device_map: dict[str, str] = {}
    for block in top_blocks:
        size = sizes[block]
        if size <= device_budget:
            device_map[block] = "device"
            device_budget -= size
        elif size <= cpu_budget:
            device_map[block] = "cpu"
            cpu_budget -= size
        else:
            device_map[block] = "disk"
    return device_map


@dataclass
class BlockwiseModel:
    """Sequential block decomposition of a model — the unit of offload streaming.

    ``blocks`` maps block name -> ``fn(block_params, x) -> x`` applied in order;
    ``prologue``/``epilogue`` handle embedding / final head with their own param
    blocks. The param tree's first-level keys must cover all block names.
    """

    block_fns: list[tuple[str, Callable]]
    params: Any = None  # per-block: jax tree (resident) or numpy tree (offloaded)
    device_map: dict[str, str] = field(default_factory=dict)
    offload_loader: OffloadedWeightsLoader | None = None
    sharding: Any = None  # NamedSharding for resident/streamed placement

    def _block_params(self, name: str) -> Any:
        tier = self.device_map.get(name, "device")
        if tier == "device":
            return self.params[name]
        if tier == "cpu":
            host = self.params[name]
        else:  # disk
            flat = {
                k[len(name) + 1 :]: self.offload_loader[k]
                for k in self.offload_loader
                if k.startswith(name + "/")
            }
            host = unflatten_params(flat)
        return jax.tree.map(
            lambda p: jax.device_put(p, self.sharding) if self.sharding is not None else jax.device_put(p),
            host,
        )

    def __call__(self, x: Any) -> Any:
        names = [n for n, _ in self.block_fns]
        fns = dict(self.block_fns)
        # prefetch pipeline: launch block i+1's H2D before computing block i
        next_params = self._block_params(names[0])
        for i, name in enumerate(names):
            cur = next_params
            if i + 1 < len(names):
                next_params = self._block_params(names[i + 1])
            x = fns[name](cur, x)
            if self.device_map.get(name, "device") != "device":
                jax.tree.map(
                    lambda p: p.delete() if isinstance(p, jax.Array) and not p.is_deleted() else None,
                    cur,
                    is_leaf=lambda v: isinstance(v, jax.Array),
                )
        return x


def dispatch_model(
    model: BlockwiseModel,
    device_map: dict[str, str],
    state_dict: Any,
    offload_dir: str | None = None,
    sharding: Any = None,
) -> BlockwiseModel:
    """Place each block per the device map (reference `big_modeling.py:306`):
    device blocks land sharded on the mesh now, cpu blocks stay as numpy, disk
    blocks are memmap-offloaded."""
    placed: dict[str, Any] = {}
    disk_flat: dict[str, np.ndarray] = {}
    for name, tier in device_map.items():
        block = state_dict[name]
        if tier == "device":
            placed[name] = jax.tree.map(
                lambda p: jax.device_put(p, sharding) if sharding is not None else jax.device_put(p),
                block,
            )
        elif tier == "cpu":
            placed[name] = jax.tree.map(np.asarray, block)
        else:
            for k, v in flatten_params({name: block}).items():
                disk_flat[k] = np.asarray(v)
    loader = None
    if disk_flat:
        if offload_dir is None:
            raise ValueError("disk offload requires offload_dir")
        offload_state_dict(offload_dir, disk_flat)
        loader = OffloadedWeightsLoader(save_folder=offload_dir)
    model.params = placed
    model.device_map = dict(device_map)
    model.offload_loader = loader
    model.sharding = sharding
    return model


def cpu_offload(model: BlockwiseModel, state_dict: Any) -> BlockwiseModel:
    """Everything on host, streamed per block (reference `big_modeling.py:170`)."""
    device_map = {name: "cpu" for name, _ in model.block_fns}
    return dispatch_model(model, device_map, state_dict)


def disk_offload(model: BlockwiseModel, state_dict: Any, offload_dir: str) -> BlockwiseModel:
    device_map = {name: "disk" for name, _ in model.block_fns}
    return dispatch_model(model, device_map, state_dict, offload_dir=offload_dir)


def load_checkpoint_and_dispatch(
    model: BlockwiseModel,
    checkpoint: str,
    device_map: dict[str, str] | str = "auto",
    max_memory: dict[str, int] | None = None,
    offload_folder: str | None = None,
    sharding: Any = None,
) -> BlockwiseModel:
    """Load a consolidated export and dispatch per the (possibly inferred) map
    (reference `big_modeling.py:504`)."""
    from .checkpointing import load_model_weights

    state_dict = load_model_weights(checkpoint)
    if device_map == "auto":
        device_map = infer_auto_device_map(state_dict, max_memory=max_memory)
    return dispatch_model(model, device_map, state_dict, offload_dir=offload_folder, sharding=sharding)
