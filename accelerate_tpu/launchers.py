"""In-process launchers.

Capability parity: reference `launchers.py` (302 LoC) — `notebook_launcher`
(start distributed training from a notebook) and `debug_launcher` (multi-process
CPU run for tests).

TPU-native: inside a notebook on a TPU VM the devices are already attached to
this process, so `notebook_launcher` just runs the function (per-core forking —
xmp.spawn — is a torch_xla artifact with no JAX equivalent or need). Multi-*host*
notebook launching is delegated to the CLI pod fan-out. `debug_launcher` forks
real OS processes, each a JAX "host" on the CPU platform with a localhost
coordinator — exercising the true multi-process collective path.
"""

from __future__ import annotations

import inspect
import os
import subprocess
import sys
import tempfile
import textwrap
from typing import Callable


def notebook_launcher(
    function: Callable,
    args: tuple = (),
    num_processes: int | None = None,
    mixed_precision: str = "no",
    use_port: str = "29500",
    **kwargs,
) -> None:
    """Run ``function(*args)`` on this host's devices (reference `launchers.py:40`)."""
    os.environ.setdefault("ACCELERATE_TPU_MIXED_PRECISION", mixed_precision)
    function(*args)


def debug_launcher(
    function: Callable,
    args: tuple = (),
    num_processes: int = 2,
    devices_per_process: int = 1,
) -> None:
    """Fork ``num_processes`` CPU 'hosts' over a localhost coordinator and run
    ``function(*args)`` in each (reference `launchers.py:269` — 2-proc gloo CPU).

    ``devices_per_process`` > 1 gives each child that many virtual CPU devices
    (host-platform multiplexing) — a pod-slice topology (N hosts × M chips)
    without hardware.

    The function must be importable (defined in a module, not a closure): each
    child imports it by qualified name, mirroring how torch's spawn pickles.
    """
    import socket

    module = inspect.getmodule(function)
    if module is None or not hasattr(module, "__file__"):
        raise ValueError("debug_launcher requires a function defined in an importable module file")
    fn_name = function.__qualname__
    if "." in fn_name or "<locals>" in fn_name:
        raise ValueError("debug_launcher requires a module-level function")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    runner = textwrap.dedent(
        f"""
        import runpy, sys
        from accelerate_tpu.state import PartialState
        PartialState()  # initialize jax.distributed from the env contract first
        ns = runpy.run_path({module.__file__!r})
        ns[{fn_name!r}](*{args!r})
        """
    )
    procs = []
    for i in range(num_processes):
        env = dict(os.environ)
        env.update(
            {
                "JAX_PLATFORMS": "cpu",
                "PALLAS_AXON_POOL_IPS": "",
                "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
                "JAX_NUM_PROCESSES": str(num_processes),
                "JAX_PROCESS_ID": str(i),
                "ACCELERATE_TPU_NUM_PROCESSES": str(num_processes),
            }
        )
        if devices_per_process > 1:
            flags = [
                f for f in env.get("XLA_FLAGS", "").split()
                if not f.startswith("--xla_force_host_platform_device_count")
            ]
            flags.append(f"--xla_force_host_platform_device_count={devices_per_process}")
            env["XLA_FLAGS"] = " ".join(flags)
        procs.append(subprocess.Popen([sys.executable, "-c", runner], env=env))
    codes = [p.wait() for p in procs]
    if any(codes):
        raise RuntimeError(f"debug_launcher children failed with exit codes {codes}")
