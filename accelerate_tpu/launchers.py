"""In-process launchers.

Capability parity: reference `launchers.py` (302 LoC) — `notebook_launcher`
(start distributed training from a notebook) and `debug_launcher` (multi-process
CPU run for tests).

TPU-native: inside a notebook on a TPU VM the devices are already attached to
this process, so `notebook_launcher` just runs the function (per-core forking —
xmp.spawn — is a torch_xla artifact with no JAX equivalent or need). Multi-*host*
notebook launching is delegated to the CLI pod fan-out. `debug_launcher` forks
real OS processes, each a JAX "host" on the CPU platform with a localhost
coordinator — exercising the true multi-process collective path.
"""

from __future__ import annotations

import inspect
import os
import subprocess
import sys
import tempfile
import textwrap
from typing import Callable


def notebook_launcher(
    function: Callable,
    args: tuple = (),
    num_processes: int | None = None,
    mixed_precision: str = "no",
    use_port: str = "29500",
    max_restarts: int = 0,
    **kwargs,
) -> None:
    """Start training from a notebook (reference `launchers.py:40-266`).

    On a TPU VM every local chip is already attached to THIS process, so the
    single-host case needs no elastic worker spawn: the function runs inline
    over all devices (the reference's per-core xmp.spawn is a torch_xla
    artifact). Passing ``num_processes`` > 1 forks that many real JAX
    processes over a localhost coordinator — the reference's multi-worker
    notebook path, realized with the same process machinery as
    `debug_launcher` but on the default platform; ``max_restarts`` re-runs a
    crashed generation, mirroring the reference's elastic agent restarts.
    """
    os.environ.setdefault("ACCELERATE_TPU_MIXED_PRECISION", mixed_precision)
    if num_processes is None or num_processes <= 1:
        function(*args)
        return
    if os.environ.get("ACCELERATE_TPU_NUM_PROCESSES"):
        raise RuntimeError(
            "notebook_launcher cannot nest inside an already-launched distributed job."
        )
    attempt = 0
    while True:
        try:
            debug_launcher(function, args=args, num_processes=num_processes, platform=None)
            return
        except RuntimeError:
            if attempt >= max_restarts:
                raise
            attempt += 1


def debug_launcher(
    function: Callable,
    args: tuple = (),
    num_processes: int = 2,
    devices_per_process: int = 1,
    platform: str | None = "cpu",
) -> None:
    """Fork ``num_processes`` 'hosts' over a localhost coordinator and run
    ``function(*args)`` in each (reference `launchers.py:269` — 2-proc gloo CPU).

    ``platform="cpu"`` (the default, the debug tier) forces each child onto the
    host-CPU backend; ``platform=None`` inherits the parent's platform — used
    by `notebook_launcher` so notebook-spawned workers keep their accelerator.
    ``devices_per_process`` > 1 gives each CPU child that many virtual devices
    (host-platform multiplexing) — a pod-slice topology (N hosts × M chips)
    without hardware.

    The function must be importable (defined in a module, not a closure): each
    child imports it by qualified name, mirroring how torch's spawn pickles.
    """
    import socket

    module = inspect.getmodule(function)
    if module is None or not hasattr(module, "__file__"):
        raise ValueError("debug_launcher requires a function defined in an importable module file")
    fn_name = function.__qualname__
    if "." in fn_name or "<locals>" in fn_name:
        raise ValueError("debug_launcher requires a module-level function")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    runner = textwrap.dedent(
        f"""
        import runpy, sys
        from accelerate_tpu.state import PartialState
        PartialState()  # initialize jax.distributed from the env contract first
        ns = runpy.run_path({module.__file__!r})
        ns[{fn_name!r}](*{args!r})
        """
    )
    procs = []
    for i in range(num_processes):
        env = dict(os.environ)
        env.update(
            {
                "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
                "JAX_NUM_PROCESSES": str(num_processes),
                "JAX_PROCESS_ID": str(i),
                "ACCELERATE_TPU_NUM_PROCESSES": str(num_processes),
            }
        )
        if platform is not None:
            env["JAX_PLATFORMS"] = platform
            if platform == "cpu":
                env["PALLAS_AXON_POOL_IPS"] = ""
        if devices_per_process > 1:
            flags = [
                f for f in env.get("XLA_FLAGS", "").split()
                if not f.startswith("--xla_force_host_platform_device_count")
            ]
            flags.append(f"--xla_force_host_platform_device_count={devices_per_process}")
            env["XLA_FLAGS"] = " ".join(flags)
        procs.append(subprocess.Popen([sys.executable, "-c", runner], env=env))
    codes = [p.wait() for p in procs]
    if any(codes):
        raise RuntimeError(f"debug_launcher children failed with exit codes {codes}")
