"""In-process launchers.

Capability parity: reference `launchers.py` (302 LoC) — `notebook_launcher`
(start distributed training from a notebook) and `debug_launcher` (multi-process
CPU run for tests).

TPU-native: inside a notebook on a TPU VM the devices are already attached to
this process, so single-host `notebook_launcher` just runs the function
(per-core forking — xmp.spawn — is a torch_xla artifact with no JAX equivalent
or need). ``num_processes`` > 1 forks real worker processes that *inherit the
notebook's interpreter state* — closures and cell-defined functions launch
without being importable, the property that distinguishes the notebook path
from `debug_launcher`'s importable-script contract. `debug_launcher` spawns
fresh OS processes, each a JAX "host" on the CPU platform with a localhost
coordinator — exercising the true multi-process collective path.
"""

from __future__ import annotations

import inspect
import os
import subprocess
import sys
import tempfile
import textwrap
import time
import traceback
from typing import Callable


def set_host_device_count_flag(env: dict, n_devices: int) -> None:
    """Point a child's ``XLA_FLAGS`` at ``n_devices`` virtual host-CPU chips,
    replacing any existing count flag — the one place this flag is spelled for
    child envs (the CLI launcher and debug_launcher both route here)."""
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)


def _jax_backends_initialized() -> bool:
    """True once this process has materialized any XLA backend. Forking after
    that point hands children dead device handles (the reference's analogous
    guard errors when CUDA is initialized — `launchers.py:are_libraries_initialized`
    role), so the launcher refuses rather than deadlocking."""
    xb = sys.modules.get("jax._src.xla_bridge")
    return bool(getattr(xb, "_backends", None))


def _notebook_worker(function, args, env: dict) -> None:
    """Forked child body: point the JAX env contract at the coordinator BEFORE
    any backend init, run, and `os._exit` so IPython atexit hooks inherited
    from the notebook kernel never fire in the worker."""
    os.environ.update(env)
    try:
        function(*args)
    except BaseException:
        traceback.print_exc()
        os._exit(1)
    os._exit(0)


def notebook_launcher(
    function: Callable,
    args: tuple = (),
    num_processes: int | None = None,
    mixed_precision: str = "no",
    use_port: str = "29500",
    master_addr: str = "127.0.0.1",
    node_rank: int = 0,
    num_nodes: int = 1,
    max_restarts: int = 0,
    monitor_interval: float = 0.1,
    **kwargs,
) -> None:
    """Start training from a notebook (reference `launchers.py:40-266`).

    On a TPU VM every local chip is already attached to THIS process, so the
    single-host case needs no elastic worker spawn: the function runs inline
    over all devices (the reference's per-core xmp.spawn is a torch_xla
    artifact). Passing ``num_processes`` > 1 forks that many real JAX worker
    processes over a coordinator at ``master_addr:use_port`` — because they are
    *forked*, the function may be a closure defined in a notebook cell, the
    reference's signature notebook capability. ``num_nodes``/``node_rank``
    extend the rendezvous across machines running the same notebook code
    (process ids are offset by ``node_rank * num_processes``). A crashed
    generation is re-launched up to ``max_restarts`` times, mirroring the
    reference's elastic-agent restarts; the parent polls children every
    ``monitor_interval`` seconds and tears the generation down as soon as any
    worker fails. ``use_port="0"`` picks a free port (single-node only).
    """
    os.environ.setdefault("ACCELERATE_TPU_MIXED_PRECISION", mixed_precision)
    if (num_processes is None or num_processes <= 1) and num_nodes <= 1:
        function(*args)
        return
    num_processes = num_processes or 1
    if os.environ.get("ACCELERATE_TPU_NUM_PROCESSES"):
        raise RuntimeError(
            "notebook_launcher cannot nest inside an already-launched distributed job."
        )
    if num_nodes > 1 and str(use_port) == "0":
        raise ValueError(
            "use_port='0' (ephemeral) would make each node pick a different "
            "coordinator port and hang the rendezvous; pass an explicit port "
            "for multi-node launches."
        )
    if _jax_backends_initialized():
        raise RuntimeError(
            "JAX devices are already initialized in this process; forked workers "
            "would inherit dead device handles. Restart the notebook kernel and "
            "call notebook_launcher before running any JAX computation."
        )
    import multiprocessing

    try:
        ctx = multiprocessing.get_context("fork")
    except ValueError:
        if num_nodes > 1:
            raise RuntimeError(
                "multi-node notebook_launcher requires the fork start method "
                "(unavailable on this OS); a single-node fallback would form a "
                "wrong-sized world and hang the other nodes."
            )
        # no fork on this OS: the spawn fallback re-loads the function's module
        # file in each child (debug_launcher's runpy path), so cell-defined
        # closures (the advertised API) cannot work — check debug_launcher's
        # actual requirements up front and fail naming the real limitation.
        mod = inspect.getmodule(function)
        qualname = getattr(function, "__qualname__", getattr(function, "__name__", ""))
        loadable = (
            mod is not None
            and hasattr(mod, "__file__")
            and "." not in qualname
            and "<locals>" not in qualname
        )
        if not loadable:
            raise RuntimeError(
                "notebook_launcher requires the 'fork' start method for "
                "notebook-cell functions, which this OS does not provide. The "
                f"spawn fallback re-loads the function's module file, but "
                f"{qualname!r} is not a module-level function in a file. Move "
                "it to module level in a .py file, or run on a fork-capable OS."
            )
        debug_launcher(function, args=args, num_processes=num_processes, platform=None)
        return

    world = num_nodes * num_processes
    for attempt in range(max_restarts + 1):
        port = use_port
        if str(use_port) == "0":
            import socket

            with socket.socket() as s:
                s.bind((master_addr, 0))
                port = str(s.getsockname()[1])
        procs = []
        for i in range(num_processes):
            env = {
                "JAX_COORDINATOR_ADDRESS": f"{master_addr}:{port}",
                "JAX_NUM_PROCESSES": str(world),
                "JAX_PROCESS_ID": str(node_rank * num_processes + i),
                "ACCELERATE_TPU_NUM_PROCESSES": str(world),
                "ACCELERATE_TPU_MIXED_PRECISION": mixed_precision,
            }
            p = ctx.Process(target=_notebook_worker, args=(function, args, env))
            p.start()
            procs.append(p)
        try:
            failed = None
            while failed is None and any(p.is_alive() for p in procs):
                time.sleep(monitor_interval)
                failed = next(
                    (p for p in procs if p.exitcode not in (None, 0)), None
                )
            if failed is None:
                failed = next((p for p in procs if p.exitcode not in (None, 0)), None)
        except (KeyboardInterrupt, SystemExit):
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for p in procs:
                p.join()
            raise
        if failed is not None:
            for p in procs:
                if p.is_alive():
                    p.terminate()
        for p in procs:
            p.join()
        if failed is None:
            return
        if attempt == max_restarts:
            raise RuntimeError(
                f"notebook_launcher worker {procs.index(failed)} failed with exit code "
                f"{failed.exitcode} (after {attempt} restart(s))"
            )


def debug_launcher(
    function: Callable,
    args: tuple = (),
    num_processes: int = 2,
    devices_per_process: int = 1,
    platform: str | None = "cpu",
) -> None:
    """Fork ``num_processes`` 'hosts' over a localhost coordinator and run
    ``function(*args)`` in each (reference `launchers.py:269` — 2-proc gloo CPU).

    ``platform="cpu"`` (the default, the debug tier) forces each child onto the
    host-CPU backend; ``platform=None`` inherits the parent's platform — used
    by `notebook_launcher` so notebook-spawned workers keep their accelerator.
    ``devices_per_process`` > 1 gives each CPU child that many virtual devices
    (host-platform multiplexing) — a pod-slice topology (N hosts × M chips)
    without hardware.

    The function must be importable (defined in a module, not a closure): each
    child imports it by qualified name, mirroring how torch's spawn pickles.
    """
    import socket

    module = inspect.getmodule(function)
    if module is None or not hasattr(module, "__file__"):
        raise ValueError("debug_launcher requires a function defined in an importable module file")
    fn_name = function.__qualname__
    if "." in fn_name or "<locals>" in fn_name:
        raise ValueError("debug_launcher requires a module-level function")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    runner = textwrap.dedent(
        f"""
        import runpy, sys
        from accelerate_tpu.state import PartialState
        PartialState()  # initialize jax.distributed from the env contract first
        ns = runpy.run_path({module.__file__!r})
        ns[{fn_name!r}](*{args!r})
        """
    )
    procs = []
    for i in range(num_processes):
        env = dict(os.environ)
        env.update(
            {
                "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
                "JAX_NUM_PROCESSES": str(num_processes),
                "JAX_PROCESS_ID": str(i),
                "ACCELERATE_TPU_NUM_PROCESSES": str(num_processes),
            }
        )
        if platform is not None:
            env["JAX_PLATFORMS"] = platform
            if platform == "cpu":
                env["PALLAS_AXON_POOL_IPS"] = ""
        if platform == "cpu" or devices_per_process > 1:
            # always pin the count: an inherited parent XLA_FLAGS (e.g. a test
            # host forcing 8 virtual devices) would otherwise multiply each
            # child's device count and silently change the data-axis topology
            set_host_device_count_flag(env, devices_per_process)
        procs.append(subprocess.Popen([sys.executable, "-c", runner], env=env))
    codes = [p.wait() for p in procs]
    if any(codes):
        raise RuntimeError(f"debug_launcher children failed with exit codes {codes}")
