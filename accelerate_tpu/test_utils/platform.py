"""Host-CPU platform forcing — the "multi-node without a cluster" vehicle.

The reference runs its distributed tests anywhere via a 2-process gloo fork
(``debug_launcher``, reference ``src/accelerate/launchers.py:269-302``). The
TPU-native equivalent multiplexes the host platform into N virtual XLA devices
so every sharding/collective path runs without hardware.

This must also defend against environments whose sitecustomize registers a TPU
PJRT plugin in every process and pins ``jax_platforms`` via ``jax.config``:
there, the ``JAX_PLATFORMS`` env var alone cannot redirect to CPU (config beats
env), and with the device relay down ``jax.devices()`` blocks forever. The one
audited defense lives here; tests/conftest.py, ``__graft_entry__`` and
``bench.py`` all call it.
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_cpu_platform(n_devices: int | None = None) -> None:
    """Redirect this process's JAX backend to host CPU, optionally with
    ``n_devices`` virtual devices, initializing the backend eagerly.

    Must run before any JAX backend initialization; XLA_FLAGS is restored
    afterwards so child processes don't inherit the forced topology. Safe to
    call again once forced (no-op if the CPU backend already exposes enough
    devices); raises if another platform's backend already initialized.
    """
    import jax
    from jax._src import xla_bridge

    if xla_bridge.backends_are_initialized():
        devs = jax.devices()
        if devs[0].platform == "cpu" and (n_devices is None or len(devs) >= n_devices):
            return
        raise RuntimeError(
            f"jax backend already initialized as {devs[0].platform} with "
            f"{len(devs)} devices; cannot re-force cpu"
            + (f" x{n_devices}" if n_devices else "")
        )

    old_flags = os.environ.get("XLA_FLAGS")
    if n_devices is not None:
        flags = old_flags or ""
        if _COUNT_FLAG in flags:
            flags = re.sub(rf"{_COUNT_FLAG}=\d+", f"{_COUNT_FLAG}={n_devices}", flags)
        else:
            flags = (flags + f" {_COUNT_FLAG}={n_devices}").strip()
        os.environ["XLA_FLAGS"] = flags
    try:
        jax.config.update("jax_platforms", "cpu")
        platform = jax.devices()[0].platform  # initializes the CPU client
    finally:
        if n_devices is not None:
            if old_flags is None:
                os.environ.pop("XLA_FLAGS", None)
            else:
                os.environ["XLA_FLAGS"] = old_flags
    if platform != "cpu":
        raise RuntimeError(f"expected forced cpu platform, got {platform!r}")
    if n_devices is not None and len(jax.devices()) < n_devices:
        raise RuntimeError(
            f"host platform exposes {len(jax.devices())} devices, need {n_devices}"
        )
