"""Checkpoint save -> fresh-runtime restore -> bit-exact resume on N real JAX
processes (reference `test_utils/scripts/external_deps/test_checkpointing.py`
role). Phase A trains 3 boundaries with fp16 (so scaler state is live), saves
via orbax sharded save. Phase B rebuilds Accelerator/model/optimizer from
scratch in the same processes, restores, trains 2 more boundaries. The result
must be bit-identical to an uninterrupted 5-boundary run."""


def _build(acc):
    import jax.numpy as jnp
    import optax

    params = {"w": jnp.zeros((4,)), "b": jnp.zeros(())}

    def apply_fn(p, x):
        return x @ p["w"] + p["b"]

    model, opt = acc.prepare((apply_fn, params), optax.adam(0.05))
    return model, opt


def _batches():
    import numpy as np

    rng = np.random.RandomState(3)
    W = np.array([0.5, -1.0, 1.5, 2.0], dtype=np.float32)
    xs = rng.randn(5, 16, 4).astype(np.float32)
    return [{"x": xs[i], "y": xs[i] @ W + 0.1} for i in range(5)]


def _loss(m, b):
    return ((m(b["x"]) - b["y"]) ** 2).mean()


def run_checks(ckpt_dir, expected: int = 2):
    import jax
    import numpy as np

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    state = PartialState()
    assert state.num_processes == expected, state.num_processes
    batches = _batches()

    def fresh_accelerator():
        AcceleratorState._reset_state()
        GradientState._reset_state()
        return Accelerator(mixed_precision="fp16")

    def train(acc, model, opt, batch_slice):
        step = acc.make_train_step(_loss)
        for b in batch_slice:
            step(b)

    # --- uninterrupted run -------------------------------------------------
    acc = fresh_accelerator()
    model, opt = _build(acc)
    train(acc, model, opt, batches)
    expect = jax.tree.map(lambda a: np.asarray(jax.device_get(a)), acc.get_state_dict(model))
    expect_opt_steps = opt._num_updates

    # --- phase A: train 3, save -------------------------------------------
    acc = fresh_accelerator()
    model, opt = _build(acc)
    train(acc, model, opt, batches[:3])
    acc.save_state(ckpt_dir)
    state.wait_for_everyone()

    # --- phase B: fresh runtime objects, restore, resume -------------------
    acc = fresh_accelerator()
    model, opt = _build(acc)
    acc.load_state(ckpt_dir)
    assert opt._num_updates == 3, opt._num_updates
    assert opt.scaler_state is not None
    train(acc, model, opt, batches[3:])
    got = jax.tree.map(lambda a: np.asarray(jax.device_get(a)), acc.get_state_dict(model))
    assert opt._num_updates == expect_opt_steps

    for k in expect:
        np.testing.assert_array_equal(got[k], expect[k]), k
    state.wait_for_everyone()
    print(f"proc {state.process_index}: checkpoint resume bit-exact OK", flush=True)


if __name__ == "__main__":
    import sys

    from accelerate_tpu.state import PartialState

    PartialState()
    run_checks(sys.argv[1])
