"""DataLoaderDispatcher loop on N real JAX processes (reference
`test_utils/scripts/test_distributed_data_loop.py` role): process 0 reads an
UNEVEN iterable dataset, broadcasts each global batch, every process slices its
share (topology-generic); the ragged final batch is completed by wrapping and recorded in
`remainder`, so gather_for_metrics returns exactly the dataset."""


def run_checks(expected: int = 2):
    import numpy as np

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.data_loader import DataLoaderDispatcher
    from accelerate_tpu.state import PartialState

    state = PartialState()
    assert state.num_processes == expected, state.num_processes

    # 27 samples in batches of 8: final batch has 3 -> not divisible by the process count
    data = np.arange(27.0)
    batches = [data[i : i + 8] for i in range(0, 27, 8)]
    # only the main process actually has the dataset (iterable semantics)
    source = batches if state.is_main_process else []

    acc = Accelerator()
    dl = acc.prepare(DataLoaderDispatcher(source))
    seen = []
    sizes = []
    for batch in dl:
        sizes.append(batch.shape[0])
        seen.append(np.asarray(acc.gather_for_metrics(batch)))
    # every global batch is shape-complete (XLA equal-shard requirement)
    assert all(s % state.num_processes == 0 for s in sizes), sizes
    out = np.concatenate(seen)
    np.testing.assert_array_equal(out, data)
    assert dl.remainder == 3, dl.remainder
    state.wait_for_everyone()
    print(f"proc {state.process_index}: dispatcher uneven-dataset loop OK", flush=True)


if __name__ == "__main__":
    from accelerate_tpu.state import PartialState

    PartialState()
    run_checks()
