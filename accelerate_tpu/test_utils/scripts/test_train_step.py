"""Fused-train-step parity on 2 real JAX processes (reference
`test_utils/scripts/test_script.py:449-622` signature-parity role): the same
model trained through the framework's multi-host path — DataLoaderShard
assembling global arrays via `jax.make_array_from_process_local_data` — must
land on exactly the weights of an independently computed single-process
full-batch baseline."""


def run_checks():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.data_loader import DataLoaderShard
    from accelerate_tpu.state import PartialState

    state = PartialState()
    assert state.num_processes == 2, state.num_processes

    # Deterministic dataset, identical on every process
    rng = np.random.RandomState(7)
    W = np.array([1.5, -0.5, 2.0, 0.25], dtype=np.float32)
    xs = rng.randn(8, 16, 4).astype(np.float32)  # 8 global batches of 16
    ys = xs @ W + 0.3

    # Each process feeds only ITS half of every global batch — the loader must
    # assemble the global sharded array from process-local data.
    half = 16 // 2
    lo, hi = state.process_index * half, (state.process_index + 1) * half
    local_batches = [{"x": xs[i, lo:hi], "y": ys[i, lo:hi]} for i in range(8)]

    acc = Accelerator(gradient_accumulation_steps=2)
    params = {"w": jnp.zeros((4,)), "b": jnp.zeros(())}

    def apply_fn(p, x):
        return x @ p["w"] + p["b"]

    def loss_fn(m, b):
        return ((m(b["x"]) - b["y"]) ** 2).mean()

    model, opt, dl = acc.prepare((apply_fn, params), optax.sgd(0.1), DataLoaderShard(local_batches))
    step = acc.make_train_step(loss_fn)
    for batch in dl:
        assert not batch["x"].is_fully_addressable  # true multi-host global array
        step(batch)
    got = jax.tree.map(lambda a: np.asarray(jax.device_get(a)), acc.get_state_dict(model))

    # Independent single-process baseline on the full global batches
    p = {"w": jnp.zeros((4,)), "b": jnp.zeros(())}

    def jloss(p, x, y):
        return ((x @ p["w"] + p["b"] - y) ** 2).mean()

    accg = None
    for i in range(8):
        g = jax.grad(jloss)(p, jnp.asarray(xs[i]), jnp.asarray(ys[i]))
        accg = g if accg is None else jax.tree.map(jnp.add, accg, g)
        if i % 2 == 1:
            p = jax.tree.map(lambda w, g: w - 0.1 * g / 2, p, accg)
            accg = None
    np.testing.assert_allclose(got["w"], np.asarray(p["w"]), rtol=2e-6)
    np.testing.assert_allclose(got["b"], np.asarray(p["b"]), rtol=2e-6)
    state.wait_for_everyone()
    print(f"proc {state.process_index}: fused train step multi-host parity OK", flush=True)


if __name__ == "__main__":
    from accelerate_tpu.state import PartialState

    PartialState()
    run_checks()
