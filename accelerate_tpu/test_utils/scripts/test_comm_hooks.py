"""Gradient-compression comm hooks across 2 real JAX processes (reference
`test_utils/scripts/test_ddp_comm_hook.py` role): every hook must (a) keep
replicas bit-identical after each update — the DDP invariant the hooks must
not break — and (b) still train to (near-)baseline quality. Run under
`debug_launcher`; each process is one data-parallel replica."""


def _setup():
    import numpy as np

    rng = np.random.default_rng(7)
    W = rng.normal(size=(8, 8)).astype(np.float32)
    batches = [
        {"x": (x := rng.normal(size=(16, 8)).astype(np.float32)), "y": x @ W}
        for _ in range(24)
    ]
    params = {"w": np.zeros((8, 8), np.float32)}

    def apply_fn(p, x):
        return x @ p["w"]

    def loss_fn(m, b):
        return ((m(b["x"]) - b["y"]) ** 2).mean()

    return params, apply_fn, loss_fn, batches


def _train(comm_hook):
    import jax
    import numpy as np
    import optax

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.data_loader import DataLoaderShard
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    params, apply_fn, loss_fn, batches = _setup()
    acc = Accelerator()
    model, opt, dl = acc.prepare(
        (apply_fn, params), optax.adam(0.1), DataLoaderShard(batches)
    )
    step = acc.make_train_step(loss_fn, comm_hook=comm_hook)
    losses = [float(step(b)) for b in dl]
    final = jax.tree.map(lambda a: np.asarray(jax.device_get(a)), acc.get_state_dict(model))
    return final, losses


def run_checks():
    import numpy as np

    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils import operations

    state = PartialState()
    assert state.num_processes == 2, state.num_processes

    results = {}
    for hook in (None, "bf16", "power_sgd"):
        final, losses = _train(hook)
        assert losses[-1] < losses[0] / 3, (hook, losses[0], losses[-1])
        # DDP invariant: replicas hold identical params after every update
        gathered = operations.gather_object([final["w"].sum().item()])
        assert abs(gathered[0] - gathered[1]) < 1e-6, (hook, gathered)
        results[hook] = final["w"]

    # bf16 compression rounds the wire format only: near-baseline updates
    bf16_err = np.abs(results["bf16"] - results[None]).max()
    assert bf16_err < 0.05, bf16_err
    # powersgd is rank-limited but error feedback must keep it training toward
    # the same solution
    psgd_err = np.abs(results["power_sgd"] - results[None]).max()
    assert psgd_err < 0.5, psgd_err
    if state.is_main_process:
        print(f"comm hooks OK: bf16 max dev {bf16_err:.4f}, power_sgd {psgd_err:.4f}")


if __name__ == "__main__":
    run_checks()
