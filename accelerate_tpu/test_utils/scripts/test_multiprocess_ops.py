"""Cross-process collective assertions, run under the debug/CLI launcher on N
JAX processes (reference `test_utils/scripts/test_ops.py` pattern). Topology-
generic: every assertion derives its expectation from the live process count,
so the same script validates the 2-process and 4-process tiers."""


def run_checks(expected: int = 2):
    import os
    import tempfile

    import jax
    import numpy as np

    assert jax.process_count() == expected, jax.process_count()
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils import operations

    state = PartialState()
    n, p = state.num_processes, state.process_index
    assert n == expected

    # object all-gather across processes
    got = operations.gather_object([f"proc{p}"])
    assert got == [f"proc{i}" for i in range(n)], got

    # tensor gather across processes
    x = np.full((2,), float(p))
    g = operations.gather(x)
    np.testing.assert_array_equal(
        np.asarray(g).ravel(), np.repeat(np.arange(float(n)), 2)
    )

    # broadcast from the LAST (nonzero) rank — exercises the rotate-to-0 path
    b = operations.broadcast(np.full((3,), float(p + 5)), from_process=n - 1)
    np.testing.assert_array_equal(np.asarray(b), np.full((3,), float(n - 1 + 5)))

    # object broadcast from a nonzero rank
    objs = operations.broadcast_object_list([f"payload{p}", p * 10], from_process=n - 1)
    assert objs == [f"payload{n - 1}", (n - 1) * 10], objs

    # pad_across_processes: ragged per-process lengths pad to the global max
    ragged = np.arange(float(p + 1))  # proc i has i+1 elements
    padded = operations.pad_across_processes(ragged, dim=0)
    assert padded.shape[0] == n, padded.shape
    np.testing.assert_array_equal(np.asarray(padded)[: p + 1], ragged)
    np.testing.assert_array_equal(np.asarray(padded)[p + 1 :], 0.0)

    # main_process_first really orders main's body before every other process.
    # The marker-file proof needs a shared filesystem, so it only runs when the
    # coordinator is loopback (all processes on this host — the debug-launcher
    # tier); on a real pod the context still executes, unasserted.
    coordinator = os.environ.get("JAX_COORDINATOR_ADDRESS", "")
    single_host = coordinator.startswith(("127.", "localhost"))
    marker = os.path.join(
        tempfile.gettempdir(), "mpf_" + coordinator.replace(":", "_").replace(".", "_")
    )
    if single_host and state.is_main_process and os.path.exists(marker):
        os.remove(marker)  # stale marker from a crashed earlier run
    state.wait_for_everyone()
    with state.main_process_first():
        if state.is_main_process:
            with open(marker, "w") as f:
                f.write("main was here")
        elif single_host:
            assert os.path.exists(marker), "main_process_first did not run main first"
    state.wait_for_everyone()
    if single_host and state.is_main_process:
        os.remove(marker)

    state.wait_for_everyone()
    print(f"proc {p}/{n}: multihost collectives OK", flush=True)


if __name__ == "__main__":
    import os

    from accelerate_tpu.state import PartialState

    PartialState()
    run_checks(int(os.environ.get("ACCELERATE_TPU_NUM_PROCESSES", "2")))
