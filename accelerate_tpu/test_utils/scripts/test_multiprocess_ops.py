"""Cross-process collective assertions, run under the debug/CLI launcher on N
JAX processes (reference `test_utils/scripts/test_ops.py` pattern)."""

def run_checks():
    import jax
    import numpy as np
    assert jax.process_count() == 2, jax.process_count()
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils import operations
    state = PartialState()
    assert state.num_processes == 2
    # object all-gather across processes
    got = operations.gather_object([f"proc{state.process_index}"])
    assert got == ["proc0", "proc1"], got
    # tensor gather across processes
    x = np.full((2,), float(state.process_index))
    g = operations.gather(x)
    np.testing.assert_array_equal(np.asarray(g).ravel(), [0.0, 0.0, 1.0, 1.0])
    # broadcast
    b = operations.broadcast(np.full((3,), float(state.process_index + 5)), from_process=1)
    np.testing.assert_array_equal(np.asarray(b), [6.0, 6.0, 6.0])
    state.wait_for_everyone()
    print(f"proc {state.process_index}: multihost collectives OK", flush=True)


if __name__ == "__main__":
    from accelerate_tpu.state import PartialState

    PartialState()
    run_checks()
