"""Launched performance/quality assertion on 2 real JAX processes (reference
`test_utils/scripts/external_deps/test_performance.py` role): the same
classification workload trained through the full framework flow must reach a
quality threshold, and per-process peak memory must stay bounded (the
`test_peak_memory_usage` role — host RSS here; `Device.memory_stats` has no
meaning on the CPU debug tier)."""


def run_checks():
    import resource

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.data_loader import DataLoaderShard
    from accelerate_tpu.state import PartialState

    state = PartialState()
    assert state.num_processes == 2, state.num_processes

    # separable 2-class problem, identical on both processes; each feeds its half
    rng = np.random.RandomState(11)
    n, feats = 512, 16
    labels = rng.randint(0, 2, n).astype(np.int32)
    x = rng.randn(n, feats).astype(np.float32) + labels[:, None] * 1.5
    half = 16
    lo = state.process_index * half
    batches = [
        {"x": x[i : i + 32][lo : lo + half], "labels": labels[i : i + 32][lo : lo + half]}
        for i in range(0, n, 32)
    ]

    acc = Accelerator()
    params = {
        "w1": rng.randn(feats, 32).astype(np.float32) * 0.1,
        "b1": np.zeros(32, np.float32),
        "w2": rng.randn(32, 2).astype(np.float32) * 0.1,
        "b2": np.zeros(2, np.float32),
    }

    def apply_fn(p, xb):
        h = jnp.tanh(xb @ p["w1"] + p["b1"])
        return h @ p["w2"] + p["b2"]

    def loss_fn(m, b):
        logits = m(b["x"])
        lp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(lp, b["labels"][:, None], axis=-1).mean()

    model, opt, dl = acc.prepare((apply_fn, params), optax.adam(5e-3), DataLoaderShard(batches))
    step = acc.make_train_step(loss_fn)
    for _ in range(6):
        for b in dl:
            step(b)

    # quality threshold on the full dataset (reference asserts accuracy bounds)
    logits = model(jnp.asarray(x))
    acc_val = float((jnp.argmax(logits, -1) == jnp.asarray(labels)).mean())
    assert acc_val > 0.85, f"accuracy {acc_val} below threshold"

    # peak-memory bound: this tiny workload must not balloon host RSS
    # (ru_maxrss: kilobytes on Linux, bytes on macOS)
    import sys

    divisor = 1024 * 1024 if sys.platform == "darwin" else 1024
    peak_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / divisor
    assert peak_mb < 4096, f"peak RSS {peak_mb:.0f} MiB exceeds bound"
    state.wait_for_everyone()
    print(
        f"proc {state.process_index}: performance OK (acc={acc_val:.3f}, peak={peak_mb:.0f} MiB)",
        flush=True,
    )


if __name__ == "__main__":
    from accelerate_tpu.state import PartialState

    PartialState()
    run_checks()
