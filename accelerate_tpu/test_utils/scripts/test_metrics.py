"""gather_for_metrics correctness on N processes with a ragged final batch
(reference `test_utils/scripts/external_deps/test_metrics.py` — distributed
metric must equal the single-process truth, duplicated tail dropped).

Uses the canonical path: a torch DataLoader over the full dataset, sharded by
`prepare_data_loader` (BatchSamplerShard owns the even-batches padding math, so
the duplicates land at the global tail where gather_for_metrics drops them)."""


def run_checks():
    import numpy as np

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    state = PartialState()

    import torch.utils.data as tud

    # 22 samples, per-process batch 8 -> ragged tail; with even_batches the
    # wrapped duplicates sit at the global end and must be dropped
    n = 22
    rng = np.random.default_rng(1)
    preds = rng.integers(0, 2, size=(n,)).astype(np.int32)
    labels = rng.integers(0, 2, size=(n,)).astype(np.int32)
    truth = float((preds == labels).mean())

    class DS(tud.Dataset):
        def __len__(self):
            return n

        def __getitem__(self, i):
            return {"preds": preds[i], "labels": labels[i], "idx": np.int32(i)}

    loader = tud.DataLoader(DS(), batch_size=8, shuffle=False)
    acc = Accelerator()
    dl = acc.prepare_data_loader(loader)

    got = {"preds": [], "labels": [], "idx": []}
    for b in dl:
        g = acc.gather_for_metrics({k: b[k] for k in got})
        for k in got:
            got[k].append(np.asarray(g[k]))
    got = {k: np.concatenate(v) for k, v in got.items()}
    assert len(got["preds"]) == n, (len(got["preds"]), n)
    # every sample exactly once (order may be resharded, so compare by index)
    np.testing.assert_array_equal(np.sort(got["idx"]), np.arange(n))
    order = np.argsort(got["idx"])
    np.testing.assert_array_equal(got["preds"][order], preds)
    np.testing.assert_array_equal(got["labels"][order], labels)
    assert abs(float((got["preds"] == got["labels"]).mean()) - truth) < 1e-9
    state.wait_for_everyone()
    print(f"proc {state.process_index}: gather_for_metrics OK", flush=True)


if __name__ == "__main__":
    from accelerate_tpu.state import PartialState

    PartialState()
    run_checks()
