"""Gradient-accumulation semantics assertions, run on N JAX processes under the
debug launcher (reference `test_utils/scripts/test_sync.py` — no_sync /
accumulate equivalence and optimizer-step gating)."""


def run_checks():
    import jax
    import numpy as np
    import optax

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.data_loader import DataLoaderShard
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    state = PartialState()

    rng = np.random.default_rng(0)
    batches = [
        {"x": rng.normal(size=(4,)).astype(np.float32),
         "y": rng.normal(size=(4,)).astype(np.float32)}
        for _ in range(4)
    ]

    def apply_fn(p, x):
        return p["a"] * x + p["b"]

    def loss_fn(m, batch):
        return ((m(batch["x"]) - batch["y"]) ** 2).mean()

    params = {"a": np.zeros((1,), np.float32), "b": np.zeros((1,), np.float32)}
    lr = 0.1

    acc = Accelerator(gradient_accumulation_steps=2)
    model, opt, dl = acc.prepare((apply_fn, dict(params)), optax.sgd(lr), DataLoaderShard(batches))
    step = acc.make_train_step(loss_fn)
    sync_flags = []
    for batch in dl:
        step(batch)
        sync_flags.append(acc.gradient_state.sync_gradients)
    # 4 microbatches / accumulation 2 -> updates on batches 1 and 3 only
    assert opt._num_updates == 2, opt._num_updates
    assert sync_flags == [False, True, False, True], sync_flags

    # hand-computed baseline: mean of the two microbatch grads, two SGD steps
    ref = {k: np.asarray(v, np.float64) for k, v in params.items()}
    for pair in (batches[0:2], batches[2:4]):
        ga = gb = 0.0
        for b in pair:
            pred = ref["a"] * b["x"] + ref["b"]
            err = pred - b["y"]
            ga += (2 * err * b["x"]).mean() / 2  # /2: accumulation average
            gb += (2 * err).mean() / 2
        ref["a"] = ref["a"] - lr * ga
        ref["b"] = ref["b"] - lr * gb
    got = jax.tree.map(np.asarray, acc.get_state_dict(model))
    np.testing.assert_allclose(got["a"], ref["a"], rtol=1e-5)
    np.testing.assert_allclose(got["b"], ref["b"], rtol=1e-5)
    state.wait_for_everyone()
    print(f"proc {state.process_index}: accumulation semantics OK", flush=True)


if __name__ == "__main__":
    from accelerate_tpu.state import PartialState

    PartialState()
    run_checks()
