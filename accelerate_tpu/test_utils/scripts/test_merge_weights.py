"""Sharded save for the merge-weights flow, on 2 real JAX processes (reference
`test_utils/scripts/test_merge_weights.py` role, over the orbax/msgpack pair
instead of torch.distributed.checkpoint). The launched phase only SAVES — the
fsdp-sharded model checkpoints via `save_state`, every process writing its
shards. The merge itself (`accelerate-tpu merge-weights`) is a single-process
CLI by design (orbax restore has global barriers, so it cannot run on a
subset of a live multi-process world); the caller runs it afterwards and
verifies against `expected_params()`.
"""


def expected_params():
    """Deterministic params both the launched world and the verifying caller
    can reconstruct."""
    import numpy as np

    rng = np.random.default_rng(11)
    return {
        "w1": rng.normal(size=(16, 8)).astype(np.float32),
        "w2": rng.normal(size=(8, 4)).astype(np.float32),
    }


def run_checks(workdir):
    from pathlib import Path

    import jax
    import optax

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.parallel.mesh import ParallelismConfig
    from accelerate_tpu.state import PartialState

    state = PartialState()
    assert state.num_processes == 2, state.num_processes
    workdir = Path(workdir)

    def apply_fn(p, x):
        return jax.numpy.tanh(x @ p["w1"]) @ p["w2"]

    acc = Accelerator(parallelism_config=ParallelismConfig(fsdp_size=2))
    model, opt = acc.prepare((apply_fn, expected_params()), optax.sgd(0.1))
    # every leaf must actually be sharded over the fsdp axis for the merge to
    # prove consolidation
    for leaf in jax.tree.leaves(model.params):
        assert not leaf.sharding.is_fully_replicated, leaf.sharding
    acc.save_state(workdir / "ckpt")
    acc.wait_for_everyone()
    if state.is_main_process:
        assert (workdir / "ckpt" / "model_0").exists()
        print("sharded save OK: ready for single-process merge")


if __name__ == "__main__":
    import sys

    run_checks(sys.argv[1])
