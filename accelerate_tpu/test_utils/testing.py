"""Test decorators and harness helpers.

Capability parity: reference `test_utils/testing.py` (689 LoC) — `require_*` skip
decorators, device probing, `AccelerateTestCase` (singleton reset),
`execute_subprocess_async`, launch-command builders.
"""

from __future__ import annotations

import asyncio
import os
import subprocess
import sys
import tempfile
import unittest
from functools import partial
from pathlib import Path
from typing import Callable

import pytest

from ..utils import imports


def get_backend() -> tuple[str, int]:
    """(platform, device_count) of the default JAX backend (reference `get_backend`)."""
    import jax

    devices = jax.devices()
    return devices[0].platform, len(devices)


def require_tpu(test_case: Callable) -> Callable:
    platform, _ = get_backend()
    return pytest.mark.skipif(platform not in ("tpu", "axon"), reason="test requires TPU")(test_case)


def require_multi_device(test_case: Callable) -> Callable:
    _, n = get_backend()
    return pytest.mark.skipif(n < 2, reason="test requires multiple devices")(test_case)


def require_cpu(test_case: Callable) -> Callable:
    platform, _ = get_backend()
    return pytest.mark.skipif(platform != "cpu", reason="test requires CPU backend")(test_case)


def require_torch(test_case: Callable) -> Callable:
    return pytest.mark.skipif(not imports.is_torch_available(), reason="test requires torch")(test_case)


def require_transformers(test_case: Callable) -> Callable:
    return pytest.mark.skipif(
        not imports.is_transformers_available(), reason="test requires transformers"
    )(test_case)


def require_tensorboard(test_case: Callable) -> Callable:
    return pytest.mark.skipif(
        not imports.is_tensorboard_available(), reason="test requires tensorboard"
    )(test_case)


def require_wandb(test_case: Callable) -> Callable:
    return pytest.mark.skipif(not imports.is_wandb_available(), reason="test requires wandb")(test_case)


def slow(test_case: Callable) -> Callable:
    """Skipped unless RUN_SLOW=1 (reference `testing.py:slow`)."""
    from ..utils.environment import parse_flag_from_env

    return pytest.mark.skipif(not parse_flag_from_env("RUN_SLOW"), reason="test is slow")(test_case)


class TempDirTestCase(unittest.TestCase):
    """Each test gets a fresh scratch dir in self.tmpdir (reference `testing.py:446`)."""

    clear_on_setup = True

    @classmethod
    def setUpClass(cls):
        cls._tmpdir_handle = tempfile.TemporaryDirectory()
        cls.tmpdir = Path(cls._tmpdir_handle.name)

    @classmethod
    def tearDownClass(cls):
        cls._tmpdir_handle.cleanup()

    def setUp(self):
        if self.clear_on_setup:
            for item in self.tmpdir.glob("**/*"):
                if item.is_file():
                    item.unlink()


class AccelerateTestCase(unittest.TestCase):
    """Resets the state singletons between tests so one test's Accelerator cannot
    leak topology/precision into the next (reference `testing.py:479-490`)."""

    def tearDown(self):
        from ..state import AcceleratorState, GradientState, PartialState

        super().tearDown()
        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()


class SubprocessCallException(Exception):
    pass


def run_command(command: list[str], return_stdout: bool = False, env: dict | None = None):
    """Run a CLI command, raising with captured output on failure
    (reference `testing.py:619`)."""
    if env is None:
        env = dict(os.environ)
    try:
        output = subprocess.check_output(command, stderr=subprocess.STDOUT, env=env)
        if return_stdout:
            return output.decode()
    except subprocess.CalledProcessError as e:
        raise SubprocessCallException(
            f"Command `{' '.join(command)}` failed with:\n{e.output.decode()}"
        ) from e


def execute_subprocess_async(cmd: list[str], env: dict | None = None, timeout: int = 600) -> None:
    """Run a (possibly multi-process-launching) command asynchronously, streaming
    output, raising on nonzero exit (reference `testing.py:594`)."""

    async def _run():
        proc = await asyncio.create_subprocess_exec(
            *cmd,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.STDOUT,
            env=env or dict(os.environ),
        )
        out, _ = await asyncio.wait_for(proc.communicate(), timeout=timeout)
        if proc.returncode != 0:
            raise SubprocessCallException(
                f"Command `{' '.join(cmd)}` exited {proc.returncode}:\n{out.decode()}"
            )
        return out.decode()

    return asyncio.run(_run())


def get_launch_command(num_processes: int = 1, **kwargs) -> list[str]:
    """Build the CLI launch prefix (reference `get_launch_command`, `testing.py:91`)."""
    cmd = [sys.executable, "-m", "accelerate_tpu.commands.cli", "launch"]
    if num_processes > 1:
        cmd += ["--debug_cpu", str(num_processes)]
    for k, v in kwargs.items():
        cmd += [f"--{k}", str(v)]
    return cmd


DEFAULT_LAUNCH_COMMAND = get_launch_command(num_processes=2)
