from .platform import force_cpu_platform  # noqa: F401
