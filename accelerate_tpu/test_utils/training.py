"""Toy training fixtures (reference `test_utils/training.py` — RegressionDataset /
RegressionModel: linear y = a·x + b used by every parity test)."""

from __future__ import annotations

import numpy as np


class RegressionDataset:
    """Map-style dataset of (x, y=a*x+b+noise) pairs, torch-DataLoader compatible."""

    def __init__(self, a: float = 2.0, b: float = 3.0, length: int = 64, seed: int = 42):
        rng = np.random.default_rng(seed)
        self.length = length
        self.x = rng.normal(size=(length,)).astype(np.float32)
        self.y = (a * self.x + b + 0.05 * rng.normal(size=(length,))).astype(np.float32)

    def __len__(self) -> int:
        return self.length

    def __getitem__(self, i: int) -> dict[str, np.ndarray]:
        return {"x": self.x[i], "y": self.y[i]}


def regression_model_params(a: float = 0.0, b: float = 0.0) -> dict:
    return {"a": np.asarray([a], dtype=np.float32), "b": np.asarray([b], dtype=np.float32)}


def regression_apply_fn(params: dict, batch_x):
    return params["a"] * batch_x + params["b"]


def regression_loss_fn(model, batch):
    pred = model(batch["x"])
    return ((pred - batch["y"]) ** 2).mean()


def make_regression_batches(
    num_batches: int, batch_size: int, a: float = 2.0, b: float = 3.0, seed: int = 0
) -> list[dict[str, np.ndarray]]:
    """Pre-batched numpy data usable directly by DataLoaderShard."""
    ds = RegressionDataset(a=a, b=b, length=num_batches * batch_size, seed=seed)
    return [
        {
            "x": ds.x[i * batch_size : (i + 1) * batch_size],
            "y": ds.y[i * batch_size : (i + 1) * batch_size],
        }
        for i in range(num_batches)
    ]
