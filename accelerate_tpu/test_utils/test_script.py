"""Bundled end-to-end assertion script (reference
`test_utils/scripts/test_script.py`, 858 LoC — the master integration run by
`accelerate test` on any user box). Asserts, on whatever topology it finds:
RNG sync, dataloader sharding, training parity vs an independent baseline,
split_between_processes, collectives, and the early-stop trigger.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax


def check_dataloader() -> None:
    from ..data_loader import DataLoaderShard

    batches = [{"x": np.full((16, 2), float(i))} for i in range(3)]
    dl = DataLoaderShard(batches)
    seen = list(dl)
    assert len(seen) == 3
    assert isinstance(seen[0]["x"], jax.Array)
    assert dl.end_of_dataloader
    print("  dataloader sharding: OK")


def check_collectives() -> None:
    from ..utils import operations

    x = np.arange(8.0)
    out = operations.gather(x)
    np.testing.assert_array_equal(np.asarray(out), x)
    red = operations.reduce(np.ones((4,)), "sum")
    assert red.shape == (4,)
    print("  collectives: OK")


def check_training_parity() -> None:
    from ..accelerator import Accelerator
    from ..data_loader import DataLoaderShard
    from ..state import AcceleratorState, GradientState
    from .training import (
        make_regression_batches,
        regression_apply_fn,
        regression_loss_fn,
        regression_model_params,
    )

    AcceleratorState._reset_state()
    GradientState._reset_state()
    batches = make_regression_batches(6, 16)
    # independent single-device baseline
    params = {k: jnp.asarray(v) for k, v in regression_model_params().items()}
    for b in batches:
        b = {k: jnp.asarray(v) for k, v in b.items()}
        g = jax.grad(lambda p: ((p["a"] * b["x"] + p["b"] - b["y"]) ** 2).mean())(params)
        params = jax.tree.map(lambda p, g_: p - 0.1 * g_, params, g)

    acc = Accelerator()
    model, opt, dl = acc.prepare(
        (regression_apply_fn, regression_model_params()), optax.sgd(0.1), DataLoaderShard(batches)
    )
    for batch in dl:
        with acc.accumulate(model):
            acc.backward(regression_loss_fn, batch)
            opt.step()
            opt.zero_grad()
    got = acc.get_state_dict(model)
    np.testing.assert_allclose(np.asarray(got["a"]), np.asarray(params["a"]), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(got["b"]), np.asarray(params["b"]), rtol=1e-5)
    print("  distributed training parity: OK")


def check_split_between_processes() -> None:
    from ..state import PartialState

    state = PartialState()
    with state.split_between_processes(list(range(10))) as piece:
        assert len(piece) >= 10 // max(state.num_processes, 1) - 1
    print("  split_between_processes: OK")


def check_trigger() -> None:
    from ..accelerator import Accelerator

    acc = Accelerator()
    acc.set_trigger()
    assert acc.check_trigger()
    print("  early-stop trigger: OK")


def check_rng_sync() -> None:
    from ..utils.random import set_seed, synchronize_rng_states

    set_seed(1234)
    synchronize_rng_states()
    print("  RNG synchronization: OK")


def main() -> None:
    import jax

    print(f"Running accelerate-tpu sanity suite on {len(jax.devices())} device(s), "
          f"{jax.process_count()} process(es)")
    check_rng_sync()
    check_collectives()
    check_dataloader()
    check_split_between_processes()
    check_training_parity()
    check_trigger()


if __name__ == "__main__":
    main()
