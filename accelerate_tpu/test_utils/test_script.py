"""Bundled end-to-end assertion script (reference
`test_utils/scripts/test_script.py`, 858 LoC — the master integration run by
`accelerate test` on any user box). Covers the reference's assertion inventory
(`test_script.py:87-776`): rank-gated execution, RNG sync, shard + dispatcher
dataloader preparation across the (split_batches x even_batches x drop_last)
matrix, seedable-sampler epoch evolution, distributed-vs-single-process weight
equality (`:449-622`), mid-epoch checkpoint resume, split_between_processes
variants (`:623-742`), the early-stop trigger, and state reinstantiation.

Runs on whatever topology it finds (1..N processes, any device count); the
2-process-only launched scripts under `scripts/` are chained in automatically
when the topology matches (all except `test_performance`, the throughput
benchmark, which is not a correctness assertion).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

SEED = 0  # prepare_data_loader's default sampler seed — baselines recompute it


# --------------------------------------------------------- rank-gated execution
def check_process_execution() -> None:
    """Reference `process_execution_check` (`test_script.py:87-157`): the
    on_main/on_local_main/on_process gates fire on exactly the right ranks —
    verified globally via gather_object, not just locally."""
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils import operations

    state = PartialState()
    fired: list[str] = []

    @state.on_main_process
    def a() -> None:
        fired.append("main")

    @state.on_local_main_process
    def b() -> None:
        fired.append("local_main")

    @state.on_process(process_index=state.num_processes - 1)
    def c() -> None:
        fired.append("last")

    a(), b(), c()
    everywhere = operations.gather_object([sorted(fired)])
    expect_main = ["local_main", "main"] if state.num_processes > 1 else ["last", "local_main", "main"]
    assert everywhere[0] == sorted(expect_main), everywhere
    if state.num_processes > 1:
        assert "last" in everywhere[-1], everywhere
    # main_process_first: everyone eventually proceeds (ordering barrier works)
    with state.main_process_first():
        pass
    print("  rank-gated execution: OK")


def check_rng_sync() -> None:
    from accelerate_tpu.utils import operations
    from accelerate_tpu.utils.random import set_seed, synchronize_rng_states

    set_seed(1234)
    synchronize_rng_states()
    # sample from the GLOBAL numpy RNG — the state set_seed actually seeds —
    # so a broken sync/seed genuinely fails this check
    sample = np.random.normal(size=(4,)).tolist()
    gathered = operations.gather_object([sample])
    assert all(g == gathered[0] for g in gathered), "RNG out of sync across processes"
    set_seed(1234)
    assert np.random.normal(size=(4,)).tolist() == sample, "set_seed not reproducible"
    print("  RNG synchronization: OK")


# ----------------------------------------------------------- loader preparation
def _torch_regression_loader(n: int, batch_size: int, drop_last: bool, shuffle: bool):
    import torch
    import torch.utils.data as tud

    class DS(tud.Dataset):
        def __len__(self) -> int:
            return n

        def __getitem__(self, i: int):
            return {"x": torch.tensor([float(i)]), "idx": torch.tensor(i)}

    return tud.DataLoader(DS(), batch_size=batch_size, shuffle=shuffle, drop_last=drop_last)


def check_dl_preparation() -> None:
    """Reference `dl_preparation_check` (`test_script.py:186-245`): shard-mode
    loaders across (split_batches x even_batches x drop_last) reproduce the
    dataset exactly — order, padding placement, and drop semantics — on any
    process count (shuffle off, so the expected stream is computable)."""
    from accelerate_tpu.data_loader import prepare_data_loader
    from accelerate_tpu.state import PartialState

    state = PartialState()
    P = state.num_processes
    # the global batch must tile the mesh's data shards (device count), or the
    # loader wraps mid-stream to fill them — pick shard-aligned sizes with a
    # ragged tail on every topology
    gbs = max(4 * P, jax.device_count())
    bs = gbs // P
    n = 2 * gbs + max(gbs // 2, 1) + 1
    for split_batches in (False, True):
        for drop_last in (False, True):
            for even_batches in (True,) if P > 1 else (True, False):
                loader = _torch_regression_loader(
                    n, gbs if split_batches else bs, drop_last, shuffle=False
                )
                dl = prepare_data_loader(
                    loader,
                    split_batches=split_batches,
                    even_batches=even_batches,
                    use_seedable_sampler=False,
                )
                from accelerate_tpu.utils import operations

                got = np.concatenate([np.asarray(operations.gather(b["idx"])) for b in dl])
                tag = f"sb={split_batches} dl={drop_last} eb={even_batches}"
                if drop_last:
                    # split mode: torch drops the ragged global batch; round-robin
                    # mode additionally drops a trailing group of < P batches
                    kept = (n // gbs) * gbs if split_batches else ((n // bs) // P) * gbs
                    np.testing.assert_array_equal(got, np.arange(kept), err_msg=tag)
                else:
                    # every sample present, in order; wrapped duplicates only
                    # after the real data ends
                    np.testing.assert_array_equal(got[:n], np.arange(n), err_msg=tag)
                    assert len(got) % gbs == 0 or P == 1, (tag, len(got))
                assert dl.remainder in (-1, n % gbs), (tag, dl.remainder)

    # even_batches=False branches never run through prepare at P==1 (no shard
    # wrap) and would deadlock gathers at P>1 (uneven counts) — exercise the
    # sampler shard DIRECTLY, pure python, simulating a 4-process topology
    from accelerate_tpu.data_loader import BatchSamplerShard

    class _BS:
        batch_size, drop_last = 4, False

        def __iter__(self):
            yield from ([list(range(i, min(i + 4, 22))) for i in range(0, 22, 4)])

        def __len__(self):
            return 6

    for drop_last in (False, True):
        _BS.drop_last = drop_last
        per_proc = [
            list(BatchSamplerShard(_BS(), 4, p, split_batches=False, even_batches=False))
            for p in range(4)
        ]
        flat = [i for proc in per_proc for b in proc for i in b]
        if drop_last:
            # trailing group of 2 batches (< 4 processes) dropped whole
            assert sorted(flat) == list(range(16)), flat
            assert [len(p) for p in per_proc] == [1, 1, 1, 1], per_proc
        else:
            # no wrap, no padding: every index exactly once, ragged counts
            assert sorted(flat) == list(range(22)), flat
            assert [len(p) for p in per_proc] == [2, 2, 1, 1], per_proc
    print("  shard dataloader preparation (split x even x drop matrix): OK")


def check_central_dl_preparation() -> None:
    """Reference `central_dl_preparation_check` (`test_script.py:247-310`):
    dispatcher mode (process 0 reads, everyone slices) + gather_for_metrics
    returns exactly the dataset despite the ragged final batch."""
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.data_loader import DataLoaderDispatcher
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    state = PartialState()
    data = np.arange(27.0)
    batches = [data[i : i + 8] for i in range(0, 27, 8)]
    source = batches if state.is_main_process else []
    acc = Accelerator()
    dl = acc.prepare(DataLoaderDispatcher(source))
    seen = [np.asarray(acc.gather_for_metrics(b)) for b in dl]
    np.testing.assert_array_equal(np.concatenate(seen), data)
    print("  dispatcher dataloader + remainder-exact metrics: OK")


def check_seedable_sampler() -> None:
    """Reference `check_seedable_sampler` family (`test_script.py:358-429`):
    the same permutation on every process, a new one per epoch, reproducible
    from the seed."""
    from accelerate_tpu.data_loader import SeedableRandomSampler, prepare_data_loader
    from accelerate_tpu.utils import operations

    from accelerate_tpu.state import PartialState

    s = SeedableRandomSampler(16, seed=7)
    e0, e1 = list(s), list(s)  # iterating advances the epoch
    assert e0 != e1, "epochs must reshuffle"
    s2 = SeedableRandomSampler(16, seed=7)
    assert list(s2) == e0, "same seed+epoch must reproduce"
    # through a prepared torch loader: all processes see identical global order
    P = PartialState().num_processes
    gbs = max(4 * P, jax.device_count())
    n = 4 * gbs  # shard-aligned, no wrap
    loader = _torch_regression_loader(n, gbs // P, drop_last=False, shuffle=True)
    dl = prepare_data_loader(loader, use_seedable_sampler=True)
    order = np.concatenate([np.asarray(operations.gather(b["idx"])) for b in dl]).tolist()
    gathered = operations.gather_object([order])
    assert all(g == gathered[0] for g in gathered), "sampler out of sync"
    assert sorted(order) == list(range(n))
    print("  seedable sampler epoch evolution + cross-process sync: OK")


# ------------------------------------------------------------- training parity
def _global_batch_stream(n: int, gbs: int, epochs: int, seed: int = SEED):
    """The exact global batch stream a prepared seedable-sampler loader yields:
    per-epoch permutation from default_rng(seed + epoch), chunked by the global
    batch size (divisible n, so no wrap enters the parity run)."""
    for e in range(epochs):
        perm = np.random.default_rng(seed + e).permutation(n)
        for g in range(n // gbs):
            yield perm[g * gbs : (g + 1) * gbs]


def check_training_parity_matrix() -> None:
    """Reference `training_check` (`test_script.py:449-622`): training through
    the framework — sharded loader, global arrays, prepared optimizer — lands
    on exactly the weights of an independently computed single-process run,
    for split_batches False and True, multi-epoch (seedable re-shuffling)."""
    import torch
    import torch.utils.data as tud

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    state = PartialState()
    P = state.num_processes
    gbs = max(4 * P, jax.device_count())  # shard-aligned global batch
    bs, epochs, lr = gbs // P, 2, 0.1
    n = 4 * gbs
    rng = np.random.default_rng(3)
    xs = rng.normal(size=(n, 1)).astype(np.float32)
    ys = (3.0 * xs + 1.5).astype(np.float32)

    class DS(tud.Dataset):
        def __len__(self) -> int:
            return n

        def __getitem__(self, i: int):
            return {"x": torch.from_numpy(xs[i]), "y": torch.from_numpy(ys[i])}

    def apply_fn(p, x):
        return p["a"] * x + p["b"]

    def loss_fn(model, batch):
        return ((model(batch["x"]) - batch["y"]) ** 2).mean()

    for split_batches in (False, True):
        # independent single-process baseline over the known global stream
        params = {"a": jnp.zeros((1,)), "b": jnp.zeros((1,))}
        for idx in _global_batch_stream(n, gbs, epochs):
            bx, by = jnp.asarray(xs[idx]), jnp.asarray(ys[idx])
            g = jax.grad(lambda p: ((apply_fn(p, bx) - by) ** 2).mean())(params)
            params = jax.tree.map(lambda p_, g_: p_ - lr * g_, params, g)

        AcceleratorState._reset_state()
        GradientState._reset_state()
        acc = Accelerator(split_batches=split_batches)
        loader = tud.DataLoader(
            DS(), batch_size=gbs if split_batches else bs, shuffle=True, drop_last=False
        )
        model, opt, dl = acc.prepare(
            (apply_fn, {"a": np.zeros((1,), np.float32), "b": np.zeros((1,), np.float32)}),
            optax.sgd(lr),
            loader,
        )
        for _ in range(epochs):
            for batch in dl:
                with acc.accumulate(model):
                    acc.backward(loss_fn, batch)
                    opt.step()
                    opt.zero_grad()
        got = acc.get_state_dict(model)
        for k in ("a", "b"):
            np.testing.assert_allclose(
                np.asarray(got[k]), np.asarray(params[k]), rtol=1e-5, atol=1e-6,
                err_msg=f"split_batches={split_batches} param {k}",
            )
    print("  distributed == single-process weights (split_batches x epochs): OK")


def check_bf16_training() -> None:
    """Reference fp16/bf16 rows of `training_check` (`test_script.py:507-560`):
    mixed precision trains to finite, decreasing loss with fp32 master weights."""
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.data_loader import DataLoaderShard
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.test_utils.training import (
        make_regression_batches,
        regression_apply_fn,
        regression_loss_fn,
        regression_model_params,
    )

    AcceleratorState._reset_state()
    GradientState._reset_state()
    acc = Accelerator(mixed_precision="bf16")
    model, opt, dl = acc.prepare(
        (regression_apply_fn, regression_model_params()), optax.sgd(0.05),
        DataLoaderShard(make_regression_batches(8, 16)),
    )
    step = acc.make_train_step(regression_loss_fn)
    losses = [float(step(b)) for b in dl]
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
    assert jax.tree.leaves(model.params)[0].dtype == jnp.float32  # fp32 masters
    print("  bf16 mixed-precision training: OK")


def check_mid_epoch_resume() -> None:
    """Reference checkpointing role (`external_deps/test_checkpointing.py` +
    `skip_first_batches`): save at a mid-epoch boundary, restore into FRESH
    objects, resume with the tail of the epoch — bit-identical weights vs the
    uninterrupted run."""
    import tempfile

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.data_loader import DataLoaderShard, skip_first_batches
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.test_utils.training import (
        make_regression_batches,
        regression_apply_fn,
        regression_loss_fn,
        regression_model_params,
    )

    batches = make_regression_batches(6, 8)

    def fresh():
        AcceleratorState._reset_state()
        GradientState._reset_state()
        acc = Accelerator()
        model, opt = acc.prepare(
            (regression_apply_fn, regression_model_params()), optax.adam(0.05)
        )
        return acc, model, opt

    def run(acc, model, opt, dl):
        for b in dl:
            with acc.accumulate(model):
                acc.backward(regression_loss_fn, b)
                opt.step()
                opt.zero_grad()

    # uninterrupted
    acc, model, opt = fresh()
    run(acc, model, opt, DataLoaderShard(batches))
    want = jax.device_get(model.params)

    # interrupted after 3 batches + resumed in fresh objects. All processes
    # must address ONE checkpoint directory: process 0 picks it and broadcasts
    # (orbax coordinates the multi-process write under that path).
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils.operations import broadcast_object_list

    state = PartialState()
    payload = [tempfile.mkdtemp() if state.is_main_process else None]
    if state.num_processes > 1:
        broadcast_object_list(payload, from_process=0)
    td = payload[0]
    try:
        acc, model, opt = fresh()
        for i, b in enumerate(DataLoaderShard(batches)):
            if i == 3:
                break
            with acc.accumulate(model):
                acc.backward(regression_loss_fn, b)
                opt.step()
                opt.zero_grad()
        ckpt = acc.save_state(td + "/ck")

        acc2, model2, opt2 = fresh()
        acc2.load_state(ckpt)
        run(acc2, model2, opt2, skip_first_batches(DataLoaderShard(batches), 3))
        got = jax.device_get(model2.params)
    finally:
        state.wait_for_everyone()
        if state.is_main_process:
            import shutil

            shutil.rmtree(td, ignore_errors=True)
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    print("  mid-epoch checkpoint resume: OK")


# ------------------------------------------------------------------- utilities
def check_split_between_processes() -> None:
    """Reference `test_split_between_processes_{list,nested_dict,tensor,evenly}`
    (`test_script.py:656-742`)."""
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils import operations

    state = PartialState()
    P = state.num_processes
    # list: every element exactly once across processes
    with state.split_between_processes(list(range(10))) as piece:
        all_pieces = operations.gather_object([list(piece)])
    flat = [x for p in all_pieces for x in p]
    assert sorted(flat) == list(range(10)), flat
    # nested dict of equal-length sequences
    data = {"a": list(range(8)), "b": np.arange(8.0)}
    with state.split_between_processes(data) as piece:
        assert len(piece["a"]) == len(piece["b"])
    # tensor (array) slicing on dim 0
    with state.split_between_processes(np.arange(12.0).reshape(6, 2)) as piece:
        assert piece.shape[1] == 2
    # apply_padding: equal lengths everywhere
    with state.split_between_processes(list(range(P * 2 + 1)), apply_padding=True) as piece:
        lengths = operations.gather_object([len(piece)])
    assert len(set(lengths)) == 1, lengths
    print("  split_between_processes (list/dict/tensor/padded): OK")


def check_trigger() -> None:
    from accelerate_tpu.accelerator import Accelerator

    acc = Accelerator()
    acc.set_trigger()
    assert acc.check_trigger()
    assert not acc.check_trigger()  # reads reset the flag
    print("  early-stop trigger: OK")


def check_reinstantiated_state() -> None:
    """Reference `test_reinstantiated_state` (`test_script.py:760-773`): a
    reset + rebuilt AcceleratorState serves a working Accelerator."""
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    acc = Accelerator()
    assert acc.num_processes >= 1
    model = acc.prepare_model((lambda p, x: p["w"] * x, {"w": np.ones((1,), np.float32)}))
    out = model(jnp.ones((2, 1)))
    np.testing.assert_allclose(np.asarray(out), np.ones((2, 1)))
    print("  reinstantiated state: OK")


def check_collectives() -> None:
    from accelerate_tpu.utils import operations

    x = np.arange(8.0)
    out = np.asarray(operations.gather(x))
    # value-exact on any topology: the gathered result is N copies of x
    assert out.size % 8 == 0, out.shape
    np.testing.assert_array_equal(out.reshape(-1, 8), np.tile(x, (out.size // 8, 1)))
    red = operations.reduce(np.ones((4,)), "sum")
    assert red.shape == (4,)
    objs = operations.gather_object(["ping"])
    assert objs.count("ping") == len(objs)
    print("  collectives: OK")


def main() -> None:
    from accelerate_tpu.state import PartialState

    state = PartialState()
    print(
        f"Running accelerate-tpu sanity suite on {len(jax.devices())} device(s), "
        f"{state.num_processes} process(es)"
    )
    check_rng_sync()
    check_process_execution()
    check_collectives()
    check_dl_preparation()
    check_central_dl_preparation()
    check_seedable_sampler()
    check_split_between_processes()
    check_training_parity_matrix()
    check_bf16_training()
    check_mid_epoch_resume()
    check_trigger()
    check_reinstantiated_state()
    # 2-process launched assertion scripts chain in when the topology matches
    if state.num_processes == 2:
        from accelerate_tpu.test_utils.scripts import (
            test_checkpoint_resume,
            test_comm_hooks,
            test_dispatcher,
            test_merge_weights,
            test_multiprocess_ops,
            test_train_step,
        )

        import shutil
        import tempfile

        from accelerate_tpu.utils.operations import broadcast_object_list

        from accelerate_tpu.state import AcceleratorState, GradientState

        needs_workdir = (test_merge_weights, test_checkpoint_resume)
        for name, mod in (
            ("multiprocess ops", test_multiprocess_ops),
            ("fused train-step parity", test_train_step),
            ("dispatcher loop", test_dispatcher),
            ("merge weights", test_merge_weights),
            ("checkpoint resume", test_checkpoint_resume),
            ("comm hooks", test_comm_hooks),
        ):
            # each launched script assumes a fresh Accelerator singleton (they
            # normally run first thing in a new process pair)
            AcceleratorState._reset_state()
            GradientState._reset_state()
            if mod in needs_workdir:
                payload = [tempfile.mkdtemp() if state.is_main_process else None]
                broadcast_object_list(payload, from_process=0)
                try:
                    mod.run_checks(payload[0])
                finally:
                    state.wait_for_everyone()
                    if state.is_main_process:
                        shutil.rmtree(payload[0], ignore_errors=True)
            else:
                mod.run_checks()
            print(f"  launched-script chain [{name}]: OK")


if __name__ == "__main__":
    main()
