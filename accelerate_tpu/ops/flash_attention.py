"""Blockwise (flash) attention as a Pallas TPU kernel, with custom VJP.

Role in the framework: the reference delegates fused attention to external native
engines (Megatron fused kernels / TransformerEngine — SURVEY.md §2.4, §2.8); here
the hot op is a first-party TPU kernel. O(S) memory instead of the O(S^2) logits
buffer, so long-context training doesn't spill HBM.

Design (TPU-idiomatic, per /opt/skills/guides/pallas_guide.md):
  - grid = (batch, heads, q_blocks, kv_blocks); the *last* grid dim runs
    sequentially on a TensorCore, so the running max/denominator/accumulator live
    in VMEM scratch across kv-block iterations — no atomics, no reduction pass.
  - logits/softmax accumulate in fp32 (MXU output precision) while tensor blocks
    stay in the input dtype (bf16 on TPU).
  - causal masking skips fully-masked kv blocks via predication.
  - backward = two kernels (dq; dkv) re-streaming K/V and Q respectively against
    the saved logsumexp, plus an XLA-fused delta = rowsum(dO*O) preprocess.
  - on CPU (tests) the same kernels run under the Pallas interpreter.

Layouts are [batch, seq, heads, head_dim] at the API, transposed to
[batch, heads, seq, head_dim] internally so each (b, h) grid cell addresses a
contiguous [seq, head_dim] tile. Head dims stay NATIVE (64 for GPT-2-class
models): Mosaic lane-pads tiles in VMEM but the HBM traffic is the real 64
columns — the round-2 kernel zero-padded to 128 in HBM, which doubled every
Q/K/V/dO tensor's bytes AND the dot FLOPs (profiled at 30% of the train step).
The per-row log-sum-exp / delta tensors use an 8-lane broadcast [b, h, s, 8]
(the narrowest layout Mosaic tiles) instead of the previous 128-lane broadcast
— 1/16 the fp32 bytes (50 MB -> 3 MB per tensor at bench shapes).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _on_tpu() -> bool:
    from ..utils.environment import on_tpu_platform

    return on_tpu_platform()


# --------------------------------------------------------------------- forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr, l_scr, *, causal, block_q, block_kv, nkv):
    ik = pl.program_id(3)
    iq = pl.program_id(2)

    @pl.when(ik == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    # skip kv blocks entirely above the diagonal when causal
    run = (not causal) or (ik * block_kv <= iq * block_q + block_q - 1)

    @pl.when(run)
    def _():
        q = q_ref[0, 0]  # [block_q, d]
        k = k_ref[0, 0]  # [block_kv, d]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_kv]
        if causal:
            q_idx = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
            k_idx = ik * block_kv + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
            s = jnp.where(k_idx <= q_idx, s, NEG_INF)
        m_prev = m_scr[:, :1]  # [block_q, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)  # [block_q, block_kv]
        correction = jnp.exp(m_prev - m_new)  # [block_q, 1]
        l_new = correction * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc[:] = acc[:] * correction + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == nkv - 1)
    def _():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.broadcast_to(m_scr[:, :1] + jnp.log(safe_l), lse_ref.shape[2:])


def _fwd(q, k, v, causal, block_q, block_kv, interpret):
    b, h, sq, d = q.shape
    skv = k.shape[2]
    nq, nkv = sq // block_q, skv // block_kv
    grid = (b, h, nq, nkv)
    kernel = functools.partial(
        _fwd_kernel, causal=causal, block_q=block_q, block_kv=block_kv, nkv=nkv
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
            pl.BlockSpec((1, 1, block_kv, d), lambda b_, h_, iq, ik: (b_, h_, ik, 0)),
            pl.BlockSpec((1, 1, block_kv, d), lambda b_, h_, iq, ik: (b_, h_, ik, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
            pl.BlockSpec((1, 1, block_q, 8), lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 8), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


# -------------------------------------------------------------------- backward
def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc, *, causal, block_q, block_kv, nkv):
    ik = pl.program_id(3)
    iq = pl.program_id(2)

    @pl.when(ik == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = (not causal) or (ik * block_kv <= iq * block_q + block_q - 1)

    @pl.when(run)
    def _():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, :1]  # [block_q, 1] (8-lane broadcast storage)
        delta = delta_ref[0, 0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if causal:
            q_idx = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
            k_idx = ik * block_kv + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
            s = jnp.where(k_idx <= q_idx, s, NEG_INF)
        p = jnp.exp(s - lse)  # [block_q, block_kv]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ik == nkv - 1)
    def _():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, causal, block_q, block_kv, nq):
    iq = pl.program_id(3)  # sequential axis: q blocks
    ik = pl.program_id(2)

    @pl.when(iq == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = (not causal) or (ik * block_kv <= iq * block_q + block_q - 1)

    @pl.when(run)
    def _():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if causal:
            q_idx = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
            k_idx = ik * block_kv + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
            s = jnp.where(k_idx <= q_idx, s, NEG_INF)
        p = jnp.exp(s - lse)  # [block_q, block_kv]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(iq == nq - 1)
    def _():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(causal, block_q, block_kv, interpret, residuals, dout):
    q, k, v, out, lse = residuals
    b, h, sq, d = q.shape
    skv = k.shape[2]
    nq, nkv = sq // block_q, skv // block_kv
    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (b, h, sq, 8))  # 8-lane broadcast

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, block_q=block_q, block_kv=block_kv, nkv=nkv),
        grid=(b, h, nq, nkv),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
            pl.BlockSpec((1, 1, block_kv, d), lambda b_, h_, iq, ik: (b_, h_, ik, 0)),
            pl.BlockSpec((1, 1, block_kv, d), lambda b_, h_, iq, ik: (b_, h_, ik, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
            pl.BlockSpec((1, 1, block_q, 8), lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
            pl.BlockSpec((1, 1, block_q, 8), lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, dout, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, block_q=block_q, block_kv=block_kv, nq=nq),
        grid=(b, h, nkv, nq),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, ik, iq: (b_, h_, iq, 0)),
            pl.BlockSpec((1, 1, block_kv, d), lambda b_, h_, ik, iq: (b_, h_, ik, 0)),
            pl.BlockSpec((1, 1, block_kv, d), lambda b_, h_, ik, iq: (b_, h_, ik, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, ik, iq: (b_, h_, iq, 0)),
            pl.BlockSpec((1, 1, block_q, 8), lambda b_, h_, ik, iq: (b_, h_, iq, 0)),
            pl.BlockSpec((1, 1, block_q, 8), lambda b_, h_, ik, iq: (b_, h_, iq, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_kv, d), lambda b_, h_, ik, iq: (b_, h_, ik, 0)),
            pl.BlockSpec((1, 1, block_kv, d), lambda b_, h_, ik, iq: (b_, h_, ik, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_kv, d), jnp.float32),
            pltpu.VMEM((block_kv, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, dout, lse, delta)
    return dq, dk, dv


# ------------------------------------------- causal band (lower-triangle) grid
# For causal self-attention the rectangular grid wastes cells: above-diagonal
# blocks are predication-skipped but still fetched and iterated, and at
# s == block (one cell per (b, h)) half the computed logits are masked. This
# path enumerates ONLY the blocks inside the causal band into the last grid
# dimension, with block indices and first/last flags routed through
# scalar-prefetched maps (the splash-attention idiom). With window=None the
# band is the full lower triangle (T = nq(nq+1)/2 cells instead of nq^2, mask
# only on diagonal cells); with a sliding window W the band narrows to
# ~ceil(W/block)+1 cells per row, so compute scales with W, not seq^2 —
# Mistral-class sliding-window attention at native cost. Requires sq == skv
# and square blocks.


def _band_lo(iq: int, block: int, window: int | None) -> int:
    """Lowest kv block index row ``iq`` attends to (0 for pure causal)."""
    if window is None:
        return 0
    return max(0, (iq * block - window + 1) // block)


def _band_maps_row(nq: int, block: int, window: int | None):
    """Row-major band enumeration — kv index innermost so the fwd/dq
    accumulators run init(first-in-row) -> flush(last-in-row = diagonal)."""
    import numpy as np

    pairs = [
        (iq, ik) for iq in range(nq) for ik in range(_band_lo(iq, block, window), iq + 1)
    ]
    iqm = np.asarray([p[0] for p in pairs], np.int32)
    ikm = np.asarray([p[1] for p in pairs], np.int32)
    first = np.asarray(
        [1 if ik == _band_lo(iq, block, window) else 0 for iq, ik in pairs], np.int32
    )
    last = np.asarray([1 if ik == iq else 0 for iq, ik in pairs], np.int32)
    return iqm, ikm, first, last


def _band_maps_col(nq: int, block: int, window: int | None, groups: int = 1):
    """Column-major band enumeration for the dkv pass: for each kv column the
    sequential axis walks every (q-head-in-group, q-block) pair, so dk/dv
    accumulate in KV-HEAD shape with no cross-cell races even under GQA.
    init fires on the column's first pair, flush on its last."""
    import numpy as np

    pairs = [
        (g, iq, ik)
        for ik in range(nq)
        for g in range(groups)
        for iq in range(ik, nq)
        if ik >= _band_lo(iq, block, window)
    ]
    gm = np.asarray([p[0] for p in pairs], np.int32)
    iqm = np.asarray([p[1] for p in pairs], np.int32)
    ikm = np.asarray([p[2] for p in pairs], np.int32)
    cols = [p[2] for p in pairs]
    first = np.asarray(
        [1 if i == 0 or cols[i - 1] != cols[i] else 0 for i in range(len(pairs))], np.int32
    )
    last = np.asarray(
        [1 if i + 1 == len(pairs) or cols[i + 1] != cols[i] else 0 for i in range(len(pairs))],
        np.int32,
    )
    return iqm, ikm, gm, first, last


def _band_logits(q, k, iq, ik, block_q, block_kv, window):
    """QK^T for one band cell, masked per the causal(+window) rule — shared by
    all three band kernels so the masking cannot drift between forward and
    backward. Pure causal masks only diagonal cells; a sliding window also
    masks the low side (edge cells overhang the band by up to a block)."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    q_idx = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_idx = ik * block_kv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    if window is None:
        return jnp.where((ik == iq) & (k_idx > q_idx), NEG_INF, s)
    bad = (k_idx > q_idx) | (k_idx < q_idx - (window - 1))
    return jnp.where(bad, NEG_INF, s)


def _fwd_band_kernel(iqm, ikm, first, last, q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr, l_scr, *, block_q, block_kv, window):
    t = pl.program_id(2)
    iq, ik = iqm[t], ikm[t]

    @pl.when(first[t] == 1)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    s = _band_logits(q, k, iq, ik, block_q, block_kv, window)
    m_prev = m_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    correction = jnp.exp(m_prev - m_new)
    l_scr[:, :1] = correction * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
    acc[:] = acc[:] * correction + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(last[t] == 1)
    def _():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.broadcast_to(m_scr[:, :1] + jnp.log(safe_l), lse_ref.shape[2:])


def _dq_band_kernel(iqm, ikm, first, last, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc, *, block_q, block_kv, window):
    t = pl.program_id(2)
    iq, ik = iqm[t], ikm[t]

    @pl.when(first[t] == 1)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0][:, :1]
    delta = delta_ref[0, 0][:, :1]
    s = _band_logits(q, k, iq, ik, block_q, block_kv, window)
    p = jnp.exp(s - lse)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    dq_acc[:] += jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(last[t] == 1)
    def _():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_band_kernel(iqm, ikm, gm, first, last, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, block_q, block_kv, window):
    t = pl.program_id(2)
    iq, ik = iqm[t], ikm[t]

    @pl.when(first[t] == 1)  # first cell of this kv column
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0][:, :1]
    delta = delta_ref[0, 0][:, :1]
    s = _band_logits(q, k, iq, ik, block_q, block_kv, window)
    p = jnp.exp(s - lse)
    dv_acc[:] += jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    dk_acc[:] += jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(last[t] == 1)
    def _():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _band_grid_spec(n_cells, b, h, block_q, block_kv, d, n_in, out_specs, scratch_shapes, groups=1):
    """PrefetchScalarGridSpec over the linearized band; q-indexed inputs use
    iqm, kv-indexed use ikm (the four scalar-prefetch operands lead the kernel
    args). Under GQA (``groups`` > 1) the grid's head axis is the QUERY head
    and kv blocks come from head ``h // groups`` — K/V are never repeated in
    HBM. Scratch lives in the spec — pallas_call rejects it separately when a
    grid_spec is given."""
    q_spec = pl.BlockSpec(
        (1, 1, block_q, d), lambda b_, h_, t, iqm, ikm, first, last: (b_, h_, iqm[t], 0)
    )
    kv_spec = pl.BlockSpec(
        (1, 1, block_kv, d),
        lambda b_, h_, t, iqm, ikm, first, last: (b_, h_ // groups, ikm[t], 0),
    )
    row8 = pl.BlockSpec(
        (1, 1, block_q, 8), lambda b_, h_, t, iqm, ikm, first, last: (b_, h_, iqm[t], 0)
    )
    per_input = {"q": q_spec, "kv": kv_spec, "row8": row8}
    return pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(b, h, n_cells),
        in_specs=[per_input[kind] for kind in n_in],
        out_specs=out_specs,
        scratch_shapes=scratch_shapes,
    )


def _band_grid_spec_dkv(n_cells, b, hk, block, d, out_specs, scratch_shapes, groups=1):
    """dkv-pass grid spec: head axis is the KV head; q-side inputs come from
    query head ``h * groups + gm[t]`` (five scalar-prefetch operands)."""
    q_spec = pl.BlockSpec(
        (1, 1, block, d),
        lambda b_, h_, t, iqm, ikm, gm, first, last: (b_, h_ * groups + gm[t], iqm[t], 0),
    )
    kv_spec = pl.BlockSpec(
        (1, 1, block, d), lambda b_, h_, t, iqm, ikm, gm, first, last: (b_, h_, ikm[t], 0)
    )
    row8 = pl.BlockSpec(
        (1, 1, block, 8),
        lambda b_, h_, t, iqm, ikm, gm, first, last: (b_, h_ * groups + gm[t], iqm[t], 0),
    )
    per_input = {"q": q_spec, "kv": kv_spec, "row8": row8}
    return pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(b, hk, n_cells),
        in_specs=[per_input[kind] for kind in ["q", "kv", "kv", "q", "row8", "row8"]],
        out_specs=out_specs,
        scratch_shapes=scratch_shapes,
    )


def _q_out_spec(block, d):
    return pl.BlockSpec(
        (1, 1, block, d), lambda b_, h_, t, iqm, ikm, first, last: (b_, h_, iqm[t], 0)
    )


def _kv_out_spec_dkv(block, d):
    return pl.BlockSpec(
        (1, 1, block, d), lambda b_, h_, t, iqm, ikm, gm, first, last: (b_, h_, ikm[t], 0)
    )


def _fwd_band(q, k, v, block, window, interpret):
    b, h, sq, d = q.shape
    groups = h // k.shape[1]
    nq = sq // block
    maps = _band_maps_row(nq, block, window)
    grid_spec = _band_grid_spec(
        len(maps[0]), b, h, block, block, d, ["q", "kv", "kv"],
        [
            _q_out_spec(block, d),
            pl.BlockSpec(
                (1, 1, block, 8), lambda b_, h_, t, iqm, ikm, first, last: (b_, h_, iqm[t], 0)
            ),
        ],
        [
            pltpu.VMEM((block, d), jnp.float32),
            pltpu.VMEM((block, 128), jnp.float32),
            pltpu.VMEM((block, 128), jnp.float32),
        ],
        groups=groups,
    )
    out, lse = pl.pallas_call(
        functools.partial(_fwd_band_kernel, block_q=block, block_kv=block, window=window),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 8), jnp.float32),
        ],
        interpret=interpret,
    )(*maps, q, k, v)
    return out, lse


def _bwd_band(block, window, interpret, residuals, dout):
    q, k, v, out, lse = residuals
    b, h, sq, d = q.shape
    hk = k.shape[1]
    groups = h // hk
    nq = sq // block
    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (b, h, sq, 8))

    maps = _band_maps_row(nq, block, window)
    dq = pl.pallas_call(
        functools.partial(_dq_band_kernel, block_q=block, block_kv=block, window=window),
        grid_spec=_band_grid_spec(
            len(maps[0]), b, h, block, block, d,
            ["q", "kv", "kv", "q", "row8", "row8"],
            _q_out_spec(block, d),
            [pltpu.VMEM((block, d), jnp.float32)],
            groups=groups,
        ),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(*maps, q, k, v, dout, lse, delta)

    maps2 = _band_maps_col(nq, block, window, groups)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_band_kernel, block_q=block, block_kv=block, window=window),
        grid_spec=_band_grid_spec_dkv(
            len(maps2[0]), b, hk, block, d,
            [_kv_out_spec_dkv(block, d), _kv_out_spec_dkv(block, d)],
            [
                pltpu.VMEM((block, d), jnp.float32),
                pltpu.VMEM((block, d), jnp.float32),
            ],
            groups=groups,
        ),
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=interpret,
    )(*maps2, q, k, v, dout, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_band(q, k, v, block, window, interpret):
    out, _ = _fwd_band(q, k, v, block, window, interpret)
    return out


def _flash_band_fwd(q, k, v, block, window, interpret):
    out, lse = _fwd_band(q, k, v, block, window, interpret)
    return out, (q, k, v, out, lse)


_flash_band.defvjp(_flash_band_fwd, _bwd_band)


# ------------------------------------------------------------------ public API
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_kv, interpret):
    out, _ = _fwd(q, k, v, causal, block_q, block_kv, interpret)
    return out


def _flash_fwd(q, k, v, causal, block_q, block_kv, interpret):
    out, lse = _fwd(q, k, v, causal, block_q, block_kv, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_kv, interpret, residuals, dout):
    return _bwd(causal, block_q, block_kv, interpret, residuals, dout)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _env_block(name: str, default: int) -> int:
    from ..utils.environment import parse_int_from_env

    return parse_int_from_env(name, default)


def band_block_default(sq: int) -> int | None:
    """Default band-grid block for a causal/windowed seq: the largest divisor
    of ``sq`` that is <= 512 (one tiling policy for the kernel and the
    dispatcher's auto routing). None when the best divisor is < 8 — a band
    grid that narrow (e.g. prime sq) degenerates to pathological 1-wide tiles."""
    best = next(b for b in range(min(512, sq), 0, -1) if sq % b == 0)
    return best if best >= 8 else None


def flash_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: float | None = None,
    block_q: int | None = None,
    block_kv: int | None = None,
    triangle_block: int | None = None,
    window: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Flash attention over [batch, seq, heads, head_dim] inputs.

    Sequence lengths must divide the (auto-shrunk) block sizes. head_dim is
    used NATIVELY when it is a multiple of the 8-sublane width (64 for GPT-2
    class models — Mosaic lane-pads in VMEM, HBM moves only real bytes);
    other head dims are zero-padded up to the next multiple of 128.

    ``triangle_block`` (or env ``ACCELERATE_TPU_FLASH_TRIANGLE=<block>``)
    switches causal self-attention onto the band grid: only blocks inside the
    causal band exist as grid cells, halving attention FLOPs/fetches at large
    seq vs the rectangular grid's predication skip. ``window=W`` (sliding
    window: query i attends to keys in (i-W, i]) narrows the band so compute
    scales with W rather than seq — Mistral-class attention; it requires the
    band grid (``triangle_block``/env, defaulting to 512 when only ``window``
    is given).
    """
    b, sq, hn, d = q.shape
    skv = k.shape[1]
    if interpret is None:
        interpret = not _on_tpu()
    scale = 1.0 / math.sqrt(d) if scale is None else scale
    if window is not None:
        if not causal or sq != skv:
            raise ValueError(
                "window applies only to causal self-attention (sq == skv); "
                f"got causal={causal}, sq={sq}, skv={skv}"
            )
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if triangle_block is None:
            triangle_block = _env_block("ACCELERATE_TPU_FLASH_TRIANGLE", 0) or None
            if triangle_block is None:
                best = band_block_default(sq)
                if best is None:  # e.g. prime sq: a 1-wide band grid is pathological
                    raise ValueError(
                        f"window={window} needs a band grid, but seq {sq} has no "
                        "block divisor >= 8. Pad the sequence to a tileable "
                        "length, pass triangle_block explicitly (or via "
                        "ACCELERATE_TPU_FLASH_TRIANGLE), or use "
                        "implementation='xla'."
                    )
                triangle_block = best
    # An EXPLICIT triangle_block is a strict request: reject configurations it
    # cannot serve rather than silently measuring the rectangular kernel. The
    # env knob is a global default (cross-attention in the same model must
    # still work), so it falls back silently instead.
    if triangle_block is not None:
        if not causal or sq != skv:
            raise ValueError(
                "triangle_block applies only to causal self-attention (sq == skv); "
                f"got causal={causal}, sq={sq}, skv={skv}"
            )
        if block_q is not None or block_kv is not None:
            raise ValueError("triangle_block and block_q/block_kv are mutually exclusive")
        if sq % min(triangle_block, sq):
            raise ValueError(
                f"triangle_block {triangle_block} must divide seq {sq}"
            )
    else:
        triangle_block = _env_block("ACCELERATE_TPU_FLASH_TRIANGLE", 0) or None

    hk = k.shape[2]
    if hn != hk and (hk == 0 or hn % hk):
        raise ValueError(f"q heads ({hn}) must be a multiple of kv heads ({hk})")

    qt = jnp.transpose(q, (0, 2, 1, 3)) * jnp.asarray(scale, q.dtype)
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    d_pad = 0 if d % 64 == 0 else (128 - d % 128) % 128
    if d_pad:
        pad = [(0, 0), (0, 0), (0, 0), (0, d_pad)]
        qt, kt, vt = jnp.pad(qt, pad), jnp.pad(kt, pad), jnp.pad(vt, pad)

    if causal and triangle_block and sq == skv and sq % min(triangle_block, sq) == 0:
        # GQA runs natively on the band grid: kv blocks are fetched from head
        # h // groups, so K/V are never repeated in HBM and dk/dv come back in
        # kv-head shape
        out = _flash_band(qt, kt, vt, min(triangle_block, sq), window, interpret)
    else:
        if hn != hk:
            groups = hn // hk
            kt = jnp.repeat(kt, groups, axis=1)
            vt = jnp.repeat(vt, groups, axis=1)
        # Block defaults are env-tunable for sweeps (ACCELERATE_TPU_FLASH_BLOCK_*).
        # 1024×1024 won the round-3 sweep (docs/PERF_NOTES.md): at s<=1024 the
        # whole (b,h) attention runs in ONE grid cell, and the [block_q, block_kv]
        # fp32 logits tile (4 MB) still fits VMEM comfortably; longer sequences
        # fall back to 1024-wide tiles.
        block_q = _env_block("ACCELERATE_TPU_FLASH_BLOCK_Q", 1024) if block_q is None else block_q
        block_kv = _env_block("ACCELERATE_TPU_FLASH_BLOCK_KV", 1024) if block_kv is None else block_kv
        block_q = min(block_q, sq)
        block_kv = min(block_kv, skv)
        if sq % block_q or skv % block_kv:
            raise ValueError(
                f"seq lengths ({sq}, {skv}) must divide block sizes ({block_q}, {block_kv})"
            )
        out = _flash(qt, kt, vt, causal, block_q, block_kv, interpret)
    if d_pad:
        out = out[..., :d]
    return jnp.transpose(out, (0, 2, 1, 3))


# ------------------------------------------------- paged decode (serving)
def _paged_decode_kernel(
    tables, lengths, q_ref, k_ref, v_ref, *rest,
    block_tokens, span, scale, groups, exact,
):
    """One grid cell = (slot row, table block j). The block axis is LAST —
    sequential on a TensorCore — so the K/V blocks the table names accumulate
    in VMEM scratch across iterations and the flush at the final block runs
    the whole single-query attention for ALL heads in one pass: fp32 QK^T,
    scale after the dot, finfo.min frontier mask, global-max softmax, PV.
    K/V blocks stream straight from the pool through the scalar-prefetched
    block table — nothing is materialized in HBM.

    ``exact`` (interpret mode, CPU CI) computes the flush with the head axis
    BATCHED using the same `dot_general` dimension_numbers the gather
    oracle's two einsums lower to. XLA's CPU emitter is invariant to the
    batch extent but NOT to degenerate (size-1) batch dims — a per-head
    formulation differs by ~1 ulp — so keeping heads batched makes the fused
    path bit-identical to `dot_product_attention` over the gathered view,
    which is the parity bar the serving tests hold (docs/serving.md). On TPU
    the flush unrolls per head into MXU-friendly 2-D dots instead.

    An int8 pool rides two extra refs — the fp32 absmax scale planes
    (``[1, block_tokens, kv_heads]`` per block) — and each block dequantizes
    AT STAGING into the fp32 VMEM scratch (value × scale, round-tripped
    through the compute dtype exactly like the gather oracle's `_dq`), so the
    quantized pool is never materialized at full precision in HBM and the
    flush math below is byte-for-byte the same in both modes."""
    if len(rest) == 5:
        ks_ref, vs_ref, o_ref, k_scr, v_scr = rest
    else:
        ks_ref = vs_ref = None
        o_ref, k_scr, v_scr = rest
    b_ = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    length = lengths[b_]  # valid kv span for this row (frontier cursor + 1)
    window = pl.ds(j * block_tokens, block_tokens)

    @pl.when(j * block_tokens < length)
    def _():
        if ks_ref is None:
            k_scr[window] = k_ref[0].astype(jnp.float32)  # [bt, kv_heads, d]
            v_scr[window] = v_ref[0].astype(jnp.float32)
        else:
            cdt = q_ref.dtype
            k_scr[window] = (k_ref[0].astype(jnp.float32)
                             * ks_ref[0][..., None]).astype(cdt).astype(jnp.float32)
            v_scr[window] = (v_ref[0].astype(jnp.float32)
                             * vs_ref[0][..., None]).astype(cdt).astype(jnp.float32)

    @pl.when(j * block_tokens >= length)
    def _():
        # past-frontier blocks (incl. clamped sentinel table entries): every
        # position is re-masked at the flush, but the rows must be finite —
        # a stale NaN would poison the 0-weight products
        zeros = jnp.zeros((block_tokens,) + k_scr.shape[1:], jnp.float32)
        k_scr[window] = zeros
        v_scr[window] = zeros

    @pl.when(j == nj - 1)
    def _():
        hq, d = q_ref.shape[1], q_ref.shape[2]
        kvh = k_scr.shape[1]
        neg = jnp.finfo(jnp.float32).min
        if exact:
            q4 = q_ref[...].astype(jnp.float32).reshape(1, 1, hq, d)  # [b,q,h,d]
            k4 = k_scr[...].reshape(1, span, kvh, d)  # [b,k,h,d]
            v4 = v_scr[...].reshape(1, span, kvh, d)
            if groups > 1:
                # attention() repeats kv heads before the xla path; mirror it
                k4 = jnp.repeat(k4, groups, axis=2)
                v4 = jnp.repeat(v4, groups, axis=2)
            s = jax.lax.dot_general(
                q4, k4, (((3,), (3,)), ((0, 2), (0, 2))),
                preferred_element_type=jnp.float32,
            )  # [1, h, 1, span] — einsum "bqhd,bkhd->bhqk"
            s = s * scale
            pos = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, span), 3)
            s = jnp.where(pos < length, s, neg)
            m = jnp.max(s, axis=-1, keepdims=True)
            p = jnp.exp(s - m)
            w = p / jnp.sum(p, axis=-1, keepdims=True)
            # einsum "bhqk,bkhd->bqhd" lowers with v as the LHS:
            # dot_general(v, w, (([1],[3]), ([0,2],[0,1]))) -> [b,h,d,q]
            o = jax.lax.dot_general(
                v4, w, (((1,), (3,)), ((0, 2), (0, 1))),
                preferred_element_type=jnp.float32,
            )  # [1, h, d, 1]
            o_ref[0] = jnp.transpose(o, (0, 3, 1, 2)).reshape(hq, d).astype(o_ref.dtype)
        else:
            for hh in range(hq):
                q2 = q_ref[0, hh].astype(jnp.float32).reshape(1, d)
                k2 = k_scr[:, hh // groups, :]  # [span, d]
                s = jax.lax.dot_general(
                    q2, k2, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) * scale  # [1, span]
                pos = jax.lax.broadcasted_iota(jnp.int32, (1, span), 1)
                s = jnp.where(pos < length, s, neg)
                m = jnp.max(s, axis=-1, keepdims=True)
                p = jnp.exp(s - m)
                w = p / jnp.sum(p, axis=-1, keepdims=True)
                o = jax.lax.dot_general(
                    w, v_scr[:, hh // groups, :], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )  # [1, d]
                o_ref[0, hh] = o.reshape(d).astype(o_ref.dtype)


def paged_decode_attention(
    q: jax.Array,  # [b, n_heads, head_dim] — ONE decode query per slot row
    k_pool: jax.Array,  # [num_blocks, block_tokens, kv_heads, head_dim]
    v_pool: jax.Array,
    block_tables: jax.Array,  # [b, blocks_per_slot] int32 pool block ids
    lengths: jax.Array,  # [b] int32 valid kv positions (frontier cursor + 1)
    *,
    k_scale_pool: jax.Array | None = None,  # [num_blocks, block_tokens, kv_heads]
    v_scale_pool: jax.Array | None = None,  # fp32 absmax planes (int8 pool)
    scale: float | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Single-query paged attention that reads K/V blocks IN PLACE from the
    per-layer block pool (`models/kv_cache.py` `paged_decode_write`) — the
    fused replacement for the serving engine's ``pool[table]`` gather, which
    materializes a contiguous ``[b, span, heads, head_dim]`` copy per layer
    per decode step.

    Row ``i`` attends positions ``0..lengths[i]-1`` of its logical sequence;
    position ``p`` lives in pool block ``block_tables[i, p // block_tokens]``
    at offset ``p % block_tokens`` (the paged admission/decode layout).
    Table entries at or past the pool size (the engine's released-slot
    sentinel) are clamped to a real block id — every position they could
    contribute is past the frontier and masked. GQA pools read kv head
    ``h // (n_heads // kv_heads)`` directly; K/V are never repeated in HBM.

    VMEM cost per slot-row cell is ``2 * span * kv_heads * head_dim`` fp32 —
    the attended K/V span lives in scratch so the flush runs a single
    global-max softmax, bit-identical to the XLA gather oracle under the
    interpreter (`docs/serving.md` "Fused paged decode"); spans beyond a few
    thousand tokens should stay on the gather path until an online-softmax
    variant exists. Returns ``[b, n_heads, head_dim]`` in ``q.dtype``. On
    CPU (tests/CI) runs under the Pallas interpreter.

    An int8 pool (`kv_cache_dtype=int8` paged serving) passes its fp32 absmax
    planes as ``k_scale_pool``/``v_scale_pool`` (``[num_blocks, block_tokens,
    kv_heads]``, addressed through the same block table); each block is
    dequantized in VMEM scratch at staging time, so the quantized pool is
    never materialized at full precision."""
    b, hq, d = q.shape
    num_blocks, block_tokens, kvh, dk = k_pool.shape
    if dk != d:
        raise ValueError(f"q head_dim {d} != pool head_dim {dk}")
    if hq % kvh:
        raise ValueError(f"q heads ({hq}) must be a multiple of kv heads ({kvh})")
    if (k_scale_pool is None) != (v_scale_pool is None):
        raise ValueError("k_scale_pool and v_scale_pool must be passed together")
    quant = k_scale_pool is not None
    if quant and k_scale_pool.shape != (num_blocks, block_tokens, kvh):
        raise ValueError(
            f"scale pool shape {k_scale_pool.shape} != "
            f"{(num_blocks, block_tokens, kvh)} (per-block absmax planes)"
        )
    groups = hq // kvh
    bps = block_tables.shape[1]
    span = bps * block_tokens
    if interpret is None:
        interpret = not _on_tpu()
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    # released slots park their whole table at the sentinel id num_blocks;
    # clamp to a real block (fully frontier-masked) so the index map never
    # reads out of range
    tables = jnp.minimum(block_tables.astype(jnp.int32), num_blocks - 1)
    lengths = lengths.astype(jnp.int32)

    in_specs = [
        pl.BlockSpec((1, hq, d), lambda b_, j, t, l: (b_, 0, 0)),
        pl.BlockSpec(
            (1, block_tokens, kvh, d),
            lambda b_, j, t, l: (t[b_, j], 0, 0, 0),
        ),
        pl.BlockSpec(
            (1, block_tokens, kvh, d),
            lambda b_, j, t, l: (t[b_, j], 0, 0, 0),
        ),
    ]
    inputs = [tables, lengths, q, k_pool, v_pool]
    if quant:
        # the scale planes page in through the same block-table index map as
        # their payload blocks, one [block_tokens, kv_heads] plane per cell
        in_specs += [
            pl.BlockSpec(
                (1, block_tokens, kvh),
                lambda b_, j, t, l: (t[b_, j], 0, 0),
            ),
            pl.BlockSpec(
                (1, block_tokens, kvh),
                lambda b_, j, t, l: (t[b_, j], 0, 0),
            ),
        ]
        inputs += [k_scale_pool.astype(jnp.float32),
                   v_scale_pool.astype(jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, bps),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, hq, d), lambda b_, j, t, l: (b_, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((span, kvh, d), jnp.float32),
            pltpu.VMEM((span, kvh, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_decode_kernel, block_tokens=block_tokens, span=span, scale=scale,
        groups=groups, exact=bool(interpret),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hq, d), q.dtype),
        interpret=interpret,
    )(*inputs)
