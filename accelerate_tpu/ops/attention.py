"""Attention ops.

The compute core the reference delegates to external engines (Megatron fused
kernels, TransformerEngine) is implemented here natively for TPU:

  - ``dot_product_attention``: XLA path — einsum QK^T -> masked softmax -> PV.
    XLA fuses the elementwise chain into the matmuls; with bf16 inputs both
    matmuls tile straight onto the MXU. Good to ~4k sequence.
  - a Pallas flash/splash kernel lives in `ops/flash_attention.py` (blockwise,
    O(seq) memory) and is selected automatically for long sequences on TPU.
  - ring attention for sequence-parallel meshes lives in
    `parallel/ring_attention.py` (ppermute KV rotation over ICI).

All functions take [batch, seq, heads, head_dim] ("BSHD") layouts — the layout
that keeps the head dim contiguous in lane registers on TPU.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def causal_mask(q_len: int, kv_len: int, dtype=jnp.float32, offset: int = 0) -> jax.Array:
    """Additive causal mask [q_len, kv_len]; query i attends to keys <= i+offset."""
    q_idx = jnp.arange(q_len)[:, None]
    k_idx = jnp.arange(kv_len)[None, :]
    allowed = k_idx <= (q_idx + offset)
    return jnp.where(allowed, 0.0, jnp.finfo(dtype).min).astype(dtype)


def dot_product_attention(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Sk, H, D]
    v: jax.Array,  # [B, Sk, H, D]
    bias: jax.Array | None = None,
    mask: jax.Array | None = None,  # boolean [B, 1|H, Sq, Sk] or [Sq, Sk], True=keep
    causal: bool = False,
    window: int | None = None,  # sliding window: query i sees keys in (i-W, i]
    scale: float | None = None,
    dropout_rate: float = 0.0,
    dropout_rng: jax.Array | None = None,
    dtype=None,
) -> jax.Array:
    """Plain XLA attention. Softmax accumulates in fp32 regardless of input dtype
    (bf16 logits lose too much range), output returns to the input dtype."""
    orig_dtype = q.dtype
    d = q.shape[-1]
    scale = 1.0 / math.sqrt(d) if scale is None else scale
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if causal:
        logits = logits + causal_mask(q.shape[1], k.shape[1])[None, None, :, :]
    if window is not None:
        if not causal:
            raise ValueError(
                "window requires causal=True (one rule across xla and flash paths; "
                "a low-side-only band would silently attend future keys)"
            )
        q_idx = jnp.arange(q.shape[1])[:, None]
        k_idx = jnp.arange(k.shape[1])[None, :]
        in_band = k_idx > q_idx - window
        logits = jnp.where(in_band[None, None], logits, jnp.finfo(jnp.float32).min)
    if mask is not None:
        if mask.ndim == 2:
            mask = mask[None, None, :, :]
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    weights = jax.nn.softmax(logits, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, weights.shape)
        weights = jnp.where(keep, weights / (1.0 - dropout_rate), 0.0)
    weights = weights.astype(orig_dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


def _tp_shard_map(flash_fn, q, k):
    """Under a live tensor-parallel mesh, run the Pallas kernel per head shard
    via shard_map: XLA cannot partition a custom call, so without this it
    all-gathers the sharded activations and computes attention replicated on
    every device — correct but O(tp) redundant. Returns None when no TP mesh
    is active or head counts don't divide the axis (caller runs unwrapped)."""
    from ..parallel.mesh import active_batch_axes, inside_shard_map
    from ..state import AcceleratorState

    if "mesh" not in AcceleratorState._shared_state:  # initialized check only:
        return None  # a bare truthiness test could side-effect-init the singleton
    mesh = AcceleratorState().mesh
    tp = mesh.shape.get("tensor", 1)
    if tp <= 1:
        return None
    if inside_shard_map(mesh):
        return None  # already per-shard (pipeline/ring region): nesting would fail
    hq, hk = q.shape[2], k.shape[2]
    if hq % tp or hk % tp:
        return None  # heads don't divide the axis (contiguous sharding keeps
        # whole GQA groups per shard whenever both counts divide)
    from jax import shard_map

    batch_axes = active_batch_axes(mesh)
    batch_div = math.prod(mesh.shape[a] for a in batch_axes) if batch_axes else 1
    if q.shape[0] % batch_div:
        return None  # e.g. batch-1 eval: keep the replicated (correct) path
    spec = P(batch_axes if batch_axes else None, None, "tensor", None)
    return shard_map(
        flash_fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    mask: jax.Array | None = None,
    window: int | None = None,
    implementation: str = "auto",
    block_q: int | None = None,
    block_kv: int | None = None,
) -> jax.Array:
    """Dispatching entry point: 'xla' | 'flash' | 'auto'.

    'auto' picks the Pallas flash kernel on TPU for sequences where the
    O(S^2) logits buffer dominates HBM traffic, else the fused XLA path.
    ``window`` is Mistral-class sliding-window attention: on the flash path it
    runs on the band grid (compute scales with the window, not seq^2).
    """
    if k.shape[2] != q.shape[2] and (k.shape[2] == 0 or q.shape[2] % k.shape[2]):
        raise ValueError(
            f"q heads ({q.shape[2]}) must be a multiple of kv heads ({k.shape[2]})"
        )
    if mask is not None and implementation != "xla":
        # the flash kernel has no arbitrary-mask support; computing over the
        # masked positions would be silently wrong, so masked calls take the
        # XLA path regardless of the requested implementation
        implementation = "xla"
    if implementation == "auto":
        from ..utils.environment import on_tpu_platform

        on_tpu = on_tpu_platform()
        implementation = "flash" if (on_tpu and q.shape[1] >= 1024 and q.shape[1] == k.shape[1]) else "xla"
        if window is not None and implementation == "flash":
            # the band grid needs a block divisor of seq; un-tileable lengths
            # (e.g. prime) would raise in the kernel — auto routes them to xla
            from .flash_attention import band_block_default

            if band_block_default(q.shape[1]) is None:
                implementation = "xla"
    if implementation == "flash":
        from .flash_attention import flash_attention

        # GQA K/V pass through unrepeated — the band grid reads kv head
        # h // groups directly; the rectangular path repeats internally
        flash = partial(
            flash_attention, causal=causal, window=window, block_q=block_q, block_kv=block_kv
        )
        wrapped = _tp_shard_map(flash, q, k)
        if wrapped is not None:
            return wrapped(q, k, v)
        return flash(q, k, v)
    if k.shape[2] != q.shape[2]:
        groups = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, groups, axis=2)
        v = jnp.repeat(v, groups, axis=2)
    return dot_product_attention(q, k, v, causal=causal, mask=mask, window=window)
