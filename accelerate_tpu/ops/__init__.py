"""TPU-native compute ops: attention (XLA + Pallas flash), MoE, fp8 matmul."""

from .attention import attention, causal_mask, dot_product_attention  # noqa: F401
from .fp8 import (  # noqa: F401
    DelayedScalingRecipe,
    Fp8Dense,
    convert_dense_to_fp8,
    fp8_dot,
    quantize_dequantize,
)
from .moe import MoEConfig, MoEMLP, collect_aux_losses, moe_sharding_rules  # noqa: F401
