"""Fused LM-head + cross-entropy as a Pallas TPU kernel, with custom VJP.

Role: the profiled train step (docs/PERF_NOTES.md) spends ~15% on the LM head
and CE softmax over the [tokens, vocab] fp32 logits — written, re-read by
log-softmax, and re-materialized in the backward. This kernel streams vocab
tiles flash-attention-style: for each row chunk the logits tile lives only in
VMEM, reduced online to (logsumexp, label-logit); the backward recomputes tiles
against the saved lse. The full logits tensor never exists in HBM, and unlike
the `lax.scan` chunked CE (`models.gpt2.chunked_cross_entropy`) there is no
serialized scan carry — row chunks run as parallel grid cells.

Design (pallas_guide.md idioms):
  - grid = (row_chunks, vocab_chunks); vocab is the last (sequential) dim so
    the running max / sum / label-logit live in VMEM scratch.
  - logits accumulate in fp32 via the MXU (preferred_element_type); the label
    gather is a one-hot compare-and-reduce on the VPU (no dynamic indexing).
  - vocab padded to the tile width; padded columns masked to -inf statically.
  - per-row outputs stored 8-lane broadcast ([N, 8]) — narrowest Mosaic tile.
  - backward = two kernels: dH (rows parallel, vocab sequential) and dW
    (vocab parallel, rows sequential), both recomputing p = exp(logits - lse).
  - block_v default 1024: at 2048 the backward's per-cell working set
    (double-buffered [block_v, e] weight tile + the fused logits/p/dlogits
    intermediates) was measured by Mosaic at 18.68 MiB — over the 16 MiB
    scoped-VMEM limit on v5e at e=768. HBM traffic is unchanged by block_v
    (the full vocab streams once per row chunk either way).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _on_tpu() -> bool:
    from ..utils.environment import on_tpu_platform

    return on_tpu_platform()


# --------------------------------------------------------------------- forward
def _fwd_kernel(h_ref, w_ref, lab_ref, lse_ref, ll_ref, m_scr, l_scr, ll_scr, *, vocab, block_v, nv):
    jv = pl.program_id(1)

    @pl.when(jv == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        ll_scr[:] = jnp.zeros_like(ll_scr)

    h = h_ref[...]  # [R, e]
    w = w_ref[...]  # [block_v, e]
    logits = jax.lax.dot_general(
        h, w, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [R, block_v]
    col = jv * block_v + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    logits = jnp.where(col < vocab, logits, NEG_INF)
    lab = lab_ref[...][:, :1]  # [R, 1]
    ll_scr[:, :1] += jnp.sum(jnp.where(col == lab, logits, 0.0), axis=-1, keepdims=True)
    m_prev = m_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1, keepdims=True))
    l_scr[:, :1] = l_scr[:, :1] * jnp.exp(m_prev - m_new) + jnp.sum(
        jnp.exp(logits - m_new), axis=-1, keepdims=True
    )
    m_scr[:, :1] = m_new

    @pl.when(jv == nv - 1)
    def _():
        safe_l = jnp.where(l_scr[:, :1] == 0.0, 1.0, l_scr[:, :1])
        lse_ref[...] = jnp.broadcast_to(m_scr[:, :1] + jnp.log(safe_l), lse_ref.shape)
        ll_ref[...] = jnp.broadcast_to(ll_scr[:, :1], ll_ref.shape)


def _dh_kernel(h_ref, w_ref, lab_ref, lse_ref, glse_ref, gll_ref, dh_ref, dh_scr, *, vocab, block_v, nv):
    jv = pl.program_id(1)

    @pl.when(jv == 0)
    def _():
        dh_scr[:] = jnp.zeros_like(dh_scr)

    h = h_ref[...]
    w = w_ref[...]
    logits = jax.lax.dot_general(h, w, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    col = jv * block_v + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    logits = jnp.where(col < vocab, logits, NEG_INF)
    p = jnp.exp(logits - lse_ref[...][:, :1])
    lab = lab_ref[...][:, :1]
    dlogits = glse_ref[...][:, :1] * p + gll_ref[...][:, :1] * (col == lab)
    dh_scr[:] += jax.lax.dot_general(
        dlogits.astype(w.dtype), w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(jv == nv - 1)
    def _():
        dh_ref[...] = dh_scr[:].astype(dh_ref.dtype)


def _dw_kernel(h_ref, w_ref, lab_ref, lse_ref, glse_ref, gll_ref, dw_ref, dw_scr, *, vocab, block_v, nr):
    ir = pl.program_id(1)  # rows sequential
    jv = pl.program_id(0)

    @pl.when(ir == 0)
    def _():
        dw_scr[:] = jnp.zeros_like(dw_scr)

    h = h_ref[...]
    w = w_ref[...]
    logits = jax.lax.dot_general(h, w, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    col = jv * block_v + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    logits = jnp.where(col < vocab, logits, NEG_INF)
    p = jnp.exp(logits - lse_ref[...][:, :1])
    lab = lab_ref[...][:, :1]
    dlogits = glse_ref[...][:, :1] * p + gll_ref[...][:, :1] * (col == lab)
    dw_scr[:] += jax.lax.dot_general(
        dlogits.astype(h.dtype), h, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(ir == nr - 1)
    def _():
        dw_ref[...] = dw_scr[:].astype(dw_ref.dtype)


def _lse_ll(h, w, labels, vocab_true, block_r, block_v, interpret):
    n, e = h.shape
    vpad, _ = w.shape
    nr, nv = n // block_r, vpad // block_v
    lab8 = jnp.broadcast_to(labels[:, None], (n, 8)).astype(jnp.int32)
    lse, ll = pl.pallas_call(
        functools.partial(_fwd_kernel, vocab=vocab_true, block_v=block_v, nv=nv),
        grid=(nr, nv),
        in_specs=[
            pl.BlockSpec((block_r, e), lambda ir, jv: (ir, 0)),
            pl.BlockSpec((block_v, e), lambda ir, jv: (jv, 0)),
            pl.BlockSpec((block_r, 8), lambda ir, jv: (ir, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_r, 8), lambda ir, jv: (ir, 0)),
            pl.BlockSpec((block_r, 8), lambda ir, jv: (ir, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 8), jnp.float32),
            jax.ShapeDtypeStruct((n, 8), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_r, 128), jnp.float32),
            pltpu.VMEM((block_r, 128), jnp.float32),
            pltpu.VMEM((block_r, 128), jnp.float32),
        ],
        interpret=interpret,
    )(h, w, lab8)
    return lse[:, 0], ll[:, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _fused_head_lse(h, w, labels, vocab, block_r, block_v, interpret):
    return _lse_ll(h, w, labels, vocab, block_r, block_v, interpret)


def _fused_fwd(h, w, labels, vocab, block_r, block_v, interpret):
    lse, ll = _lse_ll(h, w, labels, vocab, block_r, block_v, interpret)
    return (lse, ll), (h, w, labels, lse)


def _fused_bwd(vocab, block_r, block_v, interpret, res, g):
    h, w, labels, lse = res
    glse, gll = g
    n, e = h.shape
    vpad = w.shape[0]
    nr, nv = n // block_r, vpad // block_v
    lab8 = jnp.broadcast_to(labels[:, None], (n, 8)).astype(jnp.int32)
    lse8 = jnp.broadcast_to(lse[:, None], (n, 8)).astype(jnp.float32)
    glse8 = jnp.broadcast_to(glse[:, None], (n, 8)).astype(jnp.float32)
    gll8 = jnp.broadcast_to(gll[:, None], (n, 8)).astype(jnp.float32)

    dh = pl.pallas_call(
        functools.partial(_dh_kernel, vocab=vocab, block_v=block_v, nv=nv),
        grid=(nr, nv),
        in_specs=[
            pl.BlockSpec((block_r, e), lambda ir, jv: (ir, 0)),
            pl.BlockSpec((block_v, e), lambda ir, jv: (jv, 0)),
            pl.BlockSpec((block_r, 8), lambda ir, jv: (ir, 0)),
            pl.BlockSpec((block_r, 8), lambda ir, jv: (ir, 0)),
            pl.BlockSpec((block_r, 8), lambda ir, jv: (ir, 0)),
            pl.BlockSpec((block_r, 8), lambda ir, jv: (ir, 0)),
        ],
        out_specs=pl.BlockSpec((block_r, e), lambda ir, jv: (ir, 0)),
        out_shape=jax.ShapeDtypeStruct((n, e), h.dtype),
        scratch_shapes=[pltpu.VMEM((block_r, e), jnp.float32)],
        interpret=interpret,
    )(h, w, lab8, lse8, glse8, gll8)

    dw = pl.pallas_call(
        functools.partial(_dw_kernel, vocab=vocab, block_v=block_v, nr=nr),
        grid=(nv, nr),
        in_specs=[
            pl.BlockSpec((block_r, e), lambda jv, ir: (ir, 0)),
            pl.BlockSpec((block_v, e), lambda jv, ir: (jv, 0)),
            pl.BlockSpec((block_r, 8), lambda jv, ir: (ir, 0)),
            pl.BlockSpec((block_r, 8), lambda jv, ir: (ir, 0)),
            pl.BlockSpec((block_r, 8), lambda jv, ir: (ir, 0)),
            pl.BlockSpec((block_r, 8), lambda jv, ir: (ir, 0)),
        ],
        out_specs=pl.BlockSpec((block_v, e), lambda jv, ir: (jv, 0)),
        out_shape=jax.ShapeDtypeStruct((vpad, e), w.dtype),
        scratch_shapes=[pltpu.VMEM((block_v, e), jnp.float32)],
        interpret=interpret,
    )(h, w, lab8, lse8, glse8, gll8)
    import numpy as np

    dlabels = np.zeros(labels.shape, jax.dtypes.float0)  # int primal: zero cotangent
    return dh, dw, dlabels


_fused_head_lse.defvjp(_fused_fwd, _fused_bwd)


def fused_cross_entropy(
    hidden: jax.Array,  # [N, e] compute dtype
    wte: jax.Array,  # [V, e]
    labels: jax.Array,  # [N] int
    ignore_index: int = -100,
    block_r: int = 512,
    block_v: int = 1024,
    interpret: bool | None = None,
) -> jax.Array:
    """Mean CE over valid rows with the tied head fused in; the [N, V] logits
    tensor never reaches HBM. Differentiable w.r.t. hidden and wte."""
    if interpret is None:
        interpret = not _on_tpu()
    n, e = hidden.shape
    v = wte.shape[0]
    mask = labels != ignore_index
    safe = jnp.where(mask, labels, 0).astype(jnp.int32)
    # shrink blocks BEFORE padding so tiny inputs don't pad up to a full
    # 512/2048 block of wasted rows/columns (Mosaic minimum tile: 8 x 128)
    block_r = min(block_r, -(-n // 8) * 8)
    block_v = min(block_v, -(-v // 128) * 128)
    rpad = (-n) % block_r
    if rpad:
        hidden = jnp.pad(hidden, ((0, rpad), (0, 0)))
        safe = jnp.pad(safe, (0, rpad))
        mask = jnp.pad(mask, (0, rpad))
    vpad = (-v) % block_v
    if vpad:
        wte = jnp.pad(wte, ((0, vpad), (0, 0)))
    lse, ll = _fused_head_lse(hidden, wte, safe, v, block_r, block_v, interpret)
    per_row = (lse - ll) * mask
    return per_row.sum() / jnp.maximum(mask.sum(), 1)
