"""FP8 training path: delayed-scaling quantize-dequantize matmul.

Capability position: the reference delegates fp8 to TransformerEngine
(`utils/transformer_engine.py:26-138` — swap `nn.Linear` → `te.Linear`, wrap the
forward in `te.fp8_autocast` with a `DelayedScaling` recipe) or MS-AMP
(`accelerator.py:2015-2057`); the recipe surface is `FP8RecipeKwargs`
(`utils/dataclasses.py:283-404`).

TPU-native design: no engine swap and no autocast context. We use the
quantize→dequantize (q-dq) idiom: inputs and kernels are cast to
``float8_e4m3fn`` (forward) / incoming cotangents to ``float8_e5m2`` (backward)
with per-tensor scaling, then immediately dequantized and fed to a bf16
``dot_general``. XLA pattern-matches q-dq around a dot into a native fp8 MXU
matmul on hardware that has one, and degrades to a plain bf16 matmul (with fp8
rounding applied) everywhere else — so the same program is correct on CPU test
meshes and fast on fp8-capable TPUs.

Forward scaling is *delayed* (the TE recipe): activations and kernels carry a
rolling amax history in a mutable ``fp8_meta`` flax collection; the scale used
at step t comes from steps < t, so forward quantization is a static
elementwise op. Gradient scaling is *current* (computed from the cotangent
itself inside the VJP) — a single fused max-reduction per backward matmul,
which sidesteps the reference's awkward backward-amax plumbing entirely.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, NamedTuple

import flax
import flax.linen as nn
import jax
import jax.numpy as jnp

E4M3 = jnp.float8_e4m3fn
E5M2 = jnp.float8_e5m2
E4M3_MAX = 448.0
E5M2_MAX = 57344.0


@dataclass(frozen=True)
class DelayedScalingRecipe:
    """Functional mirror of `FP8RecipeKwargs` (reference `dataclasses.py:283-404`).

    ``backend`` picks the matmul lowering: "native" feeds REAL fp8 arrays to
    `dot_general` (fp8 bytes in HBM, native fp8 MXU issue where the hardware
    has it — the measurable-speed/memory path); "qdq" rounds through fp8 and
    runs a bf16 dot (numerics simulation that XLA may still pattern-match;
    always safe). Same scaling state either way.
    """

    margin: int = 0
    amax_history_len: int = 16
    fp8_format: str = "HYBRID"  # E4M3 fwd / E5M2 bwd; "E4M3" uses e4m3 both ways
    backend: str = "native"  # "native" | "qdq"

    def __post_init__(self):
        if self.backend not in ("native", "qdq"):
            raise ValueError(
                f"DelayedScalingRecipe.backend must be 'native' or 'qdq', got "
                f"{self.backend!r} — a typo here would silently measure the "
                "wrong matmul path."
            )


def new_meta(history_len: int) -> dict[str, jax.Array]:
    """Fresh per-tensor scaling state: scale + rolling amax history."""
    return {
        "scale": jnp.ones((), jnp.float32),
        "amax_history": jnp.zeros((history_len,), jnp.float32),
    }


def _compute_scale(amax_history: jax.Array, fp8_max: float, margin: int) -> jax.Array:
    """scale = fp8_max / (2^margin * max(amax_history)), guarded against 0/inf."""
    amax = jnp.max(amax_history)
    sf = fp8_max / jnp.maximum(amax, 1e-12) / (2.0 ** margin)
    sf = jnp.where(amax > 0.0, sf, 1.0)
    return jnp.where(jnp.isfinite(sf), sf, 1.0)


def _update_meta(meta: dict, x: jax.Array, fp8_max: float, margin: int) -> dict:
    """Roll the current |x|max into the history and refresh the scale."""
    amax = jnp.max(jnp.abs(x)).astype(jnp.float32)
    hist = jnp.roll(meta["amax_history"], 1).at[0].set(amax)
    return {"scale": _compute_scale(hist, fp8_max, margin), "amax_history": hist}


def quantize_dequantize(x: jax.Array, scale: jax.Array, dtype: Any, fp8_max: float) -> jax.Array:
    """The q-dq rounding op XLA rewrites into a native fp8 operand."""
    orig = x.dtype
    scaled = jnp.clip(x.astype(jnp.float32) * scale, -fp8_max, fp8_max)
    return (scaled.astype(dtype).astype(jnp.float32) / scale).astype(orig)


@jax.custom_vjp
def fp8_dot(x, kernel, x_scale, k_scale, bwd_e4m3):
    """q-dq matmul: rounds x and kernel to e4m3 at the given scales, bf16 dot."""
    xq = quantize_dequantize(x, x_scale, E4M3, E4M3_MAX)
    kq = quantize_dequantize(kernel, k_scale, E4M3, E4M3_MAX)
    return jnp.dot(xq, kq)


def _fp8_dot_fwd(x, kernel, x_scale, k_scale, bwd_e4m3):
    out = fp8_dot(x, kernel, x_scale, k_scale, bwd_e4m3)
    return out, (x, kernel, x_scale, k_scale, bwd_e4m3)


def _fp8_dot_bwd(res, g):
    x, kernel, x_scale, k_scale, e4m3_bwd = res
    bdt = E4M3 if e4m3_bwd else E5M2
    bmax = E4M3_MAX if e4m3_bwd else E5M2_MAX
    # current scaling for the cotangent: one fused max-reduction
    g_amax = jnp.max(jnp.abs(g)).astype(jnp.float32)
    g_scale = jnp.where(g_amax > 0.0, bmax / jnp.maximum(g_amax, 1e-30), 1.0)
    gq = quantize_dequantize(g, g_scale, bdt, bmax)
    xq = quantize_dequantize(x, x_scale, E4M3, E4M3_MAX)
    kq = quantize_dequantize(kernel, k_scale, E4M3, E4M3_MAX)
    dx = jnp.dot(gq, kq.T).astype(x.dtype)
    dk = jnp.dot(
        xq.reshape(-1, xq.shape[-1]).T, gq.reshape(-1, gq.shape[-1])
    ).astype(kernel.dtype)
    return dx, dk, None, None, None


fp8_dot.defvjp(_fp8_dot_fwd, _fp8_dot_bwd)


# ------------------------------------------------------------ native fp8 path
def quantize(x: jax.Array, scale: jax.Array, dtype: Any, fp8_max: float) -> jax.Array:
    """TRUE fp8 cast: the returned array's storage dtype is fp8 (1 byte/elem).
    Unlike `quantize_dequantize` there is no round-trip back to the source
    dtype — the fp8 array itself flows into the dot."""
    return jnp.clip(x.astype(jnp.float32) * scale, -fp8_max, fp8_max).astype(dtype)


def _f32_dot(a: jax.Array, b: jax.Array) -> jax.Array:
    """dot_general on fp8 operands accumulating in fp32 (the MXU contract)."""
    return jax.lax.dot_general(
        a, b, (((a.ndim - 1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


@jax.custom_vjp
def fp8_dot_native(x, kernel, x_scale, k_scale, bwd_e4m3):
    """fp8-storage matmul: x and kernel are cast to REAL e4m3 arrays (scaled),
    contracted natively with fp32 accumulation, then unscaled. On fp8-capable
    TPUs this issues fp8 MXU ops and moves 1-byte operands through HBM; on
    other backends XLA upcasts internally (still correct, same numerics class
    as q-dq)."""
    xq = quantize(x, x_scale, E4M3, E4M3_MAX)
    kq = quantize(kernel, k_scale, E4M3, E4M3_MAX)
    out = _f32_dot(xq, kq) / (x_scale * k_scale)
    return out.astype(x.dtype)


def _fp8_dot_native_fwd(x, kernel, x_scale, k_scale, bwd_e4m3):
    # residuals are the fp8 QUANTIZED tensors — the backward rereads 1-byte
    # operands instead of bf16 (the fp8 memory win applies to saved activations)
    xq = quantize(x, x_scale, E4M3, E4M3_MAX)
    kq = quantize(kernel, k_scale, E4M3, E4M3_MAX)
    out = (_f32_dot(xq, kq) / (x_scale * k_scale)).astype(x.dtype)
    return out, (xq, kq, x_scale, k_scale, bwd_e4m3)


def _fp8_dot_native_bwd(res, g):
    xq, kq, x_scale, k_scale, e4m3_bwd = res
    bdt = E4M3 if e4m3_bwd else E5M2
    bmax = E4M3_MAX if e4m3_bwd else E5M2_MAX
    g_amax = jnp.max(jnp.abs(g)).astype(jnp.float32)
    g_scale = jnp.where(g_amax > 0.0, bmax / jnp.maximum(g_amax, 1e-30), 1.0)
    gq = quantize(g, g_scale, bdt, bmax)
    # cotangent dtype == primal output dtype == x/kernel compute dtype
    dx = (_f32_dot(gq, kq.T) / (g_scale * k_scale)).astype(g.dtype)
    gq2 = gq.reshape(-1, gq.shape[-1])
    dk = (
        _f32_dot(xq.reshape(-1, xq.shape[-1]).T, gq2) / (x_scale * g_scale)
    ).astype(g.dtype)
    return dx, dk, None, None, None


fp8_dot_native.defvjp(_fp8_dot_native_fwd, _fp8_dot_native_bwd)


# --------------------------------------------------- MS-AMP-role opt levels
F16_MAX = 65504.0


class ScaleByAdamFp8State(NamedTuple):
    """Adam moments in scaled low precision (MS-AMP O2 role, reference
    `accelerator.py:2015-2057`): mu as e4m3 + per-leaf scale (1 byte/param vs
    4), nu as scaled fp16 (2 bytes vs 4). The scale keeps each leaf's values
    inside the format's dynamic range, so tiny second moments don't underflow."""

    count: jax.Array
    mu: Any
    mu_scale: Any
    nu: Any
    nu_scale: Any


def _requant_leaf(x: jax.Array, dtype: Any, fmax: float) -> tuple[jax.Array, jax.Array]:
    amax = jnp.max(jnp.abs(x)).astype(jnp.float32)
    scale = jnp.where(amax > 0.0, (fmax / 2.0) / jnp.maximum(amax, 1e-30), 1.0)
    return (x.astype(jnp.float32) * scale).astype(dtype), scale


def _dequant_leaf(q: jax.Array, scale: jax.Array) -> jax.Array:
    return q.astype(jnp.float32) / scale


def scale_by_adam_fp8(b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8):
    """optax transformation: Adam with fp8-carried first moment and fp16-carried
    second moment. Update math runs in fp32 (dequant -> update -> requant), so
    the only approximation is the storage rounding — the MS-AMP recipe."""
    import optax

    def init_fn(params):
        mu = jax.tree.map(lambda p: jnp.zeros(p.shape, E4M3), params)
        mu_scale = jax.tree.map(lambda p: jnp.ones((), jnp.float32), params)
        nu = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float16), params)
        nu_scale = jax.tree.map(lambda p: jnp.ones((), jnp.float32), params)
        return ScaleByAdamFp8State(jnp.zeros((), jnp.int32), mu, mu_scale, nu, nu_scale)

    def update_fn(updates, state, params=None):
        count = state.count + 1
        mu = jax.tree.map(
            lambda g, q, s: b1 * _dequant_leaf(q, s) + (1 - b1) * g.astype(jnp.float32),
            updates, state.mu, state.mu_scale,
        )
        nu = jax.tree.map(
            lambda g, q, s: b2 * _dequant_leaf(q, s)
            + (1 - b2) * jnp.square(g.astype(jnp.float32)),
            updates, state.nu, state.nu_scale,
        )
        bc1 = 1 - b1 ** count.astype(jnp.float32)
        bc2 = 1 - b2 ** count.astype(jnp.float32)
        out = jax.tree.map(
            lambda m, v, g: ((m / bc1) / (jnp.sqrt(v / bc2) + eps)).astype(g.dtype),
            mu, nu, updates,
        )
        mu_q = jax.tree.map(lambda m: _requant_leaf(m, E4M3, E4M3_MAX), mu)
        nu_q = jax.tree.map(lambda v: _requant_leaf(v, jnp.float16, F16_MAX), nu)
        new_state = ScaleByAdamFp8State(
            count,
            jax.tree.map(lambda t: t[0], mu_q, is_leaf=lambda t: isinstance(t, tuple)),
            jax.tree.map(lambda t: t[1], mu_q, is_leaf=lambda t: isinstance(t, tuple)),
            jax.tree.map(lambda t: t[0], nu_q, is_leaf=lambda t: isinstance(t, tuple)),
            jax.tree.map(lambda t: t[1], nu_q, is_leaf=lambda t: isinstance(t, tuple)),
        )
        return out, new_state

    return optax.GradientTransformation(init_fn, update_fn)


def adamw_fp8(
    learning_rate: Any = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    opt_level: str = "O2",
):
    """AdamW with MS-AMP-style low-precision optimizer state (reference
    `accelerator.py:2015-2057` opt levels): "O1" is plain fp32-state adamw;
    "O2" carries mu in scaled e4m3 and nu in scaled fp16 — a 2.3x optimizer
    HBM reduction at Adam-for-fp8 numerics."""
    import optax

    if opt_level == "O1":
        return optax.adamw(learning_rate, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay)
    if opt_level != "O2":
        raise ValueError(f"Unknown fp8 opt_level {opt_level!r}; use 'O1' or 'O2'")
    return optax.chain(
        scale_by_adam_fp8(b1=b1, b2=b2, eps=eps),
        optax.add_decayed_weights(weight_decay) if weight_decay else optax.identity(),
        optax.scale_by_learning_rate(learning_rate),
    )


class Fp8Dense(nn.Module):
    """Drop-in Dense with fp8 q-dq matmul and delayed scaling.

    The `te.Linear` analogue (reference `transformer_engine.py:26-82`):
    per-tensor meta (scale + amax history) for input and kernel lives in the
    mutable ``fp8_meta`` collection and is refreshed every call, so the train
    step's state threading picks it up like any other model state.
    """

    features: int
    use_bias: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    kernel_init: Any = nn.initializers.lecun_normal()
    bias_init: Any = nn.initializers.zeros
    recipe: DelayedScalingRecipe = field(default_factory=DelayedScalingRecipe)

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        r = self.recipe
        kernel = self.param(
            "kernel", self.kernel_init, (x.shape[-1], self.features), self.param_dtype
        )
        meta_init = lambda: new_meta(r.amax_history_len)  # noqa: E731
        try:
            x_meta = self.variable("fp8_meta", "input", meta_init)
            k_meta = self.variable("fp8_meta", "kernel", meta_init)
        except flax.errors.ScopeCollectionNotFound as e:
            raise ValueError(
                "Fp8Dense needs its delayed-scaling state: pass the 'fp8_meta' "
                "collection in variables (init_params returns it; "
                "Accelerator.prepare threads it as extra_state). Paths that "
                "don't thread it — e.g. models/generation.py decode — cannot "
                "run fp8 models; use the dense or weight-quantized model there."
            ) from e

        kernel = kernel.astype(self.dtype)
        xc = x.astype(self.dtype)
        lead = xc.shape[:-1]
        dot = fp8_dot_native if r.backend == "native" else fp8_dot
        out = dot(
            xc.reshape(-1, xc.shape[-1]),
            kernel,
            x_meta.value["scale"],
            k_meta.value["scale"],
            r.fp8_format.upper() == "E4M3",
        ).reshape(*lead, self.features)
        if not self.is_initializing() and self.is_mutable_collection("fp8_meta"):
            # read-only applies (eval without mutable=['fp8_meta']) keep the
            # existing scales instead of crashing on the assignment
            x_meta.value = _update_meta(x_meta.value, xc, E4M3_MAX, r.margin)
            k_meta.value = _update_meta(k_meta.value, kernel, E4M3_MAX, r.margin)
        if self.use_bias:
            bias = self.param("bias", self.bias_init, (self.features,), self.param_dtype)
            out = out + bias.astype(self.dtype)
        return out


def convert_dense_to_fp8(recipe: DelayedScalingRecipe | None = None):
    """`convert_model` analogue (reference `transformer_engine.py:26-82`).

    In flax there is no in-place layer swap; models opt in by constructing
    their projections through this factory, which returns an `Fp8Dense` maker
    when fp8 is requested and plain `nn.Dense` otherwise.
    """
    if recipe is None:
        def make_plain(features: int, **kwargs: Any) -> nn.Module:
            return nn.Dense(features, **kwargs)
        return make_plain

    def make(features: int, **kwargs: Any) -> nn.Module:
        return Fp8Dense(features=features, recipe=recipe, **kwargs)

    return make
