"""FP8 training path: delayed-scaling quantize-dequantize matmul.

Capability position: the reference delegates fp8 to TransformerEngine
(`utils/transformer_engine.py:26-138` — swap `nn.Linear` → `te.Linear`, wrap the
forward in `te.fp8_autocast` with a `DelayedScaling` recipe) or MS-AMP
(`accelerator.py:2015-2057`); the recipe surface is `FP8RecipeKwargs`
(`utils/dataclasses.py:283-404`).

TPU-native design: no engine swap and no autocast context. We use the
quantize→dequantize (q-dq) idiom: inputs and kernels are cast to
``float8_e4m3fn`` (forward) / incoming cotangents to ``float8_e5m2`` (backward)
with per-tensor scaling, then immediately dequantized and fed to a bf16
``dot_general``. XLA pattern-matches q-dq around a dot into a native fp8 MXU
matmul on hardware that has one, and degrades to a plain bf16 matmul (with fp8
rounding applied) everywhere else — so the same program is correct on CPU test
meshes and fast on fp8-capable TPUs.

Forward scaling is *delayed* (the TE recipe): activations and kernels carry a
rolling amax history in a mutable ``fp8_meta`` flax collection; the scale used
at step t comes from steps < t, so forward quantization is a static
elementwise op. Gradient scaling is *current* (computed from the cotangent
itself inside the VJP) — a single fused max-reduction per backward matmul,
which sidesteps the reference's awkward backward-amax plumbing entirely.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

E4M3 = jnp.float8_e4m3fn
E5M2 = jnp.float8_e5m2
E4M3_MAX = 448.0
E5M2_MAX = 57344.0


@dataclass(frozen=True)
class DelayedScalingRecipe:
    """Functional mirror of `FP8RecipeKwargs` (reference `dataclasses.py:283-404`)."""

    margin: int = 0
    amax_history_len: int = 16
    fp8_format: str = "HYBRID"  # E4M3 fwd / E5M2 bwd; "E4M3" uses e4m3 both ways


def new_meta(history_len: int) -> dict[str, jax.Array]:
    """Fresh per-tensor scaling state: scale + rolling amax history."""
    return {
        "scale": jnp.ones((), jnp.float32),
        "amax_history": jnp.zeros((history_len,), jnp.float32),
    }


def _compute_scale(amax_history: jax.Array, fp8_max: float, margin: int) -> jax.Array:
    """scale = fp8_max / (2^margin * max(amax_history)), guarded against 0/inf."""
    amax = jnp.max(amax_history)
    sf = fp8_max / jnp.maximum(amax, 1e-12) / (2.0 ** margin)
    sf = jnp.where(amax > 0.0, sf, 1.0)
    return jnp.where(jnp.isfinite(sf), sf, 1.0)


def _update_meta(meta: dict, x: jax.Array, fp8_max: float, margin: int) -> dict:
    """Roll the current |x|max into the history and refresh the scale."""
    amax = jnp.max(jnp.abs(x)).astype(jnp.float32)
    hist = jnp.roll(meta["amax_history"], 1).at[0].set(amax)
    return {"scale": _compute_scale(hist, fp8_max, margin), "amax_history": hist}


def quantize_dequantize(x: jax.Array, scale: jax.Array, dtype: Any, fp8_max: float) -> jax.Array:
    """The q-dq rounding op XLA rewrites into a native fp8 operand."""
    orig = x.dtype
    scaled = jnp.clip(x.astype(jnp.float32) * scale, -fp8_max, fp8_max)
    return (scaled.astype(dtype).astype(jnp.float32) / scale).astype(orig)


@jax.custom_vjp
def fp8_dot(x, kernel, x_scale, k_scale, bwd_e4m3):
    """q-dq matmul: rounds x and kernel to e4m3 at the given scales, bf16 dot."""
    xq = quantize_dequantize(x, x_scale, E4M3, E4M3_MAX)
    kq = quantize_dequantize(kernel, k_scale, E4M3, E4M3_MAX)
    return jnp.dot(xq, kq)


def _fp8_dot_fwd(x, kernel, x_scale, k_scale, bwd_e4m3):
    out = fp8_dot(x, kernel, x_scale, k_scale, bwd_e4m3)
    return out, (x, kernel, x_scale, k_scale, bwd_e4m3)


def _fp8_dot_bwd(res, g):
    x, kernel, x_scale, k_scale, e4m3_bwd = res
    bdt = E4M3 if e4m3_bwd else E5M2
    bmax = E4M3_MAX if e4m3_bwd else E5M2_MAX
    # current scaling for the cotangent: one fused max-reduction
    g_amax = jnp.max(jnp.abs(g)).astype(jnp.float32)
    g_scale = jnp.where(g_amax > 0.0, bmax / jnp.maximum(g_amax, 1e-30), 1.0)
    gq = quantize_dequantize(g, g_scale, bdt, bmax)
    xq = quantize_dequantize(x, x_scale, E4M3, E4M3_MAX)
    kq = quantize_dequantize(kernel, k_scale, E4M3, E4M3_MAX)
    dx = jnp.dot(gq, kq.T).astype(x.dtype)
    dk = jnp.dot(
        xq.reshape(-1, xq.shape[-1]).T, gq.reshape(-1, gq.shape[-1])
    ).astype(kernel.dtype)
    return dx, dk, None, None, None


fp8_dot.defvjp(_fp8_dot_fwd, _fp8_dot_bwd)


class Fp8Dense(nn.Module):
    """Drop-in Dense with fp8 q-dq matmul and delayed scaling.

    The `te.Linear` analogue (reference `transformer_engine.py:26-82`):
    per-tensor meta (scale + amax history) for input and kernel lives in the
    mutable ``fp8_meta`` collection and is refreshed every call, so the train
    step's state threading picks it up like any other model state.
    """

    features: int
    use_bias: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    kernel_init: Any = nn.initializers.lecun_normal()
    bias_init: Any = nn.initializers.zeros
    recipe: DelayedScalingRecipe = field(default_factory=DelayedScalingRecipe)

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        r = self.recipe
        kernel = self.param(
            "kernel", self.kernel_init, (x.shape[-1], self.features), self.param_dtype
        )
        meta_init = lambda: new_meta(r.amax_history_len)  # noqa: E731
        x_meta = self.variable("fp8_meta", "input", meta_init)
        k_meta = self.variable("fp8_meta", "kernel", meta_init)

        kernel = kernel.astype(self.dtype)
        xc = x.astype(self.dtype)
        lead = xc.shape[:-1]
        out = fp8_dot(
            xc.reshape(-1, xc.shape[-1]),
            kernel,
            x_meta.value["scale"],
            k_meta.value["scale"],
            r.fp8_format.upper() == "E4M3",
        ).reshape(*lead, self.features)
        if not self.is_initializing():
            x_meta.value = _update_meta(x_meta.value, xc, E4M3_MAX, r.margin)
            k_meta.value = _update_meta(k_meta.value, kernel, E4M3_MAX, r.margin)
        if self.use_bias:
            bias = self.param("bias", self.bias_init, (self.features,), self.param_dtype)
            out = out + bias.astype(self.dtype)
        return out


def convert_dense_to_fp8(recipe: DelayedScalingRecipe | None = None):
    """`convert_model` analogue (reference `transformer_engine.py:26-82`).

    In flax there is no in-place layer swap; models opt in by constructing
    their projections through this factory, which returns an `Fp8Dense` maker
    when fp8 is requested and plain `nn.Dense` otherwise.
    """
    if recipe is None:
        def make_plain(features: int, **kwargs: Any) -> nn.Module:
            return nn.Dense(features, **kwargs)
        return make_plain

    def make(features: int, **kwargs: Any) -> nn.Module:
        return Fp8Dense(features=features, recipe=recipe, **kwargs)

    return make
