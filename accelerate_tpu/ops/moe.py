"""Mixture-of-Experts layer with expert parallelism.

Capability position: the reference's only MoE support is marking MoE classes as
ZeRO-3 leaves for DeepSpeed (SURVEY.md §2.4 EP row — "not implemented"); this is
the native TPU design. Switch/GShard-style top-k routing with static capacity:

  - routing, dispatch and combine are one-hot einsums — static shapes, MXU-
    friendly, no gather/scatter (the GSPMD MoE recipe).
  - expert-stacked weights [E, in, out] shard their leading dim over the
    ``tensor`` mesh axis (EP shares the TP axis, the common economical choice);
    XLA inserts the token all-to-alls from the shardings.
  - aux load-balancing loss (Switch Transformer) is sown into the
    ``intermediates`` collection; include ``"intermediates": {}`` in the
    variables passed to ``Accelerator.prepare`` and, *inside* ``loss_fn``,
    add ``collect_aux_losses(m.extra_state)`` to the task loss (it must be
    inside the differentiated function for the router to receive gradient).

Dropped tokens (over capacity) pass through the residual stream untouched, as in
GShard/Switch.
"""

from __future__ import annotations

from collections.abc import Mapping
from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.sharding import ShardingRules


@dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    hidden_size: int = 768
    intermediate_size: int = 3072
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32


def build_dispatch_combine(
    expert_idx: jax.Array,  # [T, k] chosen experts per token
    gate_vals: jax.Array,  # [T, k] combine weights per choice
    num_experts: int,
    capacity: int,
    dtype: Any,
) -> tuple[jax.Array, jax.Array]:
    """Static-capacity dispatch/combine one-hots [T, E, C] (GShard recipe).

    Position of each token within its expert's capacity buffer comes from a
    masked cumsum; slots are processed in order, later slots offset by earlier
    slots' fill counts. Tokens beyond capacity are dropped (their dispatch and
    combine rows stay zero, so they pass through the residual stream).
    Shared by `MoEMLP` and `models.mixtral.MixtralSparseMoeBlock`.
    """
    n_tokens, k = expert_idx.shape
    E = num_experts
    dispatch = jnp.zeros((n_tokens, E, capacity), dtype=dtype)
    combine = jnp.zeros((n_tokens, E, capacity), dtype=jnp.float32)
    fill = jnp.zeros((E,), dtype=jnp.float32)
    for slot in range(k):
        onehot = jax.nn.one_hot(expert_idx[:, slot], E, dtype=jnp.float32)  # [T, E]
        within = jnp.cumsum(onehot, axis=0) - onehot  # earlier tokens, this slot
        pos_in_expert = jnp.sum((within + fill[None, :]) * onehot, axis=-1)  # [T]
        keep = pos_in_expert < capacity
        pos_oh = jax.nn.one_hot(pos_in_expert.astype(jnp.int32), capacity, dtype=jnp.float32)
        contrib = onehot[:, :, None] * pos_oh[:, None, :] * keep[:, None, None]
        dispatch = dispatch + contrib.astype(dtype)
        combine = combine + contrib * gate_vals[:, slot][:, None, None]
        fill = fill + jnp.sum(onehot * keep[:, None], axis=0)
    return dispatch, combine


def sow_aux_loss(module: nn.Module, aux: jax.Array) -> None:
    """Sum-reduce sow of a router aux loss into ``intermediates`` (stable pytree
    across steps; see the MoEMLP docstring for why sum-reduce, not append)."""
    module.sow(
        "intermediates",
        "aux_loss",
        aux,
        reduce_fn=lambda prev, new: prev + new,
        init_fn=lambda: jnp.zeros((), jnp.float32),
    )


class MoEMLP(nn.Module):
    """Top-k routed expert MLP over [batch, seq, hidden] activations."""

    config: MoEConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        b, s, e = x.shape
        n_tokens = b * s
        E = cfg.num_experts
        capacity = max(int(cfg.capacity_factor * n_tokens * cfg.top_k / E), 1)

        xt = x.reshape(n_tokens, e)
        # router in fp32 for stable softmax
        router_logits = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                                 param_dtype=cfg.param_dtype, name="router")(xt.astype(jnp.float32))
        probs = jax.nn.softmax(router_logits, axis=-1)  # [T, E]

        # top-k expert choice per token
        gate_vals, expert_idx = jax.lax.top_k(probs, cfg.top_k)  # [T, k]
        gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)

        dispatch, combine = build_dispatch_combine(
            expert_idx, gate_vals, E, capacity, cfg.dtype
        )

        # expert-stacked weights: leading dim shards over the tensor axis (EP)
        w_up = self.param("w_up", nn.initializers.lecun_normal(),
                          (E, e, cfg.intermediate_size), cfg.param_dtype)
        w_down = self.param("w_down", nn.initializers.lecun_normal(),
                            (E, cfg.intermediate_size, e), cfg.param_dtype)

        # dispatch -> expert compute -> combine (all einsums; static shapes)
        expert_in = jnp.einsum("tec,td->ecd", dispatch, xt.astype(cfg.dtype))
        h = jnp.einsum("ecd,edf->ecf", expert_in, w_up.astype(cfg.dtype))
        h = nn.gelu(h, approximate=True)
        expert_out = jnp.einsum("ecf,efd->ecd", h, w_down.astype(cfg.dtype))
        out = jnp.einsum("tec,ecd->td", combine.astype(cfg.dtype), expert_out)

        # Switch aux loss: fraction-routed x mean-prob per expert. Sown with a
        # sum-reduce into a single scalar leaf: stable pytree structure across
        # steps (tuple-append sow would grow and force recompiles when threaded
        # as extra_state), yet repeated application of one instance (weight
        # sharing / recurrence) still accumulates every call's contribution —
        # the incoming collection is emptied per call by the apply wrapper, so
        # sums never leak across steps.
        me = jnp.mean(jax.nn.one_hot(expert_idx[:, 0], E, dtype=jnp.float32), axis=0)
        ce = jnp.mean(probs, axis=0)
        aux = cfg.aux_loss_weight * E * jnp.sum(me * ce)
        sow_aux_loss(self, aux)
        return out.reshape(b, s, e).astype(x.dtype)


def collect_aux_losses(extra_state: Any) -> jax.Array:
    """Sum every sown ``aux_loss`` leaf out of a mutable-state pytree.

    Usage inside a loss_fn driven by `Accelerator.make_train_step`:
    ``loss = task_loss + collect_aux_losses(m.extra_state)`` (the BoundModel's
    ``extra_state`` holds the post-forward ``intermediates`` collection when
    the user passed one in their variables).
    """
    total = jnp.zeros((), jnp.float32)
    if not extra_state:
        return total
    inter = extra_state.get("intermediates", extra_state)
    for val in _aux_loss_leaves(inter):
        total = total + jnp.sum(jnp.asarray(val, jnp.float32))
    return total


def _aux_loss_leaves(tree: Any):
    """Yield every leaf stored under a key named 'aux_loss'."""
    if isinstance(tree, Mapping):
        for k, v in tree.items():
            if k == "aux_loss":
                yield from jax.tree.leaves(v)
            else:
                yield from _aux_loss_leaves(v)


def moe_sharding_rules() -> ShardingRules:
    """Expert parallelism: expert-stacked weights shard their leading (expert)
    dim over the tensor axis; the router stays replicated."""
    return ShardingRules(
        rules=[
            (r".*w_up", P("tensor", None, None)),
            (r".*w_down", P("tensor", None, None)),
            (r".*router.*", P(None, None)),
        ]
    )
