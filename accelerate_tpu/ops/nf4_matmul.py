"""Fused NF4 dequant-matmul Pallas kernel (staged decode lever).

Role: the reference's headline benchmark is big-model inference, and its 4-bit
rows run bitsandbytes' fused CUDA dequant-GEMV. Here the default nf4 decode
path dequantizes inside jit and lets XLA fuse (`utils/quantization.py`); this
kernel is the escalation if the hardware measurement (`BENCH_INF_QUANT=nf4`
vs fp16, queued in tools/relay_watch.py) shows dequant dominating decode: it
reads the PACKED payload (4 bits/weight) straight from HBM and dequantizes in
VMEM, so a memory-bound matvec moves ~4x fewer bytes than a bf16 weight read.

Kernel design (TPU-first):
- Plane packing: byte (k, j) holds element (k, j) in the high nibble and
  (k, j + N/2) in the low nibble — dequant needs only shift/mask/compare ops
  (no nibble interleave, no gather: the 16-entry NF4 codebook is compiled in
  as a select chain), and each grid cell emits two output tiles (left/right
  plane) with two MXU dots.
- Blockwise absmax scales (the QLoRA layout, 64 elements along a row) arrive
  pre-split per plane as [2, K, (N/2)/64]; a tile's scale columns expand over
  the lanes with an iota select — no repeat/reshape inside the kernel.
- Grid (N/2 / bn, K / bk) with accumulation over the K dim
  (`o_ref += dot(...)`); bn defaults to the full 128-lane width (two 64-wide
  scale blocks per tile).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..utils.quantization import NF4_CODE, QuantizedTensor


def _on_tpu() -> bool:
    from ..utils.environment import on_tpu_platform

    return on_tpu_platform()


def _kernel(x_ref, packed_ref, scales_ref, o_ref, *, code, bn):
    kk = pl.program_id(1)

    @pl.when(kk == 0)
    def _():
        o_ref[...] = jnp.zeros_like(o_ref)

    p = packed_ref[...].astype(jnp.int32)
    hi, lo = (p >> 4) & 0xF, p & 0xF
    n_scale = bn // 64

    def dequant(idx, s_cols):
        vals = jnp.full(idx.shape, code[0], jnp.float32)
        for c in range(1, 16):
            vals = jnp.where(idx == c, code[c], vals)
        if n_scale == 1:
            return vals * s_cols  # [bk, 1] broadcasts over the lanes
        # expand [bk, n_scale] scale columns over the 64-lane blocks with an
        # iota select — no reshape/repeat (layout-sensitive on Mosaic)
        col = jax.lax.broadcasted_iota(jnp.int32, idx.shape, 1) // 64
        s_full = jnp.broadcast_to(s_cols[:, :1], idx.shape)
        for b in range(1, n_scale):
            s_full = jnp.where(col == b, s_cols[:, b : b + 1], s_full)
        return vals * s_full

    wl = dequant(hi, scales_ref[0])
    wr = dequant(lo, scales_ref[1])
    x = x_ref[...].astype(jnp.float32)
    o_ref[0, ...] += jnp.dot(x, wl, preferred_element_type=jnp.float32)
    o_ref[1, ...] += jnp.dot(x, wr, preferred_element_type=jnp.float32)


def _block_size(qt: QuantizedTensor) -> int:
    """The quantization block length this tensor was packed with (elements per
    scale), derived from the scale count."""
    total = 1
    for dim in qt.shape:
        total *= dim
    n_blocks = int(qt.scales.shape[0])
    return -(-total // n_blocks) if n_blocks else 0


def kernel_supported(qt: QuantizedTensor) -> bool:
    """True when the fused kernel can take this tensor: nf4, 2D, 64-element
    scale blocks, N tiling two 64-wide planes, and a CONCRETE payload (inside
    jit the payload is a tracer — the host-side repack is impossible, so
    traced calls use the XLA dequant path)."""
    return (
        qt.bits == 4
        and qt.quant_type == "nf4"
        and len(qt.shape) == 2
        and qt.shape[1] % 128 == 0
        and _block_size(qt) == 64
        and not isinstance(qt.data, jax.core.Tracer)
    )


def plane_pack(qt: QuantizedTensor) -> tuple[jax.Array, jax.Array]:
    """Host-side repack of a QuantizedTensor's interleaved payload into the
    kernel's plane layout: (packed [K, N/2] uint8, scales [2, K, (N/2)/64]),
    as DEVICE arrays — cached on the tensor so the upload happens once at
    load, not per matmul."""
    cached = qt._plane_pack
    if cached is not None:
        return cached
    if qt.bits != 4 or qt.quant_type != "nf4":
        raise ValueError(f"plane_pack needs an nf4 tensor, got {qt.bits}-bit {qt.quant_type}")
    if len(qt.shape) != 2:
        raise ValueError(f"plane_pack needs a 2D weight, got shape {qt.shape}")
    K, N = qt.shape
    if N % 128:
        raise ValueError(f"N ({N}) must be a multiple of 128 (two 64-wide scale planes)")
    if _block_size(qt) != 64:
        raise ValueError(
            f"plane_pack needs 64-element scale blocks, got {_block_size(qt)}"
        )
    data = np.asarray(jax.device_get(qt.data))
    hi, lo = (data >> 4) & 0xF, data & 0xF
    idx = np.stack([hi, lo], axis=-1).reshape(-1)[: K * N].reshape(K, N)
    scales = np.asarray(jax.device_get(qt.scales)).reshape(K, N // 64)
    P = N // 2
    packed = ((idx[:, :P] << 4) | idx[:, P:]).astype(np.uint8)
    scales2 = np.stack([scales[:, : P // 64], scales[:, P // 64:]]).astype(np.float32)
    qt._plane_pack = (jnp.asarray(packed), jnp.asarray(scales2))
    return qt._plane_pack


def nf4_matmul(
    x: jax.Array,
    qt: QuantizedTensor,
    block_k: int = 256,
    block_n: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """``x @ dequantize(qt)`` with the packed payload read directly by the
    kernel. ``x`` is [..., K]; the quantized weight is [K, N]. Any tensor or
    shape the kernel cannot take (non-nf4, odd block size, un-tileable dims,
    traced payload) falls back to the XLA dequant path — same numerics."""
    K, N = qt.shape
    lead = x.shape[:-1]
    x2 = x.reshape(-1, K)
    bk = min(block_k, K)
    while K % bk:
        bk //= 2
    P = N // 2
    # largest multiple of 64 <= block_n that tiles the plane; 0 = no tiling
    bn = next(
        (c for c in range(min(block_n, P) - min(block_n, P) % 64, 63, -64) if P % c == 0),
        0,
    )
    if not kernel_supported(qt) or bk < 8 or bn < 64:
        from ..utils.quantization import dequantize

        return (x2 @ dequantize(qt, x.dtype)).reshape(*lead, N)
    if interpret is None:
        interpret = not _on_tpu()
    M = x2.shape[0]
    packed, scales2 = plane_pack(qt)
    out = pl.pallas_call(
        functools.partial(_kernel, code=[float(c) for c in NF4_CODE], bn=bn),
        grid=(P // bn, K // bk),
        in_specs=[
            pl.BlockSpec((M, bk), lambda j, k: (0, k)),
            pl.BlockSpec((bk, bn), lambda j, k: (k, j)),
            pl.BlockSpec((2, bk, bn // 64), lambda j, k: (0, k, j)),
        ],
        out_specs=pl.BlockSpec((2, M, bn), lambda j, k: (0, 0, j)),
        out_shape=jax.ShapeDtypeStruct((2, M, P), jnp.float32),
        interpret=interpret,
    )(x2, packed, scales2)
    return jnp.concatenate([out[0], out[1]], axis=-1).astype(x.dtype).reshape(*lead, N)
