"""Pipeline parallelism: GPipe microbatch schedule as SPMD collective-permute.

Capability parity: the reference's PP comes from external engines — Megatron's
pipelined train_step for training and PiPPy's `ScheduleGPipe` for inference
(SURVEY.md §2.4 PP row). TPU-native re-founding (MPMD-over-SPMD): every device
runs the *same* jitted program over a ``stage`` mesh axis; stage-local parameters
are sharded on the leading (stage) dim, activations hop stage r -> r+1 with
`lax.ppermute` each tick, and the classic GPipe bubble (M + S - 1 ticks for M
microbatches over S stages) emerges from the schedule, not from per-rank code.

The tick loop is a `lax.scan` (reverse-differentiable); `jax.checkpoint` around
the stage body keeps backward memory at one activation per tick instead of the
whole per-tick residual set. Loss can be folded in on the last stage so only a
scalar psum leaves the pipeline.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_local(
    stage_fn: Callable,
    params: Any,  # this stage's param slice (leading stage dim consumed)
    x_mb: Any,  # [M, mb, ...]-leaved pytree, microbatched input, replicated across stages
    out_fn: Callable | None,
    out_fn_args: Any,
    out_fn_extra: Any,  # replicated pytree (e.g. head params) forwarded to out_fn
    axis_name: str,
    data_axis: str | None = None,  # batch-sharding axis: loss is pmean'd over it
):
    S = jax.lax.axis_size(axis_name)
    r = jax.lax.axis_index(axis_name)
    # shard_map leaves a local leading stage dim of size 1 on the param slice
    params = jax.tree.map(lambda p: p[0], params)
    M = jax.tree.leaves(x_mb)[0].shape[0]
    T = M + S - 1
    ckpt_stage = jax.checkpoint(lambda p, x: stage_fn(p, x))

    def tick(carry, t):
        state = carry  # activation entering this stage this tick
        # stage 0 injects microbatch t (clamped; masked-out ticks produce garbage
        # that never reaches an output row). The activation is a pytree (e.g.
        # (x, encoder_out) for a T5 decoder stage), injected leaf-wise.
        inj = jax.tree.map(lambda a: a[jnp.clip(t, 0, M - 1)], x_mb)
        state = jax.tree.map(
            lambda i, s: jnp.where(r == 0, i.astype(s.dtype), s), inj, state
        )
        y = ckpt_stage(params, state)
        # pass activations along the ring; the wraparound (last -> 0) is ignored
        # because stage 0 overwrites with the next injection
        y_next = jax.tree.map(
            lambda a: jax.lax.ppermute(a, axis_name, [(i, (i + 1) % S) for i in range(S)]), y
        )
        return y_next, y

    state0 = stage_eval_shape(stage_fn, params, jax.tree.map(lambda a: a[0], x_mb))
    _, ys = jax.lax.scan(tick, state0, jnp.arange(T))  # ys: [T, mb, ...] per stage

    # microbatch m exits the last stage at tick m + S - 1
    outs = jax.tree.map(lambda a: a[S - 1 :], ys)  # [M, mb, ...] valid only on the last stage
    if out_fn is None:
        # replicate the last stage's outputs everywhere (scalar-free generic path)
        return jax.tree.map(
            lambda o: jax.lax.psum(o * (r == S - 1).astype(o.dtype), axis_name), outs
        )
    if out_fn_extra is None:
        losses = jax.vmap(lambda y, a: out_fn(y, a))(outs, out_fn_args)  # [M]
    else:
        losses = jax.vmap(lambda y, a: out_fn(y, a, out_fn_extra))(outs, out_fn_args)
    mask = (r == S - 1).astype(losses.dtype)
    loss = jax.lax.psum((losses * mask).mean(), axis_name)
    if data_axis is not None:
        # batch sharded over the data axis: the global loss is the mean of the
        # per-shard means (equal shard sizes by the divisibility gate below)
        loss = jax.lax.pmean(loss, data_axis)
    return loss


def stage_eval_shape(stage_fn: Callable, params: Any, x: Any) -> Any:
    """Zero-cost shape probe of a stage's output (stages must be shape-preserving
    pipelines over the same activation structure, the GPipe contract). Returns a
    zeros pytree matching the stage output."""
    shapes = jax.eval_shape(stage_fn, params, x)
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)


def pipeline_apply(
    stage_fn: Callable,
    stacked_params: Any,  # pytree; every leaf has leading dim = num stages
    x: Any,  # global input: array [batch, ...] or pytree of such
    mesh: Mesh,
    num_microbatches: int,
    out_fn: Callable | None = None,
    out_fn_args: Any = None,
    out_fn_extra: Any = None,
    axis_name: str = "stage",
    data_axis: str | None = "data",
) -> Any:
    """Run a stage-sharded model as a GPipe pipeline under jit.

    ``stage_fn(stage_params, x_mb) -> y_mb`` is one stage's forward on one
    microbatch. The activation may be an arbitrary pytree as long as every stage
    preserves its structure — e.g. ``(hidden, encoder_out)`` for a T5 decoder
    stage, where ``encoder_out`` rides through unchanged. With
    ``out_fn(y_mb, args_mb) -> scalar`` given, returns the mean
    loss (computed on the last stage, psum-broadcast); otherwise returns the
    stacked outputs [batch, ...]. ``out_fn_extra`` is an optional replicated
    pytree (e.g. LM-head parameters) passed as a third argument to ``out_fn`` —
    it enters the shard_map as an explicit operand so gradients flow to it
    (closures over tracers inside shard_map are not differentiable operands).
    """
    S = mesh.shape[axis_name]
    if S == 1:
        raise ValueError("pipeline_apply requires a non-trivial stage axis")
    lead = {l.shape[0] for l in jax.tree.leaves(stacked_params)}
    if lead and lead != {S}:
        raise ValueError(
            f"stacked_params leading (stage) dim {sorted(lead)} must equal the "
            f"mesh's {axis_name!r} axis size {S} — one param slice per stage "
            "(extra stages would be silently dropped, missing ones under-shard)."
        )
    b = jax.tree.leaves(x)[0].shape[0]
    if b % num_microbatches:
        raise ValueError(f"batch {b} must divide into {num_microbatches} microbatches")
    mb = b // num_microbatches
    x_mb = jax.tree.map(lambda a: a.reshape(num_microbatches, mb, *a.shape[1:]), x)
    args_mb = None
    if out_fn_args is not None:
        args_mb = jax.tree.map(
            lambda a: a.reshape(num_microbatches, mb, *a.shape[1:]), out_fn_args
        )

    from jax import shard_map

    # Shard the microbatch-sample dim over the data axis when it divides: each
    # data replica pipelines only its slice (dp x pp composition). Indivisible
    # shapes fall back to replicated compute — numerically identical, dp-times
    # redundant — with a warning so the waste is never silent.
    dp = mesh.shape.get(data_axis, 1) if data_axis is not None else 1
    use_dp = dp > 1 and mb % dp == 0
    if dp > 1 and not use_dp:
        import warnings

        warnings.warn(
            f"pipeline_apply: microbatch size {mb} not divisible by the "
            f"{data_axis!r} axis ({dp}); the batch is replicated and every data "
            "replica redundantly computes the full pipeline."
        )
    bspec = P(None, data_axis) if use_dp else P()
    param_specs = jax.tree.map(lambda _: P(axis_name), stacked_params)
    fn = functools.partial(
        _pipeline_local,
        stage_fn,
        axis_name=axis_name,
        data_axis=data_axis if (use_dp and out_fn is not None) else None,
    )

    def wrapped(params, x_mb, args_mb, extra):
        return fn(params, x_mb, out_fn, args_mb, extra)

    result = shard_map(
        wrapped,
        mesh=mesh,
        in_specs=(param_specs, bspec, bspec, P()),
        out_specs=(bspec if out_fn is None else P()),
        check_vma=False,
    )(stacked_params, x_mb, args_mb, out_fn_extra)
    if out_fn is None:
        return jax.tree.map(lambda a: a.reshape(b, *a.shape[2:]), result)
    return result


def stack_stage_params(per_stage_params: list[Any]) -> Any:
    """Stack a list of per-stage param pytrees along a new leading stage dim."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)
