"""Parameter-sharding inference: every parallelism strategy as a sharding plan.

This is the TPU-native replacement for the reference's per-engine wrapping code
paths (DDP wrap `accelerator.py:1458`, FSDP wrap `:1463-1507`, DeepSpeed ZeRO init
`:1632-1872`, Megatron TP rebuild `utils/megatron_lm.py:91-141`): under GSPMD all of
them collapse to *where each parameter array is placed on the mesh*:

  - DP            -> replicate params, shard the batch on ``data``
  - FSDP / ZeRO-3 -> additionally shard each param's largest divisible dim on
                     ``fsdp`` (XLA schedules the all-gather/reduce-scatter pairs
                     that DeepSpeed hand-codes)
  - ZeRO-1        -> params replicated, *optimizer state* sharded on ``fsdp``
  - TP            -> rule-based Megatron-style column/row splits on ``tensor``
  - SP/PP         -> activation shardings, handled in the step/kernels, not here

Rules are (path-regex -> PartitionSpec) pairs, first match wins, mirroring the
plugin surface of `FullyShardedDataParallelPlugin.auto_wrap_policy` at far lower
complexity.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec


def param_path_names(params: Any) -> Any:
    """Pytree of '/'-joined path strings, aligned with the params tree."""

    def _name(path) -> str:
        parts = []
        for p in path:
            if hasattr(p, "key"):
                parts.append(str(p.key))
            elif hasattr(p, "idx"):
                parts.append(str(p.idx))
            elif hasattr(p, "name"):
                parts.append(str(p.name))
            else:
                parts.append(str(p))
        return "/".join(parts)

    paths_leaves = jax.tree_util.tree_flatten_with_path(params)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    names = [_name(path) for path, _ in paths_leaves[0]]
    return jax.tree_util.tree_unflatten(treedef, names)


@dataclass
class ShardingRules:
    """Ordered (regex, PartitionSpec) rules mapping parameter paths to shardings.

    Example TP rules for a transformer block::

        ShardingRules(rules=[
            (r".*attention.*(query|key|value).*kernel", P(None, "tensor")),   # column
            (r".*attention.*out.*kernel",               P("tensor", None)),   # row
            (r".*mlp.*up.*kernel",                      P(None, "tensor")),
            (r".*mlp.*down.*kernel",                    P("tensor", None)),
        ])
    """

    rules: list[tuple[str, PartitionSpec]] = field(default_factory=list)

    def match(self, path: str) -> PartitionSpec | None:
        for pattern, spec in self.rules:
            if re.fullmatch(pattern, path) or re.search(pattern, path):
                return spec
        return None


def _fsdp_spec(shape: tuple[int, ...], existing: PartitionSpec | None, fsdp_size: int) -> PartitionSpec:
    """Add ``fsdp`` sharding on the largest dim divisible by the axis size that is
    not already sharded; replicate scalars/indivisible leaves.

    1-D leaves (biases, layernorm scales) are deliberately left replicated:
    sharding a vector the size of the embedding dim saves nothing but makes XLA
    propagate an embedding-dim sharding onto the (batch, seq, embed) activation
    gradients in the backward, which conflicts with their batch sharding and
    triggers involuntary full rematerialization (spmd_partitioner warnings).
    """
    used = set()
    parts: list = list(existing) if existing is not None else [None] * len(shape)
    while len(parts) < len(shape):
        parts.append(None)
    for p in parts:
        if p is None:
            continue
        for name in (p if isinstance(p, tuple) else (p,)):
            used.add(name)
    if "fsdp" in used or fsdp_size <= 1 or len(shape) < 2:
        return PartitionSpec(*parts)
    candidates = [
        (shape[i], i)
        for i in range(len(shape))
        if parts[i] is None and shape[i] % fsdp_size == 0 and shape[i] >= fsdp_size
    ]
    if not candidates:
        return PartitionSpec(*parts)
    _, dim = max(candidates)
    parts[dim] = "fsdp"
    return PartitionSpec(*parts)


def _sanitize_spec(spec: PartitionSpec, shape: tuple[int, ...], mesh: Mesh) -> PartitionSpec:
    """Make ``spec`` valid for a leaf of ``shape`` on ``mesh``, degrading to
    replication instead of erroring.

    Three repair steps, each dropping only the offending piece:
      - axis names the mesh does not carry are removed (a serving mesh without
        an ``fsdp`` axis treats an fsdp reference as degree 1 — no sharding);
      - a spec longer than the leaf's rank collapses to fully replicated (the
        scalar/1-D fallback: GPT-2 layernorm scales/biases matched by a 2-D
        rule must come out replicated, not raise in ``device_put``);
      - a dim whose size is not divisible by its axes' total degree is
        replicated (uneven param shards would silently pad).
    """
    if len(spec) > len(shape):
        return PartitionSpec(*([None] * len(shape)))
    parts: list = []
    for dim, entry in enumerate(spec):
        if entry is None:
            parts.append(None)
            continue
        names = tuple(n for n in (entry if isinstance(entry, tuple) else (entry,))
                      if n in mesh.shape)
        degree = math.prod(mesh.shape[n] for n in names)
        if not names or (degree > 1 and shape[dim] % degree != 0):
            parts.append(None)
        else:
            parts.append(names if len(names) > 1 else names[0])
    return PartitionSpec(*parts)


def infer_param_shardings(
    params: Any,
    mesh: Mesh,
    rules: ShardingRules | None = None,
    shard_params_on_fsdp: bool = True,
) -> Any:
    """Pytree of NamedShardings for a params pytree.

    TP rules apply first (by path); the ``fsdp`` axis is then folded into whatever
    dims remain free. With ``shard_params_on_fsdp=False`` the fsdp axis only shards
    optimizer state (ZeRO-1 semantics, reference `DeepSpeedPlugin.zero_stage==1`).

    Leaves no rule fits — or that a rule fits *invalidly* (spec rank above the
    leaf's, axes the mesh lacks, indivisible dims) — come out REPLICATED rather
    than raising: scalar and 1-D leaves like layernorm scales/biases must never
    block sharding the tree they ride in (see `_sanitize_spec`).
    """
    fsdp_size = mesh.shape.get("fsdp", 1)
    names = param_path_names(params)

    def _spec(name: str, leaf: Any) -> NamedSharding:
        base = rules.match(name) if rules is not None else None
        shape = tuple(getattr(leaf, "shape", ()))
        if base is not None:
            base = _sanitize_spec(base, shape, mesh)
        if shard_params_on_fsdp:
            spec = _fsdp_spec(shape, base, fsdp_size)
        else:
            spec = base if base is not None else PartitionSpec()
        return NamedSharding(mesh, _sanitize_spec(spec, shape, mesh))

    return jax.tree.map(_spec, names, params)


def shard_params(params: Any, shardings: Any) -> Any:
    """Place every leaf according to its NamedSharding (the actual ZeRO-3 shard
    moment — after this, each device holds only its slice)."""
    return jax.tree.map(lambda p, s: jax.device_put(p, s), params, shardings)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    from .mesh import data_axes

    return NamedSharding(mesh, PartitionSpec(data_axes(mesh)))


def constrain(x: Any, mesh: Mesh, spec: PartitionSpec) -> Any:
    """with_sharding_constraint helper usable inside jitted code."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


# --------------------------------------------------------------------------- KV
# Serving-side sharding rules: the engine's slot-pool KV cache and the prefix
# block pool are pytrees of a known leaf zoo (models/kv_cache.py):
#   cached_key / cached_value  [slots, max_len, kv_heads, head_dim]
#   key_scale  / value_scale   [slots, max_len, kv_heads]        (int8 storage)
#   cache_index                [slots]
# Tensor parallelism shards the HEAD dim (attention is embarrassingly parallel
# over heads — the collectives stay in the proj/down matmuls, exactly where the
# training-mesh rules already put them); data parallelism shards the SLOT dim
# so replicas decode disjoint slot ranges. Block pools shard heads only — a
# block is one shared prefix, readable by every replica.


@dataclass(frozen=True)
class KVCacheSharding:
    """The NamedShardings a per-slot decode cache needs (hashable, so it
    can ride inside a frozen model config — `GPT2Config.kv_cache_sharding` —
    down to `models/kv_cache.decode_cache_update`'s in-jit constraints).

    In paged mode (`kv_cache_sharding(..., paged=True)`) ``kv`` describes the
    shared ``[num_blocks, block_tokens, ...]`` block pool instead of slot
    rows, and ``gathered`` carries the layout of the per-slot attended view
    the paged update assembles (`models/kv_cache.paged_decode_update`) — the
    slot-pool layout, so attention math shards identically in both modes.
    """

    kv: NamedSharding  # [slots, max_len, kv_heads, head_dim] buffers (or the block pool)
    scale: NamedSharding  # [slots, max_len, kv_heads] int8 absmax scales
    index: NamedSharding  # [slots] write cursor
    gathered: NamedSharding | None = None  # paged: [slots, span, kv_heads, head_dim] view


def _is_cache_index(path) -> bool:
    return getattr(path[-1], "key", getattr(path[-1], "name", None)) == "cache_index"


def kv_cache_sharding(
    mesh: Mesh,
    *,
    slots: int | None = None,
    batch_axes: tuple[str, ...] = ("data",),
    head_axis: str = "tensor",
    paged: bool = False,
) -> KVCacheSharding:
    """Build the `KVCacheSharding` for a slot-pool cache on ``mesh``.

    The slot dim is sharded over ``batch_axes`` only when ``slots`` divides
    their total degree (pass ``slots=None`` to force replication of the slot
    dim — the admission prefill's fresh rows use the head sharding alone).

    ``paged=True`` describes the paged-KV layout instead: the block pool
    replicates blocks across the data axis (any replica's slot may own or
    alias any block — block ids ride as data, the table gather must be able
    to reach the whole pool) and shards heads on the model axis; the per-slot
    write cursor and the gathered attended view keep the slot-dim rules.
    """
    batch_axes = tuple(n for n in batch_axes if mesh.shape.get(n, 1) > 1)
    dsize = math.prod(mesh.shape[n] for n in batch_axes) if batch_axes else 1
    row = batch_axes if (slots is not None and dsize > 1 and slots % dsize == 0) else None
    head = head_axis if mesh.shape.get(head_axis, 1) > 1 else None
    if paged:
        return KVCacheSharding(
            kv=NamedSharding(mesh, P(None, None, head, None)),
            scale=NamedSharding(mesh, P(None, None, head)),
            index=NamedSharding(mesh, P(row)),
            gathered=NamedSharding(mesh, P(row, None, head, None)),
        )
    return KVCacheSharding(
        kv=NamedSharding(mesh, P(row, None, head, None)),
        scale=NamedSharding(mesh, P(row, None, head)),
        index=NamedSharding(mesh, P(row)),
    )


def block_table_sharding(
    mesh: Mesh,
    *,
    slots: int | None = None,
    batch_axes: tuple[str, ...] = ("data",),
) -> NamedSharding:
    """Sharding for the paged engine's ``[slots, blocks_per_slot]`` block
    tables: the slot dim follows the cache's slot rule (sharded on the data
    axes only when divisible), the table entries themselves replicate —
    they are pool block IDS, data consumed by every tensor shard's gather."""
    batch_axes = tuple(n for n in batch_axes if mesh.shape.get(n, 1) > 1)
    dsize = math.prod(mesh.shape[n] for n in batch_axes) if batch_axes else 1
    row = batch_axes if (slots is not None and dsize > 1 and slots % dsize == 0) else None
    return NamedSharding(mesh, P(row, None))


def infer_cache_shardings(cache: Any, sharding: KVCacheSharding) -> Any:
    """Pytree of NamedShardings congruent with a slot-pool cache pytree (or its
    `jax.eval_shape` ShapeDtypeStructs) — the engine's jit in/out_shardings for
    every donated cache argument."""

    def pick(path, leaf):
        if _is_cache_index(path):
            return sharding.index
        return sharding.kv if getattr(leaf, "ndim", len(leaf.shape)) == 4 else sharding.scale

    return jax.tree_util.tree_map_with_path(pick, cache)


def infer_block_pool_shardings(pool: Any, mesh: Mesh, *, head_axis: str = "tensor") -> Any:
    """NamedShardings for a prefix block pool: heads sharded like the slot
    cache, blocks replicated across the data axis (any replica may gather any
    cached prefix block — prefix reuse must not depend on which replica's slot
    donated it)."""
    head = head_axis if mesh.shape.get(head_axis, 1) > 1 else None

    def pick(path, leaf):
        if _is_cache_index(path):
            return NamedSharding(mesh, P(None))
        ndim = getattr(leaf, "ndim", len(leaf.shape))
        return NamedSharding(mesh, P(None, None, head, None) if ndim == 4
                             else P(None, None, head))

    return jax.tree_util.tree_map_with_path(pick, pool)
