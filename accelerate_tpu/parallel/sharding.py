"""Parameter-sharding inference: every parallelism strategy as a sharding plan.

This is the TPU-native replacement for the reference's per-engine wrapping code
paths (DDP wrap `accelerator.py:1458`, FSDP wrap `:1463-1507`, DeepSpeed ZeRO init
`:1632-1872`, Megatron TP rebuild `utils/megatron_lm.py:91-141`): under GSPMD all of
them collapse to *where each parameter array is placed on the mesh*:

  - DP            -> replicate params, shard the batch on ``data``
  - FSDP / ZeRO-3 -> additionally shard each param's largest divisible dim on
                     ``fsdp`` (XLA schedules the all-gather/reduce-scatter pairs
                     that DeepSpeed hand-codes)
  - ZeRO-1        -> params replicated, *optimizer state* sharded on ``fsdp``
  - TP            -> rule-based Megatron-style column/row splits on ``tensor``
  - SP/PP         -> activation shardings, handled in the step/kernels, not here

Rules are (path-regex -> PartitionSpec) pairs, first match wins, mirroring the
plugin surface of `FullyShardedDataParallelPlugin.auto_wrap_policy` at far lower
complexity.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec


def param_path_names(params: Any) -> Any:
    """Pytree of '/'-joined path strings, aligned with the params tree."""

    def _name(path) -> str:
        parts = []
        for p in path:
            if hasattr(p, "key"):
                parts.append(str(p.key))
            elif hasattr(p, "idx"):
                parts.append(str(p.idx))
            elif hasattr(p, "name"):
                parts.append(str(p.name))
            else:
                parts.append(str(p))
        return "/".join(parts)

    paths_leaves = jax.tree_util.tree_flatten_with_path(params)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    names = [_name(path) for path, _ in paths_leaves[0]]
    return jax.tree_util.tree_unflatten(treedef, names)


@dataclass
class ShardingRules:
    """Ordered (regex, PartitionSpec) rules mapping parameter paths to shardings.

    Example TP rules for a transformer block::

        ShardingRules(rules=[
            (r".*attention.*(query|key|value).*kernel", P(None, "tensor")),   # column
            (r".*attention.*out.*kernel",               P("tensor", None)),   # row
            (r".*mlp.*up.*kernel",                      P(None, "tensor")),
            (r".*mlp.*down.*kernel",                    P("tensor", None)),
        ])
    """

    rules: list[tuple[str, PartitionSpec]] = field(default_factory=list)

    def match(self, path: str) -> PartitionSpec | None:
        for pattern, spec in self.rules:
            if re.fullmatch(pattern, path) or re.search(pattern, path):
                return spec
        return None


def _fsdp_spec(shape: tuple[int, ...], existing: PartitionSpec | None, fsdp_size: int) -> PartitionSpec:
    """Add ``fsdp`` sharding on the largest dim divisible by the axis size that is
    not already sharded; replicate scalars/indivisible leaves.

    1-D leaves (biases, layernorm scales) are deliberately left replicated:
    sharding a vector the size of the embedding dim saves nothing but makes XLA
    propagate an embedding-dim sharding onto the (batch, seq, embed) activation
    gradients in the backward, which conflicts with their batch sharding and
    triggers involuntary full rematerialization (spmd_partitioner warnings).
    """
    used = set()
    parts: list = list(existing) if existing is not None else [None] * len(shape)
    while len(parts) < len(shape):
        parts.append(None)
    for p in parts:
        if p is None:
            continue
        for name in (p if isinstance(p, tuple) else (p,)):
            used.add(name)
    if "fsdp" in used or fsdp_size <= 1 or len(shape) < 2:
        return PartitionSpec(*parts)
    candidates = [
        (shape[i], i)
        for i in range(len(shape))
        if parts[i] is None and shape[i] % fsdp_size == 0 and shape[i] >= fsdp_size
    ]
    if not candidates:
        return PartitionSpec(*parts)
    _, dim = max(candidates)
    parts[dim] = "fsdp"
    return PartitionSpec(*parts)


def infer_param_shardings(
    params: Any,
    mesh: Mesh,
    rules: ShardingRules | None = None,
    shard_params_on_fsdp: bool = True,
) -> Any:
    """Pytree of NamedShardings for a params pytree.

    TP rules apply first (by path); the ``fsdp`` axis is then folded into whatever
    dims remain free. With ``shard_params_on_fsdp=False`` the fsdp axis only shards
    optimizer state (ZeRO-1 semantics, reference `DeepSpeedPlugin.zero_stage==1`).
    """
    fsdp_size = mesh.shape.get("fsdp", 1)
    names = param_path_names(params)

    def _spec(name: str, leaf: Any) -> NamedSharding:
        base = rules.match(name) if rules is not None else None
        shape = tuple(getattr(leaf, "shape", ()))
        if shard_params_on_fsdp:
            spec = _fsdp_spec(shape, base, fsdp_size)
        else:
            spec = base if base is not None else PartitionSpec()
        return NamedSharding(mesh, spec)

    return jax.tree.map(_spec, names, params)


def shard_params(params: Any, shardings: Any) -> Any:
    """Place every leaf according to its NamedSharding (the actual ZeRO-3 shard
    moment — after this, each device holds only its slice)."""
    return jax.tree.map(lambda p, s: jax.device_put(p, s), params, shardings)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    from .mesh import data_axes

    return NamedSharding(mesh, PartitionSpec(data_axes(mesh)))


def constrain(x: Any, mesh: Mesh, spec: PartitionSpec) -> Any:
    """with_sharding_constraint helper usable inside jitted code."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
