"""Gradient communication hooks: compressed data-parallel gradient reductions.

TPU-native analogue of the reference's DDP comm hooks
(`utils/dataclasses.py:117-213` — `DDPCommHookType` fp16/bf16/powerSGD and
`DistributedDataParallelKwargs.register_comm_hook`, applied to the NCCL gradient
all-reduce). Under SPMD the gradient reduction is implicit in the jitted step, so
hooks are realized by computing per-replica gradients inside `shard_map` over the
``data`` axis and performing the cross-replica mean explicitly in compressed form:

- ``fp16`` / ``bf16``: cast gradients to the low-precision dtype, ``pmean`` over
  the data axis, cast back — halves gradient all-reduce bytes exactly like the
  reference's fp16/bf16 compression wrappers.
- ``power_sgd`` / ``batched_power_sgd``: rank-r low-rank approximation with
  per-replica error feedback (Vogels et al., PowerSGD) — each 2D+ gradient G is
  approximated as P @ Q^T where only P and Q are reduced. The error buffer is
  worker-local state, exactly as in the algorithm; it is stored with a leading
  replica axis and sharded over ``data`` so each replica reads/writes only its
  own slice. 1D tensors (biases, norms) are reduced uncompressed, as in the
  reference implementation. The warm-start phase (``start_powerSGD_iter``) is
  honored by the caller (`Accelerator.make_train_step`) by routing the first
  updates through the uncompressed step function.

All hooks are pure functions threading explicit state so they compose with jit.
Hook state is a ``(replicated, per_replica)`` pair: ``replicated`` carries the
warm-start Q factors and step counters (identical on every replica),
``per_replica`` carries the error-feedback buffers (leading axis = replica).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from enum import Enum
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

class DDPCommunicationHookType(str, Enum):
    """Mirrors reference `DDPCommunicationHookType` (`utils/dataclasses.py:80-115`);
    values interchange with the plain hook-name strings accepted everywhere a
    hook is configured."""

    NO = "no"
    FP16 = "fp16"
    BF16 = "bf16"
    POWER_SGD = "power_sgd"
    BATCHED_POWER_SGD = "batched_power_sgd"


COMM_HOOK_TYPES = tuple(e.value for e in DDPCommunicationHookType)


@dataclass
class CommHookConfig:
    """Configuration for a gradient communication hook.

    ``matrix_approximation_rank`` / ``start_powerSGD_iter`` mirror the reference's
    PowerSGD state kwargs (`comm_wrapper`/`comm_state_option`,
    `utils/dataclasses.py:190-213`). For the first ``start_powerSGD_iter``
    optimizer updates the step runs with uncompressed reductions (vanilla
    all-reduce warm-up, as in the reference).
    """

    comm_hook: str = "no"
    matrix_approximation_rank: int = 1
    start_powerSGD_iter: int = 2
    min_compression_elems: int = 1024  # tensors smaller than this go uncompressed

    def __post_init__(self):
        if isinstance(self.comm_hook, DDPCommunicationHookType):
            self.comm_hook = self.comm_hook.value
        if self.comm_hook not in COMM_HOOK_TYPES:
            raise ValueError(f"comm_hook must be one of {COMM_HOOK_TYPES}, got {self.comm_hook!r}")

    @property
    def is_powersgd(self) -> bool:
        return self.comm_hook in ("power_sgd", "batched_power_sgd")

    @property
    def warmup_updates(self) -> int:
        return self.start_powerSGD_iter if self.is_powersgd else 0


def _as_matrix(g: jax.Array) -> jax.Array:
    """Collapse all leading dims so g is (M, N) with N the last dim."""
    return g.reshape(-1, g.shape[-1])


def _compressible(shape: tuple, size: int, cfg: CommHookConfig) -> bool:
    return len(shape) >= 2 and size >= cfg.min_compression_elems


def init_comm_state(
    grads_shape: Any,
    cfg: CommHookConfig,
    num_replicas: int = 1,
    seed: int = 0,
    mesh: Any = None,
    axis: str = "data",
) -> tuple[Any, Any]:
    """Build the persistent hook state for a gradient pytree (shapes only).

    Returns ``(replicated, per_replica)``. PowerSGD keeps, per compressible leaf:
    Q (N, r) warm-start factor + step counter (replicated) and the error-feedback
    buffer E with shape (num_replicas, *grad_shape) (per-replica). When ``mesh``
    is given the error buffers are *allocated* sharded over ``axis`` — each
    device only ever holds its own (1, *shape) slice; the full per-replica stack
    never exists on any single device (it is params-sized × num_replicas, i.e.
    exactly the scale where PowerSGD is used because HBM is tight).
    Stateless hooks (fp16/bf16/no) get ``(None, None)``.
    """
    if not cfg.is_powersgd:
        return None, None
    key = jax.random.key(seed)
    leaves, treedef = jax.tree.flatten(grads_shape)
    keys = jax.random.split(key, max(len(leaves), 1))

    def rep_one(leaf, k):
        shape = tuple(leaf.shape)
        if not _compressible(shape, math.prod(shape), cfg):
            return None
        n = shape[-1]
        m = math.prod(shape[:-1])
        r = min(cfg.matrix_approximation_rank, n, m)
        q = jax.random.normal(k, (n, r), jnp.float32)
        return {"q": q, "step": jnp.zeros((), jnp.int32)}

    rep = jax.tree.unflatten(treedef, [rep_one(l, k) for l, k in zip(leaves, keys)])

    err_shapes = [
        tuple(l.shape) if _compressible(tuple(l.shape), math.prod(tuple(l.shape)), cfg) else None
        for l in leaves
    ]

    def zeros_all():
        return tuple(
            jnp.zeros((num_replicas, *s), jnp.float32) for s in err_shapes if s is not None
        )

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        # ONE jitted program zero-fills every buffer directly in its shards —
        # no device ever holds a full (num_replicas, *shape) copy and there is
        # a single compile, not one per parameter tensor.
        sharding = NamedSharding(mesh, PartitionSpec(axis))
        n_bufs = sum(s is not None for s in err_shapes)
        zeros = jax.jit(zeros_all, out_shardings=(sharding,) * n_bufs)()
    else:
        zeros = zeros_all()
    it = iter(zeros)
    err = jax.tree.unflatten(treedef, [next(it) if s is not None else None for s in err_shapes])
    return rep, err


def _orthogonalize(p: jax.Array) -> jax.Array:
    """Orthonormalize the columns of p (modified Gram-Schmidt; r is tiny so the
    sequential loop is negligible and avoids jnp.linalg.qr inside shard_map)."""
    scale = jnp.linalg.norm(p) + 1e-20
    cols = []
    for i in range(p.shape[-1]):
        c = p[:, i]
        for prev in cols:
            c = c - jnp.dot(prev, c) * prev
        n = jnp.linalg.norm(c)
        # a column that is (numerically) in the span of earlier ones must become
        # zero, not normalized round-off noise — that noise has unit norm and
        # corrupts the approximation
        c = jnp.where(n > 1e-6 * scale, c / jnp.maximum(n, 1e-20), jnp.zeros_like(c))
        cols.append(c)
    return jnp.stack(cols, axis=-1)


def _powersgd_leaf(g: jax.Array, rep: dict | None, err: jax.Array | None, axis: str, cfg):
    """One PowerSGD round for a single leaf. ``err`` is this replica's slice of
    the error buffer, shape (1, *g.shape). Returns (replicated ĝ, rep', err')."""
    if rep is None:
        return lax.pmean(g, axis), None, None
    g32 = g.astype(jnp.float32) + err[0]
    m = _as_matrix(g32)
    p = m @ rep["q"]  # (M, r)
    p = lax.pmean(p, axis)
    p = _orthogonalize(p)
    q = m.T @ p  # (N, r)
    q = lax.pmean(q, axis)
    approx = (p @ q.T).reshape(g.shape)
    candidate = g32 - approx  # worker-local residual, fed back next round
    # A non-finite gradient (fp16 overflow) must not poison the PERSISTENT
    # hook state: keep the previous residual and warm-start factor for this
    # leaf. Per-leaf select on the leaf's own finiteness keeps buffer
    # lifetimes local, so XLA can still alias the donated error buffers.
    leaf_ok = jnp.all(jnp.isfinite(candidate))
    new_err = jnp.where(leaf_ok, candidate, err[0])[None]
    new_rep = {"q": jnp.where(leaf_ok, q, rep["q"]), "step": rep["step"] + 1}
    return approx.astype(g.dtype), new_rep, new_err


def reduce_gradients(grads: Any, rep_state: Any, err_state: Any, axis: str, cfg: CommHookConfig):
    """Cross-replica-mean a gradient pytree under the configured hook.

    Must be called inside ``shard_map`` with ``axis`` bound; ``err_state`` leaves
    are this replica's (1, *shape) slices. Returns
    ``(replicated_grads, new_rep_state, new_err_state)``.
    """
    if cfg.comm_hook in ("fp16", "bf16"):
        dt = jnp.float16 if cfg.comm_hook == "fp16" else jnp.bfloat16
        out = jax.tree.map(lambda g: lax.pmean(g.astype(dt), axis).astype(g.dtype), grads)
        return out, rep_state, err_state
    if cfg.is_powersgd:
        g_leaves, treedef = jax.tree.flatten(grads)
        r_leaves = treedef.flatten_up_to(rep_state)
        e_leaves = treedef.flatten_up_to(err_state)
        triples = [
            _powersgd_leaf(g, r, e, axis, cfg)
            for g, r, e in zip(g_leaves, r_leaves, e_leaves)
        ]
        new_g = jax.tree.unflatten(treedef, [t[0] for t in triples])
        new_r = jax.tree.unflatten(treedef, [t[1] for t in triples])
        new_e = jax.tree.unflatten(treedef, [t[2] for t in triples])
        return new_g, new_r, new_e
    return jax.tree.map(lambda g: lax.pmean(g, axis), grads), rep_state, err_state
