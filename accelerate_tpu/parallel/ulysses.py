"""Ulysses-style sequence parallelism: all-to-all head redistribution.

Second long-context strategy alongside `ring_attention` (SURVEY.md §5: "Ulysses-
style all-to-all head redistribution as the alternative when heads >= sequence
shards"). Where ring attention keeps heads whole and rotates KV chunks, Ulysses
transposes the parallelism: activations arrive sequence-sharded, an all-to-all
regroups them to *head-sharded with full sequence*, each device runs ordinary
(flash) attention on its head slice with the entire sequence visible, and a
second all-to-all restores sequence sharding.

Trade-offs on TPU: two all-to-alls per attention vs ring's (n-1) ppermutes; with
heads % shards == 0 and moderate ring sizes the all-to-all rides ICI efficiently
and composes with any attention kernel unchanged (no lse merging), but the ring
scales to shard counts beyond the head count where Ulysses cannot.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.attention import dot_product_attention


def _all_to_all_seq_to_heads(x: jax.Array, axis_name: str) -> jax.Array:
    """[B, S/n, H, D] (sequence-sharded) -> [B, S, H/n, D] (head-sharded).

    tiled all-to-all: the head dim splits into n groups (group j to device j) and
    received sequence chunks concatenate in device order along the seq dim, so
    global ordering of both axes is preserved."""
    return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)


def _all_to_all_heads_to_seq(x: jax.Array, axis_name: str) -> jax.Array:
    """[B, S, H/n, D] (head-sharded) -> [B, S/n, H, D] (sequence-sharded)."""
    return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jax.Array,  # local [B, S/n, H, D]
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sequence",
    causal: bool = False,
    window: int | None = None,
    scale: float | None = None,
) -> jax.Array:
    """Call inside shard_map with ``axis_name`` bound; requires H % n == 0.
    After the all-to-all each head slice sees the FULL sequence, so
    ``window`` (sliding-window attention) composes unchanged."""
    n = jax.lax.axis_size(axis_name)
    h = q.shape[2]
    if h % n:
        raise ValueError(f"Ulysses needs heads ({h}) divisible by sequence shards ({n}).")
    qh = _all_to_all_seq_to_heads(q, axis_name)
    kh = _all_to_all_seq_to_heads(k, axis_name)
    vh = _all_to_all_seq_to_heads(v, axis_name)
    out = dot_product_attention(qh, kh, vh, causal=causal, window=window, scale=scale)
    return _all_to_all_heads_to_seq(out, axis_name)


def ulysses_attention_sharded(
    q: jax.Array,  # global [B, S, H, D]
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = False,
    window: int | None = None,
    scale: float | None = None,
) -> jax.Array:
    """shard_map wrapper over the sequence axis (same contract as
    `ring_attention_sharded`)."""
    if mesh.shape.get("sequence", 1) == 1:
        return dot_product_attention(q, k, v, causal=causal, window=window, scale=scale)
    from jax import shard_map

    from .mesh import active_batch_axes

    batch_axes = active_batch_axes(mesh)
    spec = P(batch_axes if batch_axes else None, "sequence", None, None)
    fn = functools.partial(
        ulysses_attention, axis_name="sequence", causal=causal, window=window, scale=scale
    )
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)(
        q, k, v
    )
