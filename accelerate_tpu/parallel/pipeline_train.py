"""Pipeline-parallel TRAINING: the GPipe schedule fused into the train-step path.

Capability parity: the reference trains pipelined models through Megatron-LM's
engine (`utils/megatron_lm.py:1035-1057` train_step: forward-backward over
microbatches, then a single optimizer tick). TPU-native re-founding: the whole
thing — GPipe ticks, loss, backward, gradient accumulation, adamw update — is
ONE jitted SPMD program over a ``stage`` mesh axis. `pipeline_apply` is
reverse-differentiable (scan + ppermute transpose to the reverse schedule), so
"pipeline backward" is just `jax.grad` of the pipelined loss; stage-sharded
parameters get stage-sharded gradients and optimizer state by construction.

Model layout: ``params = {"stages": stacked, "pre": ..., "post": ...}`` where
``stacked`` holds every (homogeneous) stage's weights on a leading stage dim
(sharded over the ``stage`` axis — each device stores only its stage), and the
optional ``pre``/``post`` trees (embedding / LM head) are replicated. ``pre``
runs outside the pipeline on the full microbatched input; ``post`` enters the
shard_map as an explicit replicated operand so its gradient is a psum over the
last stage's loss — closures over tracers are not differentiable shard_map
operands.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from .pipeline import pipeline_apply, stack_stage_params


def stage_shardings(params: Any, mesh, axis_name: str = "stage") -> Any:
    """Shardings for a pipeline param tree: ``stages`` leaves on the stage axis
    (leading dim), everything else replicated."""
    stage_sh = NamedSharding(mesh, P(axis_name))
    rep = NamedSharding(mesh, P())
    return {
        k: jax.tree.map(lambda _: stage_sh if k == "stages" else rep, v)
        for k, v in params.items()
    }


def build_pipeline_params(
    per_stage_params: list[Any] | Any,
    pre_params: Any = None,
    post_params: Any = None,
) -> dict:
    """Assemble the canonical pipeline param tree. ``per_stage_params`` is a
    list of per-stage pytrees (stacked here) or an already-stacked tree."""
    stacked = (
        stack_stage_params(per_stage_params)
        if isinstance(per_stage_params, list)
        else per_stage_params
    )
    params = {"stages": stacked}
    if pre_params is not None:
        params["pre"] = pre_params
    if post_params is not None:
        params["post"] = post_params
    return params


def pipeline_loss(
    stage_fn: Callable,
    params: dict,
    x: jax.Array,
    targets: Any,
    mesh,
    num_microbatches: int,
    *,
    pre_fn: Callable | None = None,
    loss_fn: Callable,
    post_fn: Callable | None = None,
    axis_name: str = "stage",
) -> jax.Array:
    """Mean loss of the pipelined model — differentiable wrt every param group.

    ``pre_fn(pre_params, x) -> h`` (optional embedding, replicated),
    ``stage_fn(stage_params, h_mb) -> h_mb`` (one homogeneous stage),
    ``post_fn(post_params, y_mb) -> pred_mb`` (optional head, replicated),
    ``loss_fn(pred_mb, target_mb) -> scalar`` (per-microbatch mean).
    """
    h = pre_fn(params["pre"], x) if pre_fn is not None else x
    post = params.get("post")
    if post_fn is not None and post is None:
        raise ValueError("post_fn given but params has no 'post' group")

    if post is None:
        out_fn = lambda y, t, _=None: loss_fn(y, t)  # noqa: E731
        extra = None
    else:
        out_fn = lambda y, t, pp: loss_fn(post_fn(pp, y) if post_fn else y, t)  # noqa: E731
        extra = post
    return pipeline_apply(
        stage_fn,
        params["stages"],
        h,
        mesh,
        num_microbatches,
        out_fn=out_fn,
        out_fn_args=targets,
        out_fn_extra=extra,
        axis_name=axis_name,
    )


def make_pipeline_train_step(
    accelerator,
    stage_fn: Callable,
    loss_fn: Callable,
    model=None,
    optimizer=None,
    *,
    num_microbatches: int,
    pre_fn: Callable | None = None,
    post_fn: Callable | None = None,
    max_grad_norm: float | None = None,
    donate: bool = True,
    axis_name: str = "stage",
) -> Callable:
    """Fused jitted GPipe train step over the accelerator's ``stage`` mesh axis.

    Returns ``step(batch) -> loss`` with ``batch = (x, targets)``. Honors
    gradient accumulation exactly like `Accelerator.make_train_step`: microbatch
    calls accumulate gradients in a donated buffer; each sync boundary runs one
    donated update (mean + optional global-norm clip + optax update + apply).
    The GPipe *microbatches* (``num_microbatches``) live INSIDE one step —
    gradient accumulation composes on top across steps (SURVEY hard part #4).
    """
    from ..accelerator import _clip_tree

    if model is None:
        model = accelerator._models[0]
    if optimizer is None:
        optimizer = accelerator._optimizer_for(model)
    if max_grad_norm is None:
        max_grad_norm = accelerator.gradient_clipping
    mesh = accelerator.mesh
    if mesh is None or mesh.shape.get(axis_name, 1) <= 1:
        raise ValueError(
            f"make_pipeline_train_step needs a mesh with a non-trivial {axis_name!r} "
            "axis (ParallelismConfig(stage_size=...))."
        )
    if getattr(accelerator, "scaler", None) is not None:
        raise NotImplementedError(
            "make_pipeline_train_step does not support fp16 dynamic loss scaling "
            "yet (no inner scale / overflow skip on this path — an overflowed "
            "microbatch would corrupt params silently). Use bf16 (the TPU "
            "default) or fp32 for pipeline training."
        )
    policy = accelerator.policy
    tx = optimizer.optimizer
    param_shardings = getattr(model, "shardings", None)

    def constrain(tree):
        if param_shardings is None or tree is None:
            return tree
        return jax.tree.map(jax.lax.with_sharding_constraint, tree, param_shardings)

    def loss_of(params, batch):
        x, targets = batch
        p = policy.cast_to_compute(params)
        loss = pipeline_loss(
            stage_fn,
            p,
            x,
            targets,
            mesh,
            num_microbatches,
            pre_fn=pre_fn,
            loss_fn=loss_fn,
            post_fn=post_fn,
            axis_name=axis_name,
        )
        return loss.astype(jnp.float32)

    @jax.jit
    def micro_first(params, batch):
        loss, grads = jax.value_and_grad(loss_of)(params, batch)
        return constrain(grads), loss

    # donate the accumulator so HBM holds one gradient copy during accumulation
    from functools import partial

    @partial(jax.jit, donate_argnums=(1,) if donate else ())
    def micro_acc(params, acc, batch):
        loss, grads = jax.value_and_grad(loss_of)(params, batch)
        return constrain(jax.tree.map(jnp.add, acc, grads)), loss

    def _update(params, opt_state, acc, batch, inv_k):
        loss, grads = jax.value_and_grad(loss_of)(params, batch)
        if acc is not None:
            grads = jax.tree.map(jnp.add, acc, grads)
        grads = constrain(jax.tree.map(lambda g: g * inv_k, grads))
        if max_grad_norm is not None:
            grads, _ = _clip_tree(grads, max_grad_norm)
        updates, new_opt_state = tx.update(grads, opt_state, params)
        new_params = constrain(optax.apply_updates(params, updates))
        return new_params, new_opt_state, loss

    update = jax.jit(_update, donate_argnums=(0, 1, 2) if donate else ())
    box = {"acc": None}

    def step(batch: Any) -> jax.Array:
        accelerator._do_sync()
        if accelerator.gradient_state.sync_gradients:
            inv_k = jnp.asarray(
                1.0 / accelerator.gradient_state.num_steps, dtype=jnp.float32
            )
            params, opt_state, loss = update(
                model.params, optimizer.opt_state, box["acc"], batch, inv_k
            )
            model.params = params
            optimizer.opt_state = opt_state
            optimizer._num_updates += 1
            box["acc"] = None
        else:
            if box["acc"] is None:
                box["acc"], loss = micro_first(model.params, batch)
            else:
                box["acc"], loss = micro_acc(model.params, box["acc"], batch)
        return loss

    return step
