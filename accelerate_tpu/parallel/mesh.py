"""Device-mesh construction over ICI x DCN axes.

This replaces the reference's process-group machinery (`state.py:710-767` backend
selection + `init_process_group`): on TPU there is no NCCL/MPI rendezvous — a single
logical mesh over all chips is built once, and every parallelism strategy (DP, FSDP,
TP, SP, PP) is a sharding annotation over its axes rather than a separate engine.

Axis order follows `constants.MESH_AXIS_NAMES`: the leading axes change slowest
across the device list, so with multiple hosts/slices the ``data`` (and ``fsdp``)
axes naturally span DCN while ``tensor``/``sequence`` stay inside a slice on ICI —
the layout the scaling playbook prescribes (collectives for model parallelism ride
ICI; only gradient reductions cross DCN).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field, fields

import jax
import numpy as np
from jax.sharding import Mesh

from ..utils.constants import MESH_AXIS_NAMES


@dataclass
class ParallelismConfig:
    """Degrees for each mesh axis. ``-1`` on ``data_parallel_size`` means "use all
    remaining devices" (the common case). Every strategy in the reference's plugin
    zoo (`DistributedDataParallelKwargs`, `FullyShardedDataParallelPlugin`,
    `MegatronLMPlugin` tp/pp degrees — reference `utils/dataclasses.py:974-2363`)
    maps onto one or more of these numbers.
    """

    data_parallel_size: int = -1
    fsdp_size: int = 1
    stage_size: int = 1  # pipeline stages
    sequence_size: int = 1  # sequence/context parallelism (ring attention)
    tensor_size: int = 1

    def axis_sizes(self, num_devices: int) -> dict[str, int]:
        sizes = {
            "data": self.data_parallel_size,
            "fsdp": self.fsdp_size,
            "stage": self.stage_size,
            "sequence": self.sequence_size,
            "tensor": self.tensor_size,
        }
        fixed = math.prod(v for v in sizes.values() if v != -1)
        n_infer = sum(1 for v in sizes.values() if v == -1)
        if n_infer > 1:
            raise ValueError("At most one mesh axis may be -1 (inferred).")
        if n_infer == 1:
            if num_devices % fixed != 0:
                raise ValueError(
                    f"Cannot infer axis size: {num_devices} devices not divisible by {fixed}."
                )
            sizes = {k: (num_devices // fixed if v == -1 else v) for k, v in sizes.items()}
        total = math.prod(sizes.values())
        if total != num_devices:
            raise ValueError(
                f"Mesh {sizes} covers {total} devices but {num_devices} are available."
            )
        return sizes

    @classmethod
    def from_kwargs(cls, **kwargs) -> "ParallelismConfig":
        valid = {f.name for f in fields(cls)}
        return cls(**{k: v for k, v in kwargs.items() if k in valid})

    @property
    def non_data_degree(self) -> int:
        return (
            max(self.fsdp_size, 1)
            * max(self.stage_size, 1)
            * max(self.sequence_size, 1)
            * max(self.tensor_size, 1)
        )


def build_mesh(
    config: ParallelismConfig | None = None,
    devices: list | None = None,
) -> Mesh:
    """Build the global device mesh.

    Device ordering: ``jax.devices()`` enumerates host-major, so reshaping with
    ``data`` as the leading axis places replica boundaries at host boundaries —
    gradient all-reduce crosses DCN only on the ``data``/``fsdp`` axes while
    ``tensor``/``sequence``/``stage`` collectives stay on ICI.
    """
    config = config or ParallelismConfig()
    if devices is None:
        devices = jax.devices()
    sizes = config.axis_sizes(len(devices))
    shape = tuple(sizes[name] for name in MESH_AXIS_NAMES)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, MESH_AXIS_NAMES)


def serving_mesh(
    data: int = 1,
    model: int = 1,
    devices: list | None = None,
) -> Mesh:
    """The serving engine's ``(data, model)`` mesh: ``data`` replicas each
    holding a ``model``-way tensor-parallel shard of the weights and KV pool
    (`serving/engine.py` ``mesh=``). The model axis IS the standard ``tensor``
    axis, so `gpt2_sharding_rules()` and the training-path TP annotations apply
    unchanged; the remaining axes are degree 1.

    Unlike `build_mesh` this takes the FIRST ``data * model`` devices instead
    of requiring an exact cover — a (2, 2) serving mesh on an 8-device host is
    a normal single-host-multi-device test topology.
    """
    data, model = int(data), int(model)
    if data < 1 or model < 1:
        raise ValueError(f"mesh degrees must be >= 1, got data={data} model={model}")
    if devices is None:
        devices = jax.devices()
    need = data * model
    if len(devices) < need:
        raise ValueError(
            f"serving mesh ({data}, {model}) needs {need} devices, "
            f"only {len(devices)} available"
        )
    sizes = {"data": data, "tensor": model}
    shape = tuple(sizes.get(name, 1) for name in MESH_AXIS_NAMES)
    dev_array = np.asarray(devices[:need]).reshape(shape)
    return Mesh(dev_array, MESH_AXIS_NAMES)


def mesh_axis_size(mesh: Mesh, *names: str) -> int:
    """Product of the sizes of the given axes."""
    return math.prod(mesh.shape[n] for n in names)


def data_axes(mesh: Mesh) -> tuple[str, ...]:
    """Axes over which the global batch is sharded (data + fsdp: FSDP shards both
    parameters and, like ZeRO, the batch — each fsdp group member sees distinct data)."""
    return tuple(n for n in ("data", "fsdp") if mesh.shape.get(n, 1) >= 1)


def active_batch_axes(mesh: Mesh) -> tuple[str, ...]:
    """The non-trivial batch-sharding axes — the shard_map in_spec form used by
    the ring/ulysses/flash wrappers (one definition so they cannot drift)."""
    return tuple(n for n in ("data", "fsdp") if mesh.shape.get(n, 1) > 1)


def inside_shard_map(mesh: Mesh) -> bool:
    """True when tracing inside a shard_map region that binds any of this
    mesh's axes — nesting another shard_map over the same mesh there would
    fail at trace time."""
    import jax

    for name in mesh.axis_names:
        try:
            jax.lax.axis_index(name)
            return True
        except Exception:
            continue
    return False
