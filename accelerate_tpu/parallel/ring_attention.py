"""Ring attention: exact attention over a sequence-sharded mesh axis.

The reference has **no** context parallelism at all (SURVEY.md §5 long-context:
"no ring attention, Ulysses, or blockwise attention anywhere in the repo") — its
only lever is Megatron's activation-sharding flag. This module is the green-field
TPU design: the sequence dimension is sharded over the ``sequence`` mesh axis and
KV chunks rotate around the ring with `lax.ppermute` while each device accumulates
its queries' attention with running log-sum-exp merging (blockwise-exact, no
approximation). On TPU the ppermute rides ICI neighbor links, overlapping with the
local attention compute — sequence length scales linearly with ring size at
constant per-device memory.

Each ring step is wrapped in `jax.checkpoint` so backward recomputes block logits
instead of storing O(S^2/n) residuals per step.

Use `ring_attention` inside `shard_map`, or `ring_attention_sharded` as a drop-in
for [batch, seq, heads, head_dim] global arrays under jit.
"""

from __future__ import annotations

import functools
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _block_attention(q, k, v, q_offset, kv_offset, causal, scale):
    """Attention of a local Q chunk against one KV chunk, returning the
    *unnormalized* accumulator and per-row (max, denom) statistics in fp32.

    q: [B, Sq, H, D]; k/v: [B, Skv, H, D]. Offsets are global positions of the
    chunks, used for exact causal masking at shard boundaries.
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])[:, None]
        kv_pos = kv_offset + jnp.arange(k.shape[1])[None, :]
        logits = jnp.where((kv_pos <= q_pos)[None, None], logits, NEG_INF)
    m = jnp.max(logits, axis=-1, keepdims=True)  # [B,H,Sq,1]
    p = jnp.exp(logits - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v).astype(jnp.float32)
    return acc, m[..., 0], l[..., 0]  # acc [B,Sq,H,D]; m,l [B,H,Sq]


def ring_attention(
    q: jax.Array,  # local chunk [B, S/n, H, D]
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sequence",
    causal: bool = False,
    scale: float | None = None,
) -> jax.Array:
    """Exact ring attention; call inside shard_map with ``axis_name`` bound.

    Device r holds query chunk r. At ring step t it attends the KV chunk that
    started on device (r + t) mod n, then passes its current KV to device r-1
    (so chunks travel r -> r-1 -> ...). Running (max, denom, acc) statistics merge
    each block exactly as flash attention does across kv blocks.
    """
    n = jax.lax.axis_size(axis_name)
    r = jax.lax.axis_index(axis_name)
    b, s_chunk, h, d = q.shape
    scale = 1.0 / math.sqrt(d) if scale is None else scale
    q_offset = r * s_chunk

    def step(t, carry):
        k_cur, v_cur, acc, m, l = carry
        kv_idx = (r + t) % n
        kv_offset = kv_idx * s_chunk

        blk = functools.partial(_block_attention, causal=causal, scale=scale)
        acc_b, m_b, l_b = jax.checkpoint(blk)(q, k_cur, v_cur, q_offset, kv_offset)

        # merge running statistics (flash-style)
        m_new = jnp.maximum(m, m_b)  # [B,H,Sq]
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_b - m_new)
        l_new = l * alpha + l_b * beta
        # acc layout [B,Sq,H,D]; stats layout [B,H,Sq] -> transpose for broadcast
        alpha_t = jnp.transpose(alpha, (0, 2, 1))[..., None]
        beta_t = jnp.transpose(beta, (0, 2, 1))[..., None]
        acc_new = acc * alpha_t + acc_b * beta_t

        # rotate KV around the ring: send to r-1, receive from r+1
        perm = [(i, (i - 1) % n) for i in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, acc_new, m_new, l_new

    acc0 = jnp.zeros((b, s_chunk, h, d), dtype=jnp.float32)
    m0 = jnp.full((b, h, s_chunk), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((b, h, s_chunk), dtype=jnp.float32)
    _, _, acc, m, l = jax.lax.fori_loop(0, n, step, (k, v, acc0, m0, l0))
    l_t = jnp.transpose(l, (0, 2, 1))[..., None]
    out = acc / jnp.where(l_t == 0.0, 1.0, l_t)
    return out.astype(q.dtype)


def ring_attention_sharded(
    q: jax.Array,  # global [B, S, H, D]
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = False,
    scale: float | None = None,
) -> jax.Array:
    """shard_map wrapper: batch over data axes, sequence over the ring axis.
    Falls back to plain attention when the sequence axis is trivial."""
    if mesh.shape.get("sequence", 1) == 1:
        from ..ops.attention import dot_product_attention

        return dot_product_attention(q, k, v, causal=causal, scale=scale)
    from jax import shard_map

    from .mesh import active_batch_axes

    batch_axes = active_batch_axes(mesh)
    spec = P(batch_axes if batch_axes else None, "sequence", None, None)

    fn = functools.partial(ring_attention, axis_name="sequence", causal=causal, scale=scale)
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)
