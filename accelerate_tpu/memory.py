"""OOM-retry utilities.

Capability parity: reference `src/accelerate/utils/memory.py` (179 LoC) —
`find_executable_batch_size` halves the batch size and retries the wrapped
function on OOM; `release_memory` drops references and clears device allocations.

TPU-native notes: XLA raises `XlaRuntimeError` with RESOURCE_EXHAUSTED when a
program doesn't fit HBM (usually at compile/first-execute). Retrying with a
smaller static batch recompiles — exactly the reference workflow. `clear_device
_cache` maps to clearing jax's compiled-program and array caches.
"""

from __future__ import annotations

import functools
import gc
import inspect
from typing import Callable

import jax


def should_reduce_batch_size(exception: Exception) -> bool:
    """True for device-memory exhaustion errors (reference `memory.py:69-95`)."""
    msg = str(exception)
    markers = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory", "OOM")
    return any(m in msg for m in markers)


def clear_device_cache(garbage_collection: bool = False) -> None:
    if garbage_collection:
        gc.collect()
    jax.clear_caches()


def release_memory(*objects):
    """Drop references and free device memory (reference `memory.py:41`)."""
    cleared = [None for _ in objects]
    clear_device_cache(garbage_collection=True)
    return cleared if len(cleared) != 1 else cleared[0]


def find_executable_batch_size(
    function: Callable | None = None, starting_batch_size: int = 128
) -> Callable:
    """Decorator: call ``function(batch_size, *args, **kwargs)``, halving
    ``batch_size`` and retrying whenever the device reports memory exhaustion
    (reference `memory.py:111-168`)."""
    if function is None:
        return functools.partial(find_executable_batch_size, starting_batch_size=starting_batch_size)

    params = list(inspect.signature(function).parameters)
    if not params or params[0] == "self" and len(params) < 2:
        raise TypeError(
            f"Batch-size argument must be first in {function.__name__}'s signature."
        )

    @functools.wraps(function)
    def wrapper(*args, **kwargs):
        batch_size = wrapper.batch_size
        while True:
            if batch_size == 0:
                raise RuntimeError("No executable batch size found, reached zero.")
            try:
                result = function(batch_size, *args, **kwargs)
                wrapper.batch_size = batch_size
                return result
            except Exception as e:
                if should_reduce_batch_size(e):
                    clear_device_cache(garbage_collection=True)
                    batch_size //= 2
                else:
                    raise

    wrapper.batch_size = starting_batch_size
    return wrapper
