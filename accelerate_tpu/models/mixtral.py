"""Mixtral: Llama backbone with a sparse top-k mixture-of-experts FFN.

Capability position: the reference has no MoE model support at all — its only
MoE surface is marking expert classes as DeepSpeed ZeRO-3 leaves
(`utils/dataclasses.py:1352-1370`; SURVEY.md §2.4 EP row "not implemented").
This is the TPU-native design: GShard/Switch-style static-capacity dispatch as
one-hot einsums (MXU-friendly, no gather/scatter), expert-stacked weights whose
leading dim shards over the ``tensor`` mesh axis (expert parallelism), and XLA
deriving the token all-to-alls from the shardings.

Routing follows HF Mixtral semantics: softmax over the selected top-k logits
(not over all experts), SwiGLU experts (w1 gate, w3 up, w2 down). Tokens beyond
an expert's capacity fall through on the residual stream (GShard behavior; set
``capacity_factor >= num_experts / top_k`` for drop-free routing, e.g. in parity
tests).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..parallel.sharding import ShardingRules
from .llama import LlamaAttention, LlamaConfig, RMSNorm


@dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    max_position_embeddings: int = 4096
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 2.0
    aux_loss_weight: float = 0.001  # HF MixtralConfig.router_aux_loss_coef default
    rope_theta: float = 1e6
    rms_norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False
    remat_policy: str | None = None  # see utils/remat.py
    attention_impl: str = "auto"
    sliding_window: int | None = None  # HF MixtralConfig.sliding_window role
    kv_cache_dtype: Any = None  # None | jnp.int8 (see LlamaConfig.kv_cache_dtype)

    @classmethod
    def mixtral_8x7b(cls, **kw) -> "MixtralConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "MixtralConfig":
        return cls(**{**dict(vocab_size=256, max_position_embeddings=128, hidden_size=64,
                             intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
                             num_experts=4, top_k=2), **kw})

    def as_llama(self) -> LlamaConfig:
        """Attention/backbone hyperparams reused from the Llama implementation."""
        return LlamaConfig(
            vocab_size=self.vocab_size,
            max_position_embeddings=self.max_position_embeddings,
            hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            num_layers=self.num_layers,
            num_heads=self.num_heads,
            num_kv_heads=self.num_kv_heads,
            rope_theta=self.rope_theta,
            rms_norm_eps=self.rms_norm_eps,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            remat=self.remat,
            attention_impl=self.attention_impl,
            sliding_window=self.sliding_window,
            kv_cache_dtype=self.kv_cache_dtype,
        )


class MixtralSparseMoeBlock(nn.Module):
    """Top-k routed SwiGLU experts with static-capacity einsum dispatch."""

    config: MixtralConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        b, s, e = x.shape
        n_tokens = b * s
        E, k = cfg.num_experts, cfg.top_k
        capacity = max(int(cfg.capacity_factor * n_tokens * k / E), 1)

        xt = x.reshape(n_tokens, e)
        router_logits = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                                 param_dtype=cfg.param_dtype, name="gate")(
            xt.astype(jnp.float32)
        )
        # HF Mixtral: softmax over the SELECTED top-k logits
        top_logits, expert_idx = jax.lax.top_k(router_logits, k)  # [T, k]
        gate_vals = jax.nn.softmax(top_logits, axis=-1)  # [T, k]

        from ..ops.moe import build_dispatch_combine, sow_aux_loss

        dispatch, combine = build_dispatch_combine(
            expert_idx, gate_vals, E, capacity, cfg.dtype
        )

        # expert-stacked SwiGLU weights; leading (expert) dim shards over tensor
        w1 = self.param("w1", nn.initializers.lecun_normal(),
                        (E, e, cfg.intermediate_size), cfg.param_dtype)
        w3 = self.param("w3", nn.initializers.lecun_normal(),
                        (E, e, cfg.intermediate_size), cfg.param_dtype)
        w2 = self.param("w2", nn.initializers.lecun_normal(),
                        (E, cfg.intermediate_size, e), cfg.param_dtype)

        expert_in = jnp.einsum("tec,td->ecd", dispatch, xt.astype(cfg.dtype))
        gate_h = jnp.einsum("ecd,edf->ecf", expert_in, w1.astype(cfg.dtype))
        up_h = jnp.einsum("ecd,edf->ecf", expert_in, w3.astype(cfg.dtype))
        h = jax.nn.silu(gate_h) * up_h
        expert_out = jnp.einsum("ecf,efd->ecd", h, w2.astype(cfg.dtype))
        out = jnp.einsum("tec,ecd->td", combine.astype(cfg.dtype), expert_out)

        # HF load-balancing aux loss: fraction of tokens per expert counted over
        # ALL top-k selections (summed over slots, NOT divided by k — HF's
        # load_balancing_loss_func sums the top-k one-hots) x mean full-softmax
        # prob. HF computes ONE loss over the concat of every layer's gates
        # (i.e. a mean across layers); the sown per-layer terms are summed by
        # collect_aux_losses, so divide by num_layers here to land on the same
        # total magnitude for the default router_aux_loss_coef.
        all_sel = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [T, k, E]
        me = jnp.mean(jnp.sum(all_sel, axis=1), axis=0)  # [E]
        ce = jnp.mean(jax.nn.softmax(router_logits, axis=-1), axis=0)
        aux = cfg.aux_loss_weight * E * jnp.sum(me * ce) / cfg.num_layers
        sow_aux_loss(self, aux)
        return out.reshape(b, s, e).astype(x.dtype)


class MixtralBlock(nn.Module):
    config: MixtralConfig

    @nn.compact
    def __call__(self, x: jax.Array, decode: bool = False, position_offset: Any = 0) -> jax.Array:
        cfg = self.config
        lcfg = cfg.as_llama()
        x = x + LlamaAttention(lcfg, name="attn")(
            RMSNorm(cfg.rms_norm_eps, cfg.param_dtype, name="input_norm")(x),
            decode, position_offset,
        )
        x = x + MixtralSparseMoeBlock(cfg, name="moe")(
            RMSNorm(cfg.rms_norm_eps, cfg.param_dtype, name="post_attn_norm")(x)
        )
        return x


class MixtralForCausalLM(nn.Module):
    """Returns fp32 logits [batch, seq, vocab]."""

    config: MixtralConfig

    @nn.compact
    def __call__(
        self,
        input_ids: jax.Array,
        deterministic: bool = True,
        decode: bool = False,
        position_offset: Any = 0,
        return_hidden: bool = False,
    ) -> jax.Array:
        cfg = self.config
        embed = self.param("embed_tokens", nn.initializers.normal(0.02),
                           (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        x = embed.astype(cfg.dtype)[input_ids]
        if cfg.remat:
            from ..utils.remat import remat_block

            block = remat_block(MixtralBlock, cfg.remat_policy, static_argnums=(2,))
        else:
            block = MixtralBlock
        for i in range(cfg.num_layers):
            x = block(cfg, name=f"layer_{i}")(x, decode, position_offset)
        x = RMSNorm(cfg.rms_norm_eps, cfg.param_dtype, name="final_norm")(x)
        if return_hidden:
            # fused-CE path (see llama.py): head folds into the loss kernel
            return x
        lm_head = self.param("lm_head", nn.initializers.normal(0.02),
                             (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        return jnp.einsum("bse,ve->bsv", x.astype(cfg.dtype), lm_head.astype(cfg.dtype),
                          preferred_element_type=jnp.float32)

    def init_params(self, rng: jax.Array, batch: int = 2, seq: int = 16) -> Any:
        return self.init(rng, jnp.zeros((batch, seq), dtype=jnp.int32))["params"]


def mixtral_sharding_rules() -> ShardingRules:
    """TP on attention + EP on experts: q/k/v column-parallel, o row-parallel,
    expert-stacked w1/w2/w3 shard their leading (expert) dim over ``tensor``,
    the router stays replicated (reference has no equivalent; SURVEY.md §2.4)."""
    return ShardingRules(
        rules=[
            (r".*attn/(q_proj|k_proj|v_proj)/kernel", P(None, "tensor")),
            (r".*attn/o_proj/kernel", P("tensor", None)),
            (r".*moe/(w1|w2|w3)", P("tensor", None, None)),
            (r".*moe/gate.*", P(None, None)),
            (r".*embed_tokens", P("tensor", None)),
            (r".*lm_head", P("tensor", None)),
        ]
    )


def mixtral_blockwise(config: MixtralConfig):
    """Decompose Mixtral into sequential blocks (embed -> layer_i... -> head)
    for blockwise offload streaming and `prepare_pippy` PP inference, like
    `llama_blockwise`. The router's aux-loss sow is a no-op on this path
    (no mutable 'intermediates' collection at inference)."""
    from ..big_modeling import BlockwiseModel

    def embed_fn(p, input_ids):
        return p["embed_tokens"].astype(config.dtype)[input_ids]

    def make_block_fn(i):
        def block_fn(p, x):
            return MixtralBlock(config, name=f"layer_{i}").apply({"params": p}, x)

        return block_fn

    def head_fn(p, x):
        x = RMSNorm(config.rms_norm_eps, config.param_dtype, name="final_norm").apply(
            {"params": p["final_norm"]}, x
        )
        return jnp.einsum(
            "bse,ve->bsv", x.astype(config.dtype), p["lm_head"].astype(config.dtype),
            preferred_element_type=jnp.float32,
        )

    fns = [("embed", embed_fn)]
    fns += [(f"layer_{i}", make_block_fn(i)) for i in range(config.num_layers)]
    fns += [("head", head_fn)]
    return BlockwiseModel(block_fns=fns)


def mixtral_blockwise_state_dict(params: dict) -> dict:
    """Regroup a MixtralForCausalLM param tree into the blockwise layout."""
    out = {"embed": {"embed_tokens": params["embed_tokens"]}}
    for k in params:
        if k.startswith("layer_"):
            out[k] = params[k]
    out["head"] = {"final_norm": params["final_norm"], "lm_head": params["lm_head"]}
    return out


def mixtral_loss_fn(model, batch) -> jax.Array:
    """LM loss + sown router aux losses (must be added inside the grad fn)."""
    from ..ops.moe import collect_aux_losses
    from .gpt2 import _next_token_labels, cross_entropy_loss

    logits = model(batch["input_ids"])
    return cross_entropy_loss(logits, _next_token_labels(batch)) + collect_aux_losses(
        model.extra_state
    )


def mixtral_loss_fn_fused(model, batch, block_r: int | None = None,
                          block_v: int | None = None) -> jax.Array:
    """`mixtral_loss_fn` with the LM head folded into the Pallas fused-CE
    kernel (no [b, s, V] logits in HBM) + the sown router aux losses."""
    from ..ops.fused_ce import fused_cross_entropy
    from ..ops.moe import collect_aux_losses
    from ..utils.environment import parse_int_from_env
    from .gpt2 import _next_token_labels

    if block_r is None:
        block_r = parse_int_from_env("ACCELERATE_TPU_FUSED_CE_BLOCK_R", 512)
    if block_v is None:
        block_v = parse_int_from_env("ACCELERATE_TPU_FUSED_CE_BLOCK_V", 1024)
    hidden = model(batch["input_ids"], return_hidden=True)
    labels = _next_token_labels(batch)
    b, s, e = hidden.shape
    head = model.params["lm_head"].astype(hidden.dtype)
    ce = fused_cross_entropy(
        hidden.reshape(b * s, e), head, labels.reshape(b * s),
        block_r=block_r, block_v=block_v,
    )
    return ce + collect_aux_losses(model.extra_state)


def params_from_hf_mixtral(hf_state_dict: dict, config: MixtralConfig) -> dict:
    """Map HF transformers MixtralForCausalLM weights into this layout (torch
    Linear stores [out, in] -> transpose; per-expert w1/w2/w3 stack on a leading
    expert dim)."""

    def _np(t):
        return t.detach().cpu().numpy() if hasattr(t, "detach") else np.asarray(t)

    def _lin(key):
        return _np(hf_state_dict[key]).T

    p: dict[str, Any] = {
        "embed_tokens": _np(hf_state_dict["model.embed_tokens.weight"]),
        "final_norm": {"scale": _np(hf_state_dict["model.norm.weight"])},
        "lm_head": _np(hf_state_dict["lm_head.weight"]),
    }
    for i in range(config.num_layers):
        hf = f"model.layers.{i}."
        moe = hf + "block_sparse_moe."
        p[f"layer_{i}"] = {
            "input_norm": {"scale": _np(hf_state_dict[hf + "input_layernorm.weight"])},
            "post_attn_norm": {"scale": _np(hf_state_dict[hf + "post_attention_layernorm.weight"])},
            "attn": {
                "q_proj": {"kernel": _lin(hf + "self_attn.q_proj.weight")},
                "k_proj": {"kernel": _lin(hf + "self_attn.k_proj.weight")},
                "v_proj": {"kernel": _lin(hf + "self_attn.v_proj.weight")},
                "o_proj": {"kernel": _lin(hf + "self_attn.o_proj.weight")},
            },
            "moe": {
                "gate": {"kernel": _lin(moe + "gate.weight")},
                "w1": np.stack([_lin(moe + f"experts.{j}.w1.weight")
                                for j in range(config.num_experts)]),
                "w3": np.stack([_lin(moe + f"experts.{j}.w3.weight")
                                for j in range(config.num_experts)]),
                "w2": np.stack([_lin(moe + f"experts.{j}.w2.weight")
                                for j in range(config.num_experts)]),
            },
        }
    return p
