"""ViT: Vision Transformer for image classification.

Capability parity: the reference trains any torch vision model (its
`examples/cv_example.py` uses timm resnet50); ViT is the transformer-native
vision family for this framework, with an HF `ViTForImageClassification` weight
mapping (reference checkpoint ingestion analogue, `utils/modeling.py:1611`).

TPU notes: patch embedding is extract-patches + one matmul (identical math to
HF's strided Conv2d but expressed as a dense op the MXU tiles directly);
attention is bidirectional over `num_patches + 1` tokens so sequence lengths
stay static; pre-LN blocks keep residuals in the compute dtype.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops.attention import dot_product_attention
from ..parallel.sharding import ShardingRules


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_channels: int = 3
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_ratio: int = 4
    num_labels: int = 1000
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @classmethod
    def base(cls, **kw) -> "ViTConfig":
        return cls(**kw)

    @classmethod
    def large(cls, **kw) -> "ViTConfig":
        return cls(**{**dict(hidden_size=1024, num_layers=24, num_heads=16), **kw})

    @classmethod
    def tiny(cls, **kw) -> "ViTConfig":
        return cls(**{**dict(image_size=32, patch_size=8, hidden_size=64,
                             num_layers=2, num_heads=4, num_labels=10), **kw})

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


def patchify(pixel_values: jax.Array, patch: int) -> jax.Array:
    """[B, C, H, W] -> [B, n_patches, C*patch*patch], channel-major per patch
    (the flattening order of a torch Conv2d kernel, so HF weights map 1:1)."""
    b, c, h, w = pixel_values.shape
    x = pixel_values.reshape(b, c, h // patch, patch, w // patch, patch)
    x = x.transpose(0, 2, 4, 1, 3, 5)  # [B, gh, gw, C, ph, pw]
    return x.reshape(b, (h // patch) * (w // patch), c * patch * patch)


class ViTSelfAttention(nn.Module):
    config: ViTConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        b, s, e = x.shape
        head_dim = e // cfg.num_heads
        dense = lambda name: nn.Dense(e, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name=name)
        q = dense("query")(x).reshape(b, s, cfg.num_heads, head_dim)
        k = dense("key")(x).reshape(b, s, cfg.num_heads, head_dim)
        v = dense("value")(x).reshape(b, s, cfg.num_heads, head_dim)
        out = dot_product_attention(q, k, v, causal=False)
        return dense("out")(out.reshape(b, s, e))


class ViTBlock(nn.Module):
    config: ViTConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        ln = lambda name: nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                                       param_dtype=cfg.param_dtype, name=name)
        x = x + ViTSelfAttention(cfg, name="attn")(ln("ln_before")(x).astype(cfg.dtype))
        h = ln("ln_after")(x).astype(cfg.dtype)
        h = nn.Dense(cfg.mlp_ratio * cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="mlp_up")(h)
        h = nn.gelu(h, approximate=False)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="mlp_down")(h)
        return x + h


class ViTForImageClassification(nn.Module):
    """Returns fp32 logits [batch, num_labels]; input [B, C, H, W] (HF layout)."""

    config: ViTConfig

    @nn.compact
    def __call__(self, pixel_values: jax.Array, deterministic: bool = True) -> jax.Array:
        cfg = self.config
        patches = patchify(pixel_values.astype(cfg.dtype), cfg.patch_size)
        x = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     name="patch_embed")(patches)
        cls = self.param("cls_token", nn.initializers.zeros, (1, 1, cfg.hidden_size),
                         cfg.param_dtype)
        x = jnp.concatenate([jnp.broadcast_to(cls.astype(cfg.dtype),
                                              (x.shape[0], 1, cfg.hidden_size)), x], axis=1)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, cfg.num_patches + 1, cfg.hidden_size), cfg.param_dtype)
        x = x + pos.astype(cfg.dtype)
        for i in range(cfg.num_layers):
            x = ViTBlock(cfg, name=f"block_{i}")(x)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         param_dtype=cfg.param_dtype, name="ln_final")(x)
        cls_out = x[:, 0].astype(jnp.float32)  # keep the fp32 LayerNorm output
        return nn.Dense(cfg.num_labels, dtype=jnp.float32, param_dtype=cfg.param_dtype,
                        name="classifier")(cls_out)

    def init_params(self, rng: jax.Array, batch: int = 2) -> Any:
        cfg = self.config
        shape = (batch, cfg.num_channels, cfg.image_size, cfg.image_size)
        return self.init(rng, jnp.zeros(shape, cfg.dtype))["params"]


def vit_sharding_rules() -> ShardingRules:
    """TP: qkv/up column-parallel, out/down row-parallel (Megatron split)."""
    return ShardingRules(
        rules=[
            (r".*attn/(query|key|value)/kernel", P(None, "tensor")),
            (r".*attn/out/kernel", P("tensor", None)),
            (r".*mlp_up/kernel", P(None, "tensor")),
            (r".*mlp_down/kernel", P("tensor", None)),
        ]
    )


def vit_loss_fn(model, batch) -> jax.Array:
    import optax

    logits = model(batch["pixel_values"])
    return optax.softmax_cross_entropy_with_integer_labels(logits, batch["labels"]).mean()


def params_from_hf_vit(hf_state_dict: dict, config: ViTConfig) -> dict:
    """Map HF transformers ViTForImageClassification weights into this layout.
    The Conv2d patch projection [hidden, C, ph, pw] flattens to a dense kernel
    [C*ph*pw, hidden] (same contraction order as `patchify`)."""

    def _np(t):
        return t.detach().cpu().numpy() if hasattr(t, "detach") else np.asarray(t)

    def _lin(key):
        return _np(hf_state_dict[key]).T

    def _ln(prefix):
        return {"scale": _np(hf_state_dict[prefix + ".weight"]),
                "bias": _np(hf_state_dict[prefix + ".bias"])}

    conv = _np(hf_state_dict["vit.embeddings.patch_embeddings.projection.weight"])
    p: dict[str, Any] = {
        "patch_embed": {
            "kernel": conv.reshape(conv.shape[0], -1).T,
            "bias": _np(hf_state_dict["vit.embeddings.patch_embeddings.projection.bias"]),
        },
        "cls_token": _np(hf_state_dict["vit.embeddings.cls_token"]),
        "pos_embed": _np(hf_state_dict["vit.embeddings.position_embeddings"]),
        "ln_final": _ln("vit.layernorm"),
        "classifier": {
            "kernel": _lin("classifier.weight"),
            "bias": _np(hf_state_dict["classifier.bias"]),
        },
    }
    for i in range(config.num_layers):
        hf = f"vit.encoder.layer.{i}."
        att = hf + "attention.attention."
        p[f"block_{i}"] = {
            "ln_before": _ln(hf + "layernorm_before"),
            "ln_after": _ln(hf + "layernorm_after"),
            "attn": {
                "query": {"kernel": _lin(att + "query.weight"),
                          "bias": _np(hf_state_dict[att + "query.bias"])},
                "key": {"kernel": _lin(att + "key.weight"),
                        "bias": _np(hf_state_dict[att + "key.bias"])},
                "value": {"kernel": _lin(att + "value.weight"),
                          "bias": _np(hf_state_dict[att + "value.bias"])},
                "out": {"kernel": _lin(hf + "attention.output.dense.weight"),
                        "bias": _np(hf_state_dict[hf + "attention.output.dense.bias"])},
            },
            "mlp_up": {"kernel": _lin(hf + "intermediate.dense.weight"),
                       "bias": _np(hf_state_dict[hf + "intermediate.dense.bias"])},
            "mlp_down": {"kernel": _lin(hf + "output.dense.weight"),
                         "bias": _np(hf_state_dict[hf + "output.dense.bias"])},
        }
    return p
