"""Model families (the reference's Megatron adapter matrix — Bert/GPT/T5,
`utils/megatron_lm.py:446-864` — plus Llama/ResNet from the example suite)."""

from .bert import BertConfig, BertForSequenceClassification, bert_sharding_rules
from .gpt2 import (
    GPT2Config,
    GPT2LMHead,
    chunked_cross_entropy,
    gpt2_sharding_rules,
    lm_loss_fn,
    lm_loss_fn_fused,
    lm_loss_fn_pallas,
    params_from_hf_gpt2,
)
from .llama import (
    LlamaConfig,
    LlamaForCausalLM,
    llama_loss_fn,
    llama_loss_fn_fused,
    llama_sharding_rules,
    params_from_hf_llama,
)
from .mixtral import (
    MixtralConfig,
    MixtralForCausalLM,
    mixtral_loss_fn,
    mixtral_loss_fn_fused,
    mixtral_sharding_rules,
    params_from_hf_mixtral,
)
from .resnet import ResNet, ResNetConfig, image_classification_loss_fn
from .vit import (
    ViTConfig,
    ViTForImageClassification,
    params_from_hf_vit,
    vit_loss_fn,
    vit_sharding_rules,
)
from .t5 import (
    T5Config,
    T5ForConditionalGeneration,
    params_from_hf_t5,
    seq2seq_loss_fn,
    seq2seq_loss_fn_fused,
    shift_tokens_right,
    t5_sharding_rules,
)
