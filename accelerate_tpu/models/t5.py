"""T5 encoder-decoder family, TPU-first.

Capability position: the reference's Megatron adapter ships per-arch train
steps for Bert/GPT/**T5** (`utils/megatron_lm.py:446-864`, T5TrainStep at
`:700`+) — T5 is the encoder-decoder member of its model matrix. This is a
native flax implementation in the same style as `bert.py`/`llama.py`: bf16
compute / fp32 masters, fp32 norm + softmax statistics, attention through
`ops.attention`, TP expressed as sharding rules.

Architecture notes (T5 v1.1): RMS LayerNorm without bias or mean subtraction,
bucketed relative position bias computed once per stack and shared across
layers, gated-GELU feed-forward, no positional embeddings, tied or untied LM
head with the d_model**-0.5 logit rescale when tied.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops.attention import dot_product_attention
from ..parallel.sharding import ShardingRules


@dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 768
    d_kv: int = 64
    d_ff: int = 2048
    num_layers: int = 12
    num_decoder_layers: int = 12
    num_heads: int = 12
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    layer_norm_eps: float = 1e-6
    dropout: float = 0.0
    tie_word_embeddings: bool = False  # v1.1 unties; v1.0 ties
    gated_ffn: bool = True  # v1.1 gated-GELU; False = v1.0 ReLU
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @classmethod
    def base(cls, **kw) -> "T5Config":
        return cls(**kw)

    @classmethod
    def small(cls, **kw) -> "T5Config":
        return cls(**{**dict(d_model=512, d_ff=1024, num_layers=8, num_decoder_layers=8,
                             num_heads=6), **kw})

    @classmethod
    def tiny(cls, **kw) -> "T5Config":
        return cls(**{**dict(vocab_size=512, d_model=64, d_kv=16, d_ff=128, num_layers=2,
                             num_decoder_layers=2, num_heads=4), **kw})


class T5LayerNorm(nn.Module):
    """RMS norm, no bias, no mean subtraction — statistics in fp32."""

    config: T5Config

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), cfg.param_dtype)
        x32 = x.astype(jnp.float32)
        var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
        return (x32 * jax.lax.rsqrt(var + cfg.layer_norm_eps)).astype(cfg.dtype) * scale.astype(cfg.dtype)


def relative_position_bucket(
    relative_position: jax.Array,
    bidirectional: bool,
    num_buckets: int,
    max_distance: int,
) -> jax.Array:
    """T5's log-bucketed relative positions: half the buckets exact, half log-spaced."""
    ret = jnp.zeros_like(relative_position)
    n = -relative_position
    if bidirectional:
        num_buckets //= 2
        ret = ret + (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_if_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / np.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_if_large = jnp.minimum(val_if_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_if_large)


class T5RelativeBias(nn.Module):
    """Per-stack learned bias table; returns [1, H, Sq, Sk] added to attn logits."""

    config: T5Config
    bidirectional: bool

    @nn.compact
    def __call__(self, q_len: int, k_len: int) -> jax.Array:
        cfg = self.config
        table = self.param(
            "rel_embedding", nn.initializers.normal(0.02),
            (cfg.relative_attention_num_buckets, cfg.num_heads), cfg.param_dtype,
        )
        ctx = jnp.arange(q_len)[:, None]
        mem = jnp.arange(k_len)[None, :]
        bucket = relative_position_bucket(
            mem - ctx, self.bidirectional,
            cfg.relative_attention_num_buckets, cfg.relative_attention_max_distance,
        )
        bias = table[bucket]  # [Sq, Sk, H]
        return jnp.transpose(bias, (2, 0, 1))[None].astype(jnp.float32)


class T5Attention(nn.Module):
    """Self- or cross-attention. T5 uses unscaled dot product (scale folded
    into init), per-head dim d_kv independent of d_model."""

    config: T5Config

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        kv: jax.Array | None = None,
        bias: jax.Array | None = None,
        mask: jax.Array | None = None,
        causal: bool = False,
    ) -> jax.Array:
        cfg = self.config
        b, s, _ = x.shape
        kv = x if kv is None else kv
        inner = cfg.num_heads * cfg.d_kv
        dense = lambda n, feat: nn.Dense(feat, use_bias=False, dtype=cfg.dtype,
                                         param_dtype=cfg.param_dtype, name=n)
        q = dense("q", inner)(x).reshape(b, s, cfg.num_heads, cfg.d_kv)
        k = dense("k", inner)(kv).reshape(b, kv.shape[1], cfg.num_heads, cfg.d_kv)
        v = dense("v", inner)(kv).reshape(b, kv.shape[1], cfg.num_heads, cfg.d_kv)
        out = dot_product_attention(q, k, v, bias=bias, mask=mask, causal=causal, scale=1.0)
        return dense("o", cfg.d_model)(out.reshape(b, s, inner))


class T5FeedForward(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        dense = lambda n, feat: nn.Dense(feat, use_bias=False, dtype=cfg.dtype,
                                         param_dtype=cfg.param_dtype, name=n)
        if cfg.gated_ffn:
            h = nn.gelu(dense("wi_0", cfg.d_ff)(x), approximate=True) * dense("wi_1", cfg.d_ff)(x)
        else:
            h = nn.relu(dense("wi", cfg.d_ff)(x))
        return dense("wo", cfg.d_model)(h)


class T5Block(nn.Module):
    config: T5Config
    is_decoder: bool

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        self_bias: jax.Array | None,
        enc_out: jax.Array | None = None,
        self_mask: jax.Array | None = None,
        cross_mask: jax.Array | None = None,
    ) -> jax.Array:
        cfg = self.config
        # pre-LN everywhere
        h = T5LayerNorm(cfg, name="ln_self")(x)
        x = x + T5Attention(cfg, name="self_attn")(
            h, bias=self_bias, mask=self_mask, causal=self.is_decoder
        )
        if self.is_decoder:
            h = T5LayerNorm(cfg, name="ln_cross")(x)
            x = x + T5Attention(cfg, name="cross_attn")(h, kv=enc_out, mask=cross_mask)
        h = T5LayerNorm(cfg, name="ln_ff")(x)
        return x + T5FeedForward(cfg, name="ff")(h)


class T5Stack(nn.Module):
    config: T5Config
    is_decoder: bool

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        enc_out: jax.Array | None = None,
        self_mask: jax.Array | None = None,
        cross_mask: jax.Array | None = None,
    ) -> jax.Array:
        cfg = self.config
        s = x.shape[1]
        bias = T5RelativeBias(cfg, bidirectional=not self.is_decoder, name="rel_bias")(s, s)
        n = cfg.num_decoder_layers if self.is_decoder else cfg.num_layers
        for i in range(n):
            x = T5Block(cfg, self.is_decoder, name=f"block_{i}")(
                x, bias, enc_out, self_mask, cross_mask
            )
        return T5LayerNorm(cfg, name="ln_final")(x)


class T5ForConditionalGeneration(nn.Module):
    """Full encoder-decoder LM; returns fp32 logits [b, tgt, vocab]."""

    config: T5Config

    @nn.compact
    def __call__(
        self,
        input_ids: jax.Array,
        decoder_input_ids: jax.Array,
        attention_mask: jax.Array | None = None,
        decoder_attention_mask: jax.Array | None = None,
        return_hidden: bool = False,
    ) -> jax.Array:
        cfg = self.config
        shared = self.param("shared_embedding", nn.initializers.normal(1.0),
                            (cfg.vocab_size, cfg.d_model), cfg.param_dtype)
        enc_mask = None if attention_mask is None else attention_mask[:, None, None, :].astype(bool)
        dec_mask = None if decoder_attention_mask is None else (
            decoder_attention_mask[:, None, None, :].astype(bool)
        )
        cross_mask = enc_mask

        enc_x = shared[input_ids].astype(cfg.dtype)
        enc_out = T5Stack(cfg, is_decoder=False, name="encoder")(enc_x, self_mask=enc_mask)
        dec_x = shared[decoder_input_ids].astype(cfg.dtype)
        dec_out = T5Stack(cfg, is_decoder=True, name="decoder")(
            dec_x, enc_out=enc_out, self_mask=dec_mask, cross_mask=cross_mask
        )
        if return_hidden:
            # fused-CE path: caller folds the head (tied rescale included)
            # into the loss kernel
            return dec_out
        dec_out = dec_out.astype(jnp.float32)
        if cfg.tie_word_embeddings:
            # tied head reuses the embedding; logits rescaled per T5
            logits = (dec_out * (cfg.d_model ** -0.5)) @ shared.astype(jnp.float32).T
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=jnp.float32,
                              param_dtype=cfg.param_dtype, name="lm_head")(dec_out)
        return logits

    def init_params(self, rng: jax.Array, batch: int = 2, src: int = 32, tgt: int = 16) -> Any:
        ids = jnp.zeros((batch, src), dtype=jnp.int32)
        dec = jnp.zeros((batch, tgt), dtype=jnp.int32)
        return self.init(rng, ids, dec)["params"]


def t5_sharding_rules() -> ShardingRules:
    """Megatron-style TP: q/k/v/wi column-split, o/wo row-split, embeddings row-split."""
    return ShardingRules(
        rules=[
            (r".*(self_attn|cross_attn)/(q|k|v)/kernel", P(None, "tensor")),
            (r".*(self_attn|cross_attn)/o/kernel", P("tensor", None)),
            (r".*ff/(wi|wi_0|wi_1)/kernel", P(None, "tensor")),
            (r".*ff/wo/kernel", P("tensor", None)),
            (r".*shared_embedding", P("tensor", None)),
            (r".*lm_head/kernel", P(None, "tensor")),
        ]
    )


def t5_pipeline_forward(
    config: T5Config,
    params: dict,
    mesh=None,
    num_microbatches: int | None = None,
    axis_name: str = "stage",
):
    """Pipeline-parallel T5 inference: both stacks pipelined over the ``stage``
    mesh axis (reference `examples/inference/pippy/t5.py` role — PiPPy splits
    the whole encoder-decoder; here each stack runs as its own GPipe SPMD
    program, the TPU-native equivalent).

    The decoder stage activation is the pytree ``(hidden, encoder_out)``:
    encoder output rides through every decoder stage unchanged so cross-
    attention reads it stage-locally — no per-rank broadcast program, unlike
    PiPPy's send/recv graph. The shared relative-bias table is duplicated into
    every stage's param group (it is tiny: num_buckets x num_heads).

    Returns ``forward(input_ids, decoder_input_ids) -> fp32 logits`` (jitted).
    Pad-free batches: padding masks are not plumbed through the pipeline.
    """
    from ..parallel.pipeline import pipeline_apply, stack_stage_params
    from ..state import PartialState

    cfg = config
    if mesh is None:
        mesh = PartialState().mesh
    S = mesh.shape.get(axis_name, 1)
    if S <= 1:
        raise ValueError(
            f"t5_pipeline_forward needs a non-trivial '{axis_name}' mesh axis")
    if cfg.num_layers % S or cfg.num_decoder_layers % S:
        raise ValueError(
            f"num_layers {cfg.num_layers} and num_decoder_layers "
            f"{cfg.num_decoder_layers} must both divide into {S} stages")
    M = num_microbatches or S
    per_e, per_d = cfg.num_layers // S, cfg.num_decoder_layers // S

    def _stack(side: str, per: int) -> Any:
        groups = [
            {
                "rel_bias": params[side]["rel_bias"],
                **{f"layer_{j}": params[side][f"block_{s * per + j}"] for j in range(per)},
            }
            for s in range(S)
        ]
        return stack_stage_params(groups)

    enc_stacked, dec_stacked = _stack("encoder", per_e), _stack("decoder", per_d)

    def enc_stage_fn(p, x):
        s = x.shape[1]
        bias = T5RelativeBias(cfg, bidirectional=True).apply({"params": p["rel_bias"]}, s, s)
        for j in range(per_e):
            x = T5Block(cfg, is_decoder=False, name=f"layer_{j}").apply(
                {"params": p[f"layer_{j}"]}, x, bias
            )
        return x

    def dec_stage_fn(p, xe):
        x, enc = xe
        s = x.shape[1]
        bias = T5RelativeBias(cfg, bidirectional=False).apply({"params": p["rel_bias"]}, s, s)
        for j in range(per_d):
            x = T5Block(cfg, is_decoder=True, name=f"layer_{j}").apply(
                {"params": p[f"layer_{j}"]}, x, bias, enc_out=enc
            )
        return x, enc

    shared = params["shared_embedding"]
    ln = lambda side, x: T5LayerNorm(cfg).apply({"params": params[side]["ln_final"]}, x)

    @jax.jit
    def forward(input_ids: jax.Array, decoder_input_ids: jax.Array) -> jax.Array:
        emb = shared.astype(cfg.dtype)
        enc_x = emb[input_ids]
        enc_out = pipeline_apply(
            enc_stage_fn, enc_stacked, enc_x, mesh, M, axis_name=axis_name
        )
        enc_out = ln("encoder", enc_out)
        dec_x = emb[decoder_input_ids]
        dec_out, _ = pipeline_apply(
            dec_stage_fn, dec_stacked, (dec_x, enc_out), mesh, M, axis_name=axis_name
        )
        dec_out = ln("decoder", dec_out).astype(jnp.float32)
        if cfg.tie_word_embeddings:
            return (dec_out * (cfg.d_model ** -0.5)) @ shared.astype(jnp.float32).T
        return dec_out @ params["lm_head"]["kernel"].astype(jnp.float32)

    return forward


def seq2seq_loss_fn(model, batch) -> jax.Array:
    """Padded-token-masked CE over decoder targets. Batch keys: input_ids,
    decoder_input_ids, labels (pad = -100, the HF convention)."""
    logits = model(
        batch["input_ids"],
        batch["decoder_input_ids"],
        batch.get("attention_mask"),
        batch.get("decoder_attention_mask"),
    )
    labels = batch["labels"]
    valid = labels != -100
    safe = jnp.where(valid, labels, 0)
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logprobs, safe[..., None], axis=-1)[..., 0]
    return (nll * valid).sum() / jnp.maximum(valid.sum(), 1)


def seq2seq_loss_fn_fused(model, batch, block_r: int | None = None,
                          block_v: int | None = None) -> jax.Array:
    """`seq2seq_loss_fn` with the head folded into the Pallas fused-CE kernel
    (no [b, tgt, V] logits in HBM). Tied heads fold the T5 ``d_model**-0.5``
    logit rescale into the hidden states; untied use the lm_head kernel
    transposed to [V, e]. Note the head matmul runs in compute dtype inside
    the kernel (the dense path upcasts to fp32 first) — identical at fp32,
    within bf16 rounding otherwise."""
    from ..ops.fused_ce import fused_cross_entropy
    from ..utils.environment import parse_int_from_env

    if block_r is None:
        block_r = parse_int_from_env("ACCELERATE_TPU_FUSED_CE_BLOCK_R", 512)
    if block_v is None:
        block_v = parse_int_from_env("ACCELERATE_TPU_FUSED_CE_BLOCK_V", 1024)
    hidden = model(
        batch["input_ids"],
        batch["decoder_input_ids"],
        batch.get("attention_mask"),
        batch.get("decoder_attention_mask"),
        return_hidden=True,
    )
    b, s, e = hidden.shape
    cfg_tied = "lm_head" not in model.params
    if cfg_tied:
        head = model.params["shared_embedding"].astype(hidden.dtype)
        hidden = hidden * (e ** -0.5)
    else:
        head = model.params["lm_head"]["kernel"].T.astype(hidden.dtype)
    labels = batch["labels"]
    return fused_cross_entropy(
        hidden.reshape(b * s, e), head, labels.reshape(b * s),
        block_r=block_r, block_v=block_v,
    )


def shift_tokens_right(labels: jax.Array, decoder_start_token_id: int = 0) -> jax.Array:
    """Build decoder_input_ids from labels (teacher forcing), replacing -100 with 0."""
    shifted = jnp.roll(labels, 1, axis=-1).at[:, 0].set(decoder_start_token_id)
    return jnp.where(shifted == -100, 0, shifted)


def params_from_hf_t5(hf_state_dict: dict, config: T5Config) -> dict:
    """Map HF transformers T5ForConditionalGeneration weights into this layout
    (torch [out,in] kernels transposed to [in,out])."""

    def _np(t):
        return np.asarray(t.detach().cpu().numpy() if hasattr(t, "detach") else t,
                          dtype=np.float32)

    def _lin(key):
        return _np(hf_state_dict[key]).T

    params: dict = {"shared_embedding": _np(hf_state_dict["shared.weight"])}
    if not config.tie_word_embeddings and "lm_head.weight" in hf_state_dict:
        params["lm_head"] = {"kernel": _lin("lm_head.weight")}

    for side, n_layers, is_dec in (("encoder", config.num_layers, False),
                                   ("decoder", config.num_decoder_layers, True)):
        stack: dict = {
            "rel_bias": {"rel_embedding": _np(
                hf_state_dict[f"{side}.block.0.layer.0.SelfAttention.relative_attention_bias.weight"]
            )},
            "ln_final": {"scale": _np(hf_state_dict[f"{side}.final_layer_norm.weight"])},
        }
        for i in range(n_layers):
            pre = f"{side}.block.{i}.layer"
            blk: dict = {
                "ln_self": {"scale": _np(hf_state_dict[f"{pre}.0.layer_norm.weight"])},
                "self_attn": {w: {"kernel": _lin(f"{pre}.0.SelfAttention.{w}.weight")}
                              for w in ("q", "k", "v", "o")},
            }
            ff_idx = 2 if is_dec else 1
            if is_dec:
                blk["ln_cross"] = {"scale": _np(hf_state_dict[f"{pre}.1.layer_norm.weight"])}
                blk["cross_attn"] = {w: {"kernel": _lin(f"{pre}.1.EncDecAttention.{w}.weight")}
                                     for w in ("q", "k", "v", "o")}
            blk["ln_ff"] = {"scale": _np(hf_state_dict[f"{pre}.{ff_idx}.layer_norm.weight"])}
            ff: dict = {"wo": {"kernel": _lin(f"{pre}.{ff_idx}.DenseReluDense.wo.weight")}}
            if config.gated_ffn:
                ff["wi_0"] = {"kernel": _lin(f"{pre}.{ff_idx}.DenseReluDense.wi_0.weight")}
                ff["wi_1"] = {"kernel": _lin(f"{pre}.{ff_idx}.DenseReluDense.wi_1.weight")}
            else:
                ff["wi"] = {"kernel": _lin(f"{pre}.{ff_idx}.DenseReluDense.wi.weight")}
            blk["ff"] = ff
            stack[f"block_{i}"] = blk
        params[side] = stack
    return params
