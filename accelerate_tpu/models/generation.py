"""Autoregressive generation with KV cache.

Capability role: the reference's big-model-inference benchmark surface is
`model.generate` over dispatched checkpoints (BASELINE.md table); this is the
TPU-native decode loop: prefill populates fixed-size KV caches, then a
`lax.scan` emits one token per step — fully jitted, static shapes, cache buffers
donated between steps.

Works with any flax module accepting ``(input_ids, decode=..., position_offset=...)``
and exposing a ``"cache"`` variable collection (see models/gpt2.py SelfAttention).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp


def _sample(logits: jax.Array, key: jax.Array, temperature: float, top_k: int | None) -> jax.Array:
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k is not None:
        kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1)


@functools.partial(jax.jit, static_argnums=(0, 3, 4, 5))
def _generate_impl(module, params, input_ids, max_new_tokens, temperature, top_k, rng):
    b, prompt_len = input_ids.shape
    cache = module.init(jax.random.key(0), jnp.zeros((b, 1), jnp.int32), decode=True)["cache"]

    # prefill the cache with the whole prompt in one pass
    logits, mutated = module.apply(
        {"params": params, "cache": cache}, input_ids, decode=True, position_offset=0,
        mutable=["cache"],
    )
    cache = mutated["cache"]
    rng, key = jax.random.split(rng)
    token = _sample(logits[:, -1], key, temperature, top_k)

    def step(carry, _):
        cache, token, pos, rng = carry
        logits, mutated = module.apply(
            {"params": params, "cache": cache}, token[:, None], decode=True,
            position_offset=pos, mutable=["cache"],
        )
        rng, key = jax.random.split(rng)
        nxt = _sample(logits[:, -1], key, temperature, top_k)
        return (mutated["cache"], nxt, pos + 1, rng), token

    (_, last, _, _), tokens = jax.lax.scan(
        step, (cache, token, jnp.asarray(prompt_len), rng), None, length=max_new_tokens - 1
    )
    tokens = jnp.concatenate([tokens.T, last[:, None]], axis=1)  # [b, max_new_tokens]
    return tokens


def generate(
    module: Any,
    params: Any,
    input_ids: jax.Array,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: int | None = None,
    rng: jax.Array | None = None,
) -> jax.Array:
    """Generate ``max_new_tokens`` continuations for each prompt row.

    temperature=0 is greedy; otherwise categorical sampling (optionally top-k).
    Returns [batch, max_new_tokens] new tokens (prompt not repeated).

    Contract: prompt rows share one length (the cache write index is global —
    batch ragged prompts by bucketing equal lengths, as the distributed
    inference examples do; the reference delegates generation to transformers
    entirely, so there is no reference ragged-batch behavior to match).
    """
    if rng is None:
        rng = jax.random.key(0)
    return _generate_impl(module, params, input_ids, int(max_new_tokens), float(temperature), top_k, rng)
