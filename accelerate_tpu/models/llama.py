"""Llama family, TPU-first (BASELINE.md configs[4]: Llama-2-7B sharded inference).

Modern decoder stack: RMSNorm (fp32 stats), rotary position embeddings, grouped-
query attention, SwiGLU MLP, no biases. Same framework contracts as gpt2.py:
bf16 compute / fp32 masters, flash/XLA/ring attention dispatch, KV-cache decode,
Megatron-style TP as sharding rules (GQA-aware: KV heads shard with the tensor
axis only when num_kv_heads divides it).

HF interchange: `params_from_hf_llama` maps transformers LlamaForCausalLM
weights into this layout.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops.attention import attention
from ..parallel.sharding import ShardingRules


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    max_position_embeddings: int = 4096
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False
    remat_policy: str | None = None  # see utils/remat.py
    attention_impl: str = "auto"
    sliding_window: int | None = None  # Mistral-class: query i sees keys in (i-W, i]
    # decode KV cache storage: None = compute dtype; jnp.int8 = blockwise-
    # quantized cache (absmax per position x kv-head, scales in fp32) — halves
    # cache HBM traffic and doubles the context that fits. Beyond the
    # reference's weights-only bnb quantization.
    kv_cache_dtype: Any = None
    # fp8 projections (reference TE convert_model role; see models/gpt2._dense):
    # a DelayedScalingRecipe switches every block projection to ops/fp8.Fp8Dense
    fp8_recipe: Any = None

    @classmethod
    def llama2_7b(cls, **kw) -> "LlamaConfig":
        return cls(**kw)

    @classmethod
    def llama3_8b(cls, **kw) -> "LlamaConfig":
        return cls(**{**dict(vocab_size=128256, hidden_size=4096, intermediate_size=14336,
                             num_layers=32, num_heads=32, num_kv_heads=8,
                             rope_theta=500000.0, max_position_embeddings=8192), **kw})

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        return cls(**{**dict(vocab_size=256, max_position_embeddings=128, hidden_size=64,
                             intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2), **kw})


def _dense(cfg: LlamaConfig, features: int, name: str) -> nn.Module:
    """Block projection factory: bias-free Dense, or Fp8Dense when the config
    carries an fp8 recipe (ops/fp8.convert_dense_to_fp8 — ONE switch shared
    with gpt2; same param names, so checkpoints stay compatible)."""
    from ..ops.fp8 import convert_dense_to_fp8

    return convert_dense_to_fp8(cfg.fp8_recipe)(
        features, use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name=name
    )


class RMSNorm(nn.Module):
    eps: float = 1e-5
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        dtype = x.dtype
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), self.param_dtype)
        x32 = x.astype(jnp.float32)
        y = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (y * scale).astype(dtype)


def rope_frequencies(head_dim: int, positions: jax.Array, theta: float) -> tuple[jax.Array, jax.Array]:
    """(cos, sin) tables [*pos_shape, head_dim/2] in fp32."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: [b, s, h, d]; cos/sin: [s, d/2] (or broadcastable)."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    cos = cos[None, :, None, :]
    sin = sin[None, :, None, :]
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1).astype(x.dtype)


class LlamaAttention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array, decode: bool = False, position_offset: Any = 0) -> jax.Array:
        cfg = self.config
        b, s, e = x.shape
        head_dim = e // cfg.num_heads
        dense = lambda n, name: _dense(cfg, n, name)
        q = dense(cfg.num_heads * head_dim, "q_proj")(x).reshape(b, s, cfg.num_heads, head_dim)
        k = dense(cfg.num_kv_heads * head_dim, "k_proj")(x).reshape(b, s, cfg.num_kv_heads, head_dim)
        v = dense(cfg.num_kv_heads * head_dim, "v_proj")(x).reshape(b, s, cfg.num_kv_heads, head_dim)

        positions = position_offset + jnp.arange(s)
        cos, sin = rope_frequencies(head_dim, positions, cfg.rope_theta)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)

        groups = cfg.num_heads // cfg.num_kv_heads

        if decode:
            from .kv_cache import decode_cache_update

            max_len = cfg.max_position_embeddings
            k_all, v_all, idx, is_init = decode_cache_update(
                self, k, v, max_len, kv_cache_dtype=cfg.kv_cache_dtype
            )
            if is_init:
                q_pos = idx + jnp.arange(s)[:, None]
                k_idx = jnp.arange(max_len)[None, :]
                mask = k_idx <= q_pos
                if cfg.sliding_window is not None:
                    mask = mask & (k_idx > q_pos - cfg.sliding_window)
                # GQA repeat happens inside attention()'s xla path — one source
                # of truth with the training branches
                out = attention(q, k_all, v_all, causal=False, mask=mask, implementation="xla")
            else:
                out = attention(q, k_all, v_all, causal=True, window=cfg.sliding_window,
                                implementation="xla")
        else:
            if cfg.attention_impl == "ring":
                from ..parallel.ring_attention import ring_attention_sharded
                from ..state import AcceleratorState

                if cfg.sliding_window is not None:
                    raise NotImplementedError(
                        "sliding_window is not implemented on the ring-attention path; "
                        "silently computing full causal attention would train the "
                        "wrong pattern. Use attention_impl='flash' (band grid) or 'xla'."
                    )
                out = ring_attention_sharded(
                    q, jnp.repeat(k, groups, axis=2), jnp.repeat(v, groups, axis=2),
                    AcceleratorState().mesh, causal=True,
                )
            else:
                # GQA K/V go through unrepeated; the flash band grid reads the
                # grouped kv head directly and the xla path repeats internally
                out = attention(q, k, v, causal=True, window=cfg.sliding_window,
                                implementation=cfg.attention_impl)
        out = out.reshape(b, s, e)
        return dense(e, "o_proj")(out)


class LlamaMLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        dense = lambda n, name: _dense(cfg, n, name)
        gate = dense(cfg.intermediate_size, "gate_proj")(x)
        up = dense(cfg.intermediate_size, "up_proj")(x)
        return dense(cfg.hidden_size, "down_proj")(jax.nn.silu(gate) * up)


class LlamaBlock(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array, decode: bool = False, position_offset: Any = 0) -> jax.Array:
        cfg = self.config
        x = x + LlamaAttention(cfg, name="attn")(
            RMSNorm(cfg.rms_norm_eps, cfg.param_dtype, name="input_norm")(x), decode, position_offset
        )
        x = x + LlamaMLP(cfg, name="mlp")(
            RMSNorm(cfg.rms_norm_eps, cfg.param_dtype, name="post_attn_norm")(x)
        )
        return x


class LlamaForCausalLM(nn.Module):
    """Returns fp32 logits [batch, seq, vocab]."""

    config: LlamaConfig

    @nn.compact
    def __call__(
        self,
        input_ids: jax.Array,
        deterministic: bool = True,
        decode: bool = False,
        position_offset: Any = 0,
        return_hidden: bool = False,
    ) -> jax.Array:
        cfg = self.config
        embed = self.param("embed_tokens", nn.initializers.normal(0.02),
                           (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        x = embed.astype(cfg.dtype)[input_ids]
        if cfg.remat:
            from ..utils.remat import remat_block

            block = remat_block(LlamaBlock, cfg.remat_policy, static_argnums=(2,))
        else:
            block = LlamaBlock
        for i in range(cfg.num_layers):
            x = block(cfg, name=f"layer_{i}")(x, decode, position_offset)
        x = RMSNorm(cfg.rms_norm_eps, cfg.param_dtype, name="final_norm")(x)
        if return_hidden:
            # fused-CE path: the caller folds the head matmul into the loss
            # kernel so the [b, s, V] logits never reach HBM (at Llama-3's
            # 128k vocab that tensor is the training memory wall)
            return x
        lm_head = self.param("lm_head", nn.initializers.normal(0.02),
                             (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        return jnp.einsum("bse,ve->bsv", x.astype(cfg.dtype), lm_head.astype(cfg.dtype),
                          preferred_element_type=jnp.float32)

    def init_params(self, rng: jax.Array, batch: int = 2, seq: int = 16) -> Any:
        variables = self.init(rng, jnp.zeros((batch, seq), dtype=jnp.int32))
        if len(variables) > 1:
            # mutable collections (fp8_meta scaling state) ride along; prepare()
            # splits them into PreparedModel.extra_state
            return dict(variables)
        return variables["params"]


def llama_sharding_rules(config: LlamaConfig | None = None) -> ShardingRules:
    """TP: q/gate/up column-parallel, o/down row-parallel, embeddings vocab-sharded.
    KV projections shard on tensor only if num_kv_heads divides the degree —
    callers with extreme TP should replicate KV (set rules accordingly)."""
    return ShardingRules(
        rules=[
            (r".*attn/(q_proj|k_proj|v_proj)/kernel", P(None, "tensor")),
            (r".*attn/o_proj/kernel", P("tensor", None)),
            (r".*mlp/(gate_proj|up_proj)/kernel", P(None, "tensor")),
            (r".*mlp/down_proj/kernel", P("tensor", None)),
            (r".*embed_tokens", P("tensor", None)),
            (r".*lm_head", P("tensor", None)),
        ]
    )


def llama_blockwise(config: LlamaConfig):
    """Decompose Llama into sequential blocks: embed -> layer_i... -> head.

    Serves both L5 flows (reference roles): blockwise offload-streaming
    inference (`big_modeling.BlockwiseModel`) and pipeline-parallel inference
    (`inference.prepare_pippy`, reference `examples/inference/pippy/llama.py`).
    Pair with `llama_blockwise_state_dict` to regroup a param tree."""
    from ..big_modeling import BlockwiseModel

    def embed_fn(p, input_ids):
        return p["embed_tokens"].astype(config.dtype)[input_ids]

    def make_block_fn(i):
        def block_fn(p, x):
            return LlamaBlock(config, name=f"layer_{i}").apply({"params": p}, x)

        return block_fn

    def head_fn(p, x):
        x = RMSNorm(config.rms_norm_eps, config.param_dtype, name="final_norm").apply(
            {"params": p["final_norm"]}, x
        )
        return jnp.einsum(
            "bse,ve->bsv", x.astype(config.dtype), p["lm_head"].astype(config.dtype),
            preferred_element_type=jnp.float32,
        )

    fns = [("embed", embed_fn)]
    fns += [(f"layer_{i}", make_block_fn(i)) for i in range(config.num_layers)]
    fns += [("head", head_fn)]
    return BlockwiseModel(block_fns=fns)


def llama_blockwise_state_dict(params: dict) -> dict:
    """Regroup a LlamaForCausalLM param tree into the blockwise layout."""
    out = {"embed": {"embed_tokens": params["embed_tokens"]}}
    for k in params:
        if k.startswith("layer_"):
            out[k] = params[k]
    out["head"] = {"final_norm": params["final_norm"], "lm_head": params["lm_head"]}
    return out


def llama_loss_fn(model, batch) -> jax.Array:
    from .gpt2 import _next_token_labels, cross_entropy_loss

    logits = model(batch["input_ids"])
    return cross_entropy_loss(logits, _next_token_labels(batch))


def llama_loss_fn_fused(model, batch, block_r: int | None = None,
                        block_v: int | None = None) -> jax.Array:
    """Next-token CE with the (untied) LM head folded into the Pallas fused-CE
    kernel — the [b, s, V] logits tensor never reaches HBM. The memory lever
    for large-vocab members (Llama-3: V=128k). Same contract as
    `gpt2.lm_loss_fn_pallas`."""
    from ..ops.fused_ce import fused_cross_entropy
    from ..utils.environment import parse_int_from_env

    if block_r is None:
        block_r = parse_int_from_env("ACCELERATE_TPU_FUSED_CE_BLOCK_R", 512)
    if block_v is None:
        block_v = parse_int_from_env("ACCELERATE_TPU_FUSED_CE_BLOCK_V", 1024)
    from .gpt2 import _next_token_labels

    hidden = model(batch["input_ids"], return_hidden=True)
    labels = _next_token_labels(batch)
    b, s, e = hidden.shape
    head = model.params["lm_head"].astype(hidden.dtype)
    return fused_cross_entropy(
        hidden.reshape(b * s, e), head, labels.reshape(b * s),
        block_r=block_r, block_v=block_v,
    )


def params_from_hf_llama(hf_state_dict: dict, config: LlamaConfig) -> dict:
    """Map HF transformers LlamaForCausalLM weights into this layout (torch
    Linear stores [out, in]; flax Dense kernels are [in, out] -> transpose)."""

    def _np(t):
        return t.detach().cpu().numpy() if hasattr(t, "detach") else np.asarray(t)

    def _lin(key):
        return _np(hf_state_dict[key]).T

    p: dict[str, Any] = {
        "embed_tokens": _np(hf_state_dict["model.embed_tokens.weight"]),
        "final_norm": {"scale": _np(hf_state_dict["model.norm.weight"])},
        "lm_head": _np(hf_state_dict["lm_head.weight"]),
    }
    for i in range(config.num_layers):
        hf = f"model.layers.{i}."
        p[f"layer_{i}"] = {
            "input_norm": {"scale": _np(hf_state_dict[hf + "input_layernorm.weight"])},
            "post_attn_norm": {"scale": _np(hf_state_dict[hf + "post_attention_layernorm.weight"])},
            "attn": {
                "q_proj": {"kernel": _lin(hf + "self_attn.q_proj.weight")},
                "k_proj": {"kernel": _lin(hf + "self_attn.k_proj.weight")},
                "v_proj": {"kernel": _lin(hf + "self_attn.v_proj.weight")},
                "o_proj": {"kernel": _lin(hf + "self_attn.o_proj.weight")},
            },
            "mlp": {
                "gate_proj": {"kernel": _lin(hf + "mlp.gate_proj.weight")},
                "up_proj": {"kernel": _lin(hf + "mlp.up_proj.weight")},
                "down_proj": {"kernel": _lin(hf + "mlp.down_proj.weight")},
            },
        }
    return p
