"""GPT-2 family, TPU-first.

This is the flagship training model (BASELINE.md north-star: GPT-2-medium
pretraining tokens/sec/chip). Design choices for the MXU/XLA:

  - flax.linen with explicit ``dtype`` (compute, bf16 by default on TPU) and
    ``param_dtype`` (fp32 masters) — matmuls run bf16 on the MXU, layernorm/
    softmax statistics in fp32.
  - attention dispatches to the Pallas flash kernel for long sequences (or XLA
    fused attention otherwise) via `ops.attention.attention`.
  - optional ``remat`` applies jax.checkpoint per block (HBM <-> FLOPs trade).
  - optional ``scan_layers`` stacks the blocks with `nn.scan`: one compiled block
    body instead of n_layer copies — near-constant compile time with depth, and
    the layer axis becomes a leading param dim (which also gives pipeline
    parallelism a natural stage axis).
  - weights are plain kernels ([in, out]) so Megatron-style TP is pure sharding:
    `gpt2_sharding_rules()` returns the column/row PartitionSpecs.

Interchange: `params_from_hf_gpt2` maps HuggingFace transformers GPT-2 weights
into this layout (reference capability: big-model checkpoint ingestion).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops.attention import attention
from ..parallel.sharding import ShardingRules


@dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    mlp_ratio: int = 4
    dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False
    remat_policy: str | None = None  # see utils/remat.py: full|dots|dots_no_batch
    scan_layers: bool = False
    attention_impl: str = "auto"  # 'xla' | 'flash' | 'auto'
    kv_cache_dtype: Any = None  # None | jnp.int8 (see models/kv_cache.py)
    # per-slot [b]-vector cache write index instead of one scalar shared by the
    # batch: every row decodes at its own position (the serving engine's
    # continuous-batching slot pool — serving/engine.py). position_offset may
    # then be a [b] vector too.
    kv_cache_per_slot: bool = False
    # paged KV: decode KV lives in a shared [kv_num_blocks, kv_block_tokens,
    # ...] block pool instead of per-slot rows, and each row attends through
    # its block table (models/kv_cache.paged_decode_update — the serving
    # engine's paged_kv mode, docs/serving.md "Paged KV"). Implies the
    # per-slot write-cursor semantics; block_tables must be threaded into
    # __call__ on every decode step.
    kv_cache_paged: bool = False
    kv_num_blocks: int = 0
    kv_block_tokens: int = 16
    # paged decode attention path: "gather" materializes pool[table] into a
    # contiguous per-slot view and runs XLA attention over it (the parity
    # oracle); "fused" reads K/V blocks in place through the block table with
    # the Pallas kernel `ops.flash_attention.paged_decode_attention` — no
    # per-layer per-step gather copy (docs/serving.md "Fused paged decode").
    kv_paged_attention: str = "gather"
    # mesh layout for the per-slot cache (a parallel.sharding.KVCacheSharding,
    # hashable so the frozen config stays hashable): heads sharded on the
    # serving mesh's model axis, slots optionally on data. None everywhere but
    # the mesh-sharded serving engine.
    kv_cache_sharding: Any = None
    # fp8 projections (reference TE convert_model role): a DelayedScalingRecipe
    # switches every block Dense to ops/fp8.Fp8Dense (delayed-scaling fp8
    # matmuls; scaling state rides the mutable fp8_meta collection)
    fp8_recipe: Any = None

    @classmethod
    def small(cls, **kw) -> "GPT2Config":
        return cls(**{**dict(n_embd=768, n_layer=12, n_head=12), **kw})

    @classmethod
    def medium(cls, **kw) -> "GPT2Config":
        return cls(**{**dict(n_embd=1024, n_layer=24, n_head=16), **kw})

    @classmethod
    def large(cls, **kw) -> "GPT2Config":
        return cls(**{**dict(n_embd=1280, n_layer=36, n_head=20), **kw})

    @classmethod
    def tiny(cls, **kw) -> "GPT2Config":
        """Test-sized config."""
        return cls(**{**dict(vocab_size=256, n_positions=128, n_embd=64, n_layer=2, n_head=2), **kw})


def _dense(cfg: GPT2Config, features: int, name: str) -> nn.Module:
    """Block projection factory: plain Dense, or Fp8Dense when the config
    carries an fp8 recipe (ops/fp8.convert_dense_to_fp8 — the reference
    `transformer_engine.py:26-82` convert_model role; same param names, so
    checkpoints stay compatible)."""
    from ..ops.fp8 import convert_dense_to_fp8

    return convert_dense_to_fp8(cfg.fp8_recipe)(
        features, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name=name
    )


class SelfAttention(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True, decode: bool = False,
                 cache_write_mask: jax.Array | None = None,
                 block_tables: jax.Array | None = None,
                 cache_write_len: jax.Array | None = None) -> jax.Array:
        cfg = self.config
        b, s, e = x.shape
        head_dim = e // cfg.n_head
        qkv = _dense(cfg, 3 * e, "qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, cfg.n_head, head_dim)
        k = k.reshape(b, s, cfg.n_head, head_dim)
        v = v.reshape(b, s, cfg.n_head, head_dim)
        if (decode and cfg.kv_cache_paged and cfg.kv_paged_attention == "fused"
                and s == 1 and cache_write_len is None):
            # fused paged attention: write the new token at the frontier
            # (pool leaves only — no gathered view), then the Pallas kernel
            # walks the block table in place. The frontier semantics are
            # identical to the gather branch below: the query at cursor idx
            # attends positions <= idx, i.e. a valid span of idx + 1.
            # The kernel is single-query, so multi-token verify segments
            # (s > 1 / cache_write_len — speculative decoding) fall through
            # to the gather branch; s is static, so this costs nothing on the
            # one-token fast path.
            from ..ops.flash_attention import paged_decode_attention
            from .kv_cache import paged_decode_write

            k_pool, v_pool, idx, is_init, scale_pools = paged_decode_write(
                self, k, v, cfg.kv_num_blocks, cfg.kv_block_tokens,
                block_tables, kv_cache_dtype=cfg.kv_cache_dtype,
                write_mask=cache_write_mask,
                sharding=cfg.kv_cache_sharding,
            )
            if is_init:
                k_sp, v_sp = scale_pools if scale_pools is not None else (None, None)
                out = paged_decode_attention(
                    q[:, 0], k_pool, v_pool, block_tables, idx + 1,
                    k_scale_pool=k_sp, v_scale_pool=v_sp,
                )[:, None]  # [b, 1, n_head, head_dim]
            else:
                # abstract shape-init trace: no pool yet, plain causal
                out = attention(q, k_pool, v_pool, causal=True,
                                implementation="xla")
        elif decode and cfg.kv_cache_paged:
            # paged KV: the cache collection holds a shared block pool, each
            # row attends through its block table (models/kv_cache.py)
            from .kv_cache import paged_decode_update

            k_all, v_all, idx, is_init = paged_decode_update(
                self, k, v, cfg.kv_num_blocks, cfg.kv_block_tokens,
                block_tables, kv_cache_dtype=cfg.kv_cache_dtype,
                write_mask=cache_write_mask,
                write_len=cache_write_len, sharding=cfg.kv_cache_sharding,
            )
            if is_init:
                # same frontier mask as the per-slot path: the gathered view
                # lays position p at index p, and everything past a row's
                # cursor — pad offsets in its frontier block, unallocated
                # table entries — is masked out before softmax, so stale pool
                # contents contribute exactly zero
                span = block_tables.shape[1] * cfg.kv_block_tokens
                q_pos = idx[:, None, None] + jnp.arange(s)[None, :, None]
                kv_pos = jnp.arange(span)[None, None, :]
                mask = (kv_pos <= q_pos)[:, None]  # [b, 1, s, span]
                out = attention(q, k_all, v_all, causal=False, mask=mask,
                                implementation="xla")
            else:
                out = attention(q, k_all, v_all, causal=True, implementation="xla")
        elif decode:
            # autoregressive KV cache (flax decode idiom): fixed n_positions-long
            # buffers, new keys/values written at the running index; optional
            # int8 storage (models/kv_cache.py)
            from .kv_cache import decode_cache_update

            max_len = cfg.n_positions
            k_all, v_all, idx, is_init = decode_cache_update(
                self, k, v, max_len, kv_cache_dtype=cfg.kv_cache_dtype,
                per_slot=cfg.kv_cache_per_slot, write_mask=cache_write_mask,
                write_len=cache_write_len, sharding=cfg.kv_cache_sharding,
            )
            if is_init:
                if cfg.kv_cache_per_slot:
                    # idx is [b]: row i's query j (global pos idx[i]+j) may
                    # attend its own cache slots <= idx[i]+j
                    q_pos = idx[:, None, None] + jnp.arange(s)[None, :, None]
                    kv_pos = jnp.arange(max_len)[None, None, :]
                    mask = (kv_pos <= q_pos)[:, None]  # [b, 1, s, max_len]
                else:
                    # query i (global pos idx+i) may attend cache slots <= idx+i
                    q_pos = idx + jnp.arange(s)[:, None]
                    kv_pos = jnp.arange(max_len)[None, :]
                    mask = kv_pos <= q_pos  # [s, max_len]
                out = attention(q, k_all, v_all, causal=False, mask=mask, implementation="xla")
            else:
                out = attention(q, k_all, v_all, causal=True, implementation="xla")
        elif cfg.attention_impl == "ring":
            # sequence-parallel exact attention over the mesh's ring axis
            from ..parallel.ring_attention import ring_attention_sharded
            from ..state import AcceleratorState

            out = ring_attention_sharded(q, k, v, AcceleratorState().mesh, causal=True)
        else:
            out = attention(q, k, v, causal=True, implementation=cfg.attention_impl)
        out = out.reshape(b, s, e)
        out = _dense(cfg, e, "proj")(out)
        if cfg.dropout > 0.0 and not deterministic:
            out = nn.Dropout(cfg.dropout)(out, deterministic=False)
        return out


class MLP(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True) -> jax.Array:
        cfg = self.config
        hidden = cfg.mlp_ratio * cfg.n_embd
        x = _dense(cfg, hidden, "up")(x)
        x = nn.gelu(x, approximate=True)
        x = _dense(cfg, cfg.n_embd, "down")(x)
        if cfg.dropout > 0.0 and not deterministic:
            x = nn.Dropout(cfg.dropout)(x, deterministic=False)
        return x


class Block(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True, decode: bool = False,
                 cache_write_mask: jax.Array | None = None,
                 block_tables: jax.Array | None = None,
                 cache_write_len: jax.Array | None = None) -> jax.Array:
        cfg = self.config
        # pre-norm transformer; LN statistics in fp32
        h = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=jnp.float32, param_dtype=cfg.param_dtype, name="ln_1")(x)
        x = x + SelfAttention(cfg, name="attn")(h.astype(cfg.dtype), deterministic, decode, cache_write_mask, block_tables, cache_write_len)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=jnp.float32, param_dtype=cfg.param_dtype, name="ln_2")(x)
        x = x + MLP(cfg, name="mlp")(h.astype(cfg.dtype), deterministic)
        return x


class GPT2LMHead(nn.Module):
    """Decoder-only LM. Returns logits [batch, seq, vocab] in fp32."""

    config: GPT2Config

    @nn.compact
    def __call__(
        self,
        input_ids: jax.Array,
        deterministic: bool = True,
        decode: bool = False,
        position_offset: jax.Array | int = 0,
        return_hidden: bool = False,
        cache_write_mask: jax.Array | None = None,
        block_tables: jax.Array | None = None,
        cache_write_len: jax.Array | None = None,
    ) -> jax.Array:
        cfg = self.config
        b, s = input_ids.shape
        wte = self.param(
            "wte", nn.initializers.normal(0.02), (cfg.vocab_size, cfg.n_embd), cfg.param_dtype
        )
        wpe = self.param(
            "wpe", nn.initializers.normal(0.01), (cfg.n_positions, cfg.n_embd), cfg.param_dtype
        )
        positions = jnp.asarray(position_offset)
        if positions.ndim == 0:
            positions = positions + jnp.arange(s)  # [s], shared by the batch
            pos_emb = wpe.astype(cfg.dtype)[positions][None]
        else:
            # [b]-vector offsets: every row sits at its own sequence position
            # (per-slot decode, serving/engine.py)
            positions = positions[:, None] + jnp.arange(s)  # [b, s]
            pos_emb = wpe.astype(cfg.dtype)[positions]
        x = wte.astype(cfg.dtype)[input_ids] + pos_emb

        block = Block
        if cfg.remat:
            from ..utils.remat import remat_block

            block = remat_block(Block, cfg.remat_policy, static_argnums=(2, 3))
        if cfg.scan_layers:
            x, _ = nn.scan(
                lambda mdl, carry, _: (mdl(carry, deterministic, decode, cache_write_mask, block_tables, cache_write_len), None),
                # fp8_meta (per-layer delayed-scaling state) stacks on the same
                # leading layer axis as the params
                variable_axes={"params": 0, "fp8_meta": 0},
                split_rngs={"params": True},
                length=cfg.n_layer,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(block(cfg, name="blocks"), x, None)
        else:
            for i in range(cfg.n_layer):
                x = block(cfg, name=f"block_{i}")(x, deterministic, decode, cache_write_mask, block_tables, cache_write_len)

        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=jnp.float32, param_dtype=cfg.param_dtype, name="ln_f")(x)
        if return_hidden:
            # pre-head hidden states for the fused (chunked) LM loss, which
            # applies the tied head inside the loss without ever materializing
            # the full [batch, seq, vocab] fp32 logits tensor
            return x.astype(cfg.dtype)
        # tied LM head: logits through the embedding matrix, fp32 accumulation
        logits = jnp.einsum("bse,ve->bsv", x.astype(cfg.dtype), wte.astype(cfg.dtype),
                            preferred_element_type=jnp.float32)
        return logits

    def init_params(self, rng: jax.Array, batch: int = 2, seq: int | None = None) -> Any:
        seq = seq or min(self.config.n_positions, 128)
        dummy = jnp.zeros((batch, seq), dtype=jnp.int32)
        variables = self.init(rng, dummy)
        if len(variables) > 1:
            # mutable collections (fp8_meta scaling state) ride along; prepare()
            # splits them into PreparedModel.extra_state
            return dict(variables)
        return variables["params"]


def gpt2_sharding_rules() -> ShardingRules:
    """Megatron-style TP as pure sharding annotations (SURVEY.md §2.4 TP row):
    qkv/up are column-parallel (shard output dim), proj/down row-parallel (shard
    input dim), embeddings vocab-sharded. XLA inserts the two all-reduces per
    block that Megatron hand-codes."""
    return ShardingRules(
        rules=[
            (r".*attn/qkv/kernel", P(None, "tensor")),
            (r".*attn/proj/kernel", P("tensor", None)),
            (r".*mlp/up/kernel", P(None, "tensor")),
            (r".*mlp/down/kernel", P("tensor", None)),
            # vocab dim over tensor AND fsdp, embed dim replicated: folding fsdp
            # into the embed dim makes the wte-grad scatter reshard the whole
            # (batch, seq, embed) activation gradient into a transposed layout
            # (involuntary full remat); vocab-only sharding needs just the
            # token indices replicated, which they already are.
            (r".*wte", P(("tensor", "fsdp"), None)),
            (r".*wpe", P(None, None)),
            (r".*(qkv|up)/bias", P("tensor")),
        ]
    )


def cross_entropy_loss(logits: jax.Array, labels: jax.Array, ignore_index: int = -100) -> jax.Array:
    """Token-level CE with masking, fp32 accumulation."""
    mask = labels != ignore_index
    safe_labels = jnp.where(mask, labels, 0)
    logprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logprobs, safe_labels[..., None], axis=-1)[..., 0]
    return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1)


def _next_token_labels(batch) -> jax.Array:
    """Labels for causal LM: explicit ``labels`` or input_ids shifted left with
    the trailing position ignored."""
    labels = batch.get("labels")
    if labels is None:
        labels = jnp.pad(batch["input_ids"][:, 1:], ((0, 0), (0, 1)), constant_values=-100)
    return labels


def lm_loss_fn(model, batch) -> jax.Array:
    """Next-token LM loss usable directly with Accelerator.backward/make_train_step."""
    logits = model(batch["input_ids"])
    return cross_entropy_loss(logits, _next_token_labels(batch))


def chunked_cross_entropy(
    hidden: jax.Array,  # [N, e] pre-head activations (compute dtype)
    wte: jax.Array,  # [V, e] tied embedding (compute dtype)
    labels: jax.Array,  # [N] int labels, ignore_index masked
    ignore_index: int = -100,
    chunk: int = 1024,
) -> jax.Array:
    """Head+CE fused over row chunks: the [N, V] fp32 logits never exist in HBM
    — each [chunk, V] tile is produced, reduced to (logsumexp, label-logit) and
    discarded; `jax.checkpoint` recomputes tiles in the backward. Cuts the LM
    head's HBM traffic by ~V/2 per pass at the cost of one recomputed matmul.
    (Role of reference AMP'd CE; the fusion itself is TPU-native design.)"""
    n, e = hidden.shape
    pad = (-n) % chunk
    if pad:
        hidden = jnp.pad(hidden, ((0, pad), (0, 0)))
        labels = jnp.pad(labels, (0, pad), constant_values=ignore_index)
    nc = hidden.shape[0] // chunk
    hidden = hidden.reshape(nc, chunk, e)
    labels = labels.reshape(nc, chunk)

    @jax.checkpoint
    def one_chunk(x_c, lab_c):
        mask = lab_c != ignore_index
        safe = jnp.where(mask, lab_c, 0)
        logits = jax.lax.dot_general(
            x_c, wte, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [chunk, V] — lives only inside this chunk
        lse = jax.nn.logsumexp(logits, axis=-1)
        ll = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
        return ((lse - ll) * mask).sum(), mask.sum()

    def body(carry, xs):
        loss, cnt = one_chunk(*xs)
        return (carry[0] + loss, carry[1] + cnt), None

    (total, count), _ = jax.lax.scan(body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)), (hidden, labels))
    return total / jnp.maximum(count, 1)


def lm_loss_fn_fused(model, batch, chunk: int = 1024) -> jax.Array:
    """Next-token LM loss with the head fused into chunked CE (no full-logits
    materialization). Drop-in for `lm_loss_fn` on GPT2LMHead models."""
    hidden = model(batch["input_ids"], return_hidden=True)
    labels = _next_token_labels(batch)
    b, s, e = hidden.shape
    wte = model.params["wte"].astype(hidden.dtype)
    return chunked_cross_entropy(hidden.reshape(b * s, e), wte, labels.reshape(b * s), chunk=chunk)


def lm_loss_fn_pallas(model, batch, block_r: int | None = None, block_v: int | None = None) -> jax.Array:
    """Next-token LM loss through the Pallas fused head+CE kernel
    (`ops/fused_ce.py`): logits tiles live only in VMEM, row chunks run as
    parallel grid cells (no scan serialization). Drop-in for `lm_loss_fn`.
    Block sizes default from ``ACCELERATE_TPU_FUSED_CE_BLOCK_R/_V`` (sweepable;
    larger models need smaller tiles — the dw kernel's VMEM footprint scales
    with block_v*e)."""
    from ..ops.fused_ce import fused_cross_entropy
    from ..utils.environment import parse_int_from_env

    if block_r is None:
        block_r = parse_int_from_env("ACCELERATE_TPU_FUSED_CE_BLOCK_R", 512)
    if block_v is None:
        block_v = parse_int_from_env("ACCELERATE_TPU_FUSED_CE_BLOCK_V", 1024)
    hidden = model(batch["input_ids"], return_hidden=True)
    labels = _next_token_labels(batch)
    b, s, e = hidden.shape
    wte = model.params["wte"].astype(hidden.dtype)
    return fused_cross_entropy(
        hidden.reshape(b * s, e), wte, labels.reshape(b * s), block_r=block_r, block_v=block_v
    )


def gpt2_blockwise(config: GPT2Config):
    """Decompose GPT-2 into sequential blocks for offload-streaming inference
    (`big_modeling.BlockwiseModel`): embed -> block_i... -> head. Use with
    `gpt2_blockwise_state_dict` to regroup a params tree into per-block subtrees."""
    from ..big_modeling import BlockwiseModel

    def embed_fn(p, input_ids):
        s = input_ids.shape[1]
        return p["wte"].astype(config.dtype)[input_ids] + p["wpe"].astype(config.dtype)[None, :s]

    def make_block_fn(i):
        def block_fn(p, x):
            return Block(config, name=f"block_{i}").apply({"params": p}, x)

        return block_fn

    def head_fn(p, x):
        x = nn.LayerNorm(epsilon=config.layer_norm_epsilon, dtype=jnp.float32).apply(
            {"params": p["ln_f"]}, x
        )
        return jnp.einsum(
            "bse,ve->bsv", x.astype(config.dtype), p["wte"].astype(config.dtype),
            preferred_element_type=jnp.float32,
        )

    fns = [("embed", embed_fn)]
    fns += [(f"block_{i}", make_block_fn(i)) for i in range(config.n_layer)]
    fns += [("head", head_fn)]
    return BlockwiseModel(block_fns=fns)


def gpt2_pipeline_parts(config: GPT2Config, params: dict, num_stages: int):
    """Decompose GPT-2 for PIPELINE TRAINING (`Accelerator.prepare_pipeline` /
    `make_pipeline_train_step`): returns ``(stage_fn, per_stage_params, pre,
    post)`` where each homogeneous stage runs ``n_layer / num_stages``
    transformer blocks, the embedding runs replicated before the pipeline and
    ln_f + LM head after it (reference role: Megatron-LM pp>1 model
    partitioning, `utils/megatron_lm.py`).

    Tying note: the LM head starts as a copy of ``wte`` but trains UNTIED —
    pre/post are separate parameter groups and the Megatron first/last-stage
    embedding-gradient all-reduce is not implemented. Fine-tunes from tied
    checkpoints start tied and may drift apart.
    """
    if config.n_layer % num_stages:
        raise ValueError(
            f"n_layer {config.n_layer} must divide into {num_stages} pipeline stages"
        )
    if "params" in params and "wte" not in params:
        raise ValueError(
            "gpt2_pipeline_parts takes the bare params tree; this looks like a "
            "variables dict with extra collections (fp8_recipe models carry "
            "fp8_meta state that the pipeline decomposition does not thread)."
        )
    if "block_0" not in params:
        raise ValueError(
            "gpt2_pipeline_parts needs the per-layer 'block_i' param layout; "
            "scan_layers=True stacks layers under 'blocks' — initialize the "
            "model with scan_layers=False for pipeline decomposition (the "
            "GPipe schedule is itself the scan over layers)."
        )
    per = config.n_layer // num_stages

    def pre_fn(p, input_ids):
        s = input_ids.shape[1]
        return (
            p["wte"].astype(config.dtype)[input_ids]
            + p["wpe"].astype(config.dtype)[None, :s]
        )

    def stage_fn(p, x):
        for j in range(per):
            x = Block(config, name=f"layer_{j}").apply({"params": p[f"layer_{j}"]}, x)
        return x

    def post_fn(p, y):
        y = nn.LayerNorm(
            epsilon=config.layer_norm_epsilon, dtype=jnp.float32,
            param_dtype=config.param_dtype,
        ).apply({"params": p["ln_f"]}, y)
        return jnp.einsum(
            "bse,ve->bsv", y.astype(config.dtype), p["lm_head"].astype(config.dtype),
            preferred_element_type=jnp.float32,
        )

    per_stage = [
        {f"layer_{j}": params[f"block_{s * per + j}"] for j in range(per)}
        for s in range(num_stages)
    ]
    pre_p = {"wte": params["wte"], "wpe": params["wpe"]}
    # explicit copy: the head is its own buffer from step 0 (aliasing wte would
    # both double-donate one buffer in the fused step and hide the untying)
    post_p = {"ln_f": params["ln_f"], "lm_head": jnp.array(params["wte"])}
    return stage_fn, per_stage, (pre_fn, pre_p), (post_fn, post_p)


def pipeline_lm_loss(logits: jax.Array, input_ids: jax.Array) -> jax.Array:
    """Per-microbatch next-token CE for `make_pipeline_train_step(loss_fn=...)`
    (the `lm_loss_fn` contract, shifted inside the loss so the pipeline's
    targets are just the input ids)."""
    import optax

    return optax.softmax_cross_entropy_with_integer_labels(
        logits[:, :-1].astype(jnp.float32), input_ids[:, 1:]
    ).mean()


def gpt2_blockwise_state_dict(params: dict) -> dict:
    """Regroup a GPT2LMHead param tree into the blockwise layout (the tied wte
    appears in both embed and head groups, like the reference's tied-weight map)."""
    out = {"embed": {"wte": params["wte"], "wpe": params["wpe"]}}
    for k in params:
        if k.startswith("block_"):
            out[k] = params[k]
    out["head"] = {"ln_f": params["ln_f"], "wte": params["wte"]}
    return out


def params_from_hf_gpt2(hf_state_dict: dict, config: GPT2Config) -> dict:
    """Map HuggingFace transformers GPT-2 torch weights into this layout.

    HF GPT-2 uses Conv1D (weights already [in, out]); layer names are remapped.
    (Capability parity with the reference's checkpoint ingestion,
    `utils/modeling.py:1611` load_checkpoint_in_model.)
    """

    def _np(t):
        return t.detach().cpu().numpy() if hasattr(t, "detach") else np.asarray(t)

    p: dict[str, Any] = {
        "wte": _np(hf_state_dict["wte.weight"]),
        "wpe": _np(hf_state_dict["wpe.weight"]),
        "ln_f": {"scale": _np(hf_state_dict["ln_f.weight"]), "bias": _np(hf_state_dict["ln_f.bias"])},
    }
    for i in range(config.n_layer):
        hf = f"h.{i}."
        p[f"block_{i}"] = {
            "ln_1": {"scale": _np(hf_state_dict[hf + "ln_1.weight"]), "bias": _np(hf_state_dict[hf + "ln_1.bias"])},
            "ln_2": {"scale": _np(hf_state_dict[hf + "ln_2.weight"]), "bias": _np(hf_state_dict[hf + "ln_2.bias"])},
            "attn": {
                "qkv": {"kernel": _np(hf_state_dict[hf + "attn.c_attn.weight"]), "bias": _np(hf_state_dict[hf + "attn.c_attn.bias"])},
                "proj": {"kernel": _np(hf_state_dict[hf + "attn.c_proj.weight"]), "bias": _np(hf_state_dict[hf + "attn.c_proj.bias"])},
            },
            "mlp": {
                "up": {"kernel": _np(hf_state_dict[hf + "mlp.c_fc.weight"]), "bias": _np(hf_state_dict[hf + "mlp.c_fc.bias"])},
                "down": {"kernel": _np(hf_state_dict[hf + "mlp.c_proj.weight"]), "bias": _np(hf_state_dict[hf + "mlp.c_proj.bias"])},
            },
        }
    return p
