"""ResNet family, TPU-first (for the cv_example baseline — BASELINE.md
configs[1]: ResNet-50 image classification, DP over v5e-8).

Convolutions map straight onto the MXU (XLA lowers NHWC convs to im2col-free
systolic matmuls). Normalization is GroupNorm rather than BatchNorm: identical
jit-side semantics in train and eval, no mutable running statistics to thread
through the functional step, and no cross-replica batch-stat sync — the standard
JAX substitution (BatchNorm's cross-device sync is a DDP-ism this framework
doesn't need).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: tuple[int, ...] = (3, 4, 6, 3)  # resnet50
    num_filters: int = 64
    num_classes: int = 1000
    bottleneck: bool = True
    num_groups: int = 32
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @classmethod
    def resnet18(cls, **kw) -> "ResNetConfig":
        return cls(**{**dict(stage_sizes=(2, 2, 2, 2), bottleneck=False), **kw})

    @classmethod
    def resnet50(cls, **kw) -> "ResNetConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "ResNetConfig":
        return cls(**{**dict(stage_sizes=(1, 1), num_filters=8, num_classes=10,
                             bottleneck=False, num_groups=4), **kw})


class ResNetBlock(nn.Module):
    filters: int
    config: ResNetConfig
    strides: int = 1

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        gn = lambda name: nn.GroupNorm(num_groups=min(cfg.num_groups, self.filters),
                                       dtype=jnp.float32, param_dtype=cfg.param_dtype, name=name)
        conv = lambda f, k, s, name: nn.Conv(f, (k, k), (s, s), padding="SAME", use_bias=False,
                                             dtype=cfg.dtype, param_dtype=cfg.param_dtype, name=name)
        residual = x
        if cfg.bottleneck:
            y = nn.relu(gn("gn1")(conv(self.filters, 1, 1, "conv1")(x)).astype(cfg.dtype))
            y = nn.relu(gn("gn2")(conv(self.filters, 3, self.strides, "conv2")(y)).astype(cfg.dtype))
            y = gn("gn3")(conv(4 * self.filters, 1, 1, "conv3")(y)).astype(cfg.dtype)
            out_filters = 4 * self.filters
        else:
            y = nn.relu(gn("gn1")(conv(self.filters, 3, self.strides, "conv1")(x)).astype(cfg.dtype))
            y = gn("gn2")(conv(self.filters, 3, 1, "conv2")(y)).astype(cfg.dtype)
            out_filters = self.filters
        if residual.shape[-1] != out_filters or self.strides != 1:
            residual = gn("gn_proj")(
                conv(out_filters, 1, self.strides, "conv_proj")(residual)
            ).astype(cfg.dtype)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """Returns fp32 logits [batch, num_classes]. Input NHWC."""

    config: ResNetConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        x = x.astype(cfg.dtype)
        x = nn.Conv(cfg.num_filters, (7, 7), (2, 2), padding="SAME", use_bias=False,
                    dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="conv_stem")(x)
        x = nn.GroupNorm(num_groups=min(cfg.num_groups, cfg.num_filters), dtype=jnp.float32,
                         param_dtype=cfg.param_dtype, name="gn_stem")(x).astype(cfg.dtype)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(cfg.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = ResNetBlock(cfg.num_filters * 2**i, cfg, strides, name=f"stage{i}_block{j}")(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(cfg.num_classes, dtype=jnp.float32, param_dtype=cfg.param_dtype,
                        name="classifier")(x.astype(jnp.float32))

    def init_params(self, rng: jax.Array, image_size: int = 224) -> Any:
        return self.init(rng, jnp.zeros((1, image_size, image_size, 3)))["params"]


def image_classification_loss_fn(model, batch) -> jax.Array:
    logits = model(batch["image"])
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logprobs, batch["label"][:, None], axis=-1).mean()
