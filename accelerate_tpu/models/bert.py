"""BERT encoder family, TPU-first (for the nlp_example baseline —
BASELINE.md configs[0]: BERT-base GLUE/MRPC).

Same design rules as gpt2.py: bf16 compute / fp32 masters, fp32 LN + softmax
statistics, attention via `ops.attention` (XLA-fused or Pallas flash), TP as
sharding rules. Post-LN (original BERT) with learned word/position/type
embeddings and a tanh pooler.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.attention import dot_product_attention
from ..parallel.sharding import ShardingRules


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    layer_norm_eps: float = 1e-12
    dropout: float = 0.0
    num_labels: int = 2
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @classmethod
    def base(cls, **kw) -> "BertConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "BertConfig":
        return cls(**{**dict(vocab_size=1024, max_position_embeddings=128, hidden_size=64,
                             num_layers=2, num_heads=2, intermediate_size=128), **kw})


class BertSelfAttention(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x: jax.Array, attention_mask: jax.Array | None = None) -> jax.Array:
        cfg = self.config
        b, s, e = x.shape
        head_dim = e // cfg.num_heads
        qkv = nn.Dense(3 * e, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, cfg.num_heads, head_dim)
        k = k.reshape(b, s, cfg.num_heads, head_dim)
        v = v.reshape(b, s, cfg.num_heads, head_dim)
        mask = None
        if attention_mask is not None:
            # [b, s] 1=token 0=pad -> [b, 1, 1(s broadcast), s] boolean keep-mask
            mask = attention_mask[:, None, None, :].astype(bool)
        out = dot_product_attention(q, k, v, mask=mask)
        out = out.reshape(b, s, e)
        return nn.Dense(e, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="out")(out)


class BertLayer(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x: jax.Array, attention_mask: jax.Array | None = None) -> jax.Array:
        cfg = self.config
        # post-LN (original BERT): sublayer -> residual -> LayerNorm
        attn = BertSelfAttention(cfg, name="attention")(x, attention_mask)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         param_dtype=cfg.param_dtype, name="ln_attn")(x + attn).astype(cfg.dtype)
        h = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="mlp_up")(x)
        h = nn.gelu(h, approximate=True)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="mlp_down")(h)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         param_dtype=cfg.param_dtype, name="ln_mlp")(x + h).astype(cfg.dtype)
        return x


class BertEncoder(nn.Module):
    """Returns (sequence_output [b,s,e], pooled_output [b,e])."""

    config: BertConfig

    @nn.compact
    def __call__(
        self,
        input_ids: jax.Array,
        attention_mask: jax.Array | None = None,
        token_type_ids: jax.Array | None = None,
    ):
        cfg = self.config
        b, s = input_ids.shape
        word = self.param("word_embeddings", nn.initializers.normal(0.02),
                          (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        pos = self.param("position_embeddings", nn.initializers.normal(0.02),
                         (cfg.max_position_embeddings, cfg.hidden_size), cfg.param_dtype)
        typ = self.param("token_type_embeddings", nn.initializers.normal(0.02),
                         (cfg.type_vocab_size, cfg.hidden_size), cfg.param_dtype)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = (word[input_ids] + pos[None, :s] + typ[token_type_ids]).astype(cfg.dtype)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         param_dtype=cfg.param_dtype, name="ln_embed")(x).astype(cfg.dtype)
        for i in range(cfg.num_layers):
            x = BertLayer(cfg, name=f"layer_{i}")(x, attention_mask)
        pooled = nn.tanh(
            nn.Dense(cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="pooler")(x[:, 0])
        )
        return x, pooled


class BertForSequenceClassification(nn.Module):
    """BERT + classification head; returns fp32 logits [b, num_labels]."""

    config: BertConfig

    @nn.compact
    def __call__(
        self,
        input_ids: jax.Array,
        attention_mask: jax.Array | None = None,
        token_type_ids: jax.Array | None = None,
    ) -> jax.Array:
        cfg = self.config
        _, pooled = BertEncoder(cfg, name="bert")(input_ids, attention_mask, token_type_ids)
        logits = nn.Dense(cfg.num_labels, dtype=jnp.float32, param_dtype=cfg.param_dtype,
                          name="classifier")(pooled.astype(jnp.float32))
        return logits

    def init_params(self, rng: jax.Array, batch: int = 2, seq: int = 64) -> Any:
        ids = jnp.zeros((batch, seq), dtype=jnp.int32)
        return self.init(rng, ids)["params"]


def bert_blockwise(config: BertConfig):
    """Decompose BertForSequenceClassification into sequential blocks:
    embed -> layer_i... -> head (pooler + classifier), for blockwise offload
    streaming (`big_modeling.BlockwiseModel`) and PP inference
    (`inference.prepare_pippy`, reference `examples/inference/pippy/bert.py`).

    The PP path threads ONE activation through the stages, so the optional
    padding `attention_mask` is not plumbed — pipeline pad-free batches (the
    reference pippy examples trace example inputs without masks too).
    Pair with `bert_blockwise_state_dict`."""
    from ..big_modeling import BlockwiseModel

    cfg = config

    def embed_fn(p, input_ids):
        b, s = input_ids.shape
        x = (
            p["word_embeddings"][input_ids]
            + p["position_embeddings"][None, :s]
            + p["token_type_embeddings"][jnp.zeros_like(input_ids)]
        ).astype(cfg.dtype)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         param_dtype=cfg.param_dtype).apply({"params": p["ln_embed"]}, x)
        return x.astype(cfg.dtype)

    def make_block_fn(i):
        def block_fn(p, x):
            return BertLayer(cfg, name=f"layer_{i}").apply({"params": p}, x)

        return block_fn

    def head_fn(p, x):
        pooled = nn.tanh(
            nn.Dense(cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype)
            .apply({"params": p["pooler"]}, x[:, 0])
        )
        return nn.Dense(cfg.num_labels, dtype=jnp.float32, param_dtype=cfg.param_dtype) \
            .apply({"params": p["classifier"]}, pooled.astype(jnp.float32))

    fns = [("embed", embed_fn)]
    fns += [(f"layer_{i}", make_block_fn(i)) for i in range(cfg.num_layers)]
    fns += [("head", head_fn)]
    return BlockwiseModel(block_fns=fns)


def bert_blockwise_state_dict(params: dict) -> dict:
    """Regroup a BertForSequenceClassification param tree into the blockwise
    layout (embed group, per-layer groups, pooler+classifier head group)."""
    bert = params["bert"]
    out = {"embed": {k: bert[k] for k in (
        "word_embeddings", "position_embeddings", "token_type_embeddings", "ln_embed")}}
    for k in bert:
        if k.startswith("layer_"):
            out[k] = bert[k]
    out["head"] = {"pooler": bert["pooler"], "classifier": params["classifier"]}
    return out


def bert_sharding_rules() -> ShardingRules:
    """Megatron-style TP for the encoder (same column/row pattern as GPT-2)."""
    return ShardingRules(
        rules=[
            (r".*attention/qkv/kernel", P(None, "tensor")),
            (r".*attention/out/kernel", P("tensor", None)),
            (r".*mlp_up/kernel", P(None, "tensor")),
            (r".*mlp_down/kernel", P("tensor", None)),
            (r".*word_embeddings", P("tensor", None)),
            (r".*(qkv|mlp_up)/bias", P("tensor")),
        ]
    )


def classification_loss_fn(model, batch) -> jax.Array:
    """Softmax CE over labels — usable with Accelerator.backward/make_train_step."""
    logits = model(batch["input_ids"], batch.get("attention_mask"), batch.get("token_type_ids"))
    labels = batch["labels"]
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logprobs, labels[:, None], axis=-1).mean()
