"""Decode-time KV cache: the flax decode idiom (fixed-length buffers, running
write index) shared by every autoregressive model in the zoo, with optional
int8 blockwise storage (one fp32 absmax scale per (batch, position, kv-head)).

The int8 saving is storage/capacity: the cache occupies half the HBM, so
longer contexts (or more serving slots) fit per chip. It is a *bandwidth* win
only when XLA fuses the int8->fp32 convert into the attention matmuls — the
update below dequantizes the full ``[b, max_len, kv_heads, head_dim]`` buffer
every decode step, so an unfused backend materializes a compute-dtype copy and
pays the full-precision bandwidth term anyway. Beyond the reference: its bnb
integration quantizes weights only.
"""

from __future__ import annotations

from collections import deque
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


def _q(x):
    """Blockwise int8 quantization: one fp32 absmax scale per trailing-axis
    group (per (…, kv-head) row). Returns ``(int8 values, fp32 scales)``;
    all-zero rows get scale 1.0 so the dequantized zero stays exact."""
    absmax = jnp.abs(x.astype(jnp.float32)).max(axis=-1)
    scale = jnp.where(absmax > 0, absmax, 1.0) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def _dq(q, scale, dtype):
    """Inverse of `_q`: int8 values × fp32 scales, cast to compute dtype."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def decode_cache_update(
    mod: Any,  # the flax module (self) owning the "cache" collection
    k: jax.Array,  # [b, s, kv_heads, head_dim] new keys
    v: jax.Array,
    max_len: int,
    kv_cache_dtype: Any = None,  # None = store at k.dtype; int8 = quantized
    per_slot: bool = False,  # [b]-vector write index (continuous batching)
    write_mask: jax.Array | None = None,  # [b] bool: False rows freeze (per_slot)
    write_len: jax.Array | None = None,  # [b] int32: per-row segment length cap
    sharding: Any = None,  # parallel.sharding.KVCacheSharding: in-jit mesh layout
) -> tuple[jax.Array, jax.Array, jax.Array, bool]:
    """Create/update the module's decode cache and return
    ``(k_all, v_all, write_index, is_init)``.

    ``k_all``/``v_all`` are the full ``[b, max_len, ...]`` buffers in compute
    dtype (dequantized when stored int8) — on the first (shape-init) trace they
    are just ``k``/``v`` and ``is_init`` is False. ``write_index`` is the cache
    position the new entries were written at.

    ``per_slot=True`` replaces the scalar write index shared by the whole batch
    with a ``[b]`` vector: row ``i`` writes its new entries at its own
    ``cache_index[i]`` (the serving engine's slot pool, where every slot sits at
    a different position in an independent sequence — `serving/engine.py`).
    ``write_index`` is then the ``[b]`` vector and row starts clamp into range
    exactly like ``dynamic_update_slice``.

    ``write_mask`` (per_slot only) freezes rows where it is False: the row's
    buffers and write index are left bit-identical instead of being written.
    This is the serving engine's on-device finished mask — with pipelined
    dispatch the host's retirement lags the device by up to ``pipeline_depth``
    steps, and a finished slot must not keep mutating its cache while it waits
    to be recycled.

    ``sharding`` (a `parallel.sharding.KVCacheSharding`, per_slot path) pins
    the updated buffers to the serving mesh layout with in-jit sharding
    constraints — heads on the ``model`` axis, slots optionally on ``data`` —
    so XLA's propagation cannot drift the donated pool cache's layout between
    steps. ``None`` (the default, and all of training) changes nothing.
    """
    if kv_cache_dtype is not None and np.dtype(kv_cache_dtype) != np.dtype("int8"):
        # fail fast with the cause named — an arbitrary dtype would surface as
        # an obscure lax dtype-mismatch deep in the cache update
        raise ValueError(
            f"kv_cache_dtype supports None (compute dtype) or int8, got {kv_cache_dtype}"
        )
    if write_mask is not None and not per_slot:
        raise ValueError(
            "write_mask requires per_slot=True (the scalar-index cache has no "
            "per-row freeze semantics)"
        )
    if write_len is not None and not per_slot:
        raise ValueError(
            "write_len requires per_slot=True (per-row segment clamping is a "
            "slot-pool decode concept)"
        )
    quant = kv_cache_dtype is not None
    b, s, kv_heads, head_dim = k.shape
    store_dtype = jnp.int8 if quant else k.dtype
    is_init = mod.has_variable("cache", "cached_key")
    cached_k = mod.variable("cache", "cached_key", jnp.zeros,
                            (b, max_len, kv_heads, head_dim), store_dtype)
    cached_v = mod.variable("cache", "cached_value", jnp.zeros,
                            (b, max_len, kv_heads, head_dim), store_dtype)
    if quant:
        k_scale = mod.variable("cache", "key_scale", jnp.zeros,
                               (b, max_len, kv_heads), jnp.float32)
        v_scale = mod.variable("cache", "value_scale", jnp.zeros,
                               (b, max_len, kv_heads), jnp.float32)
    cache_idx = mod.variable(
        "cache", "cache_index",
        lambda: jnp.zeros((b,) if per_slot else (), jnp.int32),
    )

    if not is_init:
        return k, v, cache_idx.value, False

    idx = cache_idx.value
    next_idx = idx + s
    if per_slot:
        # row-wise scatter: each batch row writes at its own index (vmapped
        # dynamic_update_slice keeps the update static-shape and fully jittable)
        if write_len is not None:
            # variable-length segment scatter (speculative verify,
            # serving/engine.py): row i writes only its first
            # clip(write_len[i], 0, s) new entries at idx[i].. — the rest
            # redirect past the buffer end and are dropped, so a verify
            # segment can never overrun a row's budget/context bound the way
            # a start-clamped dynamic_update_slice would (which silently
            # rewrites committed history once idx + s > max_len)
            wl = jnp.clip(write_len.astype(idx.dtype), 0, s)
            if write_mask is not None:
                wl = wl * write_mask.astype(wl.dtype)
            cols = idx[:, None] + jnp.arange(s, dtype=idx.dtype)[None, :]
            cols = jnp.where(jnp.arange(s)[None, :] < wl[:, None], cols, max_len)
            rows = jnp.arange(b)[:, None]
            row4 = lambda buf, new, i: buf.at[rows, cols].set(new, mode="drop")  # noqa: E731
            row3 = row4  # broadcasted [b, s] indices cover 3-d scale planes too
            next_idx = idx + wl
        elif write_mask is None:
            row4 = jax.vmap(lambda buf, new, i: jax.lax.dynamic_update_slice(buf, new, (i, 0, 0)))
            row3 = jax.vmap(lambda buf, new, i: jax.lax.dynamic_update_slice(buf, new, (i, 0)))
            next_idx = idx + s
        else:
            # frozen rows (mask False) re-write their CURRENT entries — a
            # bit-exact no-op — and keep their index, so a finished slot's
            # cache never moves while host retirement lags the device
            def _masked_row(lead_zeros):
                def upd(buf, new, i, m):
                    start = (i,) + (0,) * lead_zeros
                    cur = jax.lax.dynamic_slice(buf, start, new.shape)
                    return jax.lax.dynamic_update_slice(
                        buf, jnp.where(m, new, cur), start
                    )

                return jax.vmap(upd, in_axes=(0, 0, 0, 0))

            _row4, _row3 = _masked_row(2), _masked_row(1)
            row4 = lambda buf, new, i: _row4(buf, new, i, write_mask)  # noqa: E731
            row3 = lambda buf, new, i: _row3(buf, new, i, write_mask)  # noqa: E731
            next_idx = idx + s * write_mask.astype(idx.dtype)
        if quant:
            kq, ks = _q(k)
            vq, vs = _q(v)
            cached_k.value = row4(cached_k.value, kq, idx)
            cached_v.value = row4(cached_v.value, vq, idx)
            k_scale.value = row3(k_scale.value, ks, idx)
            v_scale.value = row3(v_scale.value, vs, idx)
            if sharding is not None:
                cached_k.value = jax.lax.with_sharding_constraint(cached_k.value, sharding.kv)
                cached_v.value = jax.lax.with_sharding_constraint(cached_v.value, sharding.kv)
                k_scale.value = jax.lax.with_sharding_constraint(k_scale.value, sharding.scale)
                v_scale.value = jax.lax.with_sharding_constraint(v_scale.value, sharding.scale)
            k_all = _dq(cached_k.value, k_scale.value, k.dtype)
            v_all = _dq(cached_v.value, v_scale.value, v.dtype)
        else:
            cached_k.value = row4(cached_k.value, k, idx)
            cached_v.value = row4(cached_v.value, v, idx)
            if sharding is not None:
                cached_k.value = jax.lax.with_sharding_constraint(cached_k.value, sharding.kv)
                cached_v.value = jax.lax.with_sharding_constraint(cached_v.value, sharding.kv)
            k_all, v_all = cached_k.value, cached_v.value
        if sharding is not None:
            next_idx = jax.lax.with_sharding_constraint(next_idx, sharding.index)
    elif quant:
        kq, ks = _q(k)
        vq, vs = _q(v)
        cached_k.value = jax.lax.dynamic_update_slice(cached_k.value, kq, (0, idx, 0, 0))
        cached_v.value = jax.lax.dynamic_update_slice(cached_v.value, vq, (0, idx, 0, 0))
        k_scale.value = jax.lax.dynamic_update_slice(k_scale.value, ks, (0, idx, 0))
        v_scale.value = jax.lax.dynamic_update_slice(v_scale.value, vs, (0, idx, 0))
        k_all = _dq(cached_k.value, k_scale.value, k.dtype)
        v_all = _dq(cached_v.value, v_scale.value, v.dtype)
    else:
        k_all = jax.lax.dynamic_update_slice(cached_k.value, k, (0, idx, 0, 0))
        v_all = jax.lax.dynamic_update_slice(cached_v.value, v, (0, idx, 0, 0))
        cached_k.value, cached_v.value = k_all, v_all
    cache_idx.value = next_idx
    return k_all, v_all, idx, True


def _paged_frontier_write(
    pools: tuple[jax.Array, ...],  # per-leaf [num_blocks, block_tokens, ...] pools
    news: tuple[jax.Array, ...],  # congruent [b, s, ...] new rows to land
    idx: jax.Array,  # [b] int32 write cursors
    mask: jax.Array,  # [b] bool: False rows freeze (dropped write)
    write_len: jax.Array | None,  # [b] int32 per-row segment cap, or None (s==1)
    num_blocks: int,
    block_tokens: int,
    block_tables: jax.Array,  # [b, blocks_per_slot] int32 pool block ids
) -> tuple[tuple[jax.Array, ...], jax.Array]:
    """The append-at-frontier pool write shared by `paged_decode_update` and
    `paged_decode_write`: returns ``(new_pools, next_idx)``.

    ``pools``/``news`` are congruent tuples of pool leaves and their new
    rows — (K, V) at full precision, (K, V, K-scale, V-scale) when the pool
    stores int8 (the fp32 scale planes are ``[num_blocks, block_tokens,
    kv_heads]`` and land through the same block ids/offsets, so a KV byte and
    its scale can never diverge).

    ``write_len=None`` is the classic one-token step (``s == 1``). With
    ``write_len`` ([b] int32) the segment path lands row ``i``'s first
    ``clip(write_len[i], 0, s)`` entries at token positions ``idx[i]..``
    through the row's block table (speculative verify, `serving/engine.py`);
    the rest redirect to block id ``num_blocks`` and are dropped, so a verify
    segment can never write into blocks the row's reservation does not own.
    """
    b, s = news[0].shape[:2]
    if write_len is None:
        bids = block_tables[jnp.arange(b), idx // block_tokens]  # [b]
        bids = jnp.where(mask, bids, num_blocks)  # frozen rows: dropped write
        offs = idx % block_tokens
        out = tuple(pool.at[bids, offs].set(new[:, 0], mode="drop")
                    for pool, new in zip(pools, news))
        return out, idx + mask.astype(idx.dtype)
    wl = jnp.clip(write_len.astype(idx.dtype), 0, s) * mask.astype(idx.dtype)
    cols = idx[:, None] + jnp.arange(s, dtype=idx.dtype)[None, :]  # [b, s]
    valid = jnp.arange(s)[None, :] < wl[:, None]
    bps = block_tables.shape[1]
    bids = block_tables[jnp.arange(b)[:, None],
                        jnp.clip(cols // block_tokens, 0, bps - 1)]
    bids = jnp.where(valid, bids, num_blocks)  # clamped/frozen: dropped write
    offs = cols % block_tokens
    out = tuple(pool.at[bids, offs].set(new, mode="drop")
                for pool, new in zip(pools, news))
    return out, idx + wl


def _paged_pool_step(
    mod: Any,
    k: jax.Array,
    v: jax.Array,
    num_blocks: int,
    block_tokens: int,
    block_tables: jax.Array | None,
    kv_cache_dtype: Any,
    write_mask: jax.Array | None,
    write_len: jax.Array | None,
    sharding: Any,
) -> tuple[tuple[jax.Array, ...], jax.Array, bool]:
    """Shared body of `paged_decode_update` / `paged_decode_write`: create the
    pool variables (int8 payload + fp32 scale planes when quantized), run the
    append-at-frontier write, pin shardings, commit. Returns
    ``(pool_leaves, write_index, is_init)`` where ``pool_leaves`` is
    ``(k_pool, v_pool)`` at full precision or
    ``(k_pool, v_pool, k_scale_pool, v_scale_pool)`` under int8."""
    if kv_cache_dtype is not None and np.dtype(kv_cache_dtype) != np.dtype("int8"):
        raise ValueError(
            f"kv_cache_dtype supports None (compute dtype) or int8, got {kv_cache_dtype}"
        )
    quant = kv_cache_dtype is not None
    b, s, kv_heads, head_dim = k.shape
    store_dtype = jnp.int8 if quant else k.dtype
    is_init = mod.has_variable("cache", "cached_key")
    cached_k = mod.variable("cache", "cached_key", jnp.zeros,
                            (num_blocks, block_tokens, kv_heads, head_dim), store_dtype)
    cached_v = mod.variable("cache", "cached_value", jnp.zeros,
                            (num_blocks, block_tokens, kv_heads, head_dim), store_dtype)
    if quant:
        k_scale = mod.variable("cache", "key_scale", jnp.zeros,
                               (num_blocks, block_tokens, kv_heads), jnp.float32)
        v_scale = mod.variable("cache", "value_scale", jnp.zeros,
                               (num_blocks, block_tokens, kv_heads), jnp.float32)
    cache_idx = mod.variable("cache", "cache_index",
                             lambda: jnp.zeros((b,), jnp.int32))
    if not is_init:
        return (), cache_idx.value, False
    if s != 1 and write_len is None:
        raise ValueError(
            f"paged decode writes one token per step, got a length-{s} segment "
            "(prefill runs through the contiguous admission cache, then "
            "scatter_rows_to_blocks; multi-token verify segments must pass "
            "write_len)"
        )
    if block_tables is None:
        raise ValueError("paged decode needs block_tables ([b, blocks_per_slot])")
    idx = cache_idx.value  # [b]
    mask = (jnp.ones((b,), bool) if write_mask is None
            else write_mask.astype(bool))
    if quant:
        kq, ks = _q(k)
        vq, vs = _q(v)
        pools = (cached_k.value, cached_v.value, k_scale.value, v_scale.value)
        news = (kq, vq, ks, vs)
    else:
        pools = (cached_k.value, cached_v.value)
        news = (k, v)
    new_pools, next_idx = _paged_frontier_write(
        pools, news, idx, mask, write_len,
        num_blocks, block_tokens, block_tables,
    )
    if sharding is not None:
        kv_specs = (sharding.kv, sharding.kv) + (
            (sharding.scale, sharding.scale) if quant else ())
        new_pools = tuple(
            jax.lax.with_sharding_constraint(leaf, spec)
            for leaf, spec in zip(new_pools, kv_specs)
        )
        next_idx = jax.lax.with_sharding_constraint(next_idx, sharding.index)
    cached_k.value, cached_v.value = new_pools[0], new_pools[1]
    if quant:
        k_scale.value, v_scale.value = new_pools[2], new_pools[3]
    cache_idx.value = next_idx
    return new_pools, idx, True


def paged_decode_update(
    mod: Any,  # the flax module (self) owning the "cache" collection
    k: jax.Array,  # [b, s, kv_heads, head_dim] new keys (s == 1 unless write_len)
    v: jax.Array,
    num_blocks: int,  # pool size; block id == num_blocks is the dropped write
    block_tokens: int,
    block_tables: jax.Array | None,  # [b, blocks_per_slot] int32 pool block ids
    kv_cache_dtype: Any = None,  # None = store at k.dtype; int8 = quantized pool
    write_mask: jax.Array | None = None,  # [b] bool: False rows freeze
    write_len: jax.Array | None = None,  # [b] int32: per-row segment length cap
    sharding: Any = None,  # KVCacheSharding with pool kv / scale / index / gathered
) -> tuple[jax.Array, jax.Array, jax.Array, bool]:
    """Paged variant of `decode_cache_update`: the cache collection holds ONE
    shared ``[num_blocks, block_tokens, ...]`` block pool (per layer) plus the
    per-slot ``[b]`` write cursor, and each row's KV lives wherever its block
    table says. Returns ``(k_all, v_all, write_index, is_init)`` exactly like
    the slot-pool path, with ``k_all``/``v_all`` the gathered
    ``[b, blocks_per_slot * block_tokens, ...]`` attended view.

    Append-at-frontier write: row ``i``'s new entry lands in pool block
    ``block_tables[i, idx[i] // block_tokens]`` at offset
    ``idx[i] % block_tokens``. Rows frozen by ``write_mask`` redirect their
    write to block id ``num_blocks`` — out of range, dropped by the scatter —
    and keep their cursor, so a finished slot never mutates pool state while
    host retirement lags the device. Unallocated table entries (the engine
    leaves them 0) are never written — the cursor cannot reach past the
    blocks admission reserved for the row's prompt + budget — and reads of
    them are masked out of attention at the frontier, so stale pool contents
    cannot perturb a stream (the parity bar of `docs/serving.md`).

    ``kv_cache_dtype=int8`` stores the pool quantized: the int8 payload rides
    the usual ``[num_blocks, block_tokens, kv_heads, head_dim]`` leaves and
    the fp32 absmax scales ride sibling ``key_scale``/``value_scale`` pool
    leaves of shape ``[num_blocks, block_tokens, kv_heads]`` — per-block
    planes addressed through the SAME block table, mirroring the slot path's
    per-(batch, position, kv-head) scheme. The gathered attended view is
    dequantized here (scales gathered alongside the payload), so attention
    sees compute-dtype K/V either way.
    """
    b, s, kv_heads, head_dim = k.shape
    new_pools, idx, is_init = _paged_pool_step(
        mod, k, v, num_blocks, block_tokens, block_tables, kv_cache_dtype,
        write_mask, write_len, sharding,
    )
    if not is_init:
        return k, v, idx, False
    # the attended view: each row's table blocks concatenated in token order —
    # position p of row i sits at gathered index p (block p // block_tokens,
    # offset p % block_tokens), the same layout the slot-pool cache has, so
    # the caller's frontier mask is identical in both modes
    blocks_per_slot = block_tables.shape[1]
    span = blocks_per_slot * block_tokens

    def _view(pool):
        return pool[block_tables].reshape((b, span) + pool.shape[2:])

    if kv_cache_dtype is not None:
        new_k, new_v, new_ks, new_vs = new_pools
        k_all = _dq(_view(new_k), _view(new_ks), k.dtype)
        v_all = _dq(_view(new_v), _view(new_vs), v.dtype)
    else:
        new_k, new_v = new_pools
        k_all, v_all = _view(new_k), _view(new_v)
    if sharding is not None and getattr(sharding, "gathered", None) is not None:
        k_all = jax.lax.with_sharding_constraint(k_all, sharding.gathered)
        v_all = jax.lax.with_sharding_constraint(v_all, sharding.gathered)
    return k_all, v_all, idx, True


def paged_decode_write(
    mod: Any,  # the flax module (self) owning the "cache" collection
    k: jax.Array,  # [b, s, kv_heads, head_dim] new keys (s == 1 unless write_len)
    v: jax.Array,
    num_blocks: int,  # pool size; block id == num_blocks is the dropped write
    block_tokens: int,
    block_tables: jax.Array | None,  # [b, blocks_per_slot] int32 pool block ids
    kv_cache_dtype: Any = None,  # None = store at k.dtype; int8 = quantized pool
    write_mask: jax.Array | None = None,  # [b] bool: False rows freeze
    write_len: jax.Array | None = None,  # [b] int32: per-row segment length cap
    sharding: Any = None,  # KVCacheSharding with pool kv / scale / index
) -> tuple[jax.Array, jax.Array, jax.Array, bool, tuple[jax.Array, jax.Array] | None]:
    """Write-only variant of `paged_decode_update` for the fused attention
    path: identical append-at-frontier write and cursor semantics, but returns
    the UPDATED POOL leaves — ``(k_pool, v_pool, write_index, is_init,
    scale_pools)`` with the pool still ``[num_blocks, block_tokens, ...]`` —
    instead of gathering the contiguous ``[b, span, ...]`` attended view. The
    Pallas kernel (`ops.flash_attention.paged_decode_attention`) then reads
    the blocks in place through the block table, so no per-layer per-step
    gather copy is ever materialized. Frozen rows (``write_mask`` False) still
    redirect their write to the dropped block id and keep their cursor.

    ``scale_pools`` is ``None`` at full precision; under
    ``kv_cache_dtype=int8`` it is ``(k_scale_pool, v_scale_pool)`` — the fp32
    absmax planes (``[num_blocks, block_tokens, kv_heads]``) the kernel needs
    to dequantize each block in VMEM scratch, so the pool is never
    materialized at fp32."""
    new_pools, idx, is_init = _paged_pool_step(
        mod, k, v, num_blocks, block_tokens, block_tables, kv_cache_dtype,
        write_mask, write_len, sharding,
    )
    if not is_init:
        return k, v, idx, False, None
    if kv_cache_dtype is not None:
        new_k, new_v, new_ks, new_vs = new_pools
        return new_k, new_v, idx, True, (new_ks, new_vs)
    new_k, new_v = new_pools
    return new_k, new_v, idx, True, None


def _is_index_leaf(path) -> bool:
    return getattr(path[-1], "key", None) == "cache_index"


def rewind_frontier(cache: Any, new_index: jax.Array) -> Any:
    """Move every ``cache_index`` cursor leaf to ``new_index`` ([b] int32)
    without touching a single KV byte — the speculative-decoding rollback
    (`serving/engine.py`). A rejected draft's KV entries stay behind in the
    slot buffer / block pool, but the cursor retreat makes them dead state:
    the next write lands on top of them and the frontier mask keeps attention
    from ever reading past the cursor. Works unchanged for the slot-pool,
    paged-gather, and paged-fused layouts because all three share the ``[b]``
    cursor leaf — in paged mode this is the promised block-table rollback
    with no pool copy."""

    def stamp(path, leaf):
        if _is_index_leaf(path):
            return new_index.astype(leaf.dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(stamp, cache)


class BlockAllocator:
    """Host-side free-list over a device block pool's ids (paged KV serving,
    `docs/serving.md` "Paged KV").

    The pool itself is device state (`make_block_pool` leaves); this tracks
    which block ids are owned — by a slot's private frontier or by the prefix
    trie — purely on the host, so admission never round-trips the device to
    find space. Allocation is all-or-nothing: a request that cannot get every
    block it needs gets none (backpressure, never a half-placed request), and
    a double free fails loudly (an aliasing bug would otherwise corrupt two
    requests' KV silently).
    """

    def __init__(self, num_blocks: int):
        num_blocks = int(num_blocks)
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        self.num_blocks = num_blocks
        self._free: deque[int] = deque(range(num_blocks))
        self._owned: set[int] = set()

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def owned_count(self) -> int:
        return len(self._owned)

    def alloc(self, n: int) -> list[int] | None:
        """``n`` distinct block ids, or None when fewer than ``n`` are free
        (all-or-nothing — the caller evicts or backs off, never partial)."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            return None
        ids = [self._free.popleft() for _ in range(n)]
        self._owned.update(ids)
        return ids

    def free(self, ids) -> None:
        """Return block ids to the free list (slot retirement / trie eviction)."""
        for b in ids:
            b = int(b)
            if b not in self._owned:
                raise ValueError(f"double free of block {b}")
            self._owned.discard(b)
            self._free.append(b)


# --------------------------------------------------------- byte accounting
def tree_nbytes(tree: Any) -> int:
    """Total device bytes of every array leaf in a cache/pool pytree — the
    exact allocation cost (`sum(leaf.nbytes)`), counting the int8 path's fp32
    absmax scales and the cache_index cursors alongside the KV buffers. The
    serving telemetry gauges (`serving/telemetry.py`) are contracted to match
    this number exactly; tests/test_telemetry.py holds them to it."""
    return sum(int(leaf.nbytes) for leaf in jax.tree_util.tree_leaves(tree))


def tree_bytes_by_dtype(tree: Any) -> dict[str, int]:
    """Per-dtype byte split of a cache/pool pytree (dtype name -> bytes,
    sorted by name). Separates what int8 KV storage actually buys: the int8
    buffers shrink, the fp32 scale planes ride along at full precision."""
    out: dict[str, int] = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        name = str(np.dtype(leaf.dtype))
        out[name] = out.get(name, 0) + int(leaf.nbytes)
    return dict(sorted(out.items()))


def make_cache(module: Any, batch: int, shardings: Any = None) -> Any:
    """Allocate the zeroed ``[batch, n_positions, ...]`` per-slot decode cache
    pytree for ``module`` (the serving engine's slot pool) without running a
    real forward: shapes come from `jax.eval_shape` over ``module.init``, so
    no throwaway init compute touches the device.

    ``shardings`` is an optional congruent pytree of NamedShardings
    (`parallel.sharding.infer_cache_shardings`): each leaf is then allocated
    directly into its mesh placement — a model-sharded pool never materializes
    unsharded on one device, which is the whole point of serving models that
    do not fit a single chip.
    """
    shapes = jax.eval_shape(
        lambda: module.init(
            jax.random.key(0), jnp.zeros((batch, 1), jnp.int32), decode=True
        )["cache"]
    )
    if shardings is None:
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
    return jax.tree.map(
        lambda s, sh: jax.device_put(jnp.zeros(s.shape, s.dtype), sh),
        shapes, shardings,
    )


def _constrain_tree(tree: Any, shardings: Any) -> Any:
    """Apply a congruent pytree of NamedShardings as in-jit constraints."""
    if shardings is None:
        return tree
    return jax.tree.map(jax.lax.with_sharding_constraint, tree, shardings)


def make_block_pool(cache: Any, num_blocks: int, block_tokens: int,
                    shardings: Any = None) -> Any:
    """Allocate the device-resident block pool for prefix KV reuse
    (`serving/prefix_cache.py`): a pytree mirroring a per-slot cache, but with
    every KV leaf carved into ``[num_blocks, block_tokens, ...]`` fixed-size
    blocks instead of ``[B, n_positions, ...]`` slot rows.

    ``cache_index`` leaves become per-block placeholders (the pool has no
    write cursor — block occupancy lives in the host-side radix trie); they
    exist only so the pool shares the cache's treedef and one ``tree_map``
    drives every gather/scatter.

    ``shardings`` (a congruent pytree of NamedShardings,
    `parallel.sharding.infer_block_pool_shardings`) allocates each block leaf
    straight into its mesh placement — heads sharded on the model axis, blocks
    replicated across replicas so any replica can reuse any cached prefix.
    """

    def alloc(path, leaf):
        if _is_index_leaf(path):
            return jnp.zeros((num_blocks,), leaf.dtype)
        return jnp.zeros((num_blocks, block_tokens) + leaf.shape[2:], leaf.dtype)

    pool = jax.tree_util.tree_map_with_path(alloc, cache)
    if shardings is not None:
        pool = jax.tree.map(jax.device_put, pool, shardings)
    return pool


def gather_block_rows(
    block_pool: Any,  # [num_blocks, block_tokens, ...] pool pytree
    block_tables: jax.Array,  # [nb, blocks_per_row] int32 pool block ids
    cache_index: jax.Array,  # [nb] int32 resume index (the cached prefix length)
    shardings: Any = None,  # congruent NamedShardings for the assembled rows
) -> Any:
    """Assemble ``nb`` cache rows from pool blocks in ONE gather per leaf: row
    ``i`` is ``block_tables[i]``'s blocks concatenated along the token axis
    (``blocks_per_row * block_tokens`` positions — the engine sizes the table
    so this equals ``n_positions``). Table entries past a row's real prefix
    may point anywhere valid: the positions they fill are overwritten by the
    suffix prefill or masked out of attention before anything reads them.
    ``cache_index`` leaves are set to ``cache_index`` so the suffix prefill
    writes (and attends) from each row's cached-prefix end.
    """

    def gather(path, leaf):
        if _is_index_leaf(path):
            return cache_index.astype(leaf.dtype)
        rows = leaf[block_tables]  # [nb, blocks_per_row, block_tokens, ...]
        return rows.reshape((rows.shape[0], rows.shape[1] * rows.shape[2]) + rows.shape[3:])

    return _constrain_tree(
        jax.tree_util.tree_map_with_path(gather, block_pool), shardings
    )


def scatter_block_rows(
    block_pool: Any,  # [num_blocks, block_tokens, ...] pool pytree
    cache: Any,  # the [B, n_positions, ...] slot-pool cache pytree
    slot: jax.Array,  # scalar int32 slot row to donate from
    dest_blocks: jax.Array,  # [n_positions // block_tokens] int32 pool ids; >= num_blocks drops
    shardings: Any = None,  # congruent NamedShardings keeping the pool's layout
) -> Any:
    """Donate one slot row's KV into pool blocks in ONE scatter per leaf (the
    prefix cache's retire-time donation). ``dest_blocks[j]`` is where the
    row's ``j``-th block lands; entries pointing past the pool (``num_blocks``)
    are dropped — that is how already-present trie blocks and the region past
    the donated prefix are skipped without a second compile."""

    def scatter(path, pool_leaf, cache_leaf):
        if _is_index_leaf(path):
            return pool_leaf
        row = cache_leaf[slot]  # [n_positions, ...]
        n_blocks = dest_blocks.shape[0]
        blocks = row.reshape((n_blocks, row.shape[0] // n_blocks) + row.shape[1:])
        return pool_leaf.at[dest_blocks].set(blocks, mode="drop")

    return _constrain_tree(
        jax.tree_util.tree_map_with_path(scatter, block_pool, cache), shardings
    )


def scatter_cache_slots(
    pool_cache: Any,  # the [B, ...] slot-pool cache pytree
    new_cache: Any,  # an [nb, ...] freshly prefilled cache pytree
    slots: jax.Array,  # [nb] int32 distinct pool rows to write
    cache_index: jax.Array,  # [nb] int32 per-row resume index (unpadded length)
    shardings: Any = None,  # congruent NamedShardings keeping the pool's layout
) -> Any:
    """Scatter an ``nb``-row prefill cache into pool rows ``slots`` in ONE
    jitted op per leaf (the serving engine's batched admission: `pipeline
    decode dispatch`, `serving/engine.py`).

    Every leaf's rows land at ``pool_leaf[slots[i]]``. The ``cache_index``
    leaf is OVERWRITTEN with ``cache_index`` — the prefill advanced it to the
    padded bucket length, but decode must resume (and overwrite the pad
    entries) from each row's true prompt end.
    """

    def insert(path, pool_leaf, new_leaf):
        if getattr(path[-1], "key", None) == "cache_index":
            return pool_leaf.at[slots].set(cache_index.astype(pool_leaf.dtype))
        return pool_leaf.at[slots].set(new_leaf.astype(pool_leaf.dtype))

    return _constrain_tree(
        jax.tree_util.tree_map_with_path(insert, pool_cache, new_cache), shardings
    )


def scatter_rows_to_blocks(
    paged_cache: Any,  # paged cache pytree: KV [num_blocks, block_tokens, ...], cache_index [B]
    new_cache: Any,  # an [nb, bucket, ...] freshly prefilled cache pytree
    slots: jax.Array,  # [nb] int32 slot rows whose write cursor to stamp
    dest_blocks: jax.Array,  # [nb, ceil(bucket / block_tokens)] pool ids; >= num_blocks drops
    cache_index: jax.Array,  # [nb] int32 per-row resume index (true prefill length)
    block_tokens: int,
    shardings: Any = None,  # congruent NamedShardings keeping the pool's layout
) -> Any:
    """Paged admission: carve each freshly prefilled contiguous row into
    ``block_tokens``-sized pieces and scatter them into the row's allocated
    pool blocks in ONE op per leaf (the paged counterpart of
    `scatter_cache_slots`). ``dest_blocks[i, j]`` is where row ``i``'s
    ``j``-th piece lands; entries pointing past the pool (``num_blocks``)
    are dropped — that is how a cache hit's ALIASED prefix blocks (already
    resident, trie-pinned, shared zero-copy through the block table) and the
    pad region past a short bucket are skipped without a second compile.

    The ``cache_index`` leaf rows ``slots`` are stamped with ``cache_index``
    (the true prefill length — decode's append frontier), exactly like the
    slot-pool admission scatter.
    """

    def scatter(path, pool_leaf, new_leaf):
        if _is_index_leaf(path):
            return pool_leaf.at[slots].set(cache_index.astype(pool_leaf.dtype))
        nb, bucket = new_leaf.shape[:2]
        n_blk = dest_blocks.shape[1]
        pad = n_blk * block_tokens - bucket
        if pad:
            new_leaf = jnp.pad(
                new_leaf, [(0, 0), (0, pad)] + [(0, 0)] * (new_leaf.ndim - 2)
            )
        pieces = new_leaf.reshape((nb * n_blk, block_tokens) + new_leaf.shape[2:])
        return pool_leaf.at[dest_blocks.reshape(-1)].set(
            pieces.astype(pool_leaf.dtype), mode="drop"
        )

    return _constrain_tree(
        jax.tree_util.tree_map_with_path(scatter, paged_cache, new_cache), shardings
    )
