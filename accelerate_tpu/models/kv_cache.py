"""Decode-time KV cache: the flax decode idiom (fixed-length buffers, running
write index) shared by every autoregressive model in the zoo, with optional
int8 blockwise storage (one fp32 absmax scale per (batch, position, kv-head) —
halves cache HBM, the decode-attention bandwidth term; the dequantize fuses
into the attention matmuls). Beyond the reference: its bnb integration
quantizes weights only.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


def decode_cache_update(
    mod: Any,  # the flax module (self) owning the "cache" collection
    k: jax.Array,  # [b, s, kv_heads, head_dim] new keys
    v: jax.Array,
    max_len: int,
    kv_cache_dtype: Any = None,  # None = store at k.dtype; int8 = quantized
) -> tuple[jax.Array, jax.Array, jax.Array, bool]:
    """Create/update the module's decode cache and return
    ``(k_all, v_all, write_index, is_init)``.

    ``k_all``/``v_all`` are the full ``[b, max_len, ...]`` buffers in compute
    dtype (dequantized when stored int8) — on the first (shape-init) trace they
    are just ``k``/``v`` and ``is_init`` is False. ``write_index`` is the cache
    position the new entries were written at.
    """
    if kv_cache_dtype is not None and np.dtype(kv_cache_dtype) != np.dtype("int8"):
        # fail fast with the cause named — an arbitrary dtype would surface as
        # an obscure lax dtype-mismatch deep in the cache update
        raise ValueError(
            f"kv_cache_dtype supports None (compute dtype) or int8, got {kv_cache_dtype}"
        )
    quant = kv_cache_dtype is not None
    b, s, kv_heads, head_dim = k.shape
    store_dtype = jnp.int8 if quant else k.dtype
    is_init = mod.has_variable("cache", "cached_key")
    cached_k = mod.variable("cache", "cached_key", jnp.zeros,
                            (b, max_len, kv_heads, head_dim), store_dtype)
    cached_v = mod.variable("cache", "cached_value", jnp.zeros,
                            (b, max_len, kv_heads, head_dim), store_dtype)
    if quant:
        k_scale = mod.variable("cache", "key_scale", jnp.zeros,
                               (b, max_len, kv_heads), jnp.float32)
        v_scale = mod.variable("cache", "value_scale", jnp.zeros,
                               (b, max_len, kv_heads), jnp.float32)
    cache_idx = mod.variable("cache", "cache_index", lambda: jnp.zeros((), jnp.int32))

    if not is_init:
        return k, v, cache_idx.value, False

    def _q(x):
        absmax = jnp.abs(x.astype(jnp.float32)).max(axis=-1)
        scale = jnp.where(absmax > 0, absmax, 1.0) / 127.0
        q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                     -127, 127).astype(jnp.int8)
        return q, scale

    def _dq(q, scale, dtype):
        return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)

    idx = cache_idx.value
    if quant:
        kq, ks = _q(k)
        vq, vs = _q(v)
        cached_k.value = jax.lax.dynamic_update_slice(cached_k.value, kq, (0, idx, 0, 0))
        cached_v.value = jax.lax.dynamic_update_slice(cached_v.value, vq, (0, idx, 0, 0))
        k_scale.value = jax.lax.dynamic_update_slice(k_scale.value, ks, (0, idx, 0))
        v_scale.value = jax.lax.dynamic_update_slice(v_scale.value, vs, (0, idx, 0))
        k_all = _dq(cached_k.value, k_scale.value, k.dtype)
        v_all = _dq(cached_v.value, v_scale.value, v.dtype)
    else:
        k_all = jax.lax.dynamic_update_slice(cached_k.value, k, (0, idx, 0, 0))
        v_all = jax.lax.dynamic_update_slice(cached_v.value, v, (0, idx, 0, 0))
        cached_k.value, cached_v.value = k_all, v_all
    cache_idx.value = idx + s
    return k_all, v_all, idx, True
