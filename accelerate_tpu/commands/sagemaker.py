"""Amazon SageMaker launch surface (reference `commands/config/sagemaker.py` +
`utils/launch.py:504-618` prepare_sagemager_args_inputs / sagemaker_launcher).

TPU-native re-founding: SageMaker's accelerator fleet for JAX is Trainium/
Inferentia (`ml.trn1.*`) or GPU instances running the JAX DLC — either way the
launch contract is identical to the reference's: turn the training script +
config into an estimator job spec (entry point, source dir, role, instances,
hyperparameters from the script args, the ACCELERATE_TPU_* env contract) and
submit it. Job-spec construction is pure and fully tested; submission needs
the `sagemaker` SDK and AWS credentials, and degrades to printing the exact
spec + an actionable message when the SDK is absent (nothing in this image may
pip-install boto3/sagemaker).
"""

from __future__ import annotations

import argparse
import json
import os
from dataclasses import asdict, dataclass, field
from typing import Any


@dataclass
class SageMakerConfig:
    """Reference `config_args.py:SageMakerConfig`, trimmed to the fields that
    mean something for a JAX job (no dynamo/pytorch-version pins)."""

    ec2_instance_type: str = "ml.trn1.32xlarge"
    iam_role_name: str = ""
    image_uri: str | None = None  # JAX DLC or custom image
    profile: str | None = None
    region: str = "us-east-1"
    num_machines: int = 1
    base_job_name: str = "accelerate-tpu-sagemaker"
    sagemaker_inputs_file: str | None = None
    sagemaker_metrics_file: str | None = None
    additional_args: dict = field(default_factory=dict)


def _convert_nargs_to_dict(nargs: list[str]) -> dict[str, Any]:
    """Script args -> estimator hyperparameters (reference
    `utils/launch.py:462-501` contract, including the no-store_true rule)."""

    def _infer(s: str) -> Any:
        try:
            f = float(s)
            return int(f) if f == int(f) else f
        except (ValueError, OverflowError):  # non-numeric, or inf (int(inf) raises)
            return s

    out: dict[str, Any] = {}
    i = 0
    while i < len(nargs):
        arg = nargs[i]
        if not arg.startswith("-"):
            raise ValueError(f"Positional script arg {arg!r} cannot become a hyperparameter")
        key = arg.lstrip("-")
        if "=" in key:
            key, value = key.split("=", 1)
            out[key] = _infer(value)
            i += 1
            continue
        def _is_number(s: str) -> bool:
            try:
                float(s)
                return True
            except ValueError:
                return False

        # a following token is a VALUE if it doesn't look like a flag — and a
        # negative number (-3, -1e-4) is a value, not a flag
        if i + 1 >= len(nargs) or (
            nargs[i + 1].startswith("-") and not _is_number(nargs[i + 1])
        ):
            raise ValueError(
                "SageMaker does not support store_true/store_false script flags; "
                f"give {arg!r} an explicit value (reference launch.py:485 rule)."
            )
        out[key] = _infer(nargs[i + 1])
        i += 2
    return out


def _parse_tsv_pairs(path: str, what: str) -> list[tuple[str, str]]:
    """Tab-(or whitespace-)separated `key<TAB>value` lines, comments/#/blank
    skipped — the shared shape of the inputs and metrics files (reference
    `launch.py:570-600`)."""
    pairs: list[tuple[str, str]] = []
    with open(path) as f:
        for ln, line in enumerate(f):
            if not line.strip() or line.lstrip().startswith("#"):
                continue
            parts = line.split("\t") if "\t" in line else line.split(None, 1)
            if len(parts) != 2:
                raise ValueError(f"{path}:{ln + 1}: expected '{what}'")
            pairs.append((parts[0].strip(), parts[1].strip()))
    return pairs


def _parse_inputs_file(path: str | None) -> dict[str, str] | None:
    """`channel\ts3://uri` lines (reference `launch.py:570-585`)."""
    if not path:
        return None
    return dict(_parse_tsv_pairs(path, "<channel>\\t<s3-uri>")) or None


def _parse_metrics_file(path: str | None) -> list[dict[str, str]] | None:
    """`name\tregex` lines (reference `launch.py:587-600`)."""
    if not path:
        return None
    pairs = _parse_tsv_pairs(path, "<name>\\t<regex>")
    return [{"Name": k, "Regex": v} for k, v in pairs] or None


def prepare_sagemaker_job(
    cfg: SageMakerConfig,
    training_script: str,
    script_args: list[str],
    launch_env: dict[str, str],
) -> dict[str, Any]:
    """Pure job-spec builder (reference `prepare_sagemager_args_inputs`):
    estimator kwargs + channel inputs, ready for `sagemaker.estimator.Estimator`
    or an `aws sagemaker create-training-job` translation."""
    source_dir = os.path.dirname(training_script) or "."
    entry_point = os.path.basename(training_script)
    if not entry_point.endswith(".py"):
        raise ValueError(f"Training script must be a .py file, got {entry_point!r}")
    if not cfg.iam_role_name:
        raise ValueError("SageMakerConfig.iam_role_name is required (execution role)")
    environment = dict(launch_env)
    environment["ACCELERATE_TPU_USE_SAGEMAKER"] = "true"
    if cfg.num_machines > 1:
        environment["ACCELERATE_TPU_NUM_PROCESSES"] = str(cfg.num_machines)
    spec: dict[str, Any] = {
        "estimator": {
            "entry_point": entry_point,
            "source_dir": source_dir,
            "role": cfg.iam_role_name,
            "instance_count": cfg.num_machines,
            "instance_type": cfg.ec2_instance_type,
            "base_job_name": cfg.base_job_name,
            "environment": environment,
            "hyperparameters": _convert_nargs_to_dict(script_args),
            **({"image_uri": cfg.image_uri} if cfg.image_uri else {}),
            **(cfg.additional_args or {}),
        },
        "region": cfg.region,
        **({"profile": cfg.profile} if cfg.profile else {}),
    }
    metrics = _parse_metrics_file(cfg.sagemaker_metrics_file)
    if metrics:
        spec["estimator"]["metric_definitions"] = metrics
    inputs = _parse_inputs_file(cfg.sagemaker_inputs_file)
    if inputs:
        spec["inputs"] = inputs
    return spec


def sagemaker_launcher(
    cfg: SageMakerConfig,
    args: argparse.Namespace,
    launch_env: dict[str, str],
) -> int:
    """Submit (or, without the SDK, print) the SageMaker job (reference
    `sagemaker_launcher`, `utils/launch.py:603-618`)."""
    spec = prepare_sagemaker_job(cfg, args.training_script, args.training_script_args, launch_env)
    if getattr(args, "dry_run", False):
        # dry run NEVER submits, with or without the SDK installed
        print(json.dumps(spec, indent=2))
        return 0
    os.environ.setdefault("AWS_DEFAULT_REGION", cfg.region)
    if cfg.profile:
        os.environ.setdefault("AWS_PROFILE", cfg.profile)
    try:
        from sagemaker.estimator import Estimator  # type: ignore
    except ImportError:
        print(json.dumps(spec, indent=2))
        print(
            "\nThe `sagemaker` SDK is not installed in this environment; the job "
            "spec above is what would be submitted. Install `sagemaker` (and AWS "
            "credentials) on a machine with network access, or pass --dry_run to "
            "only print the spec.",
        )
        return 1
    if not cfg.image_uri:
        raise ValueError(
            "SageMakerConfig.image_uri is required for submission — there is no "
            "default JAX container resolved automatically; point it at a JAX "
            "DLC or your own training image."
        )
    estimator = Estimator(**spec["estimator"])
    estimator.fit(inputs=spec.get("inputs"))
    print(f"Submitted SageMaker job: {estimator.latest_training_job.name}")
    return 0


def sagemaker_questionnaire(ask) -> SageMakerConfig:
    """Interactive SageMaker section (reference `commands/config/sagemaker.py`
    questionnaire, minus the boto3 IAM-role creation — roles are provided, not
    created, in a no-network environment)."""
    cfg = SageMakerConfig()
    cfg.region = ask("AWS region", cfg.region)
    cfg.profile = ask("AWS profile (empty: env credentials)", "") or None
    cfg.iam_role_name = ask("SageMaker execution role name/ARN", "")
    cfg.ec2_instance_type = ask("EC2 instance type", cfg.ec2_instance_type)
    cfg.num_machines = int(ask("Number of machines", str(cfg.num_machines)))
    cfg.image_uri = ask(
        "Training image URI (a JAX DLC or custom image; required to submit)", ""
    ) or None
    cfg.base_job_name = ask("Base job name", cfg.base_job_name)
    cfg.sagemaker_inputs_file = ask("SageMaker inputs file (empty: none)", "") or None
    cfg.sagemaker_metrics_file = ask("SageMaker metrics file (empty: none)", "") or None
    return cfg


def to_dict(cfg: SageMakerConfig) -> dict:
    return asdict(cfg)


def from_dict(data: dict | None) -> SageMakerConfig:
    data = data or {}
    known = {k: v for k, v in data.items() if k in SageMakerConfig.__dataclass_fields__}
    return SageMakerConfig(**known)
