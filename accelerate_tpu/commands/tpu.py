"""`accelerate-tpu tpu-config` — fan a command out to every worker of a TPU pod.

Capability parity: reference `commands/tpu.py` (gcloud ssh --worker=all fan-out).
Builds and (optionally) runs the gcloud command that starts `accelerate-tpu
launch` on every pod VM — the pod-level process boundary the single-process-per-
host model needs.
"""

from __future__ import annotations

import argparse
import subprocess


def build_gcloud_command(
    tpu_name: str,
    zone: str,
    command: str | None = None,
    training_script: str | None = None,
    install_accelerate: bool = False,
) -> list[str]:
    """The one gcloud `tpus tpu-vm ssh --worker=all` builder — shared by
    `tpu-config` and `launch --tpu_name` (explicit kwargs, so neither caller
    is coupled to the other's argparse surface)."""
    inner = command or "accelerate-tpu launch " + (training_script or "")
    cmd = [
        "gcloud", "compute", "tpus", "tpu-vm", "ssh", tpu_name,
        "--zone", zone,
        "--worker", "all",
        "--command", inner,
    ]
    if install_accelerate:
        cmd[-1] = f"pip install accelerate-tpu; {inner}"
    return cmd


def tpu_command(args: argparse.Namespace) -> None:
    cmd = build_gcloud_command(
        args.tpu_name, args.zone, command=args.command,
        training_script=args.training_script,
        install_accelerate=args.install_accelerate,
    )
    print("Running:", " ".join(cmd))
    if not args.dry_run:
        subprocess.run(cmd, check=True)


def add_parser(subparsers) -> None:
    p = subparsers.add_parser("tpu-config", help="run a command on every TPU pod worker")
    p.add_argument("--tpu_name", required=True)
    p.add_argument("--zone", required=True)
    p.add_argument("--command", default=None, help="full command to run on each worker")
    p.add_argument("--training_script", default=None)
    p.add_argument("--install_accelerate", action="store_true")
    p.add_argument("--dry_run", action="store_true")
    p.set_defaults(func=tpu_command)
