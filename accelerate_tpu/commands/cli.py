"""`accelerate-tpu` CLI root (reference `commands/accelerate_cli.py`):
subcommands config / env / launch / test / estimate-memory / merge-weights /
tpu-config."""

from __future__ import annotations

import argparse
import sys


def main() -> None:
    from . import config, env, estimate, launch, merge, test, tpu

    parser = argparse.ArgumentParser("accelerate-tpu", usage="accelerate-tpu <command> [<args>]")
    subparsers = parser.add_subparsers(dest="command", required=True)
    for mod in (config, env, launch, test, estimate, merge, tpu):
        mod.add_parser(subparsers)
    args = parser.parse_args()
    if not hasattr(args, "func"):
        parser.print_help()
        sys.exit(1)
    args.func(args)


if __name__ == "__main__":
    main()
