"""`accelerate-tpu env` — environment dump for bug reports (reference `commands/env.py`)."""

from __future__ import annotations

import argparse
import platform


def env_command(args: argparse.Namespace) -> None:
    import jax

    import accelerate_tpu
    from .config import default_config_file

    info = {
        "accelerate_tpu version": getattr(accelerate_tpu, "__version__", "dev"),
        "platform": platform.platform(),
        "python": platform.python_version(),
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "devices": str(jax.devices()),
        "process_count": jax.process_count(),
        "config file": str(default_config_file()),
    }
    try:
        import flax, optax  # noqa

        info["flax"] = flax.__version__
        info["optax"] = optax.__version__
    except ImportError:
        pass
    print("\n".join(f"- {k}: {v}" for k, v in info.items()))


def add_parser(subparsers) -> None:
    p = subparsers.add_parser("env", help="print environment info")
    p.set_defaults(func=env_command)
