"""`accelerate-tpu config` — interactive questionnaire + YAML config file.

Capability parity: reference `commands/config/` (cluster questionnaire,
config_args.py, default.py write_basic_config). The YAML holds the launcher
defaults; precedence everywhere is CLI flag > ACCELERATE_TPU_* env > config file
(reference §5 config planes).
"""

from __future__ import annotations

import argparse
import os
from dataclasses import asdict, dataclass, field
from pathlib import Path

import yaml

from ..utils.constants import DEFAULT_CONFIG_DIR_ENV, DEFAULT_CONFIG_NAME


def default_config_file() -> Path:
    base = os.environ.get(DEFAULT_CONFIG_DIR_ENV)
    if base is None:
        base = os.path.join(os.path.expanduser("~"), ".cache", "accelerate_tpu")
    return Path(base) / DEFAULT_CONFIG_NAME


CONFIG_VERSION = 1

# Older / HF-accelerate config files load transparently: key renames applied
# on read (reference `config_utils.py` config versioning + `config update`).
_LEGACY_KEYS = {
    "num_machines": "num_processes",
    "machine_rank": "process_id",
    "debug_mode": "debug",
}


def _migrate_legacy(data: dict) -> dict:
    out = dict(data)
    # HF configs carry BOTH num_machines (hosts) and num_processes (total GPUs);
    # here a "process" is a host, so num_machines wins unconditionally
    if "num_machines" in out:
        out.pop("num_processes", None)
    for old, new in _LEGACY_KEYS.items():
        if old in out and new not in out:
            out[new] = out.pop(old)
    # reference-style coordinator: main_process_ip + main_process_port
    ip, port = out.pop("main_process_ip", None), out.pop("main_process_port", None)
    if ip and "coordinator_address" not in out:
        out["coordinator_address"] = f"{ip}:{port or 29500}"
    # reference distributed_type hints map onto mesh degrees
    dist = str(out.pop("distributed_type", "")).upper()
    if dist == "FSDP" and "fsdp_size" not in out:
        out["fsdp_size"] = -1
        out.setdefault("data_parallel_size", 1)
    if dist == "MEGATRON_LM":
        mega = out.pop("megatron_lm_config", {}) or {}
        out.setdefault("tensor_size", int(mega.get("megatron_lm_tp_degree", 1)))
        out.setdefault("stage_size", int(mega.get("megatron_lm_pp_degree", 1)))
    if str(out.get("mixed_precision", "")).lower() in ("", "none"):
        out["mixed_precision"] = "no"
    return out


@dataclass
class LaunchConfig:
    """Everything the launcher needs to start a run (reference ClusterConfig)."""

    compute_environment: str = "LOCAL_MACHINE"  # or TPU_POD / AMAZON_SAGEMAKER
    num_processes: int = 1  # hosts
    process_id: int = 0
    coordinator_address: str | None = None  # host0:port for jax.distributed
    mixed_precision: str = "no"
    data_parallel_size: int = -1
    fsdp_size: int = 1
    tensor_size: int = 1
    sequence_size: int = 1
    stage_size: int = 1
    gradient_accumulation_steps: int = 1
    debug: bool = False
    # AMAZON_SAGEMAKER section (reference SageMakerConfig; see commands/sagemaker.py)
    sagemaker: dict | None = None

    def to_yaml(self, path: Path | None = None) -> Path:
        path = path or default_config_file()
        path.parent.mkdir(parents=True, exist_ok=True)
        with open(path, "w") as f:
            yaml.safe_dump({"config_version": CONFIG_VERSION, **asdict(self)}, f, sort_keys=False)
        return path

    @classmethod
    def from_yaml(cls, path: Path | None = None) -> "LaunchConfig":
        path = path or default_config_file()
        if not Path(path).exists():
            return cls()
        with open(path) as f:
            data = yaml.safe_load(f) or {}
        if data.get("config_version", 0) < CONFIG_VERSION:
            data = _migrate_legacy(data)
        known = {k: v for k, v in data.items() if k in cls.__dataclass_fields__}
        return cls(**known)


def write_basic_config(mixed_precision: str = "no", save_location: str | None = None) -> Path:
    """One-call default config (reference `commands/config/default.py:write_basic_config`)."""
    cfg = LaunchConfig(mixed_precision=mixed_precision)
    return cfg.to_yaml(Path(save_location) if save_location else None)


def _ask(prompt: str, default: str, choices: list[str] | None = None) -> str:
    if choices:
        from .menu import choose

        return choose(prompt, choices, default)
    raw = input(f"{prompt} ({default}): ").strip()
    return raw or default


def config_command(args: argparse.Namespace) -> None:
    if getattr(args, "default", False):
        path = write_basic_config(mixed_precision=getattr(args, "mixed_precision", "no"))
        print(f"Wrote default config to {path}")
        return
    print("accelerate-tpu configuration")
    cfg = LaunchConfig()
    cfg.compute_environment = _ask(
        "Compute environment", "LOCAL_MACHINE",
        ["LOCAL_MACHINE", "TPU_POD", "AMAZON_SAGEMAKER"],
    )
    if cfg.compute_environment == "TPU_POD":
        cfg.num_processes = int(_ask("Number of hosts (TPU workers)", "1"))
        cfg.coordinator_address = _ask("Coordinator address (host0:port)", "") or None
    elif cfg.compute_environment == "AMAZON_SAGEMAKER":
        from .sagemaker import sagemaker_questionnaire, to_dict

        cfg.sagemaker = to_dict(sagemaker_questionnaire(_ask))
        cfg.num_processes = int(cfg.sagemaker.get("num_machines", 1))
    cfg.mixed_precision = _ask("Mixed precision", "bf16", ["no", "bf16", "fp16", "fp8"])
    cfg.gradient_accumulation_steps = int(_ask("Gradient accumulation steps", "1"))
    cfg.fsdp_size = int(_ask("FSDP (parameter-shard) degree", "1"))
    cfg.tensor_size = int(_ask("Tensor-parallel degree", "1"))
    cfg.sequence_size = int(_ask("Sequence-parallel (ring) degree", "1"))
    cfg.stage_size = int(_ask("Pipeline stages", "1"))
    path = cfg.to_yaml(Path(args.config_file) if getattr(args, "config_file", None) else None)
    print(f"Configuration saved to {path}")


def update_command(args: argparse.Namespace) -> None:
    """Rewrite an old (or HF-accelerate) config in the current schema
    (reference `accelerate config update`)."""
    src = Path(args.config_file) if args.config_file else default_config_file()
    cfg = LaunchConfig.from_yaml(src)
    path = cfg.to_yaml(src)
    print(f"Rewrote {path} at config_version={CONFIG_VERSION}")


def add_parser(subparsers) -> None:
    p = subparsers.add_parser("config", help="create the launch configuration interactively")
    p.add_argument("--config_file", default=None, help="where to save the YAML")
    p.add_argument("--default", action="store_true", help="write defaults without prompting")
    p.add_argument("--mixed_precision", default="no")
    p.set_defaults(func=config_command)
    u = subparsers.add_parser("config-update", help="migrate a config file to the current schema")
    u.add_argument("--config_file", default=None)
    u.set_defaults(func=update_command)
