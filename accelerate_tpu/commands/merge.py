"""`accelerate-tpu merge-weights` — consolidate a sharded checkpoint.

Capability parity: reference `commands/merge.py` over `merge_fsdp_weights`
(`utils/fsdp_utils.py:274`): turn a distributed (orbax/tensorstore) checkpoint
directory into a single-file consolidated export.
"""

from __future__ import annotations

import argparse
from pathlib import Path


def merge_command(args: argparse.Namespace) -> None:
    from ..checkpointing import _restore_pytree_host, save_model_weights

    tree = _restore_pytree_host(Path(args.checkpoint_dir))
    written = save_model_weights(tree, args.output_dir)
    names = ", ".join(Path(f).name for f in written) if isinstance(written, (list, tuple)) else Path(str(written)).name
    print(f"Merged {args.checkpoint_dir} -> {args.output_dir} ({names})")


def add_parser(subparsers) -> None:
    p = subparsers.add_parser("merge-weights", help="merge a sharded checkpoint into one file")
    p.add_argument("checkpoint_dir", help="orbax checkpoint directory (e.g. .../model_0)")
    p.add_argument("output_dir")
    p.set_defaults(func=merge_command)
