"""`accelerate-tpu merge-weights` — consolidate a sharded checkpoint.

Capability parity: reference `commands/merge.py` over `merge_fsdp_weights`
(`utils/fsdp_utils.py:274`): turn a distributed (orbax/tensorstore) checkpoint
directory into a single-file consolidated export.
"""

from __future__ import annotations

import argparse
from pathlib import Path


def merge_command(args: argparse.Namespace) -> None:
    from ..checkpointing import _restore_pytree, save_model_weights

    tree = _restore_pytree(Path(args.checkpoint_dir))
    save_model_weights(tree, args.output_dir)
    print(f"Merged {args.checkpoint_dir} -> {Path(args.output_dir) / 'model.msgpack'}")


def add_parser(subparsers) -> None:
    p = subparsers.add_parser("merge-weights", help="merge a sharded checkpoint into one file")
    p.add_argument("checkpoint_dir", help="orbax checkpoint directory (e.g. .../model_0)")
    p.add_argument("output_dir")
    p.set_defaults(func=merge_command)
