"""`accelerate-tpu estimate-memory` — model memory estimation without weights.

Capability parity: reference `commands/estimate.py` (meta-device model sizing via
`calculate_maximum_sizes`). TPU-native: sizes come from `jax.eval_shape` over the
model init (zero FLOPs, zero memory) for in-repo models, or from a HuggingFace
config's parameter arithmetic for Hub names when transformers is installed.
"""

from __future__ import annotations

import argparse
import math

DTYPE_BYTES = {"float32": 4, "bf16": 2, "bfloat16": 2, "fp16": 2, "float16": 2, "int8": 1, "fp8": 1}


def _fmt(nbytes: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if nbytes < 1024:
            return f"{nbytes:.2f} {unit}"
        nbytes /= 1024
    return f"{nbytes:.2f} PB"


def estimate_parameters(model_name: str) -> int:
    """Parameter count for an in-repo model spec ('gpt2', 'gpt2-medium', ...) or a
    HF Hub model (config-only download)."""
    sizes = {"gpt2": "small", "gpt2-small": "small", "gpt2-medium": "medium", "gpt2-large": "large"}
    if model_name in sizes:
        import jax

        from ..models.gpt2 import GPT2Config, GPT2LMHead

        cfg = getattr(GPT2Config, sizes[model_name])()
        module = GPT2LMHead(cfg)
        shapes = jax.eval_shape(
            lambda: module.init(jax.random.key(0), jax.numpy.zeros((1, 8), dtype=jax.numpy.int32))
        )
        return sum(int(math.prod(s.shape)) for s in jax.tree.leaves(shapes))
    try:
        from transformers import AutoConfig

        cfg = AutoConfig.from_pretrained(model_name)
        from transformers import AutoModel

        import torch

        with torch.device("meta"):
            model = AutoModel.from_config(cfg)
        return sum(p.numel() for p in model.parameters())
    except Exception as e:
        raise ValueError(
            f"Unknown model {model_name!r}: not an in-repo spec and transformers "
            f"meta-load failed ({e})"
        )


def estimate_command(args: argparse.Namespace) -> None:
    n = estimate_parameters(args.model_name)
    # TPU-native extension over the reference tool: parameter-state sharding.
    # fsdp shards params+grads+optimizer state; tensor shards params+grads
    # (Megatron column/row splits); data replicates. Per-chip bytes divide by
    # the sharding degree — the reference's per-GPU table has no analogue
    # because torch DDP replicates everything.
    shard = max(1, args.fsdp) * max(1, args.tensor)
    rows = []
    for dtype in args.dtypes:
        b = DTYPE_BYTES[dtype]
        params = n * b
        # training ~= params + grads + adam (2x fp32 moments) + master fp32 params
        train = params + n * b + 2 * n * 4 + (n * 4 if b < 4 else 0)
        rows.append((dtype, _fmt(params), _fmt(train),
                     _fmt(params / shard), _fmt(train / shard)))
    w = max(len(r[1]) for r in rows) + 2
    print(f"Model: {args.model_name} — {n:,} parameters")
    header = f"{'dtype':8} {'inference':>{w}} {'training (adam)':>{w+8}}"
    if shard > 1:
        header += f" {'per-chip inf':>{w+4}} {'per-chip train':>{w+6}}"
    print(header)
    for dtype, inf, train, pinf, ptrain in rows:
        line = f"{dtype:8} {inf:>{w}} {train:>{w+8}}"
        if shard > 1:
            line += f" {pinf:>{w+4}} {ptrain:>{w+6}}"
        print(line)
    if shard > 1:
        print(f"(sharded over fsdp={args.fsdp} x tensor={args.tensor} = {shard} chips; "
              "activations/KV cache not included)")


def add_parser(subparsers) -> None:
    p = subparsers.add_parser("estimate-memory", help="estimate model memory usage")
    p.add_argument("model_name")
    p.add_argument("--dtypes", nargs="+", default=["float32", "bf16"], choices=list(DTYPE_BYTES))
    p.add_argument("--fsdp", type=int, default=1,
                   help="fsdp-axis degree: divide param/grad/optimizer bytes per chip")
    p.add_argument("--tensor", type=int, default=1,
                   help="tensor-axis degree: divide param/grad bytes per chip")
    p.set_defaults(func=estimate_command)
