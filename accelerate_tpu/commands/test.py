"""`accelerate-tpu test` — sanity-check the install by running the bundled
end-to-end script through the launcher (reference `commands/test.py`)."""

from __future__ import annotations

import argparse
import os


def test_command(args: argparse.Namespace) -> None:
    from ..test_utils import test_script

    from .config import LaunchConfig
    from .launch import launch_env

    cfg = LaunchConfig.from_yaml()
    os.environ.update(launch_env(cfg))
    test_script.main()
    print("Test is a success! You are ready for your distributed training!")


def add_parser(subparsers) -> None:
    p = subparsers.add_parser("test", help="run the bundled end-to-end sanity script")
    p.set_defaults(func=test_command)
