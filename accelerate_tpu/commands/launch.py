"""`accelerate-tpu launch` — start training across any topology.

Capability parity: reference `commands/launch.py` (1178 LoC) + `utils/launch.py`.
The reference must spawn one process per *device* (torchelastic, xmp.spawn, pdsh);
under JAX SPMD there is exactly **one process per host** and all local chips are
already visible, so launching collapses to: resolve config -> export the
launcher<->library env contract -> run the script. Modes:

  - single host ("LOCAL_MACHINE"): exec the script in-process.
  - TPU pod ("TPU_POD"): each host runs the same command (GKE/gcloud fan-out is
    `tpu-config`'s job, reference `commands/tpu.py`); env carries
    JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID, or everything
    autodetects from TPU metadata when unset.
  - `--debug_cpu N`: fork N local processes, each a JAX "host" on the CPU
    platform with a localhost coordinator — the reference's `debug_launcher`
    (2-proc gloo CPU) capability, but exercising the *real* multi-process
    collective path over gRPC.

Env contract (consumed by `state.py` / `Accelerator`): ACCELERATE_TPU_MIXED_PRECISION,
ACCELERATE_TPU_GRAD_ACCUM_STEPS, ACCELERATE_TPU_PARALLELISM (dp,fsdp,stage,seq,tp),
ACCELERATE_TPU_DEBUG_MODE.
"""

from __future__ import annotations

import argparse
import os
import runpy
import subprocess
import sys
from pathlib import Path

from .config import LaunchConfig, default_config_file


def launch_env(cfg: LaunchConfig) -> dict[str, str]:
    env: dict[str, str] = {
        "ACCELERATE_TPU_MIXED_PRECISION": cfg.mixed_precision,
        "ACCELERATE_TPU_GRAD_ACCUM_STEPS": str(cfg.gradient_accumulation_steps),
        "ACCELERATE_TPU_PARALLELISM": ",".join(
            str(x)
            for x in (
                cfg.data_parallel_size,
                cfg.fsdp_size,
                cfg.stage_size,
                cfg.sequence_size,
                cfg.tensor_size,
            )
        ),
    }
    if cfg.debug:
        env["ACCELERATE_TPU_DEBUG_MODE"] = "1"
    if cfg.num_processes > 1:
        env["ACCELERATE_TPU_NUM_PROCESSES"] = str(cfg.num_processes)
        env["JAX_NUM_PROCESSES"] = str(cfg.num_processes)
        env["JAX_PROCESS_ID"] = str(cfg.process_id)
        if cfg.coordinator_address:
            env["JAX_COORDINATOR_ADDRESS"] = cfg.coordinator_address
    return env


def _run_script(script: str, script_args: list[str], module: bool) -> None:
    sys.argv = [script] + script_args
    if module:
        runpy.run_module(script, run_name="__main__")
    else:
        runpy.run_path(script, run_name="__main__")


def _child_command(script: str, script_args: list[str], module: bool) -> list[str]:
    """The argv for a child process running the user script — honoring
    ``--module`` the same way the in-process path does (reference
    `utils/launch.py` builds `[sys.executable, "-m", ...]` likewise)."""
    if module:
        return [sys.executable, "-m", script, *script_args]
    return [sys.executable, script, *script_args]


def _debug_cpu_launch(
    n: int,
    script: str,
    script_args: list[str],
    base_env: dict[str, str],
    module: bool = False,
    max_restarts: int = 0,
    monitor_interval: float = 0.5,
    devices_per_process: int = 1,
) -> int:
    """Fork n local JAX 'hosts' over a localhost coordinator (CPU platform).

    With ``max_restarts`` this is the cross-host elastic tier (the torchelastic
    rendezvous role, reference `commands/launch.py:793`): when one host dies,
    its peers crash out of their collectives, every host's supervisor restarts
    its child, and the new generation re-forms at the SAME coordinator address
    — jax.distributed's barrier is the rendezvous. Each generation reads
    ``ACCELERATE_TPU_RESTART_COUNT`` and resumes from the latest checkpoint.
    ``devices_per_process`` > 1 gives each host that many virtual chips — a
    pod-slice topology (N hosts × M chips) without hardware.
    """
    import socket
    import time

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    def _spawn(i: int, restarts: int) -> subprocess.Popen:
        env = dict(os.environ)
        env.update(base_env)
        env.update(
            {
                "JAX_PLATFORMS": "cpu",
                "PALLAS_AXON_POOL_IPS": "",
                "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
                "JAX_NUM_PROCESSES": str(n),
                "JAX_PROCESS_ID": str(i),
                "ACCELERATE_TPU_NUM_PROCESSES": str(n),
                "ACCELERATE_TPU_RESTART_COUNT": str(restarts),
            }
        )
        if devices_per_process > 1:
            from ..launchers import set_host_device_count_flag

            set_host_device_count_flag(env, devices_per_process)
        return subprocess.Popen(_child_command(script, script_args, module), env=env)

    restarts = 0
    procs = [_spawn(i, restarts) for i in range(n)]
    if max_restarts <= 0:
        rc = 0
        for p in procs:
            rc = p.wait() or rc
        return rc
    while True:
        rcs = [p.poll() for p in procs]
        if all(rc == 0 for rc in rcs):
            return 0
        if any(rc is not None and rc != 0 for rc in rcs):
            if restarts >= max_restarts:
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                for p in procs:  # same SIGTERM->SIGKILL escalation as restarts
                    try:
                        p.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        p.kill()
                        p.wait()
                return next(rc for rc in rcs if rc)
            # one host failed: tear down the generation, restart ALL hosts so
            # the new generation rendezvouses together (elastic semantics)
            restarts += 1
            print(
                f"[accelerate-tpu launch] generation failed (exit codes {rcs}); "
                f"restart {restarts}/{max_restarts}.",
                file=sys.stderr,
            )
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    # torchelastic-style escalation: SIGTERM grace, then SIGKILL
                    p.kill()
                    p.wait()
            procs = [_spawn(i, restarts) for i in range(n)]
        time.sleep(monitor_interval)


def _supervised_launch(
    script: str,
    script_args: list[str],
    base_env: dict[str, str],
    max_restarts: int,
    monitor_interval: float,
    module: bool = False,
) -> int:
    """Failure-detecting supervisor: run the script as a child process and
    restart it on nonzero exit, up to ``max_restarts`` times.

    The reference delegates this to torchelastic (`torch.distributed.run`,
    reference `commands/launch.py:793`; `notebook_launcher` max_restarts /
    monitor_interval, `launchers.py:40-60`). Under one-process-per-host SPMD the
    equivalent is a per-host supervisor: the restarted process re-runs
    `jax.distributed.initialize` and resumes from the latest checkpoint
    (`Accelerator.load_state` — the by_feature/checkpointing.py pattern).
    ``ACCELERATE_TPU_RESTART_COUNT`` tells the script which attempt it is on.
    """
    import time

    restarts = 0
    while True:
        env = dict(os.environ)
        env.update(base_env)
        env["ACCELERATE_TPU_RESTART_COUNT"] = str(restarts)
        proc = subprocess.Popen(_child_command(script, script_args, module), env=env)
        while proc.poll() is None:
            time.sleep(monitor_interval)
        rc = proc.returncode
        if rc == 0:
            return 0
        if restarts >= max_restarts:
            print(
                f"[accelerate-tpu launch] script failed (exit {rc}) after "
                f"{restarts} restart(s); giving up.",
                file=sys.stderr,
            )
            return rc
        restarts += 1
        print(
            f"[accelerate-tpu launch] script failed (exit {rc}); "
            f"restart {restarts}/{max_restarts}.",
            file=sys.stderr,
        )


def _render_env_prefix(env: dict[str, str]) -> str:
    """Render an inline `K=V K=V ...` shell env prefix (one quoting rule for
    every pod mode)."""
    import shlex

    return " ".join(f"{k}={shlex.quote(v)}" for k, v in sorted(env.items()))


def _render_invocation(
    python_executable: str, script: str, script_args: list[str], module: bool
) -> str:
    import shlex

    invoke = f"{python_executable} {'-m ' if module else ''}{shlex.quote(script)}"
    if script_args:
        invoke += " " + " ".join(shlex.quote(a) for a in script_args)
    return invoke


def build_pod_worker_commands(
    workers: list[str],
    script: str,
    script_args: list[str],
    base_env: dict[str, str],
    coordinator_port: int = 8476,
    module: bool = False,
    ssh_user: str | None = None,
    python_executable: str = "python",
) -> list[tuple[str, str, str]]:
    """Build the (ssh_target, remote_command) pair for every pod worker.

    Pure command construction (testable without SSH): worker i gets the full
    launcher<->library env contract inline — JAX_COORDINATOR_ADDRESS pointing
    at worker 0, JAX_NUM_PROCESSES, its JAX_PROCESS_ID — followed by the
    script invocation. Returns [(worker, ssh_target, remote_command), ...].
    Reference role: the xla_dist SSH fan-out (`commands/launch.py:887-943`)
    and the PDSH/hostfile multi-node runner (`:803-853`).
    """
    import shlex

    n = len(workers)
    coordinator = f"{workers[0]}:{coordinator_port}"
    out: list[tuple[str, str, str]] = []
    for i, worker in enumerate(workers):
        env = dict(base_env)
        env.update(
            {
                "JAX_COORDINATOR_ADDRESS": coordinator,
                "JAX_NUM_PROCESSES": str(n),
                "JAX_PROCESS_ID": str(i),
                "ACCELERATE_TPU_NUM_PROCESSES": str(n),
            }
        )
        target = f"{ssh_user}@{worker}" if ssh_user else worker
        out.append((
            worker,
            target,
            f"{_render_env_prefix(env)} "
            f"{_render_invocation(python_executable, script, script_args, module)}",
        ))
    return out


def _pod_ssh_launch(
    workers: list[str],
    script: str,
    script_args: list[str],
    base_env: dict[str, str],
    coordinator_port: int,
    module: bool = False,
    ssh_user: str | None = None,
    ssh_executable: str = "ssh",
    python_executable: str = "python",
) -> int:
    """SSH-fan the per-host launch to every worker and wait for all of them.

    One `ssh worker '<env contract> python script.py ...'` per host, started
    concurrently; the first worker hosts the jax.distributed coordinator. A
    nonzero exit anywhere is the job's exit (the peers crash out of their
    collectives, exactly like a failed NCCL rank). ``ssh_executable`` is
    swappable so the fan-out path itself is rehearsable without real SSH
    (`--ssh_executable ./local_shim.sh` in tests; reference rehearses its
    PDSH runner the same way).
    """
    cmds = build_pod_worker_commands(
        workers, script, script_args, base_env,
        coordinator_port=coordinator_port, module=module, ssh_user=ssh_user,
        python_executable=python_executable,
    )
    procs = []
    for worker, target, remote in cmds:
        print(f"[accelerate-tpu launch] {worker}: {remote}", file=sys.stderr)
        procs.append(subprocess.Popen([ssh_executable, target, remote]))
    rc = 0
    for p in procs:
        rc = p.wait() or rc
    return rc


def _gcloud_pod_launch(args: argparse.Namespace, cfg: LaunchConfig) -> int:
    """Single-command Cloud TPU pod bringup: gcloud ssh --worker=all runs the
    same `accelerate-tpu launch` on every pod VM (reference `tpu_pod_launcher`
    role, `commands/launch.py:887-943`, minus xla_dist).

    The resolved run plan travels as EXPLICIT inner-launch flags, not env:
    the inner launch recomputes its env from its own flags (flags > env >
    config), so an env prefix would be clobbered. Crucially, NO
    JAX_PROCESS_ID/JAX_COORDINATOR_ADDRESS is forwarded — every VM must
    autodetect its own identity from the TPU metadata (forwarding the caller's
    process id 0 to all workers would collide the rendezvous)."""
    import shlex

    inner_flags = [
        "--mixed_precision", cfg.mixed_precision,
        "--gradient_accumulation_steps", str(cfg.gradient_accumulation_steps),
        "--data_parallel_size", str(cfg.data_parallel_size),
        "--fsdp_size", str(cfg.fsdp_size),
        "--tensor_size", str(cfg.tensor_size),
        "--sequence_size", str(cfg.sequence_size),
        "--stage_size", str(cfg.stage_size),
    ]
    if args.module:
        inner_flags.append("--module")
    if args.compilation_cache_dir:
        inner_flags += ["--compilation_cache_dir", args.compilation_cache_dir]
    inner = (
        "accelerate-tpu launch "
        + " ".join(shlex.quote(f) for f in inner_flags)
        + f" {shlex.quote(args.training_script)}"
    )
    if args.training_script_args:
        inner += " " + " ".join(shlex.quote(a) for a in args.training_script_args)
    # one gcloud-invocation builder for both surfaces (tpu-config + launch)
    from .tpu import build_gcloud_command

    cmd = build_gcloud_command(args.tpu_name, args.zone, command=inner)
    print("[accelerate-tpu launch] " + " ".join(cmd), file=sys.stderr)
    return subprocess.run(cmd).returncode


def launch_command(args: argparse.Namespace) -> None:
    cfg = LaunchConfig.from_yaml(Path(args.config_file) if args.config_file else None)
    # CLI overrides (flag > env > config file)
    for attr in (
        "num_processes",
        "process_id",
        "coordinator_address",
        "mixed_precision",
        "gradient_accumulation_steps",
        "data_parallel_size",
        "fsdp_size",
        "tensor_size",
        "sequence_size",
        "stage_size",
    ):
        value = getattr(args, attr, None)
        if value is not None:
            setattr(cfg, attr, value)
    if args.debug:
        cfg.debug = True
    if args.main_process_ip:
        cfg.coordinator_address = (
            f"{args.main_process_ip}:{args.main_process_port or 8476}"
        )

    env = launch_env(cfg)
    if args.compilation_cache_dir:
        env["JAX_COMPILATION_CACHE_DIR"] = args.compilation_cache_dir
    # explicit pod flags beat a saved AMAZON_SAGEMAKER compute_environment;
    # --sagemaker combined with a pod flag is a contradiction, not a precedence
    if args.hostfile:
        if args.workers:
            raise SystemExit("--workers and --hostfile are mutually exclusive")
        # DeepSpeed hostfile shape: "hostname slots=N" per line; SPMD runs one
        # process per host so the slot count is informational only
        hosts = []
        with open(args.hostfile) as f:
            for line in f:
                line = line.strip()
                if line and not line.startswith("#"):
                    hosts.append(line.split()[0])
        if not hosts:
            raise SystemExit(f"hostfile {args.hostfile} contains no hosts")
        args.workers = ",".join(hosts)
    if args.sagemaker and (args.workers or args.tpu_name):
        raise SystemExit("--sagemaker and --workers/--tpu_name are mutually exclusive")
    if args.sagemaker or (
        cfg.compute_environment == "AMAZON_SAGEMAKER"
        and not (args.workers or args.tpu_name)
    ):
        from .sagemaker import from_dict, sagemaker_launcher

        sys.exit(sagemaker_launcher(from_dict(cfg.sagemaker), args, env))
    if args.workers and args.tpu_name:
        raise SystemExit("--workers and --tpu_name are mutually exclusive pod modes")
    if args.workers:
        workers = [w.strip() for w in args.workers.split(",") if w.strip()]
        rc = _pod_ssh_launch(
            workers, args.training_script, args.training_script_args, env,
            coordinator_port=args.coordinator_port,
            module=args.module,
            ssh_user=args.ssh_user,
            ssh_executable=args.ssh_executable,
            python_executable=args.python_executable,
        )
        sys.exit(rc)
    if args.tpu_name:
        if not args.zone:
            raise SystemExit("--tpu_name requires --zone")
        sys.exit(_gcloud_pod_launch(args, cfg))
    if args.debug_cpu:
        rc = _debug_cpu_launch(
            args.debug_cpu, args.training_script, args.training_script_args, env,
            module=args.module,
            max_restarts=args.max_restarts,
            monitor_interval=args.monitor_interval,
            devices_per_process=args.devices_per_process,
        )
        sys.exit(rc)
    if args.max_restarts:
        rc = _supervised_launch(
            args.training_script,
            args.training_script_args,
            env,
            max_restarts=args.max_restarts,
            monitor_interval=args.monitor_interval,
            module=args.module,
        )
        sys.exit(rc)
    os.environ.update(env)
    _run_script(args.training_script, args.training_script_args, module=args.module)


def add_parser(subparsers) -> None:
    p = subparsers.add_parser("launch", help="launch a training script")
    p.add_argument("--config_file", default=None)
    p.add_argument("--num_processes", "--num_machines", type=int, default=None,
                   dest="num_processes",
                   help="number of hosts (alias --num_machines: one process "
                        "per host under SPMD, so machines == processes)")
    p.add_argument("--process_id", "--machine_rank", type=int, default=None,
                   dest="process_id", help="this host's index (alias --machine_rank)")
    p.add_argument("--coordinator_address", default=None, help="host0:port")
    p.add_argument("--main_process_ip", default=None,
                   help="coordinator host (reference alias; combined with "
                        "--main_process_port into the coordinator address)")
    p.add_argument("--main_process_port", type=int, default=None,
                   help="coordinator port for --main_process_ip")
    p.add_argument("--compilation_cache_dir", default=None,
                   help="persistent XLA compilation cache directory "
                        "(JAX_COMPILATION_CACHE_DIR; the torch.compile "
                        "cache-dir analogue)")
    p.add_argument("--mixed_precision", default=None, choices=["no", "bf16", "fp16", "fp8"])
    p.add_argument("--gradient_accumulation_steps", type=int, default=None)
    p.add_argument("--data_parallel_size", "--dp", type=int, default=None, dest="data_parallel_size")
    p.add_argument("--fsdp_size", "--fsdp", type=int, default=None, dest="fsdp_size")
    p.add_argument("--tensor_size", "--tp", type=int, default=None, dest="tensor_size")
    p.add_argument("--sequence_size", "--sp", type=int, default=None, dest="sequence_size")
    p.add_argument("--stage_size", "--pp", type=int, default=None, dest="stage_size")
    p.add_argument("--debug", action="store_true", help="enable collective shape verification")
    p.add_argument("--debug_cpu", type=int, default=None, metavar="N",
                   help="fork N local CPU 'hosts' over a localhost coordinator")
    p.add_argument("--devices_per_process", type=int, default=1, metavar="M",
                   help="with --debug_cpu: give each host M virtual chips "
                        "(rehearse an N-host x M-chip pod slice without hardware)")
    p.add_argument("--max_restarts", type=int, default=0,
                   help="restart the script on failure up to N times "
                        "(torchelastic analogue; resume via load_state)")
    p.add_argument("--monitor_interval", type=float, default=1.0,
                   help="seconds between child liveness checks under --max_restarts")
    p.add_argument("--module", action="store_true", help="treat script as a python module")
    # -------- first-class multi-host pod bringup (reference launch.py:803-943)
    p.add_argument("--workers", default=None, metavar="HOST1,HOST2,...",
                   help="SSH-fan the launch to these hosts; worker 0 hosts the "
                        "jax.distributed coordinator")
    p.add_argument("--hostfile", default=None, metavar="PATH",
                   help="PDSH/DeepSpeed-style hostfile (one host per line, "
                        "'slots=N' annotations ignored — one process per host "
                        "under SPMD); alternative to --workers")
    p.add_argument("--coordinator_port", type=int, default=8476,
                   help="with --workers: port for the coordinator on worker 0")
    p.add_argument("--ssh_user", default=None, help="with --workers: ssh as this user")
    p.add_argument("--ssh_executable", default="ssh",
                   help="with --workers: ssh command to use (swap in a shim to "
                        "rehearse the fan-out locally)")
    p.add_argument("--python_executable", default="python",
                   help="with --workers: interpreter to run on each host")
    p.add_argument("--tpu_name", default=None,
                   help="Cloud TPU pod name: run this same launch on every pod "
                        "VM via gcloud ssh --worker=all")
    p.add_argument("--zone", default=None, help="GCE zone for --tpu_name")
    p.add_argument("--sagemaker", action="store_true",
                   help="submit the script as an Amazon SageMaker training job "
                        "(config's sagemaker section provides role/instances)")
    p.add_argument("--dry_run", action="store_true",
                   help="with --sagemaker: print the job spec without submitting")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    p.set_defaults(func=launch_command)


def main() -> None:
    parser = argparse.ArgumentParser("accelerate-tpu-launch")
    sub = parser.add_subparsers(dest="_cmd")
    add_parser(sub)
    argv = sys.argv[1:]
    if argv and argv[0] != "launch":
        argv = ["launch", *argv]
    args = parser.parse_args(argv)
    args.func(args)


if __name__ == "__main__":
    main()
