"""Arrow-key selection menu for the interactive ``config`` questionnaire.

Capability parity: reference `commands/menu/` (~450 LoC: cursor helpers, keymap,
selection widget used by `commands/config/cluster.py`). Re-founded compactly:
one class, raw-terminal key decoding inline, and an injectable key reader so
tests can script keystrokes without a pty. Falls back to a numbered prompt when
stdin isn't a TTY (CI, piped input) — the reference menu simply crashes there,
so the fallback is a deliberate hardening, not a parity break.
"""

from __future__ import annotations

import sys
from typing import Callable, Sequence

# decoded key events produced by _read_key
UP, DOWN, ENTER, INTERRUPT = "up", "down", "enter", "interrupt"


def _read_key(stream=None) -> str:
    """Block for one keypress on the controlling terminal and decode it to a
    key event or a literal character. Raw mode spans exactly one key so ^C
    remains deliverable between keys."""
    import termios
    import tty

    import select

    stream = stream or sys.stdin
    fd = stream.fileno()
    saved = termios.tcgetattr(fd)
    try:
        tty.setraw(fd)
        ch = stream.read(1)
        if ch == "\x1b":  # escape sequence: arrows are ESC [ A/B
            # a bare Esc press has no tail — poll so it doesn't block the menu
            # (and later keystrokes aren't eaten as a phantom escape tail)
            tail = ""
            while len(tail) < 2 and select.select([fd], [], [], 0.05)[0]:
                tail += stream.read(1)
            if tail in ("[A", "OA"):
                return UP
            if tail in ("[B", "OB"):
                return DOWN
            return ""
        if ch in ("\r", "\n"):
            return ENTER
        if ch == "\x03":
            return INTERRUPT
        return ch
    finally:
        termios.tcsetattr(fd, termios.TCSADRAIN, saved)


class SelectionMenu:
    """Interactive single-choice menu.

    Keys: ↑/↓ (also k/j) move, digits jump, Enter selects, ^C raises
    KeyboardInterrupt. ``run()`` returns the selected *index*.
    """

    def __init__(
        self,
        prompt: str,
        choices: Sequence[str],
        default_index: int = 0,
        key_reader: Callable[[], str] | None = None,
        out=None,
    ):
        if not choices:
            raise ValueError("SelectionMenu needs at least one choice")
        self.prompt = prompt
        self.choices = list(choices)
        self.index = min(max(default_index, 0), len(choices) - 1)
        self._read = key_reader or _read_key
        self._out = out or sys.stdout

    # one menu line, highlighted when selected
    def _line(self, i: int) -> str:
        marker = "●" if i == self.index else " "
        text = f" {marker} {i}. {self.choices[i]}"
        return f"\x1b[7m{text}\x1b[0m" if i == self.index else text

    def _render(self, first: bool) -> None:
        w = self._out
        if not first:
            w.write(f"\x1b[{len(self.choices)}A")  # cursor up to re-render in place
        for i in range(len(self.choices)):
            w.write("\x1b[2K" + self._line(i) + "\n")
        w.flush()

    def step(self, key: str) -> bool:
        """Apply one key event; True when the selection is finalized."""
        if key == ENTER:
            return True
        if key == INTERRUPT:
            raise KeyboardInterrupt
        if key in (UP, "k"):
            self.index = (self.index - 1) % len(self.choices)
        elif key in (DOWN, "j"):
            self.index = (self.index + 1) % len(self.choices)
        elif key.isdigit() and int(key) < len(self.choices):
            self.index = int(key)
        return False

    def run(self) -> int:
        self._out.write(self.prompt + " (arrows + Enter):\n")
        self._render(first=True)
        while True:
            done = self.step(self._read())
            if done:
                return self.index
            self._render(first=False)


def choose(
    prompt: str,
    choices: Sequence[str],
    default: str,
    key_reader: Callable[[], str] | None = None,
) -> str:
    """Menu when interactive, numbered-input fallback otherwise; returns the
    chosen *value*. The questionnaire's one entry point."""
    default_index = choices.index(default) if default in choices else 0
    interactive = key_reader is not None or (
        sys.stdin.isatty() and sys.stdout.isatty() and _termios_available()
    )
    if interactive:
        raw_mode_errors: tuple = (OSError, ValueError)
        if _termios_available():
            import termios

            raw_mode_errors += (termios.error,)  # subclasses Exception, not OSError
        try:
            idx = SelectionMenu(prompt, choices, default_index, key_reader=key_reader).run()
            return choices[idx]
        except raw_mode_errors:
            pass  # raw mode unavailable after all — fall through
    listing = ", ".join(f"{i}={c}" for i, c in enumerate(choices))
    raw = input(f"{prompt} [{listing}] ({default}): ").strip()
    if raw.isdigit() and int(raw) < len(choices):
        return choices[int(raw)]
    if raw in choices:
        return raw
    if raw:
        print(f"  invalid choice {raw!r}, using {default}")
    return default


def _termios_available() -> bool:
    try:
        import termios  # noqa: F401
        import tty  # noqa: F401

        return True
    except ImportError:
        return False
