"""Arrow-key selection menu for the interactive ``config`` questionnaire.

Capability parity: reference `commands/menu/` (~450 LoC: cursor helpers, keymap,
selection widget used by `commands/config/cluster.py`). Re-founded compactly:
one class, raw-terminal key decoding inline, and an injectable key reader so
tests can script keystrokes without a pty. Falls back to a numbered prompt when
stdin isn't a TTY (CI, piped input) — the reference menu simply crashes there,
so the fallback is a deliberate hardening, not a parity break.
"""

from __future__ import annotations

import sys
from typing import Callable, Sequence

# decoded key events produced by _read_key
UP, DOWN, ENTER, INTERRUPT = "up", "down", "enter", "interrupt"


def _read_key(stream=None) -> str:
    """Block for one keypress on the controlling terminal and decode it to a
    key event or a literal character. Raw mode spans exactly one key so ^C
    remains deliverable between keys. Bytes come via ``os.read`` on the fd —
    a buffered ``stream.read(1)`` would slurp the whole ESC sequence into the
    TextIOWrapper buffer, the select() poll would then miss the tail, and
    arrow navigation would silently die."""
    import os
    import select
    import termios
    import tty

    stream = stream or sys.stdin
    fd = stream.fileno()
    saved = termios.tcgetattr(fd)
    try:
        tty.setraw(fd)
        ch = os.read(fd, 1).decode(errors="replace")
        if ch == "\x1b":  # escape sequence: arrows are ESC [ A/B
            # a bare Esc press has no tail — poll so it doesn't block the menu
            # (and later keystrokes aren't eaten as a phantom escape tail)
            tail = b""
            while len(tail) < 2 and select.select([fd], [], [], 0.05)[0]:
                tail += os.read(fd, 1)
            if tail in (b"[A", b"OA"):
                return UP
            if tail in (b"[B", b"OB"):
                return DOWN
            return ""
        if ch in ("\r", "\n"):
            return ENTER
        if ch == "\x03":
            return INTERRUPT
        return ch
    finally:
        termios.tcsetattr(fd, termios.TCSADRAIN, saved)


class SelectionMenu:
    """Interactive single-choice menu.

    Keys: ↑/↓ (also k/j) move, digits jump, Enter selects, ^C raises
    KeyboardInterrupt. ``run()`` returns the selected *index*.
    """

    def __init__(
        self,
        prompt: str,
        choices: Sequence[str],
        default_index: int = 0,
        key_reader: Callable[[], str] | None = None,
        out=None,
    ):
        if not choices:
            raise ValueError("SelectionMenu needs at least one choice")
        self.prompt = prompt
        self.choices = list(choices)
        self.index = min(max(default_index, 0), len(choices) - 1)
        self._read = key_reader or _read_key
        self._out = out or sys.stdout

    # one menu line, highlighted when selected
    def _line(self, i: int) -> str:
        marker = "●" if i == self.index else " "
        text = f" {marker} {i}. {self.choices[i]}"
        return f"\x1b[7m{text}\x1b[0m" if i == self.index else text

    def _render(self, first: bool) -> None:
        w = self._out
        if not first:
            w.write(f"\x1b[{len(self.choices)}A")  # cursor up to re-render in place
        for i in range(len(self.choices)):
            w.write("\x1b[2K" + self._line(i) + "\n")
        w.flush()

    def step(self, key: str) -> bool:
        """Apply one key event; True when the selection is finalized."""
        if key == ENTER:
            return True
        if key == INTERRUPT:
            raise KeyboardInterrupt
        if key in (UP, "k"):
            self.index = (self.index - 1) % len(self.choices)
        elif key in (DOWN, "j"):
            self.index = (self.index + 1) % len(self.choices)
        elif key.isdigit() and int(key) < len(self.choices):
            self.index = int(key)
        return False

    def run(self) -> int:
        self._out.write(self.prompt + " (arrows + Enter):\n")
        self._render(first=True)
        while True:
            done = self.step(self._read())
            if done:
                return self.index
            self._render(first=False)


def choose(
    prompt: str,
    choices: Sequence[str],
    default: str,
    key_reader: Callable[[], str] | None = None,
) -> str:
    """Menu when interactive, numbered-input fallback otherwise; returns the
    chosen *value*. The questionnaire's one entry point."""
    default_index = choices.index(default) if default in choices else 0
    # Probe raw-mode availability up front instead of catching errors around
    # the whole menu run — a broad catch there would mask real bugs (e.g. a
    # key_reader raising ValueError) as a silent fallback.
    interactive = key_reader is not None or (
        sys.stdin.isatty() and sys.stdout.isatty() and _raw_mode_works()
    )
    if interactive:
        idx = SelectionMenu(prompt, choices, default_index, key_reader=key_reader).run()
        return choices[idx]
    listing = ", ".join(f"{i}={c}" for i, c in enumerate(choices))
    raw = input(f"{prompt} [{listing}] ({default}): ").strip()
    if raw.isdigit() and int(raw) < len(choices):
        return choices[int(raw)]
    if raw in choices:
        return raw
    if raw:
        print(f"  invalid choice {raw!r}, using {default}")
    return default


def _raw_mode_works() -> bool:
    """True when stdin's terminal actually supports raw mode — not just when
    termios imports. termios.error subclasses Exception (not OSError), and
    ValueError covers fileno() on detached streams."""
    try:
        import termios
        import tty  # noqa: F401
    except ImportError:
        return False
    try:
        termios.tcgetattr(sys.stdin.fileno())
        return True
    except (OSError, ValueError, termios.error):
        return False
