"""Experiment trackers.

Capability parity: reference `src/accelerate/tracking.py` (1023 LoC): a
`GeneralTracker` ABC with main-process gating and built-in integrations
(TensorBoard, WandB, CometML, Aim, MLflow, ClearML, DVCLive), selected by
`filter_trackers`. All logging calls are host-side and rank-gated — nothing here
touches the device path. A dependency-free `JSONLTracker` ("jsonl") is always
available so runs on bare TPU VMs still record metrics.
"""

from __future__ import annotations

import functools
import json
import math
import os
import time
from typing import Any, Callable

from .state import PartialState
from .utils import imports
from .utils.operations import listify

_AVAILABLE: dict[str, Callable[[], bool]] = {}


def on_main_process(function: Callable) -> Callable:
    """Gate a tracker method to the main process (reference `tracking.py:67`)."""

    @functools.wraps(function)
    def wrapper(self, *args, **kwargs):
        if PartialState().is_main_process:
            return function(self, *args, **kwargs)

    return wrapper


class GeneralTracker:
    """Tracker ABC (reference `tracking.py:91-161`). Subclasses set ``name``,
    ``requires_logging_directory`` and implement store_init_configuration/log."""

    name: str = "base"
    requires_logging_directory: bool = False
    main_process_only: bool = True

    def __init__(self, *args: Any, **kwargs: Any):
        pass

    @property
    def tracker(self) -> Any:
        return None

    def store_init_configuration(self, values: dict) -> None:
        raise NotImplementedError

    def log(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        raise NotImplementedError

    def log_images(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        """Log a dict of name -> image array (HWC or NHWC, float [0,1] or uint8)
        — reference `tracking.py:251/341/540/804` per-integration variants."""
        raise NotImplementedError(f"Tracker {self.name!r} does not support log_images")

    def log_table(
        self,
        table_name: str,
        columns: list[str] | None = None,
        data: list[list[Any]] | None = None,
        dataframe: Any = None,
        step: int | None = None,
        **kwargs: Any,
    ) -> None:
        """Log tabular data as ``columns`` + ``data`` rows or a dataframe —
        reference `tracking.py:360/822`."""
        raise NotImplementedError(f"Tracker {self.name!r} does not support log_table")

    def finish(self) -> None:
        pass


def _table_rows(columns, data, dataframe):
    """Normalize the (columns, data) / dataframe dual input to (columns, rows)."""
    if dataframe is not None:
        return list(map(str, dataframe.columns)), dataframe.values.tolist()
    if data is None:
        raise ValueError("log_table needs either `data` (+ optional `columns`) or `dataframe`")
    if columns is None:
        columns = [f"col_{i}" for i in range(len(data[0]))] if data else []
    return columns, data


def _image_to_uint8_hwc(img: Any):
    """Accept HW, HWC, or CHW-ish arrays in float [0,1] or uint8; return uint8 HWC."""
    import numpy as np

    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if arr.ndim != 3:
        raise ValueError(f"expected HW or HWC image, got shape {arr.shape}")
    if arr.shape[0] in (1, 3, 4) and arr.shape[2] not in (1, 3, 4):
        arr = np.moveaxis(arr, 0, -1)  # CHW -> HWC
    if arr.dtype != np.uint8:
        if np.issubdtype(arr.dtype, np.integer):
            # integer pixels are already 0-255 counts; squeezing them through
            # the float [0,1] path would saturate everything >= 1 to white
            arr = np.clip(arr, 0, 255).astype(np.uint8)
        else:
            arr = (np.clip(arr, 0.0, 1.0) * 255).astype(np.uint8)
    return arr


def _images_as_hwc_list(v: Any) -> list:
    """A single image (HW/HWC/CHW) or an NHWC batch -> list of uint8 HWC arrays."""
    import numpy as np

    arr = np.asarray(v)
    if arr.ndim == 4:
        return [_image_to_uint8_hwc(x) for x in arr]
    return [_image_to_uint8_hwc(arr)]


def _expand_image_keys(values: dict):
    """Flatten {name: image-or-batch} to (key, hwc) pairs, suffixing batch
    members with _<i> so every integration handles NHWC input uniformly."""
    for k, v in values.items():
        imgs = _images_as_hwc_list(v)
        if len(imgs) == 1:
            yield k, imgs[0]
        else:
            for i, img in enumerate(imgs):
                yield f"{k}_{i}", img


class JSONLTracker(GeneralTracker):
    """Always-available tracker writing one JSON object per log call."""

    name = "jsonl"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str | None = None):
        self.run_name = run_name
        logging_dir = logging_dir or "."
        os.makedirs(logging_dir, exist_ok=True)
        self.path = os.path.join(logging_dir, f"{run_name}.metrics.jsonl")
        self._fh = open(self.path, "a")

    @property
    def tracker(self) -> Any:
        return self._fh

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self._fh.write(json.dumps({"_config": values, "_ts": time.time()}) + "\n")
        self._fh.flush()

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        entry = dict(listify(values))
        entry["_step"] = step
        entry["_ts"] = time.time()
        # NaN/Inf serialize as null, never as the bare ``NaN`` literal
        # json.dumps would otherwise emit (valid Python, invalid JSON — it
        # breaks every strict reader downstream, serve_top included);
        # allow_nan=False makes a missed case an error instead of bad output
        entry = {
            k: (None if isinstance(v, float) and not math.isfinite(v) else v)
            for k, v in entry.items()
        }
        self._fh.write(json.dumps(entry, allow_nan=False) + "\n")
        self._fh.flush()

    @on_main_process
    def log_images(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        """Dependency-free image logging: pixels land as .npy files next to the
        metrics file; the jsonl row records their paths."""
        import numpy as np

        media_dir = os.path.join(os.path.dirname(self.path), f"{self.run_name}.media")
        os.makedirs(media_dir, exist_ok=True)
        paths = {}
        for k, img in _expand_image_keys(values):
            safe = k.replace("/", "_")
            # per-tracker sequence number: sanitized keys can collide ("a/b"
            # and "a_b") and step=None repeats — the counter keeps every .npy
            # unique so earlier jsonl rows never point at overwritten pixels
            seq = self._media_seq = getattr(self, "_media_seq", 0) + 1
            out = os.path.join(
                media_dir, f"{safe}_{step if step is not None else 'x'}_{seq}.npy"
            )
            np.save(out, img)
            paths[k] = out
        self.log({"_images": paths}, step=step)

    @on_main_process
    def log_table(self, table_name, columns=None, data=None, dataframe=None, step=None, **kwargs):
        columns, rows = _table_rows(columns, data, dataframe)
        self.log({"_table": {"name": table_name, "columns": columns,
                             "rows": [[str(c) for c in r] for r in rows]}}, step=step)

    @on_main_process
    def finish(self) -> None:
        self._fh.close()


class TensorBoardTracker(GeneralTracker):
    name = "tensorboard"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str | None = None, **kwargs: Any):
        try:
            from torch.utils import tensorboard
        except ImportError:
            import tensorboardX as tensorboard
        self.run_name = run_name
        self.logging_dir = os.path.join(logging_dir or ".", run_name)
        self.writer = tensorboard.SummaryWriter(self.logging_dir, **kwargs)

    @property
    def tracker(self) -> Any:
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.writer.add_hparams(values, metric_dict={})
        self.writer.flush()

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        values = listify(values)
        for k, v in values.items():
            if isinstance(v, (int, float)):
                self.writer.add_scalar(k, v, global_step=step, **kwargs)
            elif isinstance(v, str):
                self.writer.add_text(k, v, global_step=step, **kwargs)
            elif isinstance(v, dict):
                self.writer.add_scalars(k, v, global_step=step, **kwargs)
        self.writer.flush()

    @on_main_process
    def log_images(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        """Reference `tracking.py:251` (`add_images`); accepts HWC/NHWC arrays."""
        import numpy as np

        for k, v in values.items():
            arr = np.asarray(v)
            if arr.ndim == 4:  # NHWC batch
                batch = np.stack([_image_to_uint8_hwc(x) for x in arr])
                self.writer.add_images(k, batch, global_step=step, dataformats="NHWC", **kwargs)
            else:
                self.writer.add_image(k, _image_to_uint8_hwc(arr), global_step=step,
                                      dataformats="HWC", **kwargs)
        self.writer.flush()

    @on_main_process
    def log_table(self, table_name, columns=None, data=None, dataframe=None, step=None, **kwargs):
        """Rendered as a markdown text summary (TB has no native table op)."""
        columns, rows = _table_rows(columns, data, dataframe)
        md = "| " + " | ".join(map(str, columns)) + " |\n"
        md += "|" + "---|" * len(columns) + "\n"
        for r in rows:
            md += "| " + " | ".join(str(c) for c in r) + " |\n"
        self.writer.add_text(table_name, md, global_step=step, **kwargs)
        self.writer.flush()

    @on_main_process
    def finish(self) -> None:
        self.writer.close()


class WandBTracker(GeneralTracker):
    name = "wandb"
    requires_logging_directory = False
    main_process_only = True

    @on_main_process
    def __init__(self, run_name: str, **kwargs: Any):
        import wandb

        self.run_name = run_name
        self.run = wandb.init(project=run_name, **kwargs)

    @property
    def tracker(self) -> Any:
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        import wandb

        wandb.config.update(values, allow_val_change=True)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        self.run.log(values, step=step, **kwargs)

    @on_main_process
    def log_images(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        """Reference `tracking.py:341`."""
        import wandb

        self.run.log(
            {k: [wandb.Image(img, **kwargs) for img in _images_as_hwc_list(v)]
             for k, v in values.items()},
            step=step,
        )

    @on_main_process
    def log_table(self, table_name, columns=None, data=None, dataframe=None, step=None, **kwargs):
        """Reference `tracking.py:360`."""
        import wandb

        if dataframe is not None:
            table = wandb.Table(dataframe=dataframe, **kwargs)
        else:
            columns, rows = _table_rows(columns, data, None)
            table = wandb.Table(columns=list(columns), data=rows, **kwargs)
        self.run.log({table_name: table}, step=step)

    @on_main_process
    def finish(self) -> None:
        self.run.finish()


class MLflowTracker(GeneralTracker):
    name = "mlflow"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str | None = None, **kwargs: Any):
        import mlflow

        self.run_name = run_name
        exp = mlflow.set_experiment(run_name)
        self.run = mlflow.start_run(experiment_id=exp.experiment_id, **kwargs)

    @property
    def tracker(self) -> Any:
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        import mlflow

        for k, v in values.items():
            mlflow.log_param(k, v)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        import mlflow

        metrics = {k: v for k, v in values.items() if isinstance(v, (int, float))}
        mlflow.log_metrics(metrics, step=step)

    @on_main_process
    def log_images(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        """Reference `tracking.py:540` (`mlflow.log_image`)."""
        import mlflow

        for k, img in _expand_image_keys(values):
            mlflow.log_image(img, key=k, step=step, **kwargs)

    @on_main_process
    def log_table(self, table_name, columns=None, data=None, dataframe=None, step=None, **kwargs):
        import mlflow

        if dataframe is None:
            columns, rows = _table_rows(columns, data, None)
            dataframe = {c: [r[i] for r in rows] for i, c in enumerate(columns)}
        artifact = table_name if table_name.endswith(".json") else f"{table_name}.json"
        mlflow.log_table(data=dataframe, artifact_file=artifact, **kwargs)

    @on_main_process
    def finish(self) -> None:
        import mlflow

        mlflow.end_run()


class CometMLTracker(GeneralTracker):
    name = "comet_ml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs: Any):
        from comet_ml import Experiment

        self.run_name = run_name
        self.writer = Experiment(project_name=run_name, **kwargs)

    @property
    def tracker(self) -> Any:
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.writer.log_parameters(values)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        if step is not None:
            self.writer.set_step(step)
        self.writer.log_metrics(values, step=step, **kwargs)

    @on_main_process
    def log_images(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        for k, img in _expand_image_keys(values):
            self.writer.log_image(img, name=k, step=step, **kwargs)

    @on_main_process
    def log_table(self, table_name, columns=None, data=None, dataframe=None, step=None, **kwargs):
        if step is not None:
            self.writer.set_step(step)
        if dataframe is not None:
            self.writer.log_table(
                table_name if table_name.endswith((".json", ".csv", ".md")) else f"{table_name}.csv",
                tabular_data=dataframe, **kwargs)
        else:
            columns, rows = _table_rows(columns, data, None)
            self.writer.log_table(
                table_name if table_name.endswith((".json", ".csv", ".md")) else f"{table_name}.csv",
                tabular_data=[columns] + rows, **kwargs)

    @on_main_process
    def finish(self) -> None:
        self.writer.end()


class AimTracker(GeneralTracker):
    name = "aim"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str | None = None, **kwargs: Any):
        from aim import Run

        self.run_name = run_name
        self.writer = Run(repo=logging_dir, **kwargs)
        self.writer.name = run_name

    @property
    def tracker(self) -> Any:
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.writer["hparams"] = values

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        for k, v in values.items():
            self.writer.track(v, name=k, step=step, **kwargs)

    @on_main_process
    def log_images(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        from aim import Image

        for k, img in _expand_image_keys(values):
            self.writer.track(Image(img), name=k, step=step, **kwargs)

    @on_main_process
    def finish(self) -> None:
        self.writer.close()


class ClearMLTracker(GeneralTracker):
    name = "clearml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs: Any):
        from clearml import Task

        self.task = Task.init(project_name=run_name, **kwargs)

    @property
    def tracker(self) -> Any:
        return self.task

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.task.connect_configuration(values)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        logger = self.task.get_logger()
        for k, v in values.items():
            if isinstance(v, (int, float)):
                logger.report_scalar(title=k, series=k, value=v, iteration=step or 0)

    @on_main_process
    def log_images(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        """Reference `tracking.py:804`."""
        logger = self.task.get_logger()
        for k, img in _expand_image_keys(values):
            logger.report_image(title=k, series=k, iteration=step or 0,
                                image=img, **kwargs)

    @on_main_process
    def log_table(self, table_name, columns=None, data=None, dataframe=None, step=None, **kwargs):
        """Reference `tracking.py:822`."""
        logger = self.task.get_logger()
        if dataframe is None:
            columns, rows = _table_rows(columns, data, None)
            import pandas as pd

            dataframe = pd.DataFrame(rows, columns=columns)
        logger.report_table(title=table_name, series=table_name, iteration=step or 0,
                            table_plot=dataframe, **kwargs)

    @on_main_process
    def finish(self) -> None:
        self.task.close()


class DVCLiveTracker(GeneralTracker):
    name = "dvclive"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs: Any):
        from dvclive import Live

        self.live = Live(**kwargs)

    @property
    def tracker(self) -> Any:
        return self.live

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.live.log_params(values)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        if step is not None:
            self.live.step = step
        for k, v in values.items():
            if isinstance(v, (int, float)):
                self.live.log_metric(k, v)
        self.live.next_step()

    @on_main_process
    def log_images(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        if step is not None:
            self.live.step = step
        for k, img in _expand_image_keys(values):
            name = k if k.endswith((".png", ".jpg")) else f"{k}.png"
            self.live.log_image(name, img, **kwargs)

    @on_main_process
    def finish(self) -> None:
        self.live.end()


LOGGER_TYPE_TO_CLASS: dict[str, type[GeneralTracker]] = {
    "jsonl": JSONLTracker,
    "tensorboard": TensorBoardTracker,
    "wandb": WandBTracker,
    "mlflow": MLflowTracker,
    "comet_ml": CometMLTracker,
    "aim": AimTracker,
    "clearml": ClearMLTracker,
    "dvclive": DVCLiveTracker,
}

_AVAILABILITY: dict[str, Callable[[], bool]] = {
    "jsonl": lambda: True,
    "tensorboard": imports.is_tensorboard_available,
    "wandb": imports.is_wandb_available,
    "mlflow": imports.is_mlflow_available,
    "comet_ml": imports.is_comet_ml_available,
    "aim": imports.is_aim_available,
    "clearml": imports.is_clearml_available,
    "dvclive": imports.is_dvclive_available,
}


def get_available_trackers() -> list[str]:
    return [name for name, probe in _AVAILABILITY.items() if probe()]


def filter_trackers(
    log_with: str | list | None,
    logging_dir: str | None,
    project_name: str,
    config: dict | None,
    init_kwargs: dict,
) -> list[GeneralTracker]:
    """Instantiate requested (or all available) trackers — reference `tracking.py:971`."""
    if log_with is None:
        return []
    if not isinstance(log_with, (list, tuple)):
        log_with = [log_with]
    trackers: list[GeneralTracker] = []
    names: list[str] = []
    for entry in log_with:
        if isinstance(entry, GeneralTracker):
            trackers.append(entry)
            continue
        entry = str(entry).lower()
        if entry == "all":
            names.extend(get_available_trackers())
        else:
            names.append(entry)
    for name in dict.fromkeys(names):
        if name not in LOGGER_TYPE_TO_CLASS:
            raise ValueError(f"Unknown tracker {name!r}; choose from {sorted(LOGGER_TYPE_TO_CLASS)}")
        if not _AVAILABILITY[name]():
            import logging

            logging.getLogger(__name__).warning("Tracker %s requested but not installed; skipping", name)
            continue
        cls = LOGGER_TYPE_TO_CLASS[name]
        kwargs = dict(init_kwargs.get(name, {}))
        if cls.requires_logging_directory:
            kwargs.setdefault("logging_dir", logging_dir)
        tracker = cls(project_name, **kwargs)
        if config:
            tracker.store_init_configuration(config)
        trackers.append(tracker)
    return trackers
