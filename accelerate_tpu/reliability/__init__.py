"""Reliability layer (`docs/reliability.md`): deterministic fault injection,
retry-with-backoff, and SIGTERM preemption handling.

At the ROADMAP's production scale, preemptions and transient I/O failures are
routine; this package supplies (a) the seeded `FaultInjector` that every
recovery path is proven against in tests, (b) the `RetryPolicy` those paths
share, and (c) the opt-in `PreemptionHandler` that lands a synchronous
checkpoint inside a SIGTERM grace window — plus its serving-aware variant
`ServingPreemptionHandler`, which drains an engine inside the window and
snapshots whatever could not finish for `ServingEngine.resume`. The serving
watchdog and the checkpoint commit-marker / restore-fallback machinery
consume these from `serving/engine.py` and `checkpointing.py`.
"""

from .faults import (
    ALL_SLOTS,
    SCOPE_CHECKPOINT_RESTORE,
    SCOPE_CHECKPOINT_SAVE,
    SCOPE_PREEMPTION,
    SCOPE_SERVING_DECODE,
    SCOPE_SERVING_DISPATCH,
    DeviceLostError,
    FaultEvent,
    FaultInjector,
    FaultSpec,
    TransientIOError,
    active_injector,
    fault_point,
    inject,
)
from .preemption import (
    SIGTERM_EXIT_CODE,
    PreemptionHandler,
    ServingPreemptionHandler,
    install_preemption_handler,
    install_serving_preemption_handler,
)
from .retry import RetryError, RetryPolicy

__all__ = [
    "FaultInjector",
    "FaultSpec",
    "FaultEvent",
    "TransientIOError",
    "DeviceLostError",
    "active_injector",
    "inject",
    "fault_point",
    "ALL_SLOTS",
    "SCOPE_CHECKPOINT_SAVE",
    "SCOPE_CHECKPOINT_RESTORE",
    "SCOPE_SERVING_DECODE",
    "SCOPE_SERVING_DISPATCH",
    "SCOPE_PREEMPTION",
    "RetryPolicy",
    "RetryError",
    "PreemptionHandler",
    "ServingPreemptionHandler",
    "install_preemption_handler",
    "install_serving_preemption_handler",
    "SIGTERM_EXIT_CODE",
]
