"""Deterministic, scoped fault injection (`docs/reliability.md`).

At production scale (the ROADMAP north star) TPU preemptions, transient I/O
errors, and poisoned decode steps are routine events, not exceptions. Every
recovery path in this repo — checkpoint retry, restore fallback, the serving
watchdog, the preemption handler — is therefore proven under *injected* faults
rather than waiting for real ones. The injector is:

- **seeded**: every decision (scheduled or probabilistic) derives from
  ``(seed, scope)``, so a failing chaos run replays bit-identically;
- **scoped**: faults fire only at named fault points (``checkpoint.save``,
  ``checkpoint.restore``, ``serving.decode``, ``preemption``) — the rest of
  the system is untouched;
- **zero-cost when inactive**: production fault points are one module-global
  ``None`` check (`fault_point`), nothing else.

Activation is lexical, via the ``inject`` context manager::

    inj = FaultInjector(seed=7, specs=[FaultSpec.io_error("checkpoint.save", at_calls=(0,))])
    with inject(inj):
        accelerator.save_state(...)   # first save attempt raises TransientIOError
    assert inj.fired  # the fault log records (scope, call index, kind)
"""

from __future__ import annotations

import os
import signal
import time
import zlib
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Iterator, Sequence

import numpy as np

# canonical fault-point names (callers may define their own scopes freely)
SCOPE_CHECKPOINT_SAVE = "checkpoint.save"
SCOPE_CHECKPOINT_RESTORE = "checkpoint.restore"
SCOPE_SERVING_DECODE = "serving.decode"
SCOPE_SERVING_DISPATCH = "serving.dispatch"
SCOPE_PREEMPTION = "preemption"
SCOPE_REPLICA_SPAWN = "cluster.replica_spawn"

# fault kinds
KIND_IO_ERROR = "io_error"
KIND_POISON_NAN = "poison_nan"
KIND_PREEMPT = "preempt"
KIND_HANG = "step_hang"
KIND_DEVICE_ERROR = "device_error"

# sentinel: a poison spec with no explicit slots poisons every active slot
ALL_SLOTS: tuple[int, ...] = ()


class TransientIOError(OSError):
    """The injected stand-in for a transient storage failure (flaky NFS/GCS,
    preempted writer, ...). An ``OSError`` subclass on purpose: the default
    `retry.RetryPolicy` retryable filter catches exactly what real transient
    I/O raises, so injected and organic faults exercise the same path."""


class DeviceLostError(RuntimeError):
    """The injected stand-in for a device/runtime failure surfacing from a
    jitted call (XLA ``RuntimeError`` on a lost TPU core, a preempted donated
    buffer, ...). A ``RuntimeError`` subclass on purpose: the supervisor's
    recoverable-exception filter catches exactly what real device loss
    raises, so injected and organic failures exercise the same restart
    ladder (`serving/supervisor.py`)."""


@dataclass(frozen=True)
class FaultSpec:
    """One scheduled or probabilistic fault at one scope.

    ``at_calls`` fires at exact 0-based call indices of the scope's fault
    point (fully deterministic); ``probability`` fires by a seeded per-spec
    Bernoulli stream (deterministic given the injector seed). ``max_faults``
    caps total firings; ``slots`` narrows a poison fault to specific serving
    slots (empty = all active slots); ``hang_s`` is how long a ``step_hang``
    fault blocks the dispatching host thread.
    """

    scope: str
    kind: str
    at_calls: tuple[int, ...] = ()
    probability: float = 0.0
    max_faults: int | None = None
    slots: tuple[int, ...] = ALL_SLOTS
    hang_s: float = 0.0

    @classmethod
    def io_error(cls, scope: str, at_calls: Sequence[int] = (),
                 probability: float = 0.0, max_faults: int | None = None) -> "FaultSpec":
        return cls(scope, KIND_IO_ERROR, tuple(at_calls), probability, max_faults)

    @classmethod
    def poison(cls, at_steps: Sequence[int] = (), probability: float = 0.0,
               slots: Sequence[int] = ALL_SLOTS, max_faults: int | None = None,
               scope: str = SCOPE_SERVING_DECODE) -> "FaultSpec":
        return cls(scope, KIND_POISON_NAN, tuple(at_steps), probability,
                   max_faults, tuple(slots))

    @classmethod
    def preempt(cls, at_calls: Sequence[int] = (), probability: float = 0.0,
                scope: str = SCOPE_PREEMPTION) -> "FaultSpec":
        return cls(scope, KIND_PREEMPT, tuple(at_calls), probability, max_faults=1)

    @classmethod
    def step_hang(cls, at_calls: Sequence[int] = (), hang_s: float = 0.05,
                  probability: float = 0.0, max_faults: int | None = None,
                  scope: str = SCOPE_SERVING_DISPATCH) -> "FaultSpec":
        """A wedged jitted dispatch: the engine's dispatch path blocks for
        ``hang_s`` seconds (``at_calls`` indexes jitted dispatches — decode
        steps and admissions alike). The supervisor's hang watchdog must
        classify the stale heartbeat as a stall and restart."""
        return cls(scope, KIND_HANG, tuple(at_calls), probability, max_faults,
                   hang_s=float(hang_s))

    @classmethod
    def device_error(cls, at_calls: Sequence[int] = (), probability: float = 0.0,
                     max_faults: int | None = None,
                     scope: str = SCOPE_SERVING_DISPATCH) -> "FaultSpec":
        """A lost device: the jitted call raises `DeviceLostError` from the
        dispatch path, the way XLA surfaces a dead TPU core."""
        return cls(scope, KIND_DEVICE_ERROR, tuple(at_calls), probability,
                   max_faults)


@dataclass
class FaultEvent:
    """One firing, recorded in `FaultInjector.fired` for assertions/replay."""

    scope: str
    call_index: int
    kind: str
    slots: tuple[int, ...] = ALL_SLOTS


class FaultInjector:
    """Seeded, scoped fault source. Thread-compatible for the single-writer
    pattern the engine and checkpointing use (one host thread hits each
    scope); not a general concurrent primitive."""

    def __init__(self, seed: int = 0, specs: Sequence[FaultSpec] = ()):
        self.seed = int(seed)
        self.specs = tuple(specs)
        self._calls: dict[str, int] = {}
        self._spec_fired: dict[int, int] = {}
        self._spec_rng: dict[int, np.random.Generator] = {}
        self.fired: list[FaultEvent] = []

    # ------------------------------------------------------------- internals
    def _rng_for(self, spec_idx: int, spec: FaultSpec) -> np.random.Generator:
        rng = self._spec_rng.get(spec_idx)
        if rng is None:
            # a per-spec substream keyed on (seed, scope, kind, position):
            # adding a spec never perturbs another spec's draw sequence
            rng = np.random.default_rng(
                [self.seed, zlib.crc32(spec.scope.encode()),
                 zlib.crc32(spec.kind.encode()), spec_idx]
            )
            self._spec_rng[spec_idx] = rng
        return rng

    def _matching(self, scope: str, kinds: tuple[str, ...], call_idx: int
                  ) -> Iterator[tuple[int, FaultSpec]]:
        """Specs of ``kinds`` at ``scope`` that fire at this call index.
        Probability draws happen for every matching call so the stream is a
        pure function of the call sequence, not of prior firings."""
        for i, spec in enumerate(self.specs):
            if spec.scope != scope or spec.kind not in kinds:
                continue
            fires = call_idx in spec.at_calls
            if spec.probability > 0.0:
                draw = float(self._rng_for(i, spec).random())
                fires = fires or draw < spec.probability
            if not fires:
                continue
            if spec.max_faults is not None and self._spec_fired.get(i, 0) >= spec.max_faults:
                continue
            self._spec_fired[i] = self._spec_fired.get(i, 0) + 1
            yield i, spec

    def _tick(self, scope: str) -> int:
        idx = self._calls.get(scope, 0)
        self._calls[scope] = idx + 1
        return idx

    # ------------------------------------------------------------ fault points
    def maybe_raise(self, scope: str) -> None:
        """I/O fault point: raise `TransientIOError` when a spec fires."""
        idx = self._tick(scope)
        for _, spec in self._matching(scope, (KIND_IO_ERROR,), idx):
            self.fired.append(FaultEvent(scope, idx, KIND_IO_ERROR))
            raise TransientIOError(f"injected transient I/O fault at {scope}#{idx}")

    def poison_slots(self, scope: str = SCOPE_SERVING_DECODE) -> tuple[int, ...] | None:
        """Decode-step fault point: the slots to poison with NaN logits this
        step, or ``None`` when no spec fires. An empty tuple (the `ALL_SLOTS`
        sentinel) means every active slot. Each call advances the scope's
        step counter, so ``at_steps`` indexes the engine's decode steps."""
        idx = self._tick(scope)
        hit: tuple[int, ...] | None = None
        for _, spec in self._matching(scope, (KIND_POISON_NAN,), idx):
            self.fired.append(FaultEvent(scope, idx, KIND_POISON_NAN, spec.slots))
            hit = spec.slots if hit is None else tuple(sorted({*hit, *spec.slots}))
            if spec.slots == ALL_SLOTS:
                hit = ALL_SLOTS
        return hit

    def maybe_preempt(self, scope: str = SCOPE_PREEMPTION) -> bool:
        """Preemption fault point: deliver a real ``SIGTERM`` to this process
        when a spec fires (exercising the installed `preemption` handler the
        way a TPU-VM maintenance event would). Returns whether it fired."""
        idx = self._tick(scope)
        for _, spec in self._matching(scope, (KIND_PREEMPT,), idx):
            self.fired.append(FaultEvent(scope, idx, KIND_PREEMPT))
            os.kill(os.getpid(), signal.SIGTERM)
            return True
        return False

    def dispatch_faults(self, scope: str = SCOPE_SERVING_DISPATCH,
                        sleep=time.sleep) -> float:
        """Dispatch-path fault point (`ServingEngine._dispatch` evaluates it
        once per jitted call, so ``at_calls`` indexes dispatches — decode
        steps and admissions alike). One shared call-index stream covers BOTH
        kinds: a ``step_hang`` spec blocks the host thread for its ``hang_s``
        (returned, for assertions) and a ``device_error`` spec raises
        `DeviceLostError` — the two failure modes a wedged accelerator
        actually presents. ``sleep`` is injectable so unit tests can observe
        the hang without paying the wall time."""
        idx = self._tick(scope)
        slept = 0.0
        for _, spec in self._matching(scope, (KIND_HANG, KIND_DEVICE_ERROR), idx):
            self.fired.append(FaultEvent(scope, idx, spec.kind))
            if spec.kind == KIND_HANG:
                sleep(spec.hang_s)
                slept += spec.hang_s
            else:
                raise DeviceLostError(
                    f"injected device/runtime fault at {scope}#{idx}")
        return slept

    def calls(self, scope: str) -> int:
        """How many times ``scope``'s fault point has been evaluated."""
        return self._calls.get(scope, 0)


# --------------------------------------------------------------- activation
_ACTIVE: FaultInjector | None = None


def active_injector() -> FaultInjector | None:
    """The injector currently activated by `inject`, or None (production)."""
    return _ACTIVE


@contextmanager
def inject(injector: FaultInjector):
    """Activate ``injector`` for the dynamic extent of the block (nestable;
    the previous injector is restored on exit)."""
    global _ACTIVE
    previous = _ACTIVE
    _ACTIVE = injector
    try:
        yield injector
    finally:
        _ACTIVE = previous


def fault_point(scope: str) -> None:
    """Production hook: raise an injected I/O fault if an active injector
    schedules one here; a no-op (one global load) otherwise."""
    if _ACTIVE is not None:
        _ACTIVE.maybe_raise(scope)
