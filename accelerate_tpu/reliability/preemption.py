"""Opt-in SIGTERM preemption handling (`docs/reliability.md`).

TPU-VM maintenance events and spot reclamation deliver ``SIGTERM`` with a
short grace window; the restart-from-checkpoint recovery loop (the baseline
failure model SimpleFSDP/GSPMD-style compiled stacks assume) only works if a
checkpoint actually lands inside that window. `PreemptionHandler` installs a
handler that writes a **synchronous** checkpoint (async would race the kill)
and then exits — or chains to whatever handler was installed before it.

Opt-in by construction: nothing installs it implicitly; a library must never
steal a host application's signal disposition.

    handler = install_preemption_handler(accelerator)
    ...training loop...            # SIGTERM now checkpoints before exit
    handler.uninstall()            # restore the previous disposition
"""

from __future__ import annotations

import signal
import time
from typing import Any

# conventional exit status for "terminated by SIGTERM" (128 + 15)
SIGTERM_EXIT_CODE = 143


class PreemptionHandler:
    """SIGTERM -> synchronous ``save_state`` -> exit (or chain).

    ``exit_on_preempt=False`` turns the handler into a checkpoint-and-continue
    hook (useful under test, or when an outer supervisor owns process death);
    ``preempted``/``checkpoint_dir`` record what happened either way.
    """

    def __init__(
        self,
        accelerator: Any,
        output_dir: str | None = None,
        *,
        exit_on_preempt: bool = True,
        exit_code: int = SIGTERM_EXIT_CODE,
    ):
        self.accelerator = accelerator
        self.output_dir = output_dir
        self.exit_on_preempt = exit_on_preempt
        self.exit_code = exit_code
        self.preempted = False
        self.checkpoint_dir: str | None = None
        # every SIGTERM delivery, including ones swallowed by the re-entrancy
        # guard while a save is already in flight
        self.signals_seen = 0
        self._previous: Any = None
        self._installed = False
        self._handling = False

    def install(self) -> "PreemptionHandler":
        """Register on ``SIGTERM`` (main thread only — CPython restriction),
        keeping the previous disposition for chaining/uninstall."""
        if self._installed:
            return self
        self._previous = signal.signal(signal.SIGTERM, self._handle)
        self._installed = True
        return self

    def uninstall(self) -> None:
        """Restore the pre-install SIGTERM disposition."""
        if self._installed:
            signal.signal(signal.SIGTERM, self._previous or signal.SIG_DFL)
            self._installed = False

    def _handle(self, signum, frame) -> None:
        self.signals_seen += 1
        if self._handling:
            # re-entrant SIGTERM while the synchronous save is mid-write:
            # re-entering save_state would corrupt the very checkpoint the
            # grace window exists to land (and double-chaining the previous
            # handler could exit before the first save returns). Count it
            # and return — the in-flight handler finishes and then exits.
            return
        self._handling = True
        self.preempted = True
        try:
            self._on_preempt()
        finally:
            self._handling = False
            previous = self._previous
            if callable(previous):
                previous(signum, frame)
            elif self.exit_on_preempt:
                raise SystemExit(self.exit_code)

    def _on_preempt(self) -> None:
        """The work a preemption must land before the process dies (subclass
        hook — the base writes a training checkpoint)."""
        # checkpointing is imported lazily: checkpointing.py itself imports
        # this package (retry/fault points), so a module-level import here
        # would be circular
        from ..checkpointing import wait_for_checkpoint_saves

        # synchronous on purpose: the grace window ends in seconds and an
        # async save's background writer would die with the process
        self.checkpoint_dir = self.accelerator.save_state(
            self.output_dir, async_save=False
        )
        wait_for_checkpoint_saves()


class ServingPreemptionHandler(PreemptionHandler):
    """SIGTERM for a serving process: drain inside the grace window, snapshot
    whatever could not finish, then exit (or chain).

    On preemption the handler (a) flips the engine into drain mode so new
    `submit` calls are rejected with ``REJECT_DRAINING``, (b) steps the engine
    until either all in-flight and queued work finishes or ``grace_s`` wall
    seconds elapse, and (c) if work remains, writes an engine snapshot to
    ``snapshot_path`` (`ServingEngine.snapshot`) that a replacement process
    resumes from with `ServingEngine.resume` — bit-for-bit, mid-stream.
    Completed outputs collected while draining land in ``drained`` so the
    host can flush responses before the exit. Size ``grace_s`` BELOW the
    platform's kill window: the snapshot write itself (queue + per-slot token
    JSON, fsync'd) must also fit inside it — see `docs/reliability.md`.

    When the engine also has a durable request journal, a SIGKILL that beats
    this handler entirely still loses nothing: `resume` replays from the
    journal instead of the snapshot.

    Deliver-at-step-boundary: a serving loop should block SIGTERM around each
    ``engine.step()`` call (``signal.pthread_sigmask``) and unblock between
    steps, so the drain here never re-enters a step the signal interrupted
    halfway — `tools/chaos_serve.py`'s crash child shows the pattern.
    """

    def __init__(
        self,
        engine: Any,
        snapshot_path: str,
        *,
        grace_s: float = 5.0,
        exit_on_preempt: bool = True,
        exit_code: int = SIGTERM_EXIT_CODE,
    ):
        super().__init__(
            accelerator=None,
            output_dir=None,
            exit_on_preempt=exit_on_preempt,
            exit_code=exit_code,
        )
        self.engine = engine
        self.snapshot_path = str(snapshot_path)
        self.grace_s = float(grace_s)
        self.drained: list[Any] = []
        self.snapshotted = False

    def _on_preempt(self) -> None:
        engine = self.engine
        engine.begin_drain()
        deadline = time.perf_counter() + self.grace_s
        finished: list[Any] = []
        try:
            while engine.has_work and time.perf_counter() < deadline:
                finished.extend(engine.step())
        finally:
            # exit path: disposition is moot; checkpoint-and-continue path
            # (exit_on_preempt=False / chained handler): the engine must
            # accept work again once the handler returns
            engine.end_drain()
        if engine.has_work:
            finished.extend(engine.snapshot(self.snapshot_path))
            self.snapshotted = True
            self.checkpoint_dir = self.snapshot_path
        self.drained = finished


def install_preemption_handler(
    accelerator: Any, output_dir: str | None = None, **kwargs: Any
) -> PreemptionHandler:
    """Install and return a `PreemptionHandler` (see class docs for knobs)."""
    return PreemptionHandler(accelerator, output_dir, **kwargs).install()


def install_serving_preemption_handler(
    engine: Any, snapshot_path: str, **kwargs: Any
) -> ServingPreemptionHandler:
    """Install and return a `ServingPreemptionHandler` (drain-or-snapshot on
    SIGTERM; see class docs for the grace-window contract)."""
    return ServingPreemptionHandler(engine, snapshot_path, **kwargs).install()
