"""Opt-in SIGTERM preemption handling (`docs/reliability.md`).

TPU-VM maintenance events and spot reclamation deliver ``SIGTERM`` with a
short grace window; the restart-from-checkpoint recovery loop (the baseline
failure model SimpleFSDP/GSPMD-style compiled stacks assume) only works if a
checkpoint actually lands inside that window. `PreemptionHandler` installs a
handler that writes a **synchronous** checkpoint (async would race the kill)
and then exits — or chains to whatever handler was installed before it.

Opt-in by construction: nothing installs it implicitly; a library must never
steal a host application's signal disposition.

    handler = install_preemption_handler(accelerator)
    ...training loop...            # SIGTERM now checkpoints before exit
    handler.uninstall()            # restore the previous disposition
"""

from __future__ import annotations

import signal
from typing import Any

# conventional exit status for "terminated by SIGTERM" (128 + 15)
SIGTERM_EXIT_CODE = 143


class PreemptionHandler:
    """SIGTERM -> synchronous ``save_state`` -> exit (or chain).

    ``exit_on_preempt=False`` turns the handler into a checkpoint-and-continue
    hook (useful under test, or when an outer supervisor owns process death);
    ``preempted``/``checkpoint_dir`` record what happened either way.
    """

    def __init__(
        self,
        accelerator: Any,
        output_dir: str | None = None,
        *,
        exit_on_preempt: bool = True,
        exit_code: int = SIGTERM_EXIT_CODE,
    ):
        self.accelerator = accelerator
        self.output_dir = output_dir
        self.exit_on_preempt = exit_on_preempt
        self.exit_code = exit_code
        self.preempted = False
        self.checkpoint_dir: str | None = None
        self._previous: Any = None
        self._installed = False

    def install(self) -> "PreemptionHandler":
        """Register on ``SIGTERM`` (main thread only — CPython restriction),
        keeping the previous disposition for chaining/uninstall."""
        if self._installed:
            return self
        self._previous = signal.signal(signal.SIGTERM, self._handle)
        self._installed = True
        return self

    def uninstall(self) -> None:
        """Restore the pre-install SIGTERM disposition."""
        if self._installed:
            signal.signal(signal.SIGTERM, self._previous or signal.SIG_DFL)
            self._installed = False

    def _handle(self, signum, frame) -> None:
        # checkpointing is imported lazily: checkpointing.py itself imports
        # this package (retry/fault points), so a module-level import here
        # would be circular
        from ..checkpointing import wait_for_checkpoint_saves

        self.preempted = True
        try:
            # synchronous on purpose: the grace window ends in seconds and an
            # async save's background writer would die with the process
            self.checkpoint_dir = self.accelerator.save_state(
                self.output_dir, async_save=False
            )
            wait_for_checkpoint_saves()
        finally:
            previous = self._previous
            if callable(previous):
                previous(signum, frame)
            elif self.exit_on_preempt:
                raise SystemExit(self.exit_code)


def install_preemption_handler(
    accelerator: Any, output_dir: str | None = None, **kwargs: Any
) -> PreemptionHandler:
    """Install and return a `PreemptionHandler` (see class docs for knobs)."""
    return PreemptionHandler(accelerator, output_dir, **kwargs).install()
