"""Reusable retry-with-backoff policy (`docs/reliability.md`).

One policy object serves every transient-failure site in the repo (checkpoint
save/restore I/O today; any flaky RPC tomorrow). Deliberately deterministic:
the jitter stream is seeded per `call`, so a retried operation backs off the
same way on every replay — fault-injection tests assert exact sleep sequences.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Iterator

import numpy as np


class RetryError(Exception):
    """All attempts failed (or the deadline expired first). ``attempts`` holds
    every underlying exception in order; ``__cause__`` is the last one."""

    def __init__(self, message: str, attempts: list[BaseException]):
        super().__init__(message)
        self.attempts = attempts


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with bounded jitter, attempt cap, wall deadline,
    and a retryable-exception filter.

    Delay before retry ``i`` (0-based) is ``min(max_delay_s, base_delay_s *
    multiplier**i)`` scaled by a uniform factor in ``[1-jitter, 1+jitter]``
    drawn from a ``seed``-keyed stream. Exceptions not matching ``retryable``
    — or matching ``non_retryable``, which wins — propagate immediately: a
    corrupt checkpoint or missing file must not be retried like a flaky disk.
    ``deadline_s`` bounds total elapsed time including sleeps: a retry that
    cannot start before the deadline is not attempted.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.1
    deadline_s: float | None = None
    retryable: tuple[type[BaseException], ...] = (OSError,)
    non_retryable: tuple[type[BaseException], ...] = (FileNotFoundError, IsADirectoryError)
    seed: int = 0

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {self.max_attempts}")
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")

    def delays(self) -> Iterator[float]:
        """The deterministic backoff sequence (one delay per retry)."""
        rng = np.random.default_rng(self.seed)
        for i in range(self.max_attempts - 1):
            delay = min(self.max_delay_s, self.base_delay_s * self.multiplier**i)
            if self.jitter:
                delay *= 1.0 + self.jitter * (2.0 * float(rng.random()) - 1.0)
            yield delay

    def call(
        self,
        fn: Callable[..., Any],
        *args: Any,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
        **kwargs: Any,
    ) -> Any:
        """Invoke ``fn(*args, **kwargs)``, retrying retryable failures under
        this policy. ``sleep``/``clock`` are injectable so tests run in zero
        wall time while asserting the exact backoff schedule."""
        start = clock()
        attempts: list[BaseException] = []
        delay_iter = self.delays()
        while True:
            try:
                return fn(*args, **kwargs)
            except self.retryable as exc:  # type: ignore[misc]
                if isinstance(exc, self.non_retryable):
                    raise
                attempts.append(exc)
                delay = next(delay_iter, None)
                if delay is None:
                    raise RetryError(
                        f"{fn!r} failed after {len(attempts)} attempts", attempts
                    ) from exc
                if (self.deadline_s is not None
                        and clock() - start + delay > self.deadline_s):
                    raise RetryError(
                        f"{fn!r} deadline {self.deadline_s}s exhausted after "
                        f"{len(attempts)} attempts", attempts
                    ) from exc
                sleep(delay)
