"""Multi-process logging.

Capability parity: reference `src/accelerate/logging.py` (125 LoC) —
`MultiProcessAdapter` gates records to the main process by default, can log on all
processes (``main_process_only=False``) or strictly one-per-rank in order
(``in_order=True``), and stamps each record with the process index.
"""

from __future__ import annotations

import functools
import logging
import os
from typing import Any


class MultiProcessAdapter(logging.LoggerAdapter):
    @staticmethod
    def _should_log(main_process_only: bool) -> bool:
        from .state import PartialState

        return not main_process_only or PartialState().is_main_process

    def log(self, level: int, msg: str, *args: Any, **kwargs: Any) -> None:
        from .state import PartialState

        main_process_only = kwargs.pop("main_process_only", True)
        in_order = kwargs.pop("in_order", False)
        if self.isEnabledFor(level):
            state = PartialState()
            kwargs.setdefault("stacklevel", 2)
            if not in_order:
                if self._should_log(main_process_only):
                    msg, kwargs = self.process(msg, kwargs)
                    self.logger.log(level, msg, *args, **kwargs)
                return
            # in_order: each process logs in rank order, separated by barriers
            for i in range(state.num_processes):
                if i == state.process_index:
                    msg_p, kwargs_p = self.process(msg, kwargs)
                    self.logger.log(level, f"[rank {i}] {msg_p}", *args, **kwargs_p)
                state.wait_for_everyone()

    def process(self, msg: str, kwargs: dict) -> tuple[str, dict]:
        return msg, kwargs


def get_logger(name: str, log_level: str | None = None) -> MultiProcessAdapter:
    """Rank-aware logger factory (reference `logging.py:85`). Level can also come
    from ``ACCELERATE_TPU_LOG_LEVEL``."""
    logger = logging.getLogger(name)
    log_level = log_level or os.environ.get("ACCELERATE_TPU_LOG_LEVEL", None)
    if log_level is not None:
        logger.setLevel(log_level.upper())
        logger.root.setLevel(log_level.upper())
    return MultiProcessAdapter(logger, {})
