"""Elastic fleet: the autoscaling control loop over `ServingCluster`
(`docs/reliability.md` "Elastic fleet").

PR 13 made the cluster route around and migrate off dead replicas; PR 15
made it shed load predictively. Both only ever SHRINK the fleet — a surge
has nowhere to go, idle capacity is never reclaimed, and a budget-exhausted
replica stays DEAD forever. The :class:`FleetAutoscaler` closes the loop:
replica count becomes a supervised control variable, driven by the same
predicted-TTFT model the front door admits against (`frontend.predict_ttft`),
with four behaviors:

- **scale up** — when the fleet-wide TTFT prediction stays past
  ``target_ttft_s`` for ``scale_up_windows`` consecutive evaluations, spawn
  one replica through the cluster's construction-time factory
  (`ServingCluster.add_replica`) into a fresh ``workdir/replica<i>/`` under a
  stable, never-reused index. Same module/params through the factory means
  the process jit cache (`_SHARED_JITS`) makes the spawn skip recompilation —
  a scale event costs a directory and a supervisor, not a compile;
- **drain and retire** — when headroom stays idle (free-slot fraction at or
  above ``idle_slots_fraction`` with an empty queue) for
  ``scale_down_idle_windows`` evaluations, the least-loaded replica enters
  the strict retire lifecycle (`ServingCluster.retire_replica`): DRAINING
  (excluded from placement, still stepped) until its in-flight work finishes,
  then RETIRED (journal closed, fsck-clean). A drain that outlives
  ``drain_grace_evals`` evaluations is forced: the remaining work
  journal-migrates to peers bit-exactly (the PR-13 machinery) and the
  replica retires anyway — zero requests lost either way;
- **replace** — a DEAD (RestartBudget-exhausted) replica is replaced by a
  successor spawn plus the existing dead-journal migration
  (`ServingCluster.replace_replica`), turning yesterday's terminal state
  into one more lifecycle edge;
- **refuse to flap** — every scale event feeds a `kv_tier.ThrashGuard`
  window; crossing ``thrash_enter_events`` freezes scaling and raises
  ``EV_ANOMALY autoscale_thrash`` (enter/exit strictly alternating, the
  validator's contract) instead of oscillating, unfreezing only after the
  window stays calm for ``thrash_exit_s``. A ``dwell_s`` minimum between
  events bounds the control rate even while unfrozen. Spawn failures (the
  ``cluster.replica_spawn`` fault point) retry under a seeded `RetryPolicy`;
  on exhaustion the target falls back to the actual size — the fleet
  degrades gracefully to what it has.

Everything is synchronous and deterministic: ``clock``/``sleep`` are
injectable, every decision derives from cluster gauges, and the loop runs
inside `ServingCluster.step` (one evaluation per step, cadence-gated by
``eval_interval_s``) so callers keep their existing serving loop.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable

from ..reliability.retry import RetryPolicy
from .frontend import predict_ttft
from .kv_tier import ThrashGuard
from .request import RequestOutput
from .trace import EV_ANOMALY, EV_SCALE

# EV_ANOMALY detector name for a frozen (thrashing) autoscaler
DETECTOR_THRASH = "autoscale_thrash"


@dataclasses.dataclass(frozen=True)
class AutoscalerConfig:
    """Knobs for the fleet control loop (`docs/reliability.md` sizes them).

    - ``min_replicas`` / ``max_replicas``: the fleet size envelope — the
      loop never drains below the floor nor spawns past the ceiling;
    - ``target_ttft_s`` / ``scale_up_windows``: scale up after this many
      consecutive evaluations predicting TTFT past the target (consecutive,
      so one slow step never spawns a replica);
    - ``idle_slots_fraction`` / ``scale_down_idle_windows``: drain-and-retire
      after this many consecutive evaluations with the queue empty and at
      least this fraction of fleet slots free;
    - ``eval_interval_s``: control cadence — evaluations closer together
      than this are no-ops (0 = every cluster step evaluates);
    - ``dwell_s``: minimum seconds between scale EVENTS (up, retire, or
      replace) — the first hysteresis layer;
    - ``drain_grace_evals``: evaluations a DRAINING replica may take to go
      idle before its remaining work is force-migrated to peers;
    - ``thrash_*``: the `ThrashGuard` window — ``thrash_enter_events`` scale
      events inside ``thrash_window_s`` freeze scaling (EV_ANOMALY
      ``autoscale_thrash``), unfreezing after the window holds at or below
      ``thrash_exit_fraction`` of the enter count for ``thrash_exit_s``;
    - ``spawn_retry``: seeded backoff for replica spawns — exhaustion
      degrades the target to the actual size instead of raising.
    """

    min_replicas: int = 1
    max_replicas: int = 4
    target_ttft_s: float = 1.0
    scale_up_windows: int = 3
    idle_slots_fraction: float = 0.5
    scale_down_idle_windows: int = 5
    eval_interval_s: float = 0.0
    dwell_s: float = 0.0
    drain_grace_evals: int = 8
    thrash_window_s: float = 60.0
    thrash_enter_events: int = 4
    thrash_exit_fraction: float = 0.25
    thrash_exit_s: float = 30.0
    spawn_retry: RetryPolicy = RetryPolicy(
        max_attempts=3, base_delay_s=0.05, max_delay_s=1.0, seed=0)

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, "
                             f"got {self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas ({self.max_replicas}) must be >= "
                f"min_replicas ({self.min_replicas})")
        if self.scale_up_windows < 1 or self.scale_down_idle_windows < 1:
            raise ValueError("scale windows must be >= 1")
        if not 0.0 < self.idle_slots_fraction <= 1.0:
            raise ValueError(f"idle_slots_fraction must be in (0, 1], "
                             f"got {self.idle_slots_fraction}")


class FleetAutoscaler:
    """The fleet control loop (module docstring). Attaches itself to the
    cluster at construction; `ServingCluster.step` then calls `evaluate()`
    once per step::

        cluster = ServingCluster(factory, workdir, replicas=1)
        scaler = FleetAutoscaler(cluster, AutoscalerConfig(
            max_replicas=4, target_ttft_s=0.5, dwell_s=2.0))
        while cluster.has_work:
            for out in cluster.step(): ...   # scaling happens inside

    ``tracer`` (optional) receives the EV_ANOMALY freeze/unfreeze pair —
    a dedicated tracer, because the anomaly validator requires strict
    per-detector enter/exit alternation on ONE event stream and replica
    tracers come and go with the replicas. EV_SCALE events ride the involved
    replica's own tracer (`ServingCluster` emits them).
    """

    # gauge names (check_metrics_docs sources these; docs/observability.md
    # documents each row)
    GAUGES = (
        "autoscaler/target_replicas",
        "autoscaler/actual_replicas",
        "autoscaler/draining_replicas",
        "autoscaler/replaced",
        "autoscaler/spawn_retries",
        "autoscaler/spawn_failures",
        "autoscaler/scale_frozen",
        "autoscaler/scale_ups",
        "autoscaler/retires",
    )

    def __init__(
        self,
        cluster: Any,
        config: AutoscalerConfig | None = None,
        *,
        clock: Callable[[], float] = time.perf_counter,
        sleep: Callable[[float], None] = time.sleep,
        tracer: Any = None,
    ):
        self.cluster = cluster
        self.config = config if config is not None else AutoscalerConfig()
        self._clock = clock
        self._sleep = sleep
        self.tracer = tracer
        cfg = self.config
        self.guard = ThrashGuard(cfg.thrash_window_s, cfg.thrash_enter_events,
                                 cfg.thrash_exit_fraction, cfg.thrash_exit_s,
                                 clock=clock)
        self.target_replicas = max(
            cfg.min_replicas,
            min(cfg.max_replicas,
                sum(1 for r in cluster.replicas if r.accepting)))
        self.scale_ups = 0
        self.retires = 0
        self.spawn_retries = 0
        self.spawn_failures = 0
        self.evaluations = 0
        self._last_eval_t: float | None = None
        self._last_scale_t: float | None = None
        self._breach_windows = 0
        self._idle_windows = 0
        self._drain_ages: dict[int, int] = {}
        cluster.autoscaler = self

    # ----------------------------------------------------------- fleet view
    def _live(self) -> list[Any]:
        return [r for r in self.cluster.replicas
                if not r.retired and not r.supervisor.unhealthy]

    def _accepting(self) -> list[Any]:
        return [r for r in self.cluster.replicas if r.accepting]

    def predict_fleet_ttft(self) -> float | None:
        """The fleet-wide TTFT estimate the control loop steers on — the
        same model the front door's admission gate uses
        (`frontend.predict_ttft` over the cluster's aggregate headroom, the
        slowest accepting replica's step-phase spine, and the summed
        accepting concurrency)."""
        accepting = self._accepting()
        if not accepting:
            return None
        timings: dict[str, float] = {}
        total_conc = 0
        for rep in accepting:
            t = getattr(rep.engine, "last_step_timings", None) or {}
            if t.get("total_s", 0.0) >= timings.get("total_s", 0.0):
                timings = t
            total_conc += int(rep.engine.max_concurrency)
        return predict_ttft(self.cluster.capacity_headroom(), timings,
                            max_concurrency=total_conc or None)

    # -------------------------------------------------------------- control
    def evaluate(self) -> list[RequestOutput]:
        """One control evaluation (cadence-gated): replace DEAD replicas,
        age drains toward the force-migrate grace bound, then run the
        scale-up / scale-down decision under dwell + thrash hysteresis.
        Returns any cluster-id outputs a forced drain migration delivered
        (`ServingCluster.step` extends its own output with them)."""
        cfg = self.config
        now = self._clock()
        if (self._last_eval_t is not None
                and now - self._last_eval_t < cfg.eval_interval_s):
            return []
        self._last_eval_t = now
        self.evaluations += 1
        if self.guard.poll() and self.tracer is not None \
                and self.tracer.enabled:
            self.tracer.emit(EV_ANOMALY, None, detector=DETECTOR_THRASH,
                             phase="exit", window_events=0)
        outputs: list[RequestOutput] = []
        self._replace_dead()
        outputs.extend(self._age_drains())
        predicted = self.predict_fleet_ttft()
        actual = len(self._accepting())
        draining = sum(1 for r in self._live() if r.draining)
        if predicted is not None and predicted > cfg.target_ttft_s:
            self._breach_windows += 1
            self._idle_windows = 0
        else:
            self._breach_windows = 0
            if self._fleet_idle():
                self._idle_windows += 1
            else:
                self._idle_windows = 0
        if (self._breach_windows >= cfg.scale_up_windows
                and actual + draining < cfg.max_replicas
                and self._may_scale(now)):
            self.target_replicas = min(cfg.max_replicas,
                                       max(self.target_replicas, actual) + 1)
        if self.target_replicas > actual + draining:
            # scale-up in flight: target leads actual until the spawn lands
            # (the front door sheds LESS while this gap is open)
            if self._spawn_one():
                self._mark_scale_event(now)
                self._breach_windows = 0
            else:
                # graceful degradation: spawn retries exhausted — fold the
                # target back to what the fleet actually has (replenished
                # the next time the breach windows accumulate)
                self.target_replicas = actual + draining
        elif (self._idle_windows >= cfg.scale_down_idle_windows
              and actual > cfg.min_replicas
              and self._may_scale(now)):
            self._retire_least_loaded()
            self._mark_scale_event(now)
            self._idle_windows = 0
            self.target_replicas = max(cfg.min_replicas, actual - 1)
        return outputs

    def _fleet_idle(self) -> bool:
        head = self.cluster.capacity_headroom()
        if int(head.get("queue_depth", 0)) > 0:
            return False
        total = sum(int(r.engine.max_concurrency) for r in self._accepting())
        if total <= 0:
            return False
        free = int(head.get("slots_free", 0))
        return free / total >= self.config.idle_slots_fraction

    def _may_scale(self, now: float) -> bool:
        if self.guard.frozen:
            return False
        if self._last_scale_t is None or self.config.dwell_s <= 0:
            return True
        return now - self._last_scale_t >= self.config.dwell_s

    def _mark_scale_event(self, now: float) -> None:
        self._last_scale_t = now
        if self.guard.record(1) and self.tracer is not None \
                and self.tracer.enabled:
            self.tracer.emit(EV_ANOMALY, None, detector=DETECTOR_THRASH,
                             phase="enter",
                             window_events=self.guard.window_events,
                             window_s=self.config.thrash_window_s)

    # --------------------------------------------------------------- spawns
    def _with_spawn_retry(self, fn: Callable[[], Any]) -> Any | None:
        """Run a spawn under the seeded retry policy. Returns the spawn's
        result, or None on exhaustion (graceful degradation — the caller
        folds the target back to the actual size)."""
        policy = self.config.spawn_retry
        delays = [0.0] + list(policy.delays())
        for attempt, delay in enumerate(delays):
            if delay > 0:
                self._sleep(delay)
            if attempt > 0:
                self.spawn_retries += 1
            try:
                return fn()
            except policy.non_retryable:
                raise
            except policy.retryable:
                continue
        self.spawn_failures += 1
        return None

    def _spawn_one(self) -> bool:
        """One scale-up spawn (with retry); True on success."""
        rep = self._with_spawn_retry(lambda: self.cluster.add_replica())
        if rep is None:
            return False
        self.scale_ups += 1
        tracer = getattr(rep.engine, "tracer", None)
        if tracer is not None and tracer.enabled:
            tracer.emit(EV_SCALE, None, action="up", replica=rep.index,
                        target=self.target_replicas,
                        actual=len(self._accepting()))
        return True

    def _replace_dead(self) -> None:
        """Spawn successors for DEAD (budget-exhausted, non-draining)
        replicas — the dead-journal migration rides `replace_replica`. A
        dead DRAINING replica is NOT replaced: the fleet was shrinking
        through it, and `ServingCluster.step` finalizes its retirement."""
        for rep in list(self.cluster.replicas):
            if rep.retired or rep.draining or not rep.supervisor.unhealthy:
                continue
            done = self._with_spawn_retry(
                lambda idx=rep.index: self.cluster.replace_replica(idx))
            if done is None:
                # degraded: the dead replica stays DEAD until a later
                # evaluation's spawn succeeds
                break

    # --------------------------------------------------------------- drains
    def _age_drains(self) -> list[RequestOutput]:
        cfg = self.config
        outputs: list[RequestOutput] = []
        for rep in self.cluster.replicas:
            if rep.retired or not rep.draining:
                self._drain_ages.pop(rep.index, None)
                continue
            age = self._drain_ages.get(rep.index, 0) + 1
            self._drain_ages[rep.index] = age
            if age > cfg.drain_grace_evals:
                outputs.extend(
                    self.cluster.retire_replica(rep.index, force=True))
                self._drain_ages.pop(rep.index, None)
        return outputs

    def _retire_least_loaded(self) -> None:
        candidates = [r for r in self._accepting()]
        if len(candidates) <= self.config.min_replicas:
            return
        # least load first; newest (highest index) breaks ties so the
        # longest-lived replicas — the warmest caches — survive
        candidates.sort(key=lambda r: (
            r.engine.scheduler.queue_depth + r.engine.active_slots,
            -r.index))
        victim = candidates[0]
        self.cluster.retire_replica(victim.index)
        self.retires += 1
        self._drain_ages[victim.index] = 0

    # ------------------------------------------------------------ telemetry
    @property
    def frozen(self) -> bool:
        return self.guard.frozen

    def gauges(self) -> dict[str, Any]:
        """The ``autoscaler/*`` gauges (merged into the cluster metrics
        view's snapshot, so telemetry/serve_top export them for free)."""
        draining = sum(1 for r in self.cluster.replicas
                       if not r.retired and r.draining)
        return {
            "autoscaler/target_replicas": self.target_replicas,
            "autoscaler/actual_replicas": len(self._accepting()),
            "autoscaler/draining_replicas": draining,
            "autoscaler/replaced": self.cluster.replaced_replicas,
            "autoscaler/spawn_retries": self.spawn_retries,
            "autoscaler/spawn_failures": self.spawn_failures,
            "autoscaler/scale_frozen": int(self.guard.frozen),
            "autoscaler/scale_ups": self.scale_ups,
            "autoscaler/retires": self.retires,
        }
